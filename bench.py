"""Benchmark driver: create_transfers validated transfers/sec on TPU.

Harness-proof, phase-isolated orchestrator (reference:
src/tigerbeetle/benchmark_driver.zig). Every phase that touches JAX runs
in a freshly-exec'd subprocess with the platform pinned in the
environment BEFORE any jax import, so a wedged TPU tunnel can never
take down the driver. Prints ONE JSON line at the end:
{"metric", "value", "unit", "vs_baseline", ...} plus diagnostics.

Phases:
  0. loopback port scan (no jax) — evidence of whether the axon relay
     is listening at all.
  1. axon backend probe (subprocess, bounded): import jax,
     jax.devices(), one tiny op. On timeout the child dumps a
     faulthandler traceback of all threads (captured into the JSON).
  2. bench run (subprocess) on axon if the probe passed, else on CPU
     as a clearly-labeled proxy. Per-config progress is streamed so a
     mid-run wedge still yields partial numbers.

Env knobs:
  BENCH_PLATFORM=cpu|axon  force the platform (skips the probe)
  BENCH_QUICK=1            small CI run
  BENCH_CONFIGS="1,2,3"    config subset
  BENCH_WATCHDOG_S=1500    total budget
  BENCH_TPU_INIT_TIMEOUT_S=420  axon probe budget
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
T0 = time.time()

# Persistent executable cache for every on-chip child (bench inner runs
# and the watcher's probes share it): tunnel windows are scarce and the
# superbatch kernels take minutes to compile remotely — a cache hit in a
# later window turns the recompile into a disk read. Harmless if the
# axon PJRT plugin doesn't support serialization (JAX logs and compiles
# as usual).
CACHE_ENV = {
    "JAX_COMPILATION_CACHE_DIR": os.path.join(REPO, "scratch", "xla_cache"),
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "5",
}


def _budget() -> float:
    return float(os.environ.get("BENCH_WATCHDOG_S", "1500"))


def _remaining(margin: float = 20.0) -> float:
    return max(5.0, _budget() - (time.time() - T0) - margin)


# ---------------------------------------------------------------- phase 0
def listening_loopback_ports() -> list[int]:
    """Listening TCP ports from /proc — is the axon relay up at all?

    The axon PJRT plugin claims its TPU grant via an orchestrator
    dialed at 127.0.0.1 (AXON_POOL_SVC_OVERRIDE); if nothing listens
    there, PJRT_Client_Create retries forever and jax.devices() never
    returns. This scan is the no-jax evidence for that diagnosis."""
    loopback_hex = {
        "0100007F",  # 127.0.0.1
        "00000000000000000000000001000000",  # ::1
        "0000000000000000FFFF00000100007F",  # ::ffff:127.0.0.1
    }
    any_hex = {"00000000", "00000000000000000000000000000000"}
    ports: set[int] = set()
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            lines = open(path).read().splitlines()[1:]
        except OSError:
            continue
        for ln in lines:
            f = ln.split()
            if len(f) > 3 and f[3] == "0A":  # TCP_LISTEN
                addr, port = f[1].rsplit(":", 1)
                # 0.0.0.0/:: wildcards accept loopback connections too.
                if addr in loopback_hex or addr in any_hex:
                    ports.add(int(port, 16))
    return sorted(ports)


def _pinned_env(platform: str) -> dict:
    """Subprocess env with the JAX platform pinned BEFORE interpreter start.

    The axon sitecustomize (PYTHONPATH hook) registers the axon PJRT
    plugin and sets jax_platforms="axon,cpu" via jax.config.update in
    every process where PALLAS_AXON_POOL_IPS is set — overriding the
    JAX_PLATFORMS env var. For non-axon children we therefore strip
    PALLAS_AXON_POOL_IPS so the plugin is never registered and the env
    var rules; for axon children we leave the hook in place (it IS the
    registration path)."""
    env = dict(os.environ, JAX_PLATFORMS=platform)
    env.pop("BENCH_PLATFORM", None)
    if platform != "axon":
        env.pop("PALLAS_AXON_POOL_IPS", None)
    else:
        for k, v in CACHE_ENV.items():
            env.setdefault(k, v)
    return env


# ---------------------------------------------------------------- phase 1
_PROBE_SRC = r'''
import faulthandler, json, os, sys, threading, time
faulthandler.enable()
deadline = float(sys.argv[1])
def _dump():
    time.sleep(max(1.0, deadline))
    sys.stderr.write("PROBE_TIMEOUT_TRACEBACK\n")
    faulthandler.dump_traceback(all_threads=True, file=sys.stderr)
    sys.stderr.flush()
    os._exit(3)
threading.Thread(target=_dump, daemon=True).start()
t0 = time.time()
import jax
t_import = time.time() - t0
t1 = time.time()
devs = jax.devices()
t_devices = time.time() - t1
import jax.numpy as jnp
t2 = time.time()
y = (jnp.arange(8) * 2).sum().block_until_ready()
t_op = time.time() - t2
print(json.dumps({
    "ok": True, "import_s": round(t_import, 2),
    "devices_s": round(t_devices, 2), "first_op_s": round(t_op, 2),
    "n_devices": len(devs), "device0": str(devs[0]),
    "platform": devs[0].platform, "result": int(y),
}))
'''


def probe_platform(platform: str, timeout_s: float) -> dict:
    """Bounded backend-init probe in a fresh subprocess.

    Runs zero repo code: import jax → jax.devices() → one op. A failure
    here is a platform failure, not a framework failure."""
    env = _pinned_env(platform)
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC, str(timeout_s)],
            capture_output=True, text=True, timeout=timeout_s + 30,
            cwd=REPO, env=env,
        )
        out, err, rc = p.stdout, p.stderr, p.returncode
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode() if isinstance(e.stderr, bytes) else (e.stderr or "")
        rc = -9
    elapsed = round(time.time() - t0, 1)
    for line in reversed(out.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
                d["elapsed_s"] = elapsed
                return d
            except json.JSONDecodeError:
                pass
    return {
        "ok": False, "rc": rc, "elapsed_s": elapsed,
        "timeout_s": timeout_s,
        "error": ("backend init did not complete: jax.devices() wedged "
                  "inside PJRT_Client_Create (no repo code involved)"),
        "stderr_tail": err[-2200:],
    }


# ---------------------------------------------------------------- phase 2
def run_bench(platform: str, timeout_s: float) -> dict:
    """Run the five configs in a subprocess pinned to `platform`.

    The child streams one '##bench {json}' line per config, so a wedge
    mid-run still yields partial per-config numbers."""
    import tempfile
    import threading

    env = _pinned_env(platform)
    # The child deadlines ITSELF (watchdog thread -> clean exit, see
    # inner_main) well before the parent's SIGKILL backstop: a mid-RPC
    # kill of an axon client can take the tunnel relay down with it
    # (observed 20260802).
    env.setdefault("BENCH_INNER_DEADLINE_S", str(max(30.0, timeout_s)))
    # stderr goes to a temp file (not a pipe): a verbose child must never
    # deadlock on a full pipe buffer while the parent reads stdout.
    with tempfile.TemporaryFile(mode="w+") as errf:
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "bench.py"), "--inner"],
            stdout=subprocess.PIPE, stderr=errf, text=True,
            cwd=REPO, env=env,
        )
        partial: dict = {}
        final: dict | None = None
        deadline = time.time() + timeout_s + 90.0

        def _kill_at_deadline():
            while proc.poll() is None:
                if time.time() > deadline:
                    proc.kill()
                    return
                time.sleep(1.0)

        threading.Thread(target=_kill_at_deadline, daemon=True).start()
        assert proc.stdout is not None
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("##bench "):
                try:
                    partial.update(json.loads(line[len("##bench "):]))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("##trace "):
                try:
                    partial.update(json.loads(line[len("##trace "):]))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("##shard "):
                try:
                    partial.update(json.loads(line[len("##shard "):]))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("##admission "):
                try:
                    partial.update(
                        json.loads(line[len("##admission "):]))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("##profile "):
                try:
                    partial.update(json.loads(line[len("##profile "):]))
                except json.JSONDecodeError:
                    pass
            elif line.startswith("{"):
                try:
                    final = json.loads(line)
                except json.JSONDecodeError:
                    pass
        proc.wait()
        errf.seek(0, os.SEEK_END)
        errf.seek(max(0, errf.tell() - 1500))
        err_tail = errf.read()
    if final is not None:
        final["ok"] = True
        return final
    partial.update({
        "ok": False, "rc": proc.returncode,
        "error": f"bench subprocess died/timed out after {timeout_s:.0f}s",
        "stderr_tail": err_tail,
    })
    return partial


def trace_overhead_probe(quick: bool) -> dict:
    """Tracing-cost guard: the SAME in-process replica commit loop run
    three ways — NullTracer default, recording tracers, and the full
    causal-tracing posture (recording tracers plus a traced client
    stamping trace contexts at sampling 1.0) — so the record carries
    all three wall clocks every run and a tracing-cost regression is
    visible in the devhub history like any throughput regression. The
    recording run's per-commit-stage aggregates double as the devhub
    "commit pipeline" panel's data; the causal run's assembled request
    trees feed the per-request waterfall panel and the
    `ctx_overhead_ratio` acceptance (<= 1.15x of NullTracer).

    Methodology of the guarded ratio: requests carry a 16-transfer
    batch (small against the system's real window sizes, so the
    traced-path share is still overstated, but not the degenerate
    1-transfer request); only the request loop is timed (cluster
    construction is not the traced path and its storage init wobbles
    by milliseconds run to run); null/traced samples interleave,
    min-of-3 each. The legacy `overhead_ratio` series keeps its
    whole-run single-sample shape."""
    from tigerbeetle_tpu import constants, multi_batch
    from tigerbeetle_tpu.state_machine import StateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.trace import Tracer
    from tigerbeetle_tpu.types import Account, Operation, Transfer

    n_ops = 16 if quick else 48
    batch = 16  # transfers per request
    was_verify = constants.VERIFY

    def run(tracer_factory, ops=None, client_tracer=None):
        # Oracle engine: a pure-Python commit pipeline, so the runs
        # differ ONLY by the tracer (no jit warmup to launder the
        # comparison) and the tracer's share of the wall clock is at its
        # honest maximum. Returns (whole-run seconds, request-loop
        # seconds, cluster).
        t0 = time.perf_counter()
        cluster = Cluster(seed=17, replica_count=1,
                          tracer_factory=tracer_factory,
                          state_machine_factory=lambda: StateMachine(
                              engine="oracle"))
        client = cluster.client(5, tracer=client_tracer)

        def drive(op, body):
            client.request(op, body)
            assert cluster.run(4000, until=lambda: client.idle), \
                cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        t1 = time.perf_counter()
        for k in range(n_ops if ops is None else ops):
            body = b"".join(
                Transfer(id=900 + k * batch + j, debit_account_id=1,
                         credit_account_id=2, amount=1 + k,
                         ledger=1, code=1).pack() for j in range(batch))
            drive(Operation.create_transfers,
                  multi_batch.encode([body], 128))
        t2 = time.perf_counter()
        return t2 - t0, t2 - t1, cluster

    try:
        run(None, ops=2)  # untimed warmup: imports, first-touch caches
        tracers = {}

        def mk(i):
            tracers[i] = Tracer(pid=i)
            return tracers[i]

        recording_s, _, _ = run(mk)
        # Causal posture: fresh recording tracers AND a traced client,
        # head sampling 1.0 — every request mints, stamps and records
        # its causal tree end to end (the most expensive honest case).
        null_s = None
        null_loop_s = None
        traced_s = None
        traced_loop_s = None
        ctx_tracers: dict = {}
        client_tracer = None
        for _ in range(3):
            n_run, n_loop, _ = run(None)
            null_s = n_run if null_s is None else min(null_s, n_run)
            null_loop_s = (n_loop if null_loop_s is None
                           else min(null_loop_s, n_loop))
            ctx_tracers = {}

            def mkc(i, _t=ctx_tracers):
                _t[i] = Tracer(pid=i)
                return _t[i]

            client_tracer = Tracer(pid=99)
            t_run, t_loop, _ = run(mkc, client_tracer=client_tracer)
            traced_s = t_run if traced_s is None else min(traced_s, t_run)
            traced_loop_s = (t_loop if traced_loop_s is None
                             else min(traced_loop_s, t_loop))
    finally:
        constants.set_verify(was_verify)  # Cluster turns it on globally
    stages = {k: v for k, v in tracers[0].aggregates.snapshot().items()
              if k.startswith("commit_")}
    spans = sum(s["count"] for s in stages.values())
    # Critical-path attribution over the recording run's merged trace:
    # which stage owns the slowest-decile windows (devhub "p99 critical
    # path" panel; trace/merge.py critical_path).
    from tigerbeetle_tpu.trace import (assemble_traces, critical_path,
                                       merge_traces)

    merged = merge_traces([tracers[i].chrome_dict()
                           for i in sorted(tracers)])
    cp = critical_path(merged, quantile=0.9)
    # Per-request waterfall: the causal run's assembled span trees,
    # slowest first (devhub "per-request waterfall" panel).
    asm = assemble_traces(merge_traces(
        [ctx_tracers[i].chrome_dict() for i in sorted(ctx_tracers)]
        + [client_tracer.chrome_dict()]))
    waterfall = [
        {"trace_id": t["trace_id"],
         "total_us": t["critical_path"]["total_us"],
         "stages": t["critical_path"]["stages"],
         "owner": t["critical_path"]["owner"],
         "keep_reason": t["keep_reason"]}
        for t in sorted(asm["traces"],
                        key=lambda t: -t["critical_path"]["total_us"])
        if t["kept"]][:12]
    return {
        "ops": n_ops + 1,
        "batch": batch,
        "null_s": round(null_s, 4),
        "recording_s": round(recording_s, 4),
        "overhead_ratio": round(recording_s / null_s, 4) if null_s else None,
        "traced_s": round(traced_s, 4),
        "null_loop_s": round(null_loop_s, 4),
        "traced_loop_s": round(traced_loop_s, 4),
        "ctx_overhead_ratio": (round(traced_loop_s / null_loop_s, 4)
                               if null_loop_s else None),
        "spans_recorded": spans,
        "commit_stages": stages,
        "critical_path": cp,
        "requests_assembled": {"total": asm["total"],
                               "complete": asm["complete"],
                               "orphan_spans": asm["orphan_spans"]},
        "request_waterfall": waterfall,
    }


def profile_probe_bench(quick: bool) -> dict:
    """Performance-observatory record (ISSUE 20): a small seeded
    serving workload run with the sampled dispatch profiler at
    sampling 1/1, so the ##profile line carries a NON-EMPTY
    dispatch_device_time histogram for every route the run drives
    (chain + per-batch here; the partitioned tiers ride the shard
    probe's mesh when >= 8 devices exist), the static FLOPs/HBM-bytes
    cost model per tier from the lowered HLO, the achieved-vs-roofline
    fraction per tier, and the memory watermark vs the committed
    membudget. Everything is assembled by trace.profile_probe over the
    run's tracer — the probe adds no dispatches of its own beyond the
    workload."""
    import numpy as np

    from tigerbeetle_tpu.serving import ServingSupervisor
    from tigerbeetle_tpu.trace import (AlertEngine, DispatchProfiler,
                                       MemWatch, Tracer, profile_probe)
    from tigerbeetle_tpu.types import Account, Transfer

    tracer = Tracer()
    prof = DispatchProfiler(tracer=tracer, sample_every=1)
    mw = MemWatch(tracer=tracer)
    eng = AlertEngine(tracer=tracer, tick_every=1)
    sup = ServingSupervisor(a_cap=1 << 9, t_cap=1 << 11,
                            epoch_interval=4, tracer=tracer,
                            profiler=prof, memwatch=mw,
                            alert_engine=eng)
    sup.create_accounts([Account(id=i, ledger=1, code=1)
                         for i in range(1, 9)], 10 ** 9)
    rng = np.random.default_rng(20)
    ts, tid = 2 * 10 ** 9, 1
    n_windows = 4 if quick else 8

    def mk_batch(n):
        nonlocal tid
        out = []
        for _ in range(n):
            dr, cr = (int(x) for x in
                      rng.choice(np.arange(1, 9), 2, replace=False))
            out.append(Transfer(id=tid, debit_account_id=dr,
                                credit_account_id=cr, amount=1,
                                ledger=1, code=1))
            tid += 1
        return out

    for _ in range(n_windows):
        # W=2 prepares -> the chain (whole-window scan) route.
        sup.create_transfers_window([mk_batch(64), mk_batch(64)],
                                    [ts, ts + 10 ** 6])
        ts += 10 ** 7
    for _ in range(max(2, n_windows // 2)):
        # Single small prepare -> the per-batch tier.
        sup.create_transfers_window([mk_batch(8)], [ts])
        ts += 10 ** 7
    sup.verify_epoch()  # final memwatch observation at the quiesce
    rec = profile_probe(tracer=tracer, profiler=prof)
    rec["memwatch"] = mw.stats()
    rec["alerts"] = eng.stats()
    rec["windows"] = sup.windows_total
    return rec


def shard_balance_probe(quick: bool) -> dict:
    """Partitioned-route balance diagnostics: mixed uniform commit
    windows through PartitionedRouter.step_window on whatever mesh
    exists — the FUSED chain dispatch (one shard_map+scan per window,
    the serving default) — reporting events routed per shard,
    cross-shard fraction, exchange overflow count, per-device resident
    bytes, the windows-by-route counters, and the warm per-window
    dispatch latency percentiles. The ##shard line of the run record
    (devhub "shard balance" panel).

    Round 10: the shard counters (events per shard, cross-shard
    transfers/fraction, exchange overflows) decode from the DEVICE
    telemetry block the fused dispatch harvests with its outputs — the
    router absorbs the block, no host-side recomputation — and the
    record additively gains the `telemetry` sub-dict (occupancy
    histogram and friends) the SLO engine's exchange-headroom burn
    objective evaluates per run. Schema otherwise unchanged."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from tigerbeetle_tpu.oracle import StateMachineOracle
    from tigerbeetle_tpu.ops.batch import transfers_to_arrays
    from tigerbeetle_tpu.parallel.partitioned import (
        PartitionedRouter,
        partitioned_state_bytes,
        replicated_state_bytes,
    )
    from tigerbeetle_tpu.types import Account, Transfer

    mesh = Mesh(np.array(jax.devices()), ("batch",))
    router = PartitionedRouter(mesh, a_cap=1 << 9, t_cap=1 << 11)
    oracle = StateMachineOracle()
    oracle.create_accounts([Account(id=i, ledger=1, code=1)
                            for i in range(1, 33)], 10 ** 9)
    state = router.from_oracle(oracle)
    rng = np.random.default_rng(11)
    ts, tid = 2 * 10 ** 9, 1
    n_windows = 2 if quick else 4
    lat_ms = []

    def mk_window():
        nonlocal ts, tid
        window, tss = [], []
        for _ in range(2):  # W=2 prepares per fused dispatch
            evs = []
            for _ in range(256):
                dr, cr = (int(x) for x in
                          rng.choice(np.arange(1, 33), 2,
                                     replace=False))
                evs.append(Transfer(id=tid, debit_account_id=dr,
                                    credit_account_id=cr, amount=1,
                                    ledger=1, code=1))
                tid += 1
            window.append(transfers_to_arrays(evs))
            tss.append(ts)
            ts += 10 ** 6
        return window, tss

    for wi in range(n_windows):
        window, tss = mk_window()
        t0 = time.perf_counter()
        state, results = router.step_window(state, window, tss, 1024)
        if wi > 0:  # window 0 pays the one-time compile; not latency
            lat_ms.append((time.perf_counter() - t0) * 1000.0)
        assert len(results) == len(window)
        assert router.host_fallbacks == 0, router.stats()

    # Live-migration probe (ISSUE 19): split half of shard 0's hash
    # space to shard 1 UNDER the same traffic — the record the devhub
    # elastic-shards row and the migration-duration trend read. The
    # whole five-stage protocol runs (snapshot/copy/double-write/
    # flip/retire); windows_live counts commit windows that landed
    # while the migration was in flight.
    migration = None
    if router.n_shards >= 2:
        from tigerbeetle_tpu.parallel.resharding import (
            ReshardController,
            ReshardPlan,
        )
        # Fresh state for the migration leg: the balance sweep above
        # deliberately fills the per-shard transfer tables near
        # capacity, and a split doubles the target's load — migrate on
        # a re-seeded state (same caps/mesh, so the compiled lowerings
        # are reused) with smaller windows (same 1024 pad bucket).
        orc_m = StateMachineOracle()
        orc_m.create_accounts([Account(id=i, ledger=1, code=1)
                               for i in range(1, 33)], 10 ** 9)
        state_m = router.from_oracle(orc_m)
        ctl = ReshardController(router, chunk_rows=256,
                                min_double_write_windows=2)
        mig_fallbacks0 = router.host_fallbacks

        def mk_small_window():
            nonlocal ts, tid
            window, tss = [], []
            for _ in range(2):
                evs = []
                for _ in range(64):
                    dr, cr = (int(x) for x in
                              rng.choice(np.arange(1, 33), 2,
                                         replace=False))
                    evs.append(Transfer(id=tid, debit_account_id=dr,
                                        credit_account_id=cr, amount=1,
                                        ledger=1, code=1))
                    tid += 1
                window.append(transfers_to_arrays(evs))
                tss.append(ts)
                ts += 10 ** 6
            return window, tss

        window, tss = mk_small_window()  # warm rows to migrate
        state_m, _ = router.step_window(state_m, window, tss, 1024)
        state_m = ctl.begin(state_m, ReshardPlan(
            lo=0, hi=(1 << 63) - 1, src=0, dst=1, kind="split"))
        guard = 0
        while ctl.stage != "done":
            window, tss = mk_small_window()
            state_m = ctl.on_window(state_m, window)
            state_m, _ = router.step_window(state_m, window, tss, 1024)
            guard += 1
            assert guard < 64, (ctl.stage, ctl.aborts)
        assert not ctl.aborts, ctl.aborts
        assert router.host_fallbacks == mig_fallbacks0, router.stats()
        m = ctl.migrations[-1]
        migration = {
            "kind": m["kind"], "src": m["src"], "dst": m["dst"],
            "rows_copied": m["rows_copied"],
            "double_write_windows": m["double_write_windows"],
            "duration_s": m["duration_s"],
            "windows_live": guard,
        }

    # Degenerate single-hot-account probe (Zipfian s -> inf): every
    # event touches ONE account, so no hash range smaller than the
    # whole shard isolates the load — the detector must answer
    # `unsplittable` (naming the hash) and must NOT thrash (cooldown:
    # the immediate re-propose returns None). The remedy documented in
    # ARCHITECTURE.md is AT2 lane parallelism, not placement.
    from tigerbeetle_tpu.parallel.resharding import HotRangeDetector
    det = HotRangeDetector(n_shards=router.n_shards)
    hot = [Transfer(id=10 ** 7 + i, debit_account_id=7,
                    credit_account_id=7, amount=1, ledger=1, code=1)
           for i in range(256)]
    for _ in range(2):
        det.observe_window([transfers_to_arrays(hot)])
    verdict = det.propose()
    assert verdict and verdict["verdict"] == "unsplittable", verdict
    assert det.propose() is None, "detector thrashed past cooldown"
    hot_range = {k: verdict[k] for k in
                 ("verdict", "shard", "fraction", "note")}

    s = router.stats()
    lat_ms.sort()

    def _pct(p):
        return round(lat_ms[min(len(lat_ms) - 1,
                                int(p * len(lat_ms)))], 3)
    try:
        # Route record for the ##diag/dispatch_routes panel: the probe
        # is the run's partitioned leg, so its windows-by-route counters
        # (partitioned_chain = the fused default) ride the same record
        # as the per-config chain routes.
        from tigerbeetle_tpu.benchmark import CONFIG_ROUTES
        CONFIG_ROUTES["shard_probe"] = dict(s["routes"])
    except Exception:
        pass
    return {
        "n_shards": router.n_shards,
        # Per-WINDOW wall latency of the fused dispatch (one
        # shard_map+scan per W=2 window; warm — window 0 carries the
        # one-time compile and is excluded).
        "window_latency": {
            "p50_ms": _pct(0.50), "p99_ms": _pct(0.99),
            "p100_ms": round(lat_ms[-1], 3),
            "windows_timed": len(lat_ms),
            "events_per_window": 512,
        },
        # Decoded from the harvested device telemetry block (the
        # router's absorb path), not recomputed host-side.
        "events_per_shard": s["events_owned"],
        "cross_shard_transfers": s["cross_shard_transfers"],
        "cross_shard_fraction": s["cross_shard_fraction"],
        "exchange_overflows": s["exchange_overflows"],
        "routes": s["routes"],
        # Device telemetry aggregates incl. the exchange-occupancy
        # histogram dict trace/slo.py evaluate_bench_record reads for
        # the exchange_occupancy_p99_pct objective.
        "telemetry": s["telemetry"],
        "state_bytes_per_device": partitioned_state_bytes(state),
        "state_bytes_replicated_equiv": replicated_state_bytes(
            router.a_cap * router.n_shards,
            router.t_cap * router.n_shards),
        # Elastic-shards probe: one live split migration's record
        # (None on a 1-shard mesh) + the degenerate single-hot-account
        # detector verdict — the devhub shard panel's migration row.
        "migration": migration,
        "hot_range": hot_range,
    }


def inner_main() -> None:
    """Runs inside the platform-pinned subprocess: execute configs."""
    platform = os.environ.get("JAX_PLATFORMS", "")
    if platform and platform != "axon":
        # Defense in depth: if the axon sitecustomize still ran (e.g.
        # invoked directly with BENCH_PLATFORM=cpu), out-pin its
        # jax.config.update before any backend initializes.
        import jax

        jax.config.update("jax_platforms", platform)
    if os.environ.get("BENCH_INNER_DEADLINE_S"):
        # Self-deadline via watchdog thread: the already-streamed
        # ##bench lines stand and the process exits before the parent's
        # SIGKILL backstop can fire mid-RPC (killing an axon client
        # mid-RPC coincided with losing the whole tunnel relay on
        # 20260802). A thread, not SIGALRM: a signal handler cannot
        # preempt a main thread blocked inside a PJRT C call.
        import threading

        _deadline = time.time() + float(
            os.environ["BENCH_INNER_DEADLINE_S"])

        def _inner_watchdog():
            while time.time() < _deadline:
                time.sleep(5.0)
            print("##bench " + json.dumps(
                {"inner_deadline_hit": True}), flush=True)
            os._exit(4)

        threading.Thread(target=_inner_watchdog, daemon=True).start()
    from tigerbeetle_tpu.benchmark import (
        BASELINE_TPS,
        CONFIG_DIAGNOSTICS,
        CONFIG_ROUTES,
        TARGET_TPS,
        bench_config1,
        bench_config2,
        bench_config3,
        bench_config4,
        bench_config6_serving,
        parity_config5,
    )

    quick = os.environ.get("BENCH_QUICK") == "1"
    subset = os.environ.get("BENCH_CONFIGS")
    run = {t.strip() for t in (subset or "1,2,3,4,5,6").split(",")}
    unknown = run - {"1", "2", "3", "4", "5", "6"}
    assert not unknown, f"BENCH_CONFIGS has unknown tokens: {sorted(unknown)}"
    # Full-mode counts are multiples of SUPERBATCH_MAX=32 so the scan
    # configs run whole commit windows (one compiled program shape).
    b1 = 8 if quick else 32
    b2 = 8 if quick else 128  # 128 * 8190 ~ 1M transfers
    b3 = 8 if quick else 32

    def emit(key, val):
        print(f"##bench {json.dumps({key: val})}", flush=True)

    def emit_diag(key):
        # Per-cause fallback counts (DeviceLedger.fallback_stats): every
        # config's "no host fallbacks" claim is a measured number in the
        # run record, streamed as it lands so a mid-run wedge keeps it.
        # Cumulative (the parent's partial.update replaces the whole
        # key): a wedge after config N keeps configs 1..N.
        if CONFIG_DIAGNOSTICS.get(key) is not None:
            emit("fallback_diagnostics", dict(CONFIG_DIAGNOSTICS))

    def tps(a, e):
        return None if a is None else round(a / e if e > 0 else 0.0, 1)

    acc1 = el1 = acc2 = el2 = acc3 = el3 = acc4 = el4 = parity = None
    if "1" in run:
        acc1, el1 = bench_config1(b1)
        emit("config1_2hot_tps", tps(acc1, el1))
        emit_diag("config1")
    if "2" in run:
        acc2, el2 = bench_config2(b2)
        emit("config2_10k_tps", tps(acc2, el2))
        emit_diag("config2")
    if "3" in run:
        acc3, el3 = bench_config3(b3)
        emit("config3_chains_tps", tps(acc3, el3))
        emit_diag("config3")
    if "4" in run:
        acc4, el4 = bench_config4(batches=2 if quick else 6)
        emit("config4_twophase_limits_tps", tps(acc4, el4))
        emit_diag("config4")
    if "5" in run:
        parity = parity_config5(n_batches=3 if quick else 6)
        emit("config5_oracle_parity", parity)
    acc6 = el6 = None
    serving_latency = None
    if "6" in run:
        acc6, el6, serving_latency = bench_config6_serving(
            batches=4 if quick else 24)
        emit("config6_serving_tps", tps(acc6, el6))
        emit_diag("config6")
        if serving_latency:
            emit("serving_batch_latency", serving_latency)

    # Chaos/recovery counters (retries, backoff time, replayed windows,
    # checksum epochs verified, recoveries by cause) per config — zeros
    # in a healthy run, and MEASURED zeros: the ledger always carries
    # the record (DeviceLedger.fallback_stats()["recovery"]), so a
    # bench that ever exercises the serving supervisor reports its
    # recoveries in the same record as its fallbacks.
    recovery = {cfg: d.get("recovery")
                for cfg, d in CONFIG_DIAGNOSTICS.items()
                if isinstance(d, dict) and d.get("recovery") is not None}
    if recovery:
        emit("recovery_diagnostics", recovery)

    # Host-staging record (ISSUE 16): per-config double-buffered window
    # staging accounting — total host staging work (work_ms), the part
    # the dispatch path actually waited on (stall_ms), windows staged
    # ahead vs packed inline, and the headline host_stall_fraction
    # (stall/work; 1.0 = fully synchronous staging, ~0 = the pack is
    # hidden behind in-flight device execution). The overlap gate leg
    # asserts a ceiling on the same number from a live seeded run.
    host_staging = {cfg: d.get("staging")
                    for cfg, d in CONFIG_DIAGNOSTICS.items()
                    if isinstance(d, dict) and d.get("staging") is not None}
    if host_staging:
        emit("host_staging", host_staging)

    # Op-budget summary (light tier subset, pure tracing — no device
    # execution): the per-run record of the kernels' heavy-op footprint
    # on its own ##opbudget line; devhub renders it next to the
    # fallback-diagnostics table. The full table incl. deep/sharded
    # tiers plus the gate ceilings live in perf/opbudget.py +
    # perf/opbudget_r06.json.
    # Tracing-cost record (##trace): NullTracer vs recording tracer on
    # one replica commit loop, plus the recorded per-commit-stage
    # aggregates (the devhub commit-pipeline panel renders them).
    trace_probe = None
    try:
        trace_probe = trace_overhead_probe(quick)
    except Exception as e:  # never let the probe kill a bench run
        trace_probe = {"error": str(e)[:200]}
    print("##trace " + json.dumps({"trace": trace_probe}), flush=True)

    # Shard-balance record (##shard): partitioned-route diagnostics —
    # events per shard, cross-shard fraction, exchange overflows — so a
    # skewed ownership hash or an overflow-prone exchange capacity is
    # visible in the devhub history like any throughput regression.
    shard = None
    try:
        shard = shard_balance_probe(quick)
    except Exception as e:  # never let the probe kill a bench run
        shard = {"error": str(e)[:200]}
    print("##shard " + json.dumps({"shard_balance": shard}), flush=True)

    # Admission record (##admission): the ISSUE 18 ingress plane under
    # a sessionized Zipfian overload on a virtual clock — sustained
    # admitted events/s plus per-class admitted-wait p99 while lower
    # classes shed explicitly (the overload gate leg asserts the same
    # contract live; this keeps the measured numbers in the run record
    # so a shed-behavior regression is visible in the devhub history).
    admission = None
    try:
        from tigerbeetle_tpu.benchmark import bench_admission

        admission = bench_admission(rounds=8 if quick else 24)
    except Exception as e:  # never let the probe kill a bench run
        admission = {"error": str(e)[:200]}
    print("##admission " + json.dumps({"admission": admission}),
          flush=True)

    # Performance-observatory record (##profile): sampled
    # dispatch_device_time histograms per route, the static
    # FLOPs/HBM-bytes cost model per tier, achieved-vs-roofline
    # fractions, and the memory watermark vs the committed membudget
    # (trace/profiler.py + trace/memwatch.py; ISSUE 20).
    profile = None
    try:
        profile = profile_probe_bench(quick)
    except Exception as e:  # never let the probe kill a bench run
        profile = {"error": str(e)[:200]}
    print("##profile " + json.dumps({"profile": profile}), flush=True)

    # Dispatch-route record: which kernel route each config's windows
    # took ("chain" = the scan-form whole-window dispatch, the default
    # serving route; "partitioned_chain" = the fused sharded-state
    # window route the shard probe takes) + the window depths used — a
    # silent route degradation is as visible as a throughput
    # regression. Emitted after the shard probe so its partitioned
    # route counters ride the same record.
    if CONFIG_ROUTES:
        emit("dispatch_routes", dict(CONFIG_ROUTES))

    opbudget = None
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "tb_opbudget", os.path.join(REPO, "perf", "opbudget.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        opbudget = mod.summary_line()
    except Exception as e:  # never let the census kill a bench run
        opbudget = {"error": str(e)[:200]}
    print("##opbudget " + json.dumps(opbudget), flush=True)

    value = None if acc2 is None else (acc2 / el2 if el2 > 0 else 0.0)
    out = {
        "metric": "create_transfers_validated_per_sec",
        "value": None if value is None else round(value, 1),
        "unit": "transfers/s",
        "vs_baseline": None if value is None else round(value / BASELINE_TPS, 4),
        "vs_target_10m": None if value is None else round(value / TARGET_TPS, 4),
        "config1_2hot_tps": tps(acc1, el1),
        "config2_10k_tps": tps(acc2, el2),
        "config3_chains_tps": tps(acc3, el3),
        "config4_twophase_limits_tps": tps(acc4, el4),
        "config5_oracle_parity": parity,
        "config6_serving_tps": tps(acc6, el6),
        # Mean 8190-event batch latency at config2 rate. (True per-batch
        # syncs would serialize the pipelined dispatch, so the mean is
        # reported under an honest name; REAL percentiles come from the
        # serving config below, whose commits are synchronous.)
        "batch_latency_mean_ms": (
            None if not acc2 else round(8190 / (acc2 / el2) * 1000, 3)),
        # Per-batch serving-commit latency percentiles (reference reports
        # p100 — benchmark_load.zig:587).
        "serving_batch_latency": serving_latency,
        # Per-config routing/fallback counters (per-cause): the measured
        # "zero host fallbacks" record behind every number above.
        "fallback_diagnostics": dict(CONFIG_DIAGNOSTICS),
        # Dispatch route + window depth per config (chain = the default
        # whole-window scan route).
        "dispatch_routes": dict(CONFIG_ROUTES),
        # Chaos/recovery counters next to the fallback record (zeros in
        # a healthy run — and recorded, not assumed).
        "recovery_diagnostics": recovery,
        # Double-buffered window-staging accounting per config: host
        # staging work vs the stall the dispatch path paid, and the
        # host_stall_fraction the overlap gate leg ceilings.
        "host_staging": host_staging,
        # Heavy-op census of the kernels this run dispatched (see the
        # ##opbudget line / perf/opbudget.py).
        "opbudget": opbudget,
        # Tracing-cost guard + commit-stage shares (##trace line).
        "trace": trace_probe,
        # Partitioned-route shard balance (##shard line): events per
        # shard, cross-shard fraction, exchange overflow count.
        "shard_balance": shard,
        # Admission-plane record (##admission line): per-class
        # admitted/shed counts, shed line, occupancy, sustained tps.
        "admission": admission,
        # Performance-observatory record (##profile line): per-route
        # sampled dispatch timing, static cost model, roofline
        # fractions, memory watermark.
        "profile": profile,
        "engine": "device_ledger_scan",
    }
    # Bottleneck analysis (VERDICT r1 #3): where the serving gap lives.
    # config2 is the pure on-device scan; config6 is the replica commit
    # boundary (wire decode + kernel + write-through mirror + encode) —
    # their ratio isolates the HOST share of the serving path.
    if acc2 and acc6 and el2 > 0 and el6 > 0:
        scan_tps = acc2 / el2
        serve_tps = acc6 / el6
        out["bottleneck"] = {
            "device_scan_tps": round(scan_tps, 1),
            "serving_tps": round(serve_tps, 1),
            "host_share_of_serving": round(
                max(0.0, 1.0 - serve_tps / scan_tps), 4),
            "note": ("serving cost beyond the device scan is host-side: "
                     "wire codecs + the write-through mirror apply"),
        }
    print(json.dumps(out), flush=True)


# ------------------------------------------------------- banked artifacts
def newest_banked_artifact() -> dict | None:
    """Summary of the newest committed on-chip bench artifact.

    The round's number of record must not depend on the tunnel being
    alive in the driver's minute (it wedged at end-of-round three rounds
    running): every on-chip window writes onchip/BENCH_ONCHIP_<utc>.json
    the moment it exists, and this picks the newest as the fallback
    record (reference analog: devhub keeps the nightly series,
    src/scripts/devhub.zig:174-237 — the dashboard survives one dead
    run)."""
    import glob
    import re
    from datetime import datetime, timezone

    paths = sorted(glob.glob(os.path.join(REPO, "onchip",
                                          "BENCH_ONCHIP_*.json")))
    best = None
    for p in reversed(paths):  # filenames sort by UTC stamp
        try:
            d = json.load(open(p))
        except (OSError, json.JSONDecodeError):
            continue
        r = d.get("result") or {}
        # Never accept a record that is itself a banked fallback (a
        # re-banked artifact would launder the true capture age).
        if d.get("quick") or r.get("value") is None \
                or r.get("value_source"):
            continue
        best = (p, d, r)
        break
    if best is None:
        return None
    p, d, r = best
    age_h = None
    m = re.match(r"BENCH_ONCHIP_(\d{8}T\d{6})Z", os.path.basename(p))
    if m:
        ts = datetime.strptime(m.group(1), "%Y%m%dT%H%M%S").replace(
            tzinfo=timezone.utc)
        age_h = round((datetime.now(timezone.utc) - ts).total_seconds()
                      / 3600, 2)
    summary = {
        "artifact_path": os.path.relpath(p, REPO),
        "utc": d.get("utc"),
        "age_hours": age_h,
        "value": r.get("value"),
        "unit": r.get("unit", "transfers/s"),
        "platform": r.get("platform"),
    }
    for k in ("config1_2hot_tps", "config2_10k_tps", "config3_chains_tps",
              "config4_twophase_limits_tps", "config5_oracle_parity",
              "config6_serving_tps", "serving_batch_latency",
              "vs_baseline", "vs_target_10m"):
        if r.get(k) is not None:
            summary[k] = r[k]
    return summary


# ---------------------------------------------------------------- driver
def main() -> None:
    ports = listening_loopback_ports()
    forced = os.environ.get("BENCH_PLATFORM")
    probe: dict | None = None
    if forced:
        platform = forced
    else:
        probe_budget = min(
            float(os.environ.get("BENCH_TPU_INIT_TIMEOUT_S", "420")),
            _remaining() * 0.5,
        )
        probe = probe_platform("axon", probe_budget)
        platform = "axon" if probe.get("ok") else "cpu"

    bench = run_bench(platform, _remaining())

    # Numbers measured on whatever platform actually ran; a partial run
    # (subprocess died mid-way) still salvages config2 if it landed.
    measured = bench.get("value")
    if measured is None:
        measured = bench.get("config2_10k_tps")
    on_tpu = platform == "axon" and measured is not None
    out = {
        "metric": "create_transfers_validated_per_sec",
        # Honest headline: a TPU-measured number when the chip ran (even
        # partially), else null — the CPU proxy is reported under its
        # own key and never impersonates the TPU.
        "value": measured if on_tpu else None,
        "unit": "transfers/s",
        # Prefer the inner run's ratios (single source of truth:
        # tigerbeetle_tpu.benchmark BASELINE_TPS/TARGET_TPS); compute only
        # when salvaging a partial run whose final JSON never arrived.
        "vs_baseline": (bench.get("vs_baseline")
                        or round(measured / 1_000_000, 4)
                        if on_tpu else None),
        "vs_target_10m": (bench.get("vs_target_10m")
                          or round(measured / 10_000_000, 4)
                          if on_tpu else None),
        "platform": platform,
        "bench": {k: v for k, v in bench.items()
                  if k not in ("metric", "value", "unit", "vs_baseline",
                               "vs_target_10m")},
        "loopback_listen_ports": ports,
        "elapsed_s": round(time.time() - T0, 1),
    }
    if probe is not None:
        out["tpu_probe"] = probe
    if on_tpu and not bench.get("ok", False):
        out["partial"] = True
    if platform != "axon" and measured is not None:
        out["cpu_proxy_tps"] = measured
    if probe is not None and not probe.get("ok"):
        out["error"] = (
            "TPU backend unavailable: jax.devices() wedges inside "
            "PJRT_Client_Create before any repo code runs (axon claim "
            "loop retries forever; orchestrator/relay not reachable on "
            f"loopback — listening ports: {ports}). See "
            "tpu_probe.stderr_tail for the faulthandler stack.")
    elif not bench.get("ok", False) and measured is None:
        out["error"] = bench.get("error", "bench did not complete")
    # Wedge-proof number of record: if the DRIVER-STYLE invocation (no
    # BENCH_PLATFORM forced — the probe decided) produced no on-chip
    # number, the newest committed onchip/BENCH_ONCHIP_*.json becomes the
    # value of record, clearly labeled with its age + path — three rounds
    # of null driver numbers behind a dead tunnel is enough. Forced runs
    # (tpu_watch captures, CI cpu proxies) never take the fallback: a
    # watcher re-committing a banked value would launder the record's
    # age, and a deliberate cpu run must stay a cpu record.
    banked = None
    if not on_tpu and forced is None:
        banked = newest_banked_artifact()
        if banked is not None:
            out["banked_onchip"] = banked
            out["value"] = banked["value"]
            out["vs_baseline"] = banked.get("vs_baseline")
            out["vs_target_10m"] = banked.get("vs_target_10m")
            # The record's platform must track its value (the "CPU
            # proxy never impersonates the TPU" invariant cuts both
            # ways); what ran locally is preserved under live_platform.
            out["platform"] = banked.get("platform")
            out["live_platform"] = platform
            out["value_platform"] = banked.get("platform")
            out["value_source"] = (
                "banked_onchip_artifact: live TPU run unavailable in the "
                "driver window; value is the newest committed solo "
                f"on-chip full-bench ({banked['artifact_path']}, "
                f"{banked.get('age_hours')}h old)")
    # Output contract (devhub analog: one parseable record per run): the
    # full diagnostic record goes on its own PRECEDING line; the FINAL
    # stdout line is a compact metric JSON that survives any tail window.
    print("##diag " + json.dumps(out), flush=True)
    compact = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": out["unit"],
        "vs_baseline": out["vs_baseline"],
        "vs_target_10m": out.get("vs_target_10m"),
        "platform": platform,
    }
    config_keys = ("config1_2hot_tps", "config2_10k_tps",
                   "config3_chains_tps", "config4_twophase_limits_tps",
                   "config5_oracle_parity", "config6_serving_tps",
                   "serving_batch_latency", "fallback_diagnostics",
                   "dispatch_routes", "shard_balance", "host_staging",
                   "admission", "profile")
    if banked is not None:
        # Self-consistent record: value, per-config numbers AND the
        # platform tag all come from the banked on-chip artifact (a
        # value!=null with platform=="cpu" would violate the "CPU proxy
        # never impersonates the TPU" invariant consumers rely on);
        # whatever the live proxy run measured is nested under its own
        # honest key.
        compact["value_source"] = "banked_onchip_artifact"
        compact["platform"] = banked.get("platform")
        compact["live_platform"] = platform
        compact["banked_onchip"] = banked
        for k in config_keys:
            if banked.get(k) is not None:
                compact[k] = banked[k]
        live = {k: bench[k] for k in config_keys
                if bench.get(k) is not None}
        if live:
            compact["live_%s_configs" % platform] = live
    else:
        for k in config_keys:
            if bench.get(k) is not None:
                compact[k] = bench[k]
    if out.get("cpu_proxy_tps") is not None:
        compact["cpu_proxy_tps"] = out["cpu_proxy_tps"]
    if out.get("error"):
        compact["error"] = out["error"][:180]
    print(json.dumps(compact), flush=True)


if __name__ == "__main__":
    if "--inner" in sys.argv[1:]:
        inner_main()
    else:
        main()
