"""Benchmark driver: create_transfers validated transfers/sec on TPU.

Thin driver over tigerbeetle_tpu.benchmark (the package-level harness,
reference: src/tigerbeetle/benchmark_driver.zig). Prints ONE JSON line
{"metric", "value", "unit", "vs_baseline", ...}.

Env: BENCH_PLATFORM=cpu to force CPU; BENCH_QUICK=1 for a small CI run.
"""

from __future__ import annotations

import json
import os

if os.environ.get("BENCH_PLATFORM"):
    # The axon site hook pins JAX_PLATFORMS; an explicit override must go
    # through jax.config before any backend initializes.
    import jax

    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

from tigerbeetle_tpu.benchmark import (
    BASELINE_TPS,
    TARGET_TPS,
    bench_config1,
    bench_config2,
    bench_config3,
    bench_config4,
    parity_config5,
)


def main():
    quick = os.environ.get("BENCH_QUICK") == "1"
    b1 = 4 if quick else 24
    b2 = 4 if quick else 122  # 122 * 8190 ~ 1M transfers
    b3 = 4 if quick else 24

    acc1, el1 = bench_config1(b1)
    acc2, el2 = bench_config2(b2)
    acc3, el3 = bench_config3(b3)
    acc4, el4 = bench_config4(batches=1 if quick else 2)
    parity = parity_config5(n_batches=3 if quick else 6)

    tps = lambda a, e: a / e if e > 0 else 0.0
    value = tps(acc2, el2)

    print(json.dumps({
        "metric": "create_transfers_validated_per_sec",
        "value": round(value, 1),
        "unit": "transfers/s",
        "vs_baseline": round(value / BASELINE_TPS, 4),
        "vs_target_10m": round(value / TARGET_TPS, 4),
        "config1_2hot_tps": round(tps(acc1, el1), 1),
        "config2_10k_tps": round(tps(acc2, el2), 1),
        "config3_chains_tps": round(tps(acc3, el3), 1),
        "config4_twophase_limits_tps": round(tps(acc4, el4), 1),
        "config5_oracle_parity": parity,
        "engine": "device_ledger_scan",
    }))


if __name__ == "__main__":
    main()
