"""Benchmark driver: create_transfers validated transfers/sec on TPU.

Measures the same quantity as the reference's `tigerbeetle benchmark`
"load accepted ... tx/s" (src/tigerbeetle/benchmark_load.zig:587): accepted
transfers / wall time, with result-code parity checked against the
sequential oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "transfers/s", "vs_baseline": N, ...}

Baseline: the reference's design claim of 1M TPS on a single core
(docs/ARCHITECTURE.md:179-184); the driver target is 10M/s on one v5e chip.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

if os.environ.get("BENCH_PLATFORM"):
    # The axon site hook pins JAX_PLATFORMS=axon; an explicit override needs
    # jax.config (must run before any backend initializes).
    import jax

    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

from tigerbeetle_tpu.constants import BATCH_MAX
from tigerbeetle_tpu.oracle.state_machine import StateMachineOracle
from tigerbeetle_tpu.types import Account, CreateTransferStatus, Transfer

BASELINE_TPS = 1_000_000  # reference design claim, single core
TARGET_TPS = 10_000_000  # driver target, single v5e chip


def _mk_transfers(n, id_base, rng, account_count, hot=None):
    """Zipfian-ish workload like benchmark_load.zig: ids sequential, accounts
    uniform over [1, account_count] (with optional hot subset)."""
    ids = np.arange(id_base, id_base + n, dtype=np.uint64)
    if account_count == 2:
        dr = np.full(n, 1, dtype=np.uint64)
        cr = np.full(n, 2, dtype=np.uint64)
    else:
        dr = rng.integers(1, account_count + 1, size=n, dtype=np.uint64)
        cr = rng.integers(1, account_count + 1, size=n, dtype=np.uint64)
        clash = dr == cr
        cr[clash] = dr[clash] % account_count + 1
    amount = rng.integers(1, 1000, size=n, dtype=np.uint64)
    z = np.zeros(n, dtype=np.uint64)
    return dict(
        id_hi=z.copy(), id_lo=ids,
        dr_hi=z.copy(), dr_lo=dr,
        cr_hi=z.copy(), cr_lo=cr,
        amt_hi=z.copy(), amt_lo=amount,
        pid_hi=z.copy(), pid_lo=z.copy(),
        ud128_hi=z.copy(), ud128_lo=z.copy(),
        ud64=z.copy(),
        ud32=np.zeros(n, dtype=np.uint32),
        timeout=np.zeros(n, dtype=np.uint32),
        ledger=np.ones(n, dtype=np.uint32),
        code=np.ones(n, dtype=np.uint32),
        flags=np.zeros(n, dtype=np.uint32),
        ts=z.copy(),
    )


def _setup_state(account_count):
    state = StateMachineOracle()
    accounts = [Account(id=i, ledger=1, code=1) for i in range(1, account_count + 1)]
    for lo in range(0, account_count, BATCH_MAX):
        chunk = accounts[lo:lo + BATCH_MAX]
        state.create_accounts(chunk, timestamp=lo + len(chunk))
    return state


def bench_sequential_kernel(account_count, batches, events_per_batch=BATCH_MAX):
    """prefetch -> device kernel -> apply, per batch (host state store)."""
    from tigerbeetle_tpu.ops.batch import prefetch_create_transfers
    from tigerbeetle_tpu.ops.create_kernels import (
        apply_create_transfers,
        create_transfers_kernel,
    )

    rng = np.random.default_rng(42)
    state = _setup_state(account_count)
    ts = 1_000_000_000

    def run_batch(i, timed_state):
        ev = _mk_transfers(events_per_batch, 1_000_000 + i * events_per_batch,
                           rng, account_count)
        nonlocal ts
        ts += events_per_batch + 1
        inputs, aux = prefetch_create_transfers(timed_state, ev, ts)
        out = create_transfers_kernel(inputs)
        return apply_create_transfers(timed_state, inputs, aux, out)

    # Warmup/compile.
    run_batch(-1, _setup_state(account_count))

    accepted = 0
    t0 = time.perf_counter()
    for i in range(batches):
        results = run_batch(i, state)
        accepted += sum(
            1 for r in results if r.status == CreateTransferStatus.created
        )
    elapsed = time.perf_counter() - t0
    return accepted, elapsed


def parity_check(n=512):
    """Kernel vs oracle on one mixed batch."""
    from tigerbeetle_tpu.ops.create_kernels import run_create_transfers

    rng = np.random.default_rng(7)
    kernel_state = _setup_state(10)
    oracle_state = _setup_state(10)
    transfers = [
        Transfer(
            id=int(i) + 1,
            debit_account_id=int(rng.integers(0, 12)),
            credit_account_id=int(rng.integers(0, 12)),
            amount=int(rng.integers(0, 1000)),
            ledger=int(rng.integers(1, 3)),
            code=1,
        )
        for i in range(n)
    ]
    ts = 10_000_000
    got = run_create_transfers(kernel_state, transfers, ts)
    want = oracle_state.create_transfers(transfers, ts)
    return all(
        g.status == w.status and g.timestamp == w.timestamp
        for g, w in zip(got, want)
    )


def main():
    quick = os.environ.get("BENCH_QUICK") == "1"
    events = 512 if quick else BATCH_MAX
    parity = parity_check()

    # Config 1: single-ledger, 2 hot accounts (repl/benchmark shape).
    acc1, el1 = bench_sequential_kernel(
        account_count=2, batches=2 if quick else 3, events_per_batch=events)
    # Config 2: random transfers over 10K accounts (fuzz shape), subsampled.
    acc2, el2 = bench_sequential_kernel(
        account_count=10_000, batches=2 if quick else 3, events_per_batch=events)

    tps1 = acc1 / el1
    tps2 = acc2 / el2
    value = tps2  # headline: the fuzz workload

    print(json.dumps({
        "metric": "create_transfers_validated_per_sec",
        "value": round(value, 1),
        "unit": "transfers/s",
        "vs_baseline": round(value / BASELINE_TPS, 4),
        "vs_target_10m": round(value / TARGET_TPS, 4),
        "config1_2acct_tps": round(tps1, 1),
        "config2_10kacct_tps": round(tps2, 1),
        "parity_vs_oracle": parity,
        "kernel": "sequential_fori",
    }))


if __name__ == "__main__":
    main()
