"""Benchmark driver: create_transfers validated transfers/sec on TPU.

Thin driver over tigerbeetle_tpu.benchmark (the package-level harness,
reference: src/tigerbeetle/benchmark_driver.zig). Prints ONE JSON line
{"metric", "value", "unit", "vs_baseline", ...}.

Env: BENCH_PLATFORM=cpu to force CPU; BENCH_QUICK=1 for a small CI run.
"""

from __future__ import annotations

import json
import os
import threading

# Watchdog: if the TPU tunnel wedges (backend init or a compile hangs),
# still emit ONE JSON line before the driver's budget burns out.
_done = threading.Event()


def _watchdog():
    timeout = float(os.environ.get("BENCH_WATCHDOG_S", "1500"))
    if not _done.wait(timeout):
        print(json.dumps({
            "metric": "create_transfers_validated_per_sec",
            "value": None, "unit": "transfers/s", "vs_baseline": None,
            "error": f"watchdog: no result within {timeout:.0f}s "
                     "(backend init or compile hang)",
        }), flush=True)
        os._exit(2)


threading.Thread(target=_watchdog, daemon=True).start()

if os.environ.get("BENCH_PLATFORM"):
    # The axon site hook pins JAX_PLATFORMS; an explicit override must go
    # through jax.config before any backend initializes.
    import jax

    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

from tigerbeetle_tpu.benchmark import (
    BASELINE_TPS,
    TARGET_TPS,
    bench_config1,
    bench_config2,
    bench_config3,
    bench_config4,
    parity_config5,
)


def main():
    quick = os.environ.get("BENCH_QUICK") == "1"
    # BENCH_CONFIGS="1,2,3" runs a subset (skipped configs report null).
    subset = os.environ.get("BENCH_CONFIGS")
    run = {t.strip() for t in (subset or "1,2,3,4,5").split(",")}
    unknown = run - {"1", "2", "3", "4", "5"}
    assert not unknown, f"BENCH_CONFIGS has unknown tokens: {sorted(unknown)}"
    # Batch counts are multiples of the scan chunk (B_CHUNK=8) so no timed
    # work is spent on empty pad batches.
    b1 = 8 if quick else 24
    b2 = 8 if quick else 120  # 120 * 8190 ~ 1M transfers
    b3 = 8 if quick else 24

    acc1 = el1 = acc2 = el2 = acc3 = el3 = acc4 = el4 = parity = None
    if "1" in run:
        acc1, el1 = bench_config1(b1)
    if "2" in run:
        acc2, el2 = bench_config2(b2)
    if "3" in run:
        acc3, el3 = bench_config3(b3)
    if "4" in run:
        acc4, el4 = bench_config4(batches=2 if quick else 6)
    if "5" in run:
        parity = parity_config5(n_batches=3 if quick else 6)

    def tps(a, e):
        return None if a is None else (a / e if e > 0 else 0.0)

    def r(x):
        return None if x is None else round(x, 1)

    value = tps(acc2, el2)

    out = {
        "metric": "create_transfers_validated_per_sec",
        "value": r(value),
        "unit": "transfers/s",
        "vs_baseline": None if value is None else round(value / BASELINE_TPS, 4),
        "vs_target_10m": None if value is None else round(value / TARGET_TPS, 4),
        "config1_2hot_tps": r(tps(acc1, el1)),
        "config2_10k_tps": r(tps(acc2, el2)),
        "config3_chains_tps": r(tps(acc3, el3)),
        "config4_twophase_limits_tps": r(tps(acc4, el4)),
        "config5_oracle_parity": parity,
        # Mean 8190-event batch latency at config2 rate. (The reference
        # reports p100 — benchmark_load.zig:587; a true max needs per-batch
        # syncs, which would serialize the on-device scan, so the mean is
        # reported under an honest name instead.)
        "batch_latency_mean_ms": (
            None if not acc2 else round(8190 / (acc2 / el2) * 1000, 3)),
        "engine": "device_ledger_scan",
    }
    _done.set()
    print(json.dumps(out))


if __name__ == "__main__":
    main()
