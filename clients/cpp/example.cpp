// C++ client sample: double-entry session against a live cluster.
//
// Usage: example <cluster> <addresses>        (or: example echo)
// Exit 0 iff every expectation holds — the integration test's contract
// (reference pattern: src/clients/c sample + per-language ci samples).

#include <cstdio>
#include <cstdlib>

#include "tb_client.hpp"

int main(int argc, char **argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <cluster> <addresses> | echo\n",
                 argv[0]);
    return 2;
  }
  try {
    if (std::string(argv[1]) == "echo") {
      tb::Client client(1, "", /*echo=*/true);
      std::vector<uint8_t> body = {1, 2, 3, 4, 5};
      auto reply = client.request(tb::Operation::create_transfers, body);
      if (reply != body) {
        std::fprintf(stderr, "echo mismatch\n");
        return 1;
      }
      std::printf("echo ok\n");
      return 0;
    }

    uint64_t cluster = std::strtoull(argv[1], nullptr, 10);
    tb::Client client(cluster, argv[2]);

    std::vector<tb::Account> accounts(2);
    accounts[0].id = 1;
    accounts[0].ledger = 700;
    accounts[0].code = 10;
    accounts[1].id = 2;
    accounts[1].ledger = 700;
    accounts[1].code = 10;
    auto acct_results = client.create_accounts(accounts);
    for (auto &r : acct_results) {
      // 'exists' (idempotent retry) is also acceptable on reconnects.
      if (r.status != tb::kCreated && r.status != tb::kAccountExists) {
        std::fprintf(stderr, "create_accounts status=%u\n", r.status);
        return 1;
      }
    }

    std::vector<tb::Transfer> transfers(2);
    transfers[0].id = 100;
    transfers[0].debit_account_id = 1;
    transfers[0].credit_account_id = 2;
    transfers[0].amount = 77;
    transfers[0].ledger = 700;
    transfers[0].code = 10;
    transfers[1].id = 101;  // debit account missing: transient failure
    transfers[1].debit_account_id = 999;
    transfers[1].credit_account_id = 2;
    transfers[1].amount = 1;
    transfers[1].ledger = 700;
    transfers[1].code = 10;
    auto xfer_results = client.create_transfers(transfers);
    bool first_ok = xfer_results[0].status == tb::kCreated ||
                    xfer_results[0].status == tb::kTransferExists;
    if (xfer_results.size() != 2 || !first_ok ||
        xfer_results[1].status == tb::kCreated) {
      std::fprintf(stderr, "create_transfers unexpected statuses\n");
      return 1;
    }

    auto looked = client.lookup_accounts({tb::u128(1), tb::u128(2)});
    if (looked.size() != 2 || looked[0].debits_posted.lo != 77 ||
        looked[1].credits_posted.lo != 77) {
      std::fprintf(stderr, "lookup_accounts balances wrong\n");
      return 1;
    }
    auto xfers = client.lookup_transfers({tb::u128(100)});
    if (xfers.size() != 1 || xfers[0].amount.lo != 77) {
      std::fprintf(stderr, "lookup_transfers wrong\n");
      return 1;
    }
    std::printf("cpp client ok: balance=%llu\n",
                (unsigned long long)looked[1].credits_posted.lo);
    return 0;
  } catch (const std::exception &e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
}
