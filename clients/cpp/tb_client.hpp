// C++ client for tigerbeetle_tpu over the shared C ABI.
//
// The reference ships per-language clients as thin typed wrappers over
// one C client (src/clients/c/tb_client.zig; e.g. src/clients/go,
// src/clients/node are codegen'd bindings around it). This header is
// that pattern for C++: typed 128-byte Account/Transfer structs
// (tigerbeetle_tpu/types.py wire layout), the multi-batch codec
// (vsr/multi_batch.py), and a synchronous Client over the thread-safe
// packet queue in native/tb_client.cpp.
//
// Build: compile your program together with native/tb_client.cpp, e.g.
//   g++ -O2 -std=c++17 example.cpp ../../native/tb_client.cpp -o example
#pragma once

#include <cstdint>
#include <cstring>
#include <random>
#include <stdexcept>
#include <string>
#include <vector>

// ---------------------------------------------------------------- C ABI
// (mirrors native/tb_client.cpp; kept in sync by the integration test)
extern "C" {
enum tbp_packet_status : uint8_t {
  TBP_PACKET_PENDING = 0,
  TBP_PACKET_OK = 1,
  TBP_PACKET_CLIENT_SHUTDOWN = 2,
  TBP_PACKET_INVALID = 3,
};
struct tbp_packet {
  struct tbp_packet *next;
  void *user_data;
  uint16_t operation;
  uint8_t status;
  uint8_t reserved;
  uint32_t data_size;
  const uint8_t *data;
  uint8_t *reply;
  uint32_t reply_size;
};
typedef void (*tbp_completion_t)(void *ctx, struct tbp_packet *packet);
struct tbp_client;
int tbp_client_init(tbp_client **out, uint64_t cluster,
                    const uint8_t client_id[16], const char *addresses,
                    tbp_completion_t on_completion, void *ctx);
int tbp_client_init_echo(tbp_client **out, uint64_t cluster,
                         const uint8_t client_id[16],
                         tbp_completion_t on_completion, void *ctx);
void tbp_client_submit(tbp_client *c, tbp_packet *p);
uint8_t tbp_client_wait(tbp_client *c, tbp_packet *p, uint32_t timeout_ms);
void tbp_client_packet_free(tbp_packet *p);
void tbp_client_deinit(tbp_client *c);
}

namespace tb {

// ------------------------------------------------------------ data model
// (tigerbeetle_tpu/types.py; reference: src/tigerbeetle.zig:10-148)

struct u128 {
  uint64_t lo = 0, hi = 0;  // little-endian in memory: lo first
  u128() = default;
  u128(uint64_t v) : lo(v), hi(0) {}
  bool operator==(const u128 &o) const { return lo == o.lo && hi == o.hi; }
};

#pragma pack(push, 1)
struct Account {
  u128 id;
  u128 debits_pending;
  u128 debits_posted;
  u128 credits_pending;
  u128 credits_posted;
  u128 user_data_128;
  uint64_t user_data_64 = 0;
  uint32_t user_data_32 = 0;
  uint32_t reserved = 0;
  uint32_t ledger = 0;
  uint16_t code = 0;
  uint16_t flags = 0;
  uint64_t timestamp = 0;
};
struct Transfer {
  u128 id;
  u128 debit_account_id;
  u128 credit_account_id;
  u128 amount;
  u128 pending_id;
  u128 user_data_128;
  uint64_t user_data_64 = 0;
  uint32_t user_data_32 = 0;
  uint32_t timeout = 0;
  uint32_t ledger = 0;
  uint16_t code = 0;
  uint16_t flags = 0;
  uint64_t timestamp = 0;
};
struct CreateResult {  // reference: src/tigerbeetle.zig:471-493
  uint64_t timestamp = 0;
  uint32_t status = 0;
  uint32_t reserved = 0;
};
#pragma pack(pop)
static_assert(sizeof(Account) == 128, "wire layout");
static_assert(sizeof(Transfer) == 128, "wire layout");
static_assert(sizeof(CreateResult) == 16, "wire layout");

// Status codes (tigerbeetle_tpu/types.py).
constexpr uint32_t kCreated = 0xFFFFFFFFu;
constexpr uint32_t kAccountExists = 21;   // idempotent re-create
constexpr uint32_t kTransferExists = 46;  // idempotent re-create

// Operations (tigerbeetle_tpu/types.py Operation; offsets from
// vsr_operations_reserved = 128).
enum class Operation : uint16_t {
  lookup_accounts = 128 + 12,
  lookup_transfers = 128 + 13,
  get_account_transfers = 128 + 14,
  get_account_balances = 128 + 15,
  query_accounts = 128 + 16,
  query_transfers = 128 + 17,
  create_accounts = 128 + 18,
  create_transfers = 128 + 19,
};

// -------------------------------------------------------- multi-batch
// (vsr/multi_batch.py: payload then a u16 trailer, padded to the
// element size, written backwards: [..counts..][batch_count])

inline std::vector<uint8_t> multi_batch_encode(
    const std::vector<uint8_t> &payload, size_t element_size) {
  if (element_size == 0 || payload.size() % element_size != 0)
    throw std::invalid_argument("payload not element-aligned");
  size_t raw = 2 * 2;  // one batch count + postamble
  size_t tsize = (raw + element_size - 1) / element_size * element_size;
  std::vector<uint8_t> out = payload;
  size_t base = out.size();
  out.resize(base + tsize, 0xFF);
  uint16_t count = static_cast<uint16_t>(payload.size() / element_size);
  uint16_t batches = 1;
  std::memcpy(&out[base + tsize - 2], &batches, 2);
  std::memcpy(&out[base + tsize - 4], &count, 2);
  return out;
}

inline std::vector<uint8_t> multi_batch_decode_one(
    const std::vector<uint8_t> &body, size_t element_size) {
  if (body.size() < 2) throw std::runtime_error("short multi-batch body");
  uint16_t batches;
  std::memcpy(&batches, &body[body.size() - 2], 2);
  if (batches != 1) throw std::runtime_error("expected one batch");
  size_t raw = (static_cast<size_t>(batches) + 1) * 2;
  size_t tsize = (raw + element_size - 1) / element_size * element_size;
  uint16_t count;
  std::memcpy(&count, &body[body.size() - 4], 2);
  size_t payload = static_cast<size_t>(count) * element_size;
  if (payload + tsize != body.size())
    throw std::runtime_error("trailer/count mismatch");
  return std::vector<uint8_t>(body.begin(), body.begin() + payload);
}

// ---------------------------------------------------------------- client

class Client {
 public:
  // addresses: "host:port,host:port,..." (empty + echo=true for the
  // echo harness — reference: tb_client init_echo).
  Client(uint64_t cluster, const std::string &addresses, bool echo = false,
         uint32_t timeout_ms = 60000)
      : timeout_ms_(timeout_ms) {
    uint8_t id[16];
    std::random_device rd;  // unique per process (rand() would collide)
    for (int i = 0; i < 16; i++)
      id[i] = static_cast<uint8_t>(rd() & 0xFF);
    id[0] |= 1;  // non-zero client id
    int rc = echo ? tbp_client_init_echo(&client_, cluster, id, nullptr,
                                         nullptr)
                  : tbp_client_init(&client_, cluster, id,
                                    addresses.c_str(), nullptr, nullptr);
    if (rc != 0)
      throw std::runtime_error("tbp_client_init failed rc=" +
                               std::to_string(rc));
  }
  ~Client() {
    if (client_) tbp_client_deinit(client_);
  }
  Client(const Client &) = delete;
  Client &operator=(const Client &) = delete;

  std::vector<uint8_t> request(Operation op,
                               const std::vector<uint8_t> &body) {
    // Heap-allocate the packet + body: the IO thread still owns them
    // after a timeout, so they must outlive this frame (abandoned, not
    // freed — the thread will write the completion into them later;
    // tbp_client_deinit drains the queue at teardown).
    auto *owned_body = new std::vector<uint8_t>(body);
    auto *p = new tbp_packet();
    std::memset(p, 0, sizeof(*p));
    p->operation = static_cast<uint16_t>(op);
    p->data = owned_body->data();
    p->data_size = static_cast<uint32_t>(owned_body->size());
    tbp_client_submit(client_, p);
    uint8_t status = tbp_client_wait(client_, p, timeout_ms_);
    if (status == TBP_PACKET_PENDING) {
      // Intentionally leak p + owned_body: still referenced by the IO
      // thread. A timed-out client should be torn down by the caller.
      throw std::runtime_error("request timed out");
    }
    std::vector<uint8_t> reply;
    if (status == TBP_PACKET_OK)
      reply.assign(p->reply, p->reply + p->reply_size);
    tbp_client_packet_free(p);
    delete p;
    delete owned_body;
    if (status != TBP_PACKET_OK)
      throw std::runtime_error("request failed status=" +
                               std::to_string(status));
    return reply;
  }

  std::vector<CreateResult> create_accounts(
      const std::vector<Account> &accounts) {
    return create_(Operation::create_accounts,
                   reinterpret_cast<const uint8_t *>(accounts.data()),
                   accounts.size());
  }
  std::vector<CreateResult> create_transfers(
      const std::vector<Transfer> &transfers) {
    return create_(Operation::create_transfers,
                   reinterpret_cast<const uint8_t *>(transfers.data()),
                   transfers.size());
  }
  std::vector<Account> lookup_accounts(const std::vector<u128> &ids) {
    return lookup_<Account>(Operation::lookup_accounts, ids);
  }
  std::vector<Transfer> lookup_transfers(const std::vector<u128> &ids) {
    return lookup_<Transfer>(Operation::lookup_transfers, ids);
  }

 private:
  std::vector<CreateResult> create_(Operation op, const uint8_t *data,
                                    size_t n) {
    std::vector<uint8_t> payload(data, data + n * 128);
    auto reply = request(op, multi_batch_encode(payload, 128));
    auto results_raw = multi_batch_decode_one(reply, 16);
    std::vector<CreateResult> out(results_raw.size() / 16);
    std::memcpy(out.data(), results_raw.data(), results_raw.size());
    return out;
  }
  template <typename T>
  std::vector<T> lookup_(Operation op, const std::vector<u128> &ids) {
    std::vector<uint8_t> payload(ids.size() * 16);
    std::memcpy(payload.data(), ids.data(), payload.size());
    auto reply = request(op, multi_batch_encode(payload, 16));
    auto raw = multi_batch_decode_one(reply, sizeof(T));
    std::vector<T> out(raw.size() / sizeof(T));
    std::memcpy(out.data(), raw.data(), raw.size());
    return out;
  }

  tbp_client *client_ = nullptr;
  uint32_t timeout_ms_;
};

}  // namespace tb
