# Generated package; compile-level CI runs wherever a
# ruby interpreter exists (stdlib only: Fiddle + minitest).
Gem::Specification.new do |s|
  s.name = 'tigerbeetle_tpu'
  s.version = '0.2.0'
  s.summary = 'Ruby client for the tigerbeetle_tpu cluster protocol'
  s.authors = ['tigerbeetle_tpu']
  s.files = Dir['lib/**/*.rb']
  s.license = 'Apache-2.0'
  s.required_ruby_version = '>= 3.0'
end
