{
  "targets": [
    {
      "target_name": "tb_client",
      "sources": ["addon/addon.c"],
      "libraries": ["-ltb_client", "-L<(module_root_dir)/../../native"],
      "ldflags": ["-Wl,-rpath,<(module_root_dir)/../../native"]
    }
  ]
}
