// tb_client: thread-safe C-ABI cluster client with an internal IO thread.
//
// The native client runtime every language binding shares (the reference's
// equivalent is src/clients/c/tb_client.zig + tb_client/context.zig: a
// packet queue drained by one IO thread running the VSR client). Packets
// are submitted from any thread; the IO thread frames them as `request`
// messages (256-byte checksummed header, tigerbeetle_tpu/vsr/header.py
// layout), sends to every replica (only the primary acts; the weak
// delivery contract tolerates the rest), resends on a timer, and completes
// packets when a matching `reply` arrives. One request in flight at a time
// (the reference serializes per-client requests the same way).
//
// Echo mode (reference: tb_client.zig init_echo) loops request bodies back
// without a network, for binding tests.

#include "blake2b.h"

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <pthread.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

// ------------------------------------------------------- header framing

const size_t HDR_SIZE = 256;
const uint8_t CMD_REQUEST = 5;
const uint8_t CMD_REPLY = 8;
const uint32_t SIZE_MAX_FRAME = 64u * 1024u * 1024u;

// Offsets per tigerbeetle_tpu/vsr/header.py _FMT.
const size_t OFF_CSUM = 0;
const size_t OFF_CSUM_BODY = 16;
const size_t OFF_CLIENT = 48;
const size_t OFF_CLUSTER = 80;
const size_t OFF_SIZE = 88;
const size_t OFF_REQUEST = 128;
const size_t OFF_OPERATION = 136;
const size_t OFF_COMMAND = 138;

const char HDR_KEY[] = "tigerbeetle-tpu-checksumhdr";
const char BODY_KEY[] = "tigerbeetle-tpu-checksumbody";

void wr_u64(uint8_t *p, uint64_t v) { memcpy(p, &v, 8); }
void wr_u32(uint8_t *p, uint32_t v) { memcpy(p, &v, 4); }
void wr_u16(uint8_t *p, uint16_t v) { memcpy(p, &v, 2); }
uint64_t rd_u64(const uint8_t *p) { uint64_t v; memcpy(&v, p, 8); return v; }
uint32_t rd_u32(const uint8_t *p) { uint32_t v; memcpy(&v, p, 4); return v; }

void header_seal(uint8_t *hdr, const uint8_t *body, uint32_t body_len) {
  wr_u32(hdr + OFF_SIZE, (uint32_t)(HDR_SIZE + body_len));
  tbp::checksum16(body, body_len, (const uint8_t *)BODY_KEY,
                  sizeof(BODY_KEY) - 1, hdr + OFF_CSUM_BODY);
  tbp::checksum16(hdr + 16, HDR_SIZE - 16, (const uint8_t *)HDR_KEY,
                  sizeof(HDR_KEY) - 1, hdr + OFF_CSUM);
}

bool header_valid(const uint8_t *hdr) {
  uint8_t digest[16];
  tbp::checksum16(hdr + 16, HDR_SIZE - 16, (const uint8_t *)HDR_KEY,
                  sizeof(HDR_KEY) - 1, digest);
  return memcmp(digest, hdr + OFF_CSUM, 16) == 0;
}

uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000 + (uint64_t)(ts.tv_nsec / 1000000);
}

}  // namespace

extern "C" {

// ------------------------------------------------------------ public ABI

enum tbp_packet_status : uint8_t {
  TBP_PACKET_PENDING = 0,
  TBP_PACKET_OK = 1,
  TBP_PACKET_CLIENT_SHUTDOWN = 2,
  TBP_PACKET_INVALID = 3,
};

struct tbp_packet {
  struct tbp_packet *next;  // internal queue linkage; caller must zero
  void *user_data;          // opaque, returned in completions
  uint16_t operation;
  uint8_t status;           // tbp_packet_status, written at completion
  uint8_t reserved;
  uint32_t data_size;
  const uint8_t *data;      // request body (already operation-encoded)
  uint8_t *reply;           // malloc'd by the client; caller frees
  uint32_t reply_size;
};

typedef void (*tbp_completion_t)(void *ctx, struct tbp_packet *packet);

struct tbp_client;

}  // extern "C" (struct/typedef only; functions re-enter below)

namespace {

struct Conn {
  int fd = -1;
  bool connecting = false;
  std::vector<uint8_t> rx;
  std::vector<uint8_t> tx;
  size_t tx_off = 0;
};

}  // namespace

extern "C" {

struct tbp_client {
  uint64_t cluster;
  uint8_t client_id[16];
  bool echo;
  std::vector<sockaddr_in> addrs;
  std::vector<Conn> conns;

  pthread_mutex_t mu;
  pthread_cond_t cv;
  pthread_t thread;
  bool shutdown;
  int wake_pipe[2];

  tbp_packet *queue_head;
  tbp_packet *queue_tail;
  tbp_packet *inflight;
  uint32_t request_number;
  uint64_t last_send_ms;
  std::vector<uint8_t> frame;  // current request frame (header + body)

  tbp_completion_t on_completion;
  void *completion_ctx;
};

}  // extern "C"

namespace {

void conn_reset(Conn &c) {
  if (c.fd >= 0) close(c.fd);
  c.fd = -1;
  c.connecting = false;
  c.rx.clear();
  c.tx.clear();
  c.tx_off = 0;
}

void conn_dial(Conn &c, const sockaddr_in &addr) {
  conn_reset(c);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return;
  fcntl(fd, F_SETFL, O_NONBLOCK);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  int rc = connect(fd, (const sockaddr *)&addr, sizeof(addr));
  if (rc != 0 && errno != EINPROGRESS) {
    close(fd);
    return;
  }
  c.fd = fd;
  c.connecting = (rc != 0);
}

void conn_enqueue(Conn &c, const std::vector<uint8_t> &frame) {
  if (c.fd < 0) return;
  if (c.tx.size() - c.tx_off > SIZE_MAX_FRAME) return;  // backpressure: drop
  if (c.tx_off > 0 && c.tx_off == c.tx.size()) {
    c.tx.clear();
    c.tx_off = 0;
  }
  c.tx.insert(c.tx.end(), frame.begin(), frame.end());
}

void conn_flush(Conn &c) {
  while (c.fd >= 0 && c.tx_off < c.tx.size()) {
    ssize_t n = send(c.fd, c.tx.data() + c.tx_off, c.tx.size() - c.tx_off,
                     MSG_NOSIGNAL);
    if (n > 0) {
      c.tx_off += (size_t)n;
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    conn_reset(c);  // error: weak delivery contract, reconnect on resend
    return;
  }
  if (c.tx_off == c.tx.size()) {
    c.tx.clear();
    c.tx_off = 0;
  }
}

void complete_packet(tbp_client *c, tbp_packet *p, uint8_t status,
                     const uint8_t *reply, uint32_t reply_size) {
  p->reply = nullptr;
  p->reply_size = 0;
  if (status == TBP_PACKET_OK && reply_size > 0) {
    p->reply = (uint8_t *)malloc(reply_size);
    memcpy(p->reply, reply, reply_size);
    p->reply_size = reply_size;
  }
  pthread_mutex_lock(&c->mu);
  p->status = status;  // last write: wait() reads it under mu
  pthread_cond_broadcast(&c->cv);
  pthread_mutex_unlock(&c->mu);
  if (c->on_completion) c->on_completion(c->completion_ctx, p);
}

void build_frame(tbp_client *c, tbp_packet *p) {
  c->request_number++;
  c->frame.assign(HDR_SIZE + p->data_size, 0);
  uint8_t *hdr = c->frame.data();
  memcpy(hdr + OFF_CLIENT, c->client_id, 16);
  wr_u64(hdr + OFF_CLUSTER, c->cluster);
  wr_u32(hdr + OFF_REQUEST, c->request_number);
  wr_u16(hdr + OFF_OPERATION, p->operation);
  hdr[OFF_COMMAND] = CMD_REQUEST;
  if (p->data_size) memcpy(hdr + HDR_SIZE, p->data, p->data_size);
  header_seal(hdr, hdr + HDR_SIZE, p->data_size);
}

// Returns true when the in-flight request completed.
bool conn_drain(tbp_client *c, Conn &conn) {
  for (;;) {
    uint8_t buf[256 * 1024];
    ssize_t n = recv(conn.fd, buf, sizeof(buf), 0);
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    if (n <= 0) {
      conn_reset(conn);
      return false;
    }
    conn.rx.insert(conn.rx.end(), buf, buf + n);
    while (conn.rx.size() >= HDR_SIZE) {
      const uint8_t *hdr = conn.rx.data();
      if (!header_valid(hdr)) {
        conn_reset(conn);  // corrupt stream: force reconnect
        return false;
      }
      uint32_t size = rd_u32(hdr + OFF_SIZE);
      if (size < HDR_SIZE || size > SIZE_MAX_FRAME) {
        conn_reset(conn);
        return false;
      }
      if (conn.rx.size() < size) break;
      uint8_t body_digest[16];
      tbp::checksum16(hdr + HDR_SIZE, size - HDR_SIZE,
                      (const uint8_t *)BODY_KEY, sizeof(BODY_KEY) - 1,
                      body_digest);
      bool body_ok = memcmp(body_digest, hdr + OFF_CSUM_BODY, 16) == 0;
      bool match = body_ok && hdr[OFF_COMMAND] == CMD_REPLY &&
                   rd_u64(hdr + OFF_CLUSTER) == c->cluster &&
                   memcmp(hdr + OFF_CLIENT, c->client_id, 16) == 0 &&
                   rd_u32(hdr + OFF_REQUEST) == c->request_number &&
                   c->inflight != nullptr;
      if (match) {
        tbp_packet *p = c->inflight;
        c->inflight = nullptr;
        complete_packet(c, p, TBP_PACKET_OK, hdr + HDR_SIZE,
                        size - HDR_SIZE);
        conn.rx.erase(conn.rx.begin(), conn.rx.begin() + size);
        return true;
      }
      conn.rx.erase(conn.rx.begin(), conn.rx.begin() + size);
    }
  }
  return false;
}

const uint64_t RESEND_MS = 500;

void *io_thread(void *arg) {
  tbp_client *c = (tbp_client *)arg;
  for (;;) {
    pthread_mutex_lock(&c->mu);
    bool shutdown = c->shutdown;
    if (!c->inflight && c->queue_head) {
      c->inflight = c->queue_head;
      c->queue_head = c->queue_head->next;
      if (!c->queue_head) c->queue_tail = nullptr;
      c->inflight->next = nullptr;
    }
    tbp_packet *p = c->inflight;
    pthread_mutex_unlock(&c->mu);

    if (shutdown) break;

    if (p && p->status == TBP_PACKET_PENDING && c->frame.empty()) {
      if (c->echo) {
        c->inflight = nullptr;
        complete_packet(c, p, TBP_PACKET_OK, p->data, p->data_size);
        continue;
      }
      build_frame(c, p);
      c->last_send_ms = 0;  // send immediately below
    }

    if (c->inflight && !c->frame.empty()) {
      uint64_t now = now_ms();
      if (now - c->last_send_ms >= RESEND_MS) {
        c->last_send_ms = now;
        for (size_t i = 0; i < c->conns.size(); i++) {
          if (c->conns[i].fd < 0) conn_dial(c->conns[i], c->addrs[i]);
          conn_enqueue(c->conns[i], c->frame);
        }
      }
    }

    // Poll: wake pipe + all sockets.
    std::vector<pollfd> fds;
    fds.push_back({c->wake_pipe[0], POLLIN, 0});
    for (Conn &conn : c->conns) {
      if (conn.fd < 0) continue;
      short ev = POLLIN;
      if (conn.connecting || conn.tx_off < conn.tx.size()) ev |= POLLOUT;
      fds.push_back({conn.fd, ev, 0});
    }
    poll(fds.data(), (nfds_t)fds.size(), 50);

    if (fds[0].revents & POLLIN) {
      uint8_t drain[64];
      while (read(c->wake_pipe[0], drain, sizeof(drain)) > 0) {}
    }
    size_t fi = 1;
    bool completed = false;
    for (Conn &conn : c->conns) {
      if (conn.fd < 0) continue;
      short re = fds[fi++].revents;
      if (re & (POLLERR | POLLHUP)) {
        conn_reset(conn);
        continue;
      }
      if (re & POLLOUT) {
        conn.connecting = false;
        conn_flush(conn);
      }
      if ((re & POLLIN) && !completed) completed = conn_drain(c, conn);
    }
    if (completed) c->frame.clear();
  }

  // Shutdown: fail everything still queued or in flight.
  pthread_mutex_lock(&c->mu);
  tbp_packet *p = c->inflight;
  c->inflight = nullptr;
  tbp_packet *q = c->queue_head;
  c->queue_head = c->queue_tail = nullptr;
  pthread_mutex_unlock(&c->mu);
  if (p) complete_packet(c, p, TBP_PACKET_CLIENT_SHUTDOWN, nullptr, 0);
  while (q) {
    tbp_packet *next = q->next;
    complete_packet(c, q, TBP_PACKET_CLIENT_SHUTDOWN, nullptr, 0);
    q = next;
  }
  for (Conn &conn : c->conns) conn_reset(conn);
  return nullptr;
}

// addresses: "host:port,host:port,...". Returns false on parse failure.
bool parse_addresses(const char *s, std::vector<sockaddr_in> *out) {
  std::string all(s ? s : "");
  size_t pos = 0;
  while (pos < all.size()) {
    size_t comma = all.find(',', pos);
    if (comma == std::string::npos) comma = all.size();
    std::string part = all.substr(pos, comma - pos);
    pos = comma + 1;
    size_t colon = part.rfind(':');
    if (colon == std::string::npos) return false;
    sockaddr_in addr;
    memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)atoi(part.c_str() + colon + 1));
    std::string host = part.substr(0, colon);
    if (host == "localhost") host = "127.0.0.1";
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) return false;
    out->push_back(addr);
  }
  return !out->empty();
}

tbp_client *client_new(uint64_t cluster, const uint8_t client_id[16],
                       bool echo) {
  tbp_client *c = new tbp_client();
  c->cluster = cluster;
  memcpy(c->client_id, client_id, 16);
  c->echo = echo;
  c->shutdown = false;
  c->queue_head = c->queue_tail = nullptr;
  c->inflight = nullptr;
  c->request_number = 0;
  c->last_send_ms = 0;
  c->on_completion = nullptr;
  c->completion_ctx = nullptr;
  pthread_mutex_init(&c->mu, nullptr);
  // Monotonic condvar clock: wall-clock steps must not skew wait deadlines.
  pthread_condattr_t attr;
  pthread_condattr_init(&attr);
  pthread_condattr_setclock(&attr, CLOCK_MONOTONIC);
  pthread_cond_init(&c->cv, &attr);
  pthread_condattr_destroy(&attr);
  if (pipe(c->wake_pipe) != 0) {
    delete c;
    return nullptr;
  }
  fcntl(c->wake_pipe[0], F_SETFL, O_NONBLOCK);
  fcntl(c->wake_pipe[1], F_SETFL, O_NONBLOCK);
  return c;
}

bool client_start(tbp_client *c, tbp_completion_t on_completion, void *ctx) {
  c->on_completion = on_completion;
  c->completion_ctx = ctx;
  c->conns.resize(c->addrs.size());
  return pthread_create(&c->thread, nullptr, io_thread, c) == 0;
}

void client_free(tbp_client *c) {
  close(c->wake_pipe[0]);
  close(c->wake_pipe[1]);
  pthread_mutex_destroy(&c->mu);
  pthread_cond_destroy(&c->cv);
  delete c;
}

}  // namespace

extern "C" {

int tbp_client_init(tbp_client **out, uint64_t cluster,
                    const uint8_t client_id[16], const char *addresses,
                    tbp_completion_t on_completion, void *ctx) {
  tbp_client *c = client_new(cluster, client_id, false);
  if (!c) return -1;
  if (!parse_addresses(addresses, &c->addrs)) {
    client_free(c);
    return -2;
  }
  if (!client_start(c, on_completion, ctx)) {
    client_free(c);
    return -3;
  }
  *out = c;
  return 0;
}

// Echo client: completes every packet with its own request body, no
// network (reference: tb_client init_echo — binding test harness).
int tbp_client_init_echo(tbp_client **out, uint64_t cluster,
                         const uint8_t client_id[16],
                         tbp_completion_t on_completion, void *ctx) {
  tbp_client *c = client_new(cluster, client_id, true);
  if (!c) return -1;
  if (!client_start(c, on_completion, ctx)) {
    client_free(c);
    return -3;
  }
  *out = c;
  return 0;
}

void tbp_client_submit(tbp_client *c, tbp_packet *p) {
  p->next = nullptr;
  p->status = TBP_PACKET_PENDING;
  p->reply = nullptr;
  p->reply_size = 0;
  pthread_mutex_lock(&c->mu);
  if (c->queue_tail) {
    c->queue_tail->next = p;
  } else {
    c->queue_head = p;
  }
  c->queue_tail = p;
  pthread_mutex_unlock(&c->mu);
  uint8_t one = 1;
  ssize_t n = write(c->wake_pipe[1], &one, 1);
  (void)n;
}

// Blocks until the packet completes; returns its status, or
// TBP_PACKET_PENDING (0) on timeout.
uint8_t tbp_client_wait(tbp_client *c, tbp_packet *p, uint32_t timeout_ms) {
  struct timespec deadline;
  clock_gettime(CLOCK_MONOTONIC, &deadline);
  deadline.tv_sec += timeout_ms / 1000;
  deadline.tv_nsec += (long)(timeout_ms % 1000) * 1000000L;
  if (deadline.tv_nsec >= 1000000000L) {
    deadline.tv_sec += 1;
    deadline.tv_nsec -= 1000000000L;
  }
  pthread_mutex_lock(&c->mu);
  while (p->status == TBP_PACKET_PENDING) {
    if (pthread_cond_timedwait(&c->cv, &c->mu, &deadline) == ETIMEDOUT) break;
  }
  uint8_t status = p->status;
  pthread_mutex_unlock(&c->mu);
  return status;
}

void tbp_client_packet_free(tbp_packet *p) {
  if (p->reply) {
    free(p->reply);
    p->reply = nullptr;
    p->reply_size = 0;
  }
}

void tbp_client_deinit(tbp_client *c) {
  pthread_mutex_lock(&c->mu);
  c->shutdown = true;
  pthread_mutex_unlock(&c->mu);
  uint8_t one = 1;
  ssize_t n = write(c->wake_pipe[1], &one, 1);
  (void)n;
  pthread_join(c->thread, nullptr);
  client_free(c);
}

}  // extern "C"
