// Keyed BLAKE2b (RFC 7693), shared by the native runtime components.
//
// Digests are bit-identical to Python's hashlib.blake2b(data,
// digest_size=16, key=...) — the wire/disk checksum contract is shared
// across the Python and C++ runtimes (tigerbeetle_tpu/vsr/checksum.py).
// Header-only so storage_engine.cpp and tb_client.cpp stay single-file
// g++ builds with no link-time coupling.

#pragma once

#include <cstdint>
#include <cstring>

namespace tbp {

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
    0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t b2b_rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

struct B2BState {
  uint64_t h[8];
  uint64_t t[2];
  uint8_t buf[128];
  size_t buflen;
  size_t outlen;
};

static inline void b2b_compress(B2BState *S, const uint8_t *block, int last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; i++) v[i] = S->h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= S->t[0];
  v[13] ^= S->t[1];
  if (last) v[14] = ~v[14];
  for (int i = 0; i < 16; i++) memcpy(&m[i], block + 8 * i, 8);

#define TBP_B2B_G(a, b, c, d, x, y)                                           \
  v[a] = v[a] + v[b] + (x);                                                   \
  v[d] = b2b_rotr64(v[d] ^ v[a], 32);                                         \
  v[c] = v[c] + v[d];                                                         \
  v[b] = b2b_rotr64(v[b] ^ v[c], 24);                                         \
  v[a] = v[a] + v[b] + (y);                                                   \
  v[d] = b2b_rotr64(v[d] ^ v[a], 16);                                         \
  v[c] = v[c] + v[d];                                                         \
  v[b] = b2b_rotr64(v[b] ^ v[c], 63);

  for (int r = 0; r < 12; r++) {
    const uint8_t *s = B2B_SIGMA[r];
    TBP_B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    TBP_B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    TBP_B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    TBP_B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    TBP_B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    TBP_B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    TBP_B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    TBP_B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef TBP_B2B_G
  for (int i = 0; i < 8; i++) S->h[i] ^= v[i] ^ v[i + 8];
}

static inline void b2b_init(B2BState *S, size_t outlen, const uint8_t *key,
                            size_t keylen) {
  memset(S, 0, sizeof(*S));
  S->outlen = outlen;
  for (int i = 0; i < 8; i++) S->h[i] = B2B_IV[i];
  // Parameter block word 0: digest_length | key_length<<8 | fanout<<16
  // | depth<<24 (sequential mode: fanout=1, depth=1).
  S->h[0] ^= (uint64_t)outlen | ((uint64_t)keylen << 8) | (1ULL << 16) |
             (1ULL << 24);
  if (keylen > 0) {
    // Keyed mode: the zero-padded key is the first 128-byte block.
    memcpy(S->buf, key, keylen);
    S->buflen = 128;
  }
}

static inline void b2b_update(B2BState *S, const uint8_t *in, size_t inlen) {
  while (inlen > 0) {
    if (S->buflen == 128) {
      // Buffer full and more input follows: not the final block.
      S->t[0] += 128;
      if (S->t[0] < 128) S->t[1]++;
      b2b_compress(S, S->buf, 0);
      S->buflen = 0;
    }
    size_t take = 128 - S->buflen;
    if (take > inlen) take = inlen;
    memcpy(S->buf + S->buflen, in, take);
    S->buflen += take;
    in += take;
    inlen -= take;
  }
}

static inline void b2b_final(B2BState *S, uint8_t *out) {
  S->t[0] += S->buflen;
  if (S->t[0] < S->buflen) S->t[1]++;
  memset(S->buf + S->buflen, 0, 128 - S->buflen);
  b2b_compress(S, S->buf, 1);
  for (size_t i = 0; i < S->outlen; i++)
    out[i] = (uint8_t)(S->h[i >> 3] >> (8 * (i & 7)));
}

static inline void checksum16(const uint8_t *data, size_t len,
                              const uint8_t *key, size_t key_len,
                              uint8_t *out16) {
  B2BState S;
  b2b_init(&S, 16, key, key_len);
  b2b_update(&S, data, len);
  b2b_final(&S, out16);
}

}  // namespace tbp
