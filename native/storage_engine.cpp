// Native storage engine: zoned data-file IO, checksums, WAL recovery scan.
//
// The native runtime component of tigerbeetle_tpu (the reference's
// equivalent layer is src/storage.zig + src/vsr/journal.zig recovery over
// io_uring). Exposed as a C ABI consumed via ctypes
// (tigerbeetle_tpu/native.py). Single-threaded, synchronous pread/pwrite —
// the replica event loop is single-threaded by design.
//
// BLAKE2b implemented from RFC 7693 (keyed mode), producing digests
// identical to Python's hashlib.blake2b(data, digest_size=16, key=...):
// the wire/disk checksum contract is shared across both runtimes.

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ------------------------------------------------------------- BLAKE2b

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
    0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3}};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

struct B2BState {
  uint64_t h[8];
  uint64_t t[2];
  uint8_t buf[128];
  size_t buflen;
  size_t outlen;
};

static void b2b_compress(B2BState *S, const uint8_t *block, int last) {
  uint64_t v[16], m[16];
  for (int i = 0; i < 8; i++) v[i] = S->h[i];
  for (int i = 0; i < 8; i++) v[i + 8] = B2B_IV[i];
  v[12] ^= S->t[0];
  v[13] ^= S->t[1];
  if (last) v[14] = ~v[14];
  for (int i = 0; i < 16; i++) memcpy(&m[i], block + 8 * i, 8);

#define B2B_G(a, b, c, d, x, y)                                               \
  v[a] = v[a] + v[b] + (x);                                                   \
  v[d] = rotr64(v[d] ^ v[a], 32);                                             \
  v[c] = v[c] + v[d];                                                         \
  v[b] = rotr64(v[b] ^ v[c], 24);                                             \
  v[a] = v[a] + v[b] + (y);                                                   \
  v[d] = rotr64(v[d] ^ v[a], 16);                                             \
  v[c] = v[c] + v[d];                                                         \
  v[b] = rotr64(v[b] ^ v[c], 63);

  for (int r = 0; r < 12; r++) {
    const uint8_t *s = B2B_SIGMA[r];
    B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef B2B_G
  for (int i = 0; i < 8; i++) S->h[i] ^= v[i] ^ v[i + 8];
}

static void b2b_init(B2BState *S, size_t outlen, const uint8_t *key,
                     size_t keylen) {
  memset(S, 0, sizeof(*S));
  S->outlen = outlen;
  for (int i = 0; i < 8; i++) S->h[i] = B2B_IV[i];
  // Parameter block word 0: digest_length | key_length<<8 | fanout<<16
  // | depth<<24 (sequential mode: fanout=1, depth=1).
  S->h[0] ^= (uint64_t)outlen | ((uint64_t)keylen << 8) | (1ULL << 16) |
             (1ULL << 24);
  if (keylen > 0) {
    // Keyed mode: the zero-padded key is the first 128-byte block.
    memcpy(S->buf, key, keylen);
    S->buflen = 128;
  }
}

static void b2b_update(B2BState *S, const uint8_t *in, size_t inlen) {
  while (inlen > 0) {
    if (S->buflen == 128) {
      // Buffer full and more input follows: not the final block.
      S->t[0] += 128;
      if (S->t[0] < 128) S->t[1]++;
      b2b_compress(S, S->buf, 0);
      S->buflen = 0;
    }
    size_t take = 128 - S->buflen;
    if (take > inlen) take = inlen;
    memcpy(S->buf + S->buflen, in, take);
    S->buflen += take;
    in += take;
    inlen -= take;
  }
}

static void b2b_final(B2BState *S, uint8_t *out) {
  S->t[0] += S->buflen;
  if (S->t[0] < S->buflen) S->t[1]++;
  memset(S->buf + S->buflen, 0, 128 - S->buflen);
  b2b_compress(S, S->buf, 1);
  for (size_t i = 0; i < S->outlen; i++)
    out[i] = (uint8_t)(S->h[i >> 3] >> (8 * (i & 7)));
}

void tbs_checksum(const uint8_t *data, uint64_t len, const uint8_t *key,
                  uint64_t key_len, uint8_t *out16) {
  B2BState S;
  b2b_init(&S, 16, key, (size_t)key_len);
  b2b_update(&S, data, (size_t)len);
  b2b_final(&S, out16);
}

// --------------------------------------------------------------- file io

int tbs_open(const char *path, uint64_t size, int create) {
  int flags = O_RDWR | (create ? O_CREAT : 0);
  int fd = open(path, flags, 0644);
  if (fd < 0) return -1;
  if (create && ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int tbs_close(int fd) { return close(fd); }

int64_t tbs_read(int fd, uint64_t off, uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, buf + done, len - done, (off_t)(off + done));
    if (n < 0) return -1;
    if (n == 0) {
      memset(buf + done, 0, len - done);
      return (int64_t)len;
    }
    done += (uint64_t)n;
  }
  return (int64_t)done;
}

int64_t tbs_write(int fd, uint64_t off, const uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(fd, buf + done, len - done, (off_t)(off + done));
    if (n < 0) return -1;
    done += (uint64_t)n;
  }
  return (int64_t)done;
}

int tbs_sync(int fd) { return fsync(fd); }

// ------------------------------------------------------------- WAL scan

// Header layout offsets (tigerbeetle_tpu/vsr/header.py).
static const uint64_t HDR_SIZE = 256;
static const uint64_t OFF_CSUM_BODY = 16;
static const uint64_t OFF_SIZE = 88;
static const uint64_t OFF_OP = 104;
static const uint64_t OFF_COMMAND = 138;
static const uint8_t CMD_PREPARE = 6;

static uint64_t rd_u64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static uint32_t rd_u32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static int header_valid(const uint8_t *hdr, const uint8_t *hdr_key,
                        uint64_t hdr_key_len) {
  uint8_t digest[16];
  tbs_checksum(hdr + 16, HDR_SIZE - 16, hdr_key, hdr_key_len, digest);
  return memcmp(digest, hdr, 16) == 0 && hdr[OFF_COMMAND] == CMD_PREPARE;
}

// Scan the WAL rings and classify every slot.
// states_out[i]: 0 = clean, 1 = faulty (header known), 2 = unknown.
// headers_out: slot_count * 256 bytes (the adopted header for clean/faulty).
// scratch must hold prepare_size_max bytes.
int tbs_wal_scan(int fd, uint64_t hdr_zone_off, uint64_t prep_zone_off,
                 uint32_t slot_count, uint64_t prepare_size_max,
                 const uint8_t *hdr_key, uint64_t hdr_key_len,
                 const uint8_t *body_key, uint64_t body_key_len,
                 uint8_t *headers_out, uint8_t *states_out,
                 uint8_t *scratch) {
  for (uint32_t slot = 0; slot < slot_count; slot++) {
    uint8_t ring_hdr[256];
    if (tbs_read(fd, hdr_zone_off + (uint64_t)slot * HDR_SIZE, ring_hdr,
                 HDR_SIZE) < 0)
      return -1;
    int ring_ok = header_valid(ring_hdr, hdr_key, hdr_key_len);

    uint64_t prep_off = prep_zone_off + (uint64_t)slot * prepare_size_max;
    if (tbs_read(fd, prep_off, scratch, HDR_SIZE) < 0) return -1;
    int prep_hdr_ok = header_valid(scratch, hdr_key, hdr_key_len);
    int prep_ok = 0;
    if (prep_hdr_ok) {
      uint32_t size = rd_u32(scratch + OFF_SIZE);
      // Protocol bound: header + body <= message_size_max == slot stride
      // (mirrors vsr/journal.py append/recover).
      if (size >= HDR_SIZE && size <= prepare_size_max) {
        if (tbs_read(fd, prep_off + HDR_SIZE, scratch + HDR_SIZE,
                     size - HDR_SIZE) < 0)
          return -1;
        uint8_t digest[16];
        tbs_checksum(scratch + HDR_SIZE, size - HDR_SIZE, body_key,
                     body_key_len, digest);
        prep_ok = memcmp(digest, scratch + OFF_CSUM_BODY, 16) == 0;
      }
    }

    uint8_t *out_hdr = headers_out + (uint64_t)slot * HDR_SIZE;
    if (ring_ok && prep_ok && memcmp(scratch, ring_hdr, 16) == 0) {
      states_out[slot] = 0;
      memcpy(out_hdr, ring_hdr, HDR_SIZE);
    } else if (prep_ok && ring_ok &&
               rd_u64(scratch + OFF_OP) > rd_u64(ring_hdr + OFF_OP)) {
      states_out[slot] = 0;
      memcpy(out_hdr, scratch, HDR_SIZE);
    } else if (prep_ok && !ring_ok) {
      states_out[slot] = 0;
      memcpy(out_hdr, scratch, HDR_SIZE);
    } else if (ring_ok) {
      states_out[slot] = 1;
      memcpy(out_hdr, ring_hdr, HDR_SIZE);
    } else {
      states_out[slot] = 2;
      memset(out_hdr, 0, HDR_SIZE);
    }
  }
  return 0;
}

// Append one prepare: body first, then the redundant header (write
// ordering is the torn-write defense; see vsr/journal.py).
int tbs_wal_append(int fd, uint64_t hdr_zone_off, uint64_t prep_zone_off,
                   uint32_t slot, uint64_t prepare_size_max,
                   const uint8_t *msg, uint64_t msg_len) {
  if (msg_len < HDR_SIZE || msg_len > prepare_size_max) return -1;
  if (tbs_write(fd, prep_zone_off + (uint64_t)slot * prepare_size_max, msg,
                msg_len) < 0)
    return -1;
  if (tbs_write(fd, hdr_zone_off + (uint64_t)slot * HDR_SIZE, msg,
                HDR_SIZE) < 0)
    return -1;
  return 0;
}

}  // extern "C"
