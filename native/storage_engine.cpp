// Native storage engine: zoned data-file IO, checksums, WAL recovery scan.
//
// The native runtime component of tigerbeetle_tpu (the reference's
// equivalent layer is src/storage.zig + src/vsr/journal.zig recovery over
// io_uring). Exposed as a C ABI consumed via ctypes
// (tigerbeetle_tpu/native.py). Single-threaded, synchronous pread/pwrite —
// the replica event loop is single-threaded by design.
//
// BLAKE2b implemented from RFC 7693 (keyed mode), producing digests
// identical to Python's hashlib.blake2b(data, digest_size=16, key=...):
// the wire/disk checksum contract is shared across both runtimes.

#include "blake2b.h"

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ------------------------------------------------------------- BLAKE2b
// Implementation shared with tb_client.cpp via blake2b.h.

void tbs_checksum(const uint8_t *data, uint64_t len, const uint8_t *key,
                  uint64_t key_len, uint8_t *out16) {
  tbp::checksum16(data, (size_t)len, key, (size_t)key_len, out16);
}

// --------------------------------------------------------------- file io

int tbs_open(const char *path, uint64_t size, int create) {
  int flags = O_RDWR | (create ? O_CREAT : 0);
  int fd = open(path, flags, 0644);
  if (fd < 0) return -1;
  if (create && ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int tbs_close(int fd) { return close(fd); }

int64_t tbs_read(int fd, uint64_t off, uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, buf + done, len - done, (off_t)(off + done));
    if (n < 0) return -1;
    if (n == 0) {
      memset(buf + done, 0, len - done);
      return (int64_t)len;
    }
    done += (uint64_t)n;
  }
  return (int64_t)done;
}

int64_t tbs_write(int fd, uint64_t off, const uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(fd, buf + done, len - done, (off_t)(off + done));
    if (n < 0) return -1;
    done += (uint64_t)n;
  }
  return (int64_t)done;
}

int tbs_sync(int fd) { return fsync(fd); }

// ------------------------------------------------------------- WAL scan

// Header layout offsets (tigerbeetle_tpu/vsr/header.py).
static const uint64_t HDR_SIZE = 256;
static const uint64_t OFF_CSUM_BODY = 16;
static const uint64_t OFF_SIZE = 88;
static const uint64_t OFF_OP = 104;
static const uint64_t OFF_COMMAND = 138;
static const uint8_t CMD_PREPARE = 6;
static const uint8_t CMD_RESERVED = 0;

static uint64_t rd_u64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static uint32_t rd_u32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static int header_valid(const uint8_t *hdr, const uint8_t *hdr_key,
                        uint64_t hdr_key_len) {
  uint8_t digest[16];
  tbs_checksum(hdr + 16, HDR_SIZE - 16, hdr_key, hdr_key_len, digest);
  // Accept prepare AND reserved commands: replica format writes valid
  // RESERVED headers into every slot so recovery can tell formatted-empty
  // (nack-eligible) from torn (must abstain); see vsr/journal.py.
  return memcmp(digest, hdr, 16) == 0 &&
         (hdr[OFF_COMMAND] == CMD_PREPARE ||
          hdr[OFF_COMMAND] == CMD_RESERVED);
}

// Scan the WAL rings and classify every slot.
// states_out[i]: 0 = clean, 1 = faulty (header known), 2 = unknown.
// headers_out: slot_count * 256 bytes (the adopted header for clean/faulty).
// scratch must hold prepare_size_max bytes.
int tbs_wal_scan(int fd, uint64_t hdr_zone_off, uint64_t prep_zone_off,
                 uint32_t slot_count, uint64_t prepare_size_max,
                 const uint8_t *hdr_key, uint64_t hdr_key_len,
                 const uint8_t *body_key, uint64_t body_key_len,
                 uint8_t *headers_out, uint8_t *states_out,
                 uint8_t *scratch) {
  for (uint32_t slot = 0; slot < slot_count; slot++) {
    uint8_t ring_hdr[256];
    if (tbs_read(fd, hdr_zone_off + (uint64_t)slot * HDR_SIZE, ring_hdr,
                 HDR_SIZE) < 0)
      return -1;
    int ring_ok = header_valid(ring_hdr, hdr_key, hdr_key_len);

    uint64_t prep_off = prep_zone_off + (uint64_t)slot * prepare_size_max;
    if (tbs_read(fd, prep_off, scratch, HDR_SIZE) < 0) return -1;
    int prep_hdr_ok = header_valid(scratch, hdr_key, hdr_key_len) &&
                      scratch[OFF_COMMAND] == CMD_PREPARE;
    int prep_ok = 0;
    if (prep_hdr_ok) {
      uint32_t size = rd_u32(scratch + OFF_SIZE);
      // Protocol bound: header + body <= message_size_max == slot stride
      // (mirrors vsr/journal.py append/recover).
      if (size >= HDR_SIZE && size <= prepare_size_max) {
        if (tbs_read(fd, prep_off + HDR_SIZE, scratch + HDR_SIZE,
                     size - HDR_SIZE) < 0)
          return -1;
        uint8_t digest[16];
        tbs_checksum(scratch + HDR_SIZE, size - HDR_SIZE, body_key,
                     body_key_len, digest);
        prep_ok = memcmp(digest, scratch + OFF_CSUM_BODY, 16) == 0;
      }
    }

    int ring_prepare = ring_ok && ring_hdr[OFF_COMMAND] == CMD_PREPARE;
    int ring_reserved = ring_ok && ring_hdr[OFF_COMMAND] == CMD_RESERVED;
    uint8_t *out_hdr = headers_out + (uint64_t)slot * HDR_SIZE;
    if (ring_prepare && prep_ok && memcmp(scratch, ring_hdr, 16) == 0) {
      states_out[slot] = 0;
      memcpy(out_hdr, ring_hdr, HDR_SIZE);
    } else if (prep_ok && ring_prepare &&
               rd_u64(scratch + OFF_OP) > rd_u64(ring_hdr + OFF_OP)) {
      states_out[slot] = 0;
      memcpy(out_hdr, scratch, HDR_SIZE);
    } else if (prep_ok && !ring_prepare) {
      // Ring header torn, absent, or still the formatted reserved one
      // (crash between prepare-body and header write): the prepare wins.
      states_out[slot] = 0;
      memcpy(out_hdr, scratch, HDR_SIZE);
    } else if (ring_prepare) {
      states_out[slot] = 1;
      memcpy(out_hdr, ring_hdr, HDR_SIZE);
    } else if (ring_reserved) {
      // Formatted-empty: provably never prepared -> clean, nack-eligible.
      states_out[slot] = 3;
      memset(out_hdr, 0, HDR_SIZE);
    } else {
      states_out[slot] = 2;
      memset(out_hdr, 0, HDR_SIZE);
    }
  }
  return 0;
}

// Append one prepare: body first, then the redundant header (write
// ordering is the torn-write defense; see vsr/journal.py).
int tbs_wal_append(int fd, uint64_t hdr_zone_off, uint64_t prep_zone_off,
                   uint32_t slot, uint64_t prepare_size_max,
                   const uint8_t *msg, uint64_t msg_len) {
  if (msg_len < HDR_SIZE || msg_len > prepare_size_max) return -1;
  if (tbs_write(fd, prep_zone_off + (uint64_t)slot * prepare_size_max, msg,
                msg_len) < 0)
    return -1;
  if (tbs_write(fd, hdr_zone_off + (uint64_t)slot * HDR_SIZE, msg,
                HDR_SIZE) < 0)
    return -1;
  return 0;
}

}  // extern "C"

// ===================================================== async IO engine
//
// The submission/completion engine under the event loop (reference:
// src/io/linux.zig io_uring submission — same contract, thread-pool
// backed here: submit read/write, poll completions, drain as the
// checkpoint barrier). Lock-based MPSC queues; worker threads execute
// pread/pwrite against the data file.

#include <pthread.h>

#include <deque>
#include <map>
#include <vector>

extern "C" {

struct tbio_op {
  uint64_t id;
  int is_write;
  int tracked;  // write whose completion the caller reaps via tbio_fetch
  uint64_t off;
  std::vector<uint8_t> buf;  // write payload, or read destination
  // Optional second ordered write (the WAL prepare->header pair: the
  // redundant header must hit the disk strictly AFTER its prepare body,
  // or torn-write recovery misclassifies the slot).
  uint64_t off2;
  std::vector<uint8_t> buf2;
  int64_t result;
};

struct tbio {
  int fd;
  pthread_mutex_t mu;
  pthread_cond_t cv_submit;   // workers wait for submissions
  pthread_cond_t cv_complete; // drain/fetch wait for completions
  std::deque<tbio_op *> submitted;
  std::map<uint64_t, tbio_op *> completed;  // READ completions only
  std::map<uint64_t, int> live;             // read ids not yet fetched
  uint64_t next_id;
  uint64_t inflight;
  bool failed;  // STICKY: any write ever failed (checked by every drain)
  bool shutdown;
  std::vector<pthread_t> workers;
};

}  // extern "C"

namespace {

void *tbio_worker(void *arg) {
  tbio *e = static_cast<tbio *>(arg);
  pthread_mutex_lock(&e->mu);
  for (;;) {
    while (e->submitted.empty() && !e->shutdown)
      pthread_cond_wait(&e->cv_submit, &e->mu);
    if (e->shutdown && e->submitted.empty()) break;
    tbio_op *op = e->submitted.front();
    e->submitted.pop_front();
    pthread_mutex_unlock(&e->mu);
    if (op->is_write) {
      op->result = tbs_write(e->fd, op->off, op->buf.data(), op->buf.size());
      if (op->result >= 0 && !op->buf2.empty()) {
        int64_t r2 =
            tbs_write(e->fd, op->off2, op->buf2.data(), op->buf2.size());
        op->result = r2 < 0 ? r2 : op->result + r2;
      }
    } else {
      op->result = tbs_read(e->fd, op->off, op->buf.data(), op->buf.size());
    }
    pthread_mutex_lock(&e->mu);
    if (op->is_write && !op->tracked) {
      // Untracked writes auto-reap at completion: the payload is freed
      // immediately (no RAM held across a checkpoint interval) and a
      // failure latches the STICKY flag so every later drain/sync reports
      // it — a lost LSM block write can never be silently consumed.
      if (op->result < 0) e->failed = true;
      delete op;
    } else {
      if (op->is_write && op->result < 0) e->failed = true;
      if (op->is_write) {
        // Payloads are dead weight once written; completions only carry
        // the result code. swap() actually releases the heap allocation
        // (clear() keeps capacity — up to message_size_max per op).
        std::vector<uint8_t>().swap(op->buf);
        std::vector<uint8_t>().swap(op->buf2);
      }
      e->completed[op->id] = op;
    }
    e->inflight--;
    pthread_cond_broadcast(&e->cv_complete);
  }
  pthread_mutex_unlock(&e->mu);
  return nullptr;
}

}  // namespace

extern "C" {

tbio *tbio_create(int fd, int workers) {
  if (workers < 1 || workers > 64) return nullptr;
  tbio *e = new tbio();
  e->fd = fd;
  e->next_id = 1;
  e->inflight = 0;
  e->failed = false;
  e->shutdown = false;
  pthread_mutex_init(&e->mu, nullptr);
  pthread_cond_init(&e->cv_submit, nullptr);
  pthread_cond_init(&e->cv_complete, nullptr);
  for (int i = 0; i < workers; i++) {
    pthread_t t;
    if (pthread_create(&t, nullptr, tbio_worker, e) != 0) {
      e->shutdown = true;
      pthread_cond_broadcast(&e->cv_submit);
      for (pthread_t w : e->workers) pthread_join(w, nullptr);
      delete e;
      return nullptr;
    }
    e->workers.push_back(t);
  }
  return e;
}

long tbio_submit_write(tbio *e, uint64_t off, const uint8_t *data,
                       uint64_t len) {
  tbio_op *op = new tbio_op();
  op->is_write = 1;
  op->tracked = 0;
  op->off = off;
  op->buf.assign(data, data + len);
  pthread_mutex_lock(&e->mu);
  op->id = e->next_id++;
  e->inflight++;
  e->submitted.push_back(op);
  pthread_cond_signal(&e->cv_submit);
  long id = static_cast<long>(op->id);
  pthread_mutex_unlock(&e->mu);
  return id;
}

// Tracked ordered write pair: data1@off1 then (strictly after) data2@off2,
// completion reported through tbio_poll/tbio_fetch like a read. This is
// the async WAL append (prepare body, then redundant header — reference:
// the journal's write_prepare -> write_header ordering,
// src/vsr/journal.zig).
long tbio_submit_write_pair(tbio *e, uint64_t off1, const uint8_t *data1,
                            uint64_t len1, uint64_t off2,
                            const uint8_t *data2, uint64_t len2) {
  tbio_op *op = new tbio_op();
  op->is_write = 1;
  op->tracked = 1;
  op->off = off1;
  op->buf.assign(data1, data1 + len1);
  op->off2 = off2;
  op->buf2.assign(data2, data2 + len2);
  pthread_mutex_lock(&e->mu);
  op->id = e->next_id++;
  e->inflight++;
  e->live[op->id] = 1;
  e->submitted.push_back(op);
  pthread_cond_signal(&e->cv_submit);
  long id = static_cast<long>(op->id);
  pthread_mutex_unlock(&e->mu);
  return id;
}

long tbio_submit_read(tbio *e, uint64_t off, uint64_t len) {
  tbio_op *op = new tbio_op();
  op->is_write = 0;
  op->off = off;
  op->buf.resize(len);
  pthread_mutex_lock(&e->mu);
  op->id = e->next_id++;
  e->inflight++;
  e->live[op->id] = 1;
  e->submitted.push_back(op);
  pthread_cond_signal(&e->cv_submit);
  long id = static_cast<long>(op->id);
  pthread_mutex_unlock(&e->mu);
  return id;
}

// Nonblocking: copy up to `max` completed ids out; the entries stay
// until fetched (reads) or reaped (writes) via tbio_fetch.
long tbio_poll(tbio *e, uint64_t *ids, long max) {
  pthread_mutex_lock(&e->mu);
  long n = 0;
  for (auto &kv : e->completed) {
    if (n >= max) break;
    ids[n++] = kv.first;
  }
  pthread_mutex_unlock(&e->mu);
  return n;
}

// Blocking fetch of one READ or TRACKED-WRITE completion: waits for
// `id`, copies read data into buf (len bytes max; writes carry no data),
// frees the entry. Returns the op's io result (bytes transferred) or -2
// if the id is unknown, already fetched, or was an untracked write
// (those auto-reap; never wait on them).
long tbio_fetch(tbio *e, uint64_t id, uint8_t *buf, uint64_t len) {
  pthread_mutex_lock(&e->mu);
  std::map<uint64_t, tbio_op *>::iterator it;
  for (;;) {
    it = e->completed.find(id);
    if (it != e->completed.end()) break;
    if (e->live.find(id) == e->live.end()) {
      pthread_mutex_unlock(&e->mu);
      return -2;
    }
    pthread_cond_wait(&e->cv_complete, &e->mu);
  }
  tbio_op *op = it->second;
  e->completed.erase(it);
  e->live.erase(id);
  pthread_mutex_unlock(&e->mu);
  long result = static_cast<long>(op->result);
  if (!op->is_write && buf != nullptr && result > 0) {
    uint64_t n = static_cast<uint64_t>(result) < len
                     ? static_cast<uint64_t>(result)
                     : len;
    memcpy(buf, op->buf.data(), n);
  }
  delete op;
  return result;
}

// Barrier: every submitted op is complete, optionally followed by
// fsync — the checkpoint durability point. A write failure is STICKY:
// once any async write has failed, every subsequent drain reports it
// (the caller must treat the storage as compromised).
int tbio_drain(tbio *e, int do_sync) {
  pthread_mutex_lock(&e->mu);
  while (e->inflight > 0) pthread_cond_wait(&e->cv_complete, &e->mu);
  int failed = e->failed ? 1 : 0;
  pthread_mutex_unlock(&e->mu);
  if (failed) return -1;
  if (do_sync) return tbs_sync(e->fd);
  return 0;
}

void tbio_destroy(tbio *e) {
  pthread_mutex_lock(&e->mu);
  e->shutdown = true;
  pthread_cond_broadcast(&e->cv_submit);
  pthread_mutex_unlock(&e->mu);
  for (pthread_t w : e->workers) pthread_join(w, nullptr);
  for (tbio_op *op : e->submitted) delete op;
  for (auto &kv : e->completed) delete kv.second;
  pthread_mutex_destroy(&e->mu);
  pthread_cond_destroy(&e->cv_submit);
  pthread_cond_destroy(&e->cv_complete);
  delete e;
}

}  // extern "C"
