// Native storage engine: zoned data-file IO, checksums, WAL recovery scan.
//
// The native runtime component of tigerbeetle_tpu (the reference's
// equivalent layer is src/storage.zig + src/vsr/journal.zig recovery over
// io_uring). Exposed as a C ABI consumed via ctypes
// (tigerbeetle_tpu/native.py). Single-threaded, synchronous pread/pwrite —
// the replica event loop is single-threaded by design.
//
// BLAKE2b implemented from RFC 7693 (keyed mode), producing digests
// identical to Python's hashlib.blake2b(data, digest_size=16, key=...):
// the wire/disk checksum contract is shared across both runtimes.

#include "blake2b.h"

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <unistd.h>

extern "C" {

// ------------------------------------------------------------- BLAKE2b
// Implementation shared with tb_client.cpp via blake2b.h.

void tbs_checksum(const uint8_t *data, uint64_t len, const uint8_t *key,
                  uint64_t key_len, uint8_t *out16) {
  tbp::checksum16(data, (size_t)len, key, (size_t)key_len, out16);
}

// --------------------------------------------------------------- file io

int tbs_open(const char *path, uint64_t size, int create) {
  int flags = O_RDWR | (create ? O_CREAT : 0);
  int fd = open(path, flags, 0644);
  if (fd < 0) return -1;
  if (create && ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int tbs_close(int fd) { return close(fd); }

int64_t tbs_read(int fd, uint64_t off, uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pread(fd, buf + done, len - done, (off_t)(off + done));
    if (n < 0) return -1;
    if (n == 0) {
      memset(buf + done, 0, len - done);
      return (int64_t)len;
    }
    done += (uint64_t)n;
  }
  return (int64_t)done;
}

int64_t tbs_write(int fd, uint64_t off, const uint8_t *buf, uint64_t len) {
  uint64_t done = 0;
  while (done < len) {
    ssize_t n = pwrite(fd, buf + done, len - done, (off_t)(off + done));
    if (n < 0) return -1;
    done += (uint64_t)n;
  }
  return (int64_t)done;
}

int tbs_sync(int fd) { return fsync(fd); }

// ------------------------------------------------------------- WAL scan

// Header layout offsets (tigerbeetle_tpu/vsr/header.py).
static const uint64_t HDR_SIZE = 256;
static const uint64_t OFF_CSUM_BODY = 16;
static const uint64_t OFF_SIZE = 88;
static const uint64_t OFF_OP = 104;
static const uint64_t OFF_COMMAND = 138;
static const uint8_t CMD_PREPARE = 6;
static const uint8_t CMD_RESERVED = 0;

static uint64_t rd_u64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}
static uint32_t rd_u32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static int header_valid(const uint8_t *hdr, const uint8_t *hdr_key,
                        uint64_t hdr_key_len) {
  uint8_t digest[16];
  tbs_checksum(hdr + 16, HDR_SIZE - 16, hdr_key, hdr_key_len, digest);
  // Accept prepare AND reserved commands: replica format writes valid
  // RESERVED headers into every slot so recovery can tell formatted-empty
  // (nack-eligible) from torn (must abstain); see vsr/journal.py.
  return memcmp(digest, hdr, 16) == 0 &&
         (hdr[OFF_COMMAND] == CMD_PREPARE ||
          hdr[OFF_COMMAND] == CMD_RESERVED);
}

// Scan the WAL rings and classify every slot.
// states_out[i]: 0 = clean, 1 = faulty (header known), 2 = unknown.
// headers_out: slot_count * 256 bytes (the adopted header for clean/faulty).
// scratch must hold prepare_size_max bytes.
int tbs_wal_scan(int fd, uint64_t hdr_zone_off, uint64_t prep_zone_off,
                 uint32_t slot_count, uint64_t prepare_size_max,
                 const uint8_t *hdr_key, uint64_t hdr_key_len,
                 const uint8_t *body_key, uint64_t body_key_len,
                 uint8_t *headers_out, uint8_t *states_out,
                 uint8_t *scratch) {
  for (uint32_t slot = 0; slot < slot_count; slot++) {
    uint8_t ring_hdr[256];
    if (tbs_read(fd, hdr_zone_off + (uint64_t)slot * HDR_SIZE, ring_hdr,
                 HDR_SIZE) < 0)
      return -1;
    int ring_ok = header_valid(ring_hdr, hdr_key, hdr_key_len);

    uint64_t prep_off = prep_zone_off + (uint64_t)slot * prepare_size_max;
    if (tbs_read(fd, prep_off, scratch, HDR_SIZE) < 0) return -1;
    int prep_hdr_ok = header_valid(scratch, hdr_key, hdr_key_len) &&
                      scratch[OFF_COMMAND] == CMD_PREPARE;
    int prep_ok = 0;
    if (prep_hdr_ok) {
      uint32_t size = rd_u32(scratch + OFF_SIZE);
      // Protocol bound: header + body <= message_size_max == slot stride
      // (mirrors vsr/journal.py append/recover).
      if (size >= HDR_SIZE && size <= prepare_size_max) {
        if (tbs_read(fd, prep_off + HDR_SIZE, scratch + HDR_SIZE,
                     size - HDR_SIZE) < 0)
          return -1;
        uint8_t digest[16];
        tbs_checksum(scratch + HDR_SIZE, size - HDR_SIZE, body_key,
                     body_key_len, digest);
        prep_ok = memcmp(digest, scratch + OFF_CSUM_BODY, 16) == 0;
      }
    }

    int ring_prepare = ring_ok && ring_hdr[OFF_COMMAND] == CMD_PREPARE;
    int ring_reserved = ring_ok && ring_hdr[OFF_COMMAND] == CMD_RESERVED;
    uint8_t *out_hdr = headers_out + (uint64_t)slot * HDR_SIZE;
    if (ring_prepare && prep_ok && memcmp(scratch, ring_hdr, 16) == 0) {
      states_out[slot] = 0;
      memcpy(out_hdr, ring_hdr, HDR_SIZE);
    } else if (prep_ok && ring_prepare &&
               rd_u64(scratch + OFF_OP) > rd_u64(ring_hdr + OFF_OP)) {
      states_out[slot] = 0;
      memcpy(out_hdr, scratch, HDR_SIZE);
    } else if (prep_ok && !ring_prepare) {
      // Ring header torn, absent, or still the formatted reserved one
      // (crash between prepare-body and header write): the prepare wins.
      states_out[slot] = 0;
      memcpy(out_hdr, scratch, HDR_SIZE);
    } else if (ring_prepare) {
      states_out[slot] = 1;
      memcpy(out_hdr, ring_hdr, HDR_SIZE);
    } else if (ring_reserved) {
      // Formatted-empty: provably never prepared -> clean, nack-eligible.
      states_out[slot] = 3;
      memset(out_hdr, 0, HDR_SIZE);
    } else {
      states_out[slot] = 2;
      memset(out_hdr, 0, HDR_SIZE);
    }
  }
  return 0;
}

// Append one prepare: body first, then the redundant header (write
// ordering is the torn-write defense; see vsr/journal.py).
int tbs_wal_append(int fd, uint64_t hdr_zone_off, uint64_t prep_zone_off,
                   uint32_t slot, uint64_t prepare_size_max,
                   const uint8_t *msg, uint64_t msg_len) {
  if (msg_len < HDR_SIZE || msg_len > prepare_size_max) return -1;
  if (tbs_write(fd, prep_zone_off + (uint64_t)slot * prepare_size_max, msg,
                msg_len) < 0)
    return -1;
  if (tbs_write(fd, hdr_zone_off + (uint64_t)slot * HDR_SIZE, msg,
                HDR_SIZE) < 0)
    return -1;
  return 0;
}

}  // extern "C"
