"""Static docs-site generator (reference analog: src/docs_website/ —
a Zig build that renders docs/ markdown through pandoc to
docs.tigerbeetle.com; here a dependency-free renderer for the markdown
subset the docs use).

Usage: python scripts/docs_build.py [--out DIR]

Renders every docs/**/*.md to HTML with a section nav, rewrites
intra-docs .md links to .html, and fails the build on a broken internal
link (link checking is the part that actually rots)."""

from __future__ import annotations

import argparse
import html
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs")

PAGE = """<!doctype html>
<html lang="en"><head><meta charset="utf-8">
<title>{title} — tigerbeetle_tpu</title>
<style>
body {{ font: 16px/1.55 system-ui, sans-serif; max-width: 72ch;
       margin: 2rem auto; padding: 0 1rem; color: #1a1a1a; }}
pre {{ background: #f6f6f6; padding: .8rem; overflow-x: auto; }}
code {{ background: #f6f6f6; padding: 0 .2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #ccc; padding: .3em .6em; text-align: left; }}
nav {{ font-size: .9em; border-bottom: 1px solid #ddd;
      margin-bottom: 1.5rem; padding-bottom: .5rem; }}
</style></head><body>
<nav><a href="{root}index.html">docs</a> · tigerbeetle_tpu</nav>
{body}
</body></html>
"""


def _inline(text: str) -> str:
    text = html.escape(text, quote=False)
    text = re.sub(r"`([^`]+)`", r"<code>\1</code>", text)
    text = re.sub(r"\*\*([^*]+)\*\*", r"<b>\1</b>", text)
    text = re.sub(r"\[([^\]]+)\]\(([^)]+)\)",
                  lambda m: '<a href="%s">%s</a>' % (
                      re.sub(r"\.md\b", ".html", m.group(2)), m.group(1)),
                  text)
    return text


def render(md: str) -> tuple[str, str]:
    """Markdown subset -> (title, html body)."""
    lines = md.splitlines()
    out: list[str] = []
    title = "docs"
    i = 0
    in_list = False

    def close_list():
        nonlocal in_list
        if in_list:
            out.append("</ul>")
            in_list = False

    while i < len(lines):
        ln = lines[i]
        if ln.startswith("```"):
            close_list()
            block = []
            i += 1
            while i < len(lines) and not lines[i].startswith("```"):
                block.append(lines[i])
                i += 1
            out.append("<pre><code>%s</code></pre>"
                       % html.escape("\n".join(block)))
        elif ln.startswith("#"):
            close_list()
            level = len(ln) - len(ln.lstrip("#"))
            text = ln.lstrip("#").strip()
            if level == 1:
                title = text
            out.append(f"<h{level}>{_inline(text)}</h{level}>")
        elif ln.startswith("|"):
            close_list()
            rows = []
            while i < len(lines) and lines[i].startswith("|"):
                cells = [c.strip() for c in
                         lines[i].strip("|").split("|")]
                if not all(re.fullmatch(r":?-+:?", c) for c in cells):
                    rows.append(cells)
                i += 1
            i -= 1
            tag = "th"
            out.append("<table>")
            for row in rows:
                out.append("<tr>" + "".join(
                    f"<{tag}>{_inline(c)}</{tag}>" for c in row) + "</tr>")
                tag = "td"
            out.append("</table>")
        elif ln.lstrip().startswith("- "):
            if not in_list:
                out.append("<ul>")
                in_list = True
            item = [ln.lstrip()[2:]]
            while (i + 1 < len(lines) and lines[i + 1].startswith("  ")
                   and not lines[i + 1].lstrip().startswith("- ")):
                i += 1
                item.append(lines[i].strip())
            out.append(f"<li>{_inline(' '.join(item))}</li>")
        elif not ln.strip():
            close_list()
        else:
            close_list()
            para = [ln]
            while (i + 1 < len(lines) and lines[i + 1].strip()
                   and not re.match(r"[#`|]|- ", lines[i + 1])):
                i += 1
                para.append(lines[i])
            out.append(f"<p>{_inline(' '.join(para))}</p>")
        i += 1
    close_list()
    return title, "\n".join(out)


def collect() -> list[str]:
    pages = []
    for root, _dirs, files in os.walk(DOCS):
        for f in sorted(files):
            if f.endswith(".md"):
                pages.append(os.path.relpath(os.path.join(root, f), DOCS))
    return pages


def check_links(pages: list[str]) -> list[str]:
    known = set(pages)
    broken = []
    for rel in pages:
        src = open(os.path.join(DOCS, rel)).read()
        for m in re.finditer(r"\]\(([^)#]+\.md)", src):
            target = os.path.normpath(
                os.path.join(os.path.dirname(rel), m.group(1)))
            if target not in known:
                broken.append(f"{rel}: {m.group(1)}")
    return broken


def build(out_dir: str) -> list[str]:
    pages = collect()
    broken = check_links(pages)
    if broken:
        raise SystemExit("broken internal links:\n  " + "\n  ".join(broken))
    for rel in pages:
        md = open(os.path.join(DOCS, rel)).read()
        title, body = render(md)
        dest_rel = re.sub(
            r"README\.md$", "index.html", rel)
        if dest_rel.endswith(".md"):
            dest_rel = dest_rel[:-3] + ".html"
        dest = os.path.join(out_dir, dest_rel)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        depth = dest_rel.count(os.sep)
        with open(dest, "w") as f:
            f.write(PAGE.format(title=html.escape(title), body=body,
                                root="../" * depth))
    return pages


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "docs", "_site"))
    args = ap.parse_args()
    pages = build(args.out)
    print(f"built {len(pages)} pages -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
