"""Tunnel watcher: capture an on-chip bench artifact whenever a window opens.

Round 2 lost its only on-chip number to a commit message because the
watcher lived in untracked scratch/ and the end-of-round tunnel wedge ate
the driver bench (BENCH_r02.json: parsed=null). Doctrine now: this script
is committed, runs all round, and the moment `jax.devices()` succeeds on
the axon backend it runs the bench in a bounded subprocess and writes
`onchip/BENCH_ONCHIP_<utc>.json` — then commits it, so no result can ever
again exist only in prose.

Usage: nohup python scripts/tpu_watch.py >onchip/watch.log 2>&1 &

Probe and bench both run in subprocesses with hard deadlines: a wedged
PJRT_Client_Create (the round-2 failure mode) kills the child, not the
watcher. After a successful capture the watcher backs off (one artifact
per WINDOW_COOLDOWN_S); failed probes retry every PROBE_PERIOD_S.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ONCHIP = os.path.join(REPO, "onchip")

PROBE_PERIOD_S = 300.0
PROBE_TIMEOUT_S = 150.0
BENCH_TIMEOUT_S = 2400.0
WINDOW_COOLDOWN_S = 3600.0

_PROBE_SRC = (
    "import jax, jax.numpy as jnp\n"
    "d = jax.devices()\n"
    "assert d and d[0].platform != 'cpu', d\n"
    "print('PROBE_OK', (jnp.arange(8).sum()).item())\n"
)


def probe() -> bool:
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            env=dict(os.environ, JAX_PLATFORMS="axon"),
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "PROBE_OK" in out.stdout


# One source of truth for the shared persistent-compile-cache env
# (remote compiles are the dominant cost of a window; a cache hit in a
# later window skips them).
sys.path.insert(0, REPO)
from bench import CACHE_ENV  # noqa: E402


QUICK_TIMEOUT_S = 1200.0  # round-3 quick captures completed in <5 min


def capture(quick: bool) -> dict | None:
    # The quick capture runs at the head of the first window and must
    # not gamble the whole window: it gets a 20-minute budget (4x its
    # historical cost), the full bench the 40-minute one.
    budget = QUICK_TIMEOUT_S if quick else BENCH_TIMEOUT_S
    env = dict(os.environ, BENCH_PLATFORM="axon",
               BENCH_WATCHDOG_S=str(int(budget - 60)),
               **CACHE_ENV)
    if quick:
        env["BENCH_QUICK"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            env=env, capture_output=True, text=True,
            # The driver kills its own wedged child at watchdog+90s and
            # then exits with the partial record; this outer SIGKILL is
            # a pure backstop and must come strictly AFTER that.
            timeout=budget + 240, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return None
    final = None
    for ln in out.stdout.splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                final = json.loads(ln)
            except json.JSONDecodeError:
                pass
    return final


def commit_file(path: str, message: str) -> None:
    subprocess.run(["git", "add", path], cwd=REPO, check=False)
    subprocess.run(["git", "commit", "-m", message, "--only", path],
                   cwd=REPO, check=False, capture_output=True)


def commit_artifact(result: dict, quick: bool) -> str:
    os.makedirs(ONCHIP, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(ONCHIP, f"BENCH_ONCHIP_{stamp}.json")
    record = {"utc": stamp, "quick": quick, "platform": "axon",
              "result": result}
    with open(path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    commit_file(path, f"On-chip bench artifact {stamp} "
                      f"(value={result.get('value')} "
                      f"{result.get('unit', '')})")
    return path


PROBES = (
    # (script, timeout_s, result_artifact) — the round-4 whole-program
    # verdict artifacts (VERDICT item 1), cheapest first. They run
    # after the bounded quick bench (which banks the round's first
    # number of record) but before the 40-min full bench, and resume
    # from their banked artifacts across runs.
    ("onchip/wholeprog_probe.py", 900, "onchip/wholeprog_probe_result.json"),
    ("onchip/chain_probe.py", 2400, "onchip/chain_probe_result.json"),
)


def _measured_keys(path: str) -> int:
    """How many actually-measured arms an artifact carries (used to
    distinguish a run that made progress from one that only banked
    errors — only progress refunds a probe attempt)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return 0
    if not isinstance(data, dict):
        return 0
    return sum(1 for k, v in data.items()
               if k.endswith(("_tps", "_ms")) and v is not None)


def _artifact_complete(path: str) -> bool:
    """A probe artifact counts as done only if it parses AND carries the
    probe's own completion marker — a partial (deadline-cut) artifact
    banks its arms but must not suppress the remaining ones."""
    try:
        with open(path) as f:
            data = json.load(f)
        return isinstance(data, dict) and bool(data.get("complete"))
    except (OSError, json.JSONDecodeError):
        return False


_probes_completed: set = set()
_probe_banked = False  # did the LAST run_probes_once bank any artifact?
# A verdict banked earlier TODAY survives a watcher restart — re-running
# a completed probe would burn window minutes re-proving a banked fact.
for _script, _t, _artifact in PROBES:
    _p = os.path.join(REPO, _artifact)
    if os.path.exists(_p) and time.time() - os.path.getmtime(_p) < 12 * 3600 \
            and _artifact_complete(_p):
        _probes_completed.add(_script)


def run_probes_once() -> bool:
    """Run the staged probes in order, skipping ones already banked;
    returns True when ALL completed. A timeout or failure aborts the
    chain (it is strong evidence the window closed — the next open
    window retries the REMAINING probes only). An artifact commits only
    if it was (re)written after the probe started (with 2 s of mtime
    slack for coarse filesystems) AND parses as JSON — a SIGKILL
    mid-write must not bank a truncated verdict."""
    for script, timeout_s, artifact in PROBES:
        if script in _probes_completed:
            continue
        print(f"[{time.strftime('%H:%M:%S')}] probe {script}", flush=True)
        t0 = time.time()
        art = os.path.join(REPO, artifact)
        measured_before = _measured_keys(art)
        timed_out = False
        rc = 0
        try:
            p = subprocess.run(
                [sys.executable, os.path.join(REPO, script)],
                env=dict(os.environ, JAX_PLATFORMS="axon",
                         PROBE_DEADLINE_S=str(int(timeout_s)),
                         **CACHE_ENV),
                capture_output=True, text=True, timeout=timeout_s + 120,
                cwd=REPO,
            )
            rc = p.returncode
            print(p.stdout[-1200:], flush=True)
        except subprocess.TimeoutExpired:
            # A probe can write its complete artifact and THEN wedge in
            # PJRT teardown (the documented rounds-2/3 failure mode):
            # still bank whatever valid result exists before aborting.
            timed_out = True
            print(f"probe {script} timed out; window likely closed",
                  flush=True)
        fresh = os.path.exists(art) and \
            os.path.getmtime(art) >= t0 - 2.0
        valid = False
        if fresh:
            try:
                with open(art) as f:
                    json.load(f)
                valid = True
            except (OSError, json.JSONDecodeError):
                pass
        if valid:
            if _measured_keys(art) > measured_before:
                # Real progress (a new measured arm) — the attempt
                # wasn't wasted. An artifact of errors is NOT progress
                # and must still burn an attempt, or a persistently
                # failing probe starves the full bench forever.
                global _probe_banked
                _probe_banked = True
            commit_file(art, "On-chip probe artifact "
                             f"{os.path.basename(artifact)}")
            print(f"committed {artifact}", flush=True)
            # A COMPLETE banked verdict is a completed probe even if
            # the process died after the write — never re-run it. A
            # partial artifact is banked but the probe re-runs next
            # window for its remaining arms.
            if _artifact_complete(art):
                _probes_completed.add(script)
        if timed_out:
            return False
        if rc != 0:
            print(f"probe rc={rc}: {p.stderr[-800:]}", flush=True)
            return False
        if not valid:
            print(f"probe wrote no fresh/valid {artifact}", flush=True)
            return False
    return True


PROBE_ATTEMPTS_MAX = 3
# ADVICE r4: the refund for making-progress probe runs must be bounded,
# or a probe that banks one arm per window and never completes defers
# the full bench forever. Likewise the head-of-window quick bench: if
# it persistently fails while the tunnel is healthy, fall through to
# the probes instead of starving them.
PROBE_RUNS_HARD_MAX = 8
QUICK_FAILURES_MAX = 3


def main() -> None:
    quick_done = False
    probes_done = False
    probe_attempts = 0
    probe_runs_total = 0
    quick_failures = 0

    def bank(quick: bool) -> bool:
        """Run one capture and bank it; True iff a value was banked."""
        result = capture(quick=quick)
        # A banked-fallback record must never be re-committed as a
        # fresh capture (it would launder the true artifact age).
        if result and result.get("value_source"):
            print("bench fell back to a banked record; not banking",
                  flush=True)
            return False
        if result and result.get("value") is not None:
            path = commit_artifact(result, quick=quick)
            print(f"captured {path}: value={result.get('value')}",
                  flush=True)
            return True
        return False

    while True:
        if probe():
            print(f"[{time.strftime('%H:%M:%S')}] window open", flush=True)
            if not quick_done:
                # The quick bench banks the round's FIRST number of
                # record with this round's kernels — since 20260802 it
                # outranks the remaining verdict probes (wholeprog is
                # already banked; chain can follow in the same window).
                quick_done = bank(quick=True)
                if not quick_done:
                    # The head-of-window quick bench just failed: the
                    # window is flaky or closed — don't immediately
                    # gamble more of it on probes or a full bench.
                    # But a bench-side bug with a healthy tunnel must
                    # not starve the probes forever (ADVICE r4): after
                    # QUICK_FAILURES_MAX consecutive failures, fall
                    # through and let the probes have the window.
                    quick_failures += 1
                    print(f"quick bench yielded no value "
                          f"({quick_failures}/{QUICK_FAILURES_MAX})",
                          flush=True)
                    if quick_failures < QUICK_FAILURES_MAX:
                        time.sleep(PROBE_PERIOD_S)
                        continue
                else:
                    quick_failures = 0
            if not probes_done and probe_attempts < PROBE_ATTEMPTS_MAX \
                    and probe_runs_total < PROBE_RUNS_HARD_MAX:
                # The verdict probes run after the bounded quick bench
                # but before the 40-min full bench, cheapest first. A
                # persistently failing probe must not starve the full
                # bench forever — after PROBE_ATTEMPTS_MAX fruitless
                # window-opens (attempts that banked NEW measured arms
                # are refunded) the watcher falls through to capturing
                # ("no result can ever again exist only in prose"
                # outranks the probes).
                global _probe_banked
                _probe_banked = False
                probe_attempts += 1
                probe_runs_total += 1
                probes_done = run_probes_once()
                if _probe_banked:
                    # Partial progress (an artifact banked) means the
                    # attempt wasn't wasted — don't let ATTEMPTS_MAX
                    # starve a probe that re-runs until complete. The
                    # refund is bounded by PROBE_RUNS_HARD_MAX total
                    # runs (ADVICE r4): slow progress must not defer
                    # the full bench without bound.
                    probe_attempts = max(0, probe_attempts - 1)
                if not probes_done and \
                        probe_attempts < PROBE_ATTEMPTS_MAX and \
                        probe_runs_total < PROBE_RUNS_HARD_MAX:
                    time.sleep(PROBE_PERIOD_S)
                    continue
            if bank(quick=False):
                time.sleep(WINDOW_COOLDOWN_S)
                continue
            print("window open but bench yielded no value", flush=True)
        else:
            print(f"[{time.strftime('%H:%M:%S')}] tunnel down", flush=True)
        time.sleep(PROBE_PERIOD_S)


if __name__ == "__main__":
    main()
