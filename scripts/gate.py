#!/usr/bin/env python
"""Pre-snapshot gate: the quick test tier + the 8-device SPMD dryrun.

Run before banking a snapshot:

    python scripts/gate.py            # quick tier + dryrun_multichip(8)
    python scripts/gate.py --no-mesh  # quick tier only
    python scripts/gate.py --tier slow   # one of: quick, slow, soak, all

Tiers (markers documented in pytest.ini):

  quick  (default) every test not marked slow/soak — the jit-light
         correctness surface; finishes well inside the tier-1 budget.
  slow   the jit-heavy parity/differential tiers (kernel parity, the
         fixpoint/balancing/imported/sharded differential suites, VOPR
         scenario sweeps): each file compiles many XLA programs.
  soak   long randomized soaks; run when touching the matching
         subsystem, not per snapshot.

The gate also runs the fixed CHAOS seed set (testing/chaos.py
gate_main: seeded device-fault injection against the serving
supervisor — zero-silent-corruption asserted per seed; skip with
--no-chaos), the REBUILD smoke (3-replica in-process cluster, zero one
data file under load, recover-from-cluster, state-epoch digest match,
plus one fixed seed each of the message_bus and storage_faults
fuzzers; skip with --no-rebuild), the CHAIN-ROUTE leg (testing/chain_smoke.py: the
default whole-window scan dispatch through the real
submit_window/resolve_windows route — chain taken by default,
per-prepare fallback parity vs the sync path and the oracle, zero
host fallbacks on plain windows, committed chain budgets present;
skip with --no-chain), the PARTITIONED-CHAIN leg
(testing/partitioned_chain_smoke.py + parallel/multihost.py: the fused
sharded-state window route — one shard_map+scan dispatch per window —
differential vs the per-batch ladder and the oracle on an 8-device
virtual mesh, then the 2-process jax.distributed local leg, skipped
gracefully where multi-process init is unavailable; skip with
--no-partitioned-chain), the OVERLAP leg
(testing/overlap_smoke.py: double-buffered window staging proven live —
a seeded pipelined serving run's host_stall_fraction strictly under the
committed STALL_CEILING with every eligible window staged ahead, the
forced-sync negative measuring exactly 1.0 and failing the predicate,
and bit-exact history parity overlapped vs sync on the chain and fused
partitioned-chain routes; skip with --no-overlap), the RESHARD leg
(testing/reshard_smoke.py: crash-safe live resharding — a seeded
split+migrate+merge_back completes under live traffic on mesh-2 and
mesh-8 with the src==dst range-digest witness at every flip, zero
aborts/host fallbacks and bit-exact history vs a never-resharded
oracle, plus the corrupted-copy negative that must abort PRE-FLIP
with a flight artifact; skip with --no-reshard), the TELEMETRY leg
(testing/telemetry_smoke.py: the device-telemetry plane of the fused
route — harvested per-prepare block decoded bit-exact vs a host
recomputation on 1/2/8-device meshes, telemetry-lane census vs the
committed budget, a negative over-budget-pack red, and the measured
telemetry-on vs -off dispatch overhead ratio under the budget's
overhead_ratio_max; skip with --no-telemetry), the TRACE-CATALOG coverage leg
(testing/trace_coverage.py: the smokes re-run under recording tracers;
red when any event in tigerbeetle_tpu/trace/event.py is never emitted
or an off-catalog name is emitted, or an emitted span/histogram event
never fed a non-empty histogram; skip with --no-trace-cov), the
METRICS leg (testing/trace_coverage.py metrics_main: perf/slo.json
must load with every objective on-catalog — a dead SLO is a RED — and
a live /metrics endpoint over a seeded serving run must serve
Prometheus-parseable text with per-route window histograms and SLO
series; skip with --no-metrics), the BENCH-REGRESSION leg
(testing/latency_smoke.py: live serving-window p99 vs the committed
perf/latency_baseline.json and the BENCH_r*.json pinned p99
trajectory; skip with --no-bench-regression), the STATIC leg
(testing/static_smoke.py: jaxhound 2.0's four whole-stack passes over
the full serving-entry registry on an 8-device virtual mesh — device
determinism, host-determinism AST lint, retrace/recompile audit vs the
committed perf/tracebudget_r*.json, sharding-spec verification of the
partitioned lowerings — plus one negative injected-violation proof per
pass, each of which must RED; writes perf/static_status.json for the
devhub panel; skip with --no-static), the CAUSALITY leg
(testing/causality_smoke.py: causal request tracing end to end on a
REAL 3-replica vortex at sampling 1.0 — one complete orphan-free span
tree per client request, the commit causally attributed inside it,
per-pid clock-skew correction from matched bus send/recv pairs, plus
two negative proofs (dropped trace-context header, dropped root span)
that must each RED; skip with --no-causality), the PROFILE leg
(testing/observatory_smoke.py: the performance observatory — per-route
dispatch_device_time histograms non-empty with finite
achieved-vs-roofline fractions, the live memory watermark green vs the
committed perf/membudget_r*.json with the injected-leak negative RED,
a seeded latency burn firing the page-severity alert (runbook anchor,
alert:<rule> tail retention, frozen flight artifact) with the
alert-disabled and dead-rule negatives, and the measured observatory
overhead ratio under the membudget's profiler ceiling; skip with
--no-profile), and the
op-budget check + jaxhound serving-path lints
(`perf/opbudget.py --check --lint`): a kernel change that raises any
tier's heavy-op count or operand bytes past its committed budget
(perf/opbudget_r09.json — incl. the chain and partitioned-chain
routes' whole-program and scan-BODY censuses), bakes a >4 KiB closure
constant into a serving
entry, drops state-buffer donation, or introduces a while loop beyond
an entry's allowance into a serving lowering is a RED. See
ARCHITECTURE.md "Op-budget workflow" for reading a failure /
intentionally raising a budget.

Exit status is nonzero on ANY red (test failure, collection error,
timeout, dryrun assertion, budget excess, lint), so
`python scripts/gate.py && snapshot` cannot bank a broken tree.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TIER_EXPR = {
    "quick": "not slow and not soak",
    "slow": "slow",
    "soak": "soak",
    "all": "",
}


def run_tests(tier: str, timeout: int) -> int:
    expr = TIER_EXPR[tier]
    cmd = [
        sys.executable, "-m", "pytest", "tests/", "-q",
        "--continue-on-collection-errors",
        "-p", "no:cacheprovider", "-p", "no:randomly",
    ]
    if expr:
        cmd += ["-m", expr]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print(f"[gate] {tier} tier: {' '.join(cmd)}", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: {tier} tier timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] {tier} tier rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_opbudget(timeout: int = 900) -> int:
    """Op-budget check + jaxhound serving-path lints (see module doc)."""
    cmd = [sys.executable, os.path.join(REPO, "perf", "opbudget.py"),
           "--check", "--lint"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print(f"[gate] opbudget: {' '.join(cmd)}", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: opbudget timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] opbudget rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_chaos(timeout: int = 900) -> int:
    """Fixed chaos seed set (CPU engine, small workloads): the serving
    recovery path — verified epochs, bounded replay, retry/backoff,
    shard-loss reroute — can never silently rot. One subprocess so the
    seeds share jit caches; see testing/chaos.py gate_main/GATE_SEEDS.
    Any undetected corruption or parity break is a RED."""
    cmd = [sys.executable, "-c",
           "import sys; from tigerbeetle_tpu.testing import chaos; "
           "sys.exit(chaos.gate_main())"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] chaos: fixed seed set (testing/chaos.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: chaos timed out after {timeout}s", flush=True)
        return 124
    print(f"[gate] chaos rc={rc} in {time.time() - t0:.0f}s", flush=True)
    return rc


def run_rebuild(timeout: int = 600) -> int:
    """Rebuild-from-cluster smoke: 3-replica in-process cluster, traffic
    past a WAL wrap, zero one replica's data file, rebuild it from its
    peers, state-epoch digest match (testing/cluster.py rebuild_smoke) —
    plus one fixed seed of each rebuild-adjacent fuzzer (message_bus,
    storage_faults). Skip with --no-rebuild."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing.cluster import rebuild_smoke; "
           "from tigerbeetle_tpu.testing import fuzz; "
           "rebuild_smoke(); "
           "fuzz.run('message_bus', 1); "
           "fuzz.run('storage_faults', 1, iterations=2); "
           "print('[gate] rebuild ok')"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] rebuild: zero-one-data-file smoke + new fuzzer seeds",
          flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: rebuild timed out after {timeout}s", flush=True)
        return 124
    print(f"[gate] rebuild rc={rc} in {time.time() - t0:.0f}s", flush=True)
    return rc


def run_chain(timeout: int = 600) -> int:
    """Chain-route leg: quick differential of the default whole-window
    scan dispatch through the REAL submit_window/resolve_windows route —
    chain taken by default, per-prepare fallback parity vs the sync
    path and the oracle, zero host fallbacks on plain windows, and the
    committed chain budgets present (testing/chain_smoke.py; the
    r07 budget values themselves are enforced by the opbudget leg).
    Skip with --no-chain."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import chain_smoke; "
           "chain_smoke.chain_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] chain: whole-window scan-route differential "
          "(testing/chain_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: chain timed out after {timeout}s", flush=True)
        return 124
    print(f"[gate] chain rc={rc} in {time.time() - t0:.0f}s", flush=True)
    return rc


def run_partitioned_chain(timeout: int = 900) -> int:
    """Partitioned-chain leg: quick differential of the FUSED
    partitioned window route (ONE shard_map+scan dispatch per window
    over account-range-sharded state) on an 8-device virtual CPU mesh —
    chain taken by default, per-prepare limit-cascade fallback with
    on-device escalation, parity vs the per-batch ladder and the
    oracle, digest equality, zero host fallbacks, committed r09 fused
    budgets present (testing/partitioned_chain_smoke.py) — then the
    2-process ``jax.distributed`` local leg (parallel/multihost.py):
    the same route over a coordinator-connected 2-process global mesh,
    skipped gracefully where the multi-process runtime is unavailable.
    Skip with --no-partitioned-chain."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import partitioned_chain_smoke"
           " as s; s.partitioned_chain_smoke(); "
           "from tigerbeetle_tpu.parallel import multihost; "
           "print('[gate] multihost 2-process: '"
           " + multihost.two_process_smoke())"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] partitioned-chain: fused sharded window route "
          "differential + 2-process multihost leg", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: partitioned-chain timed out after "
              f"{timeout}s", flush=True)
        return 124
    print(f"[gate] partitioned-chain rc={rc} in "
          f"{time.time() - t0:.0f}s", flush=True)
    return rc


def run_overlap(timeout: int = 900) -> int:
    """Overlap leg: host↔device double-buffered window staging proven
    LIVE (testing/overlap_smoke.py, 8-device virtual mesh for the
    partitioned arm) — a seeded pipelined serving run must measure a
    host_stall_fraction strictly under the committed STALL_CEILING with
    every eligible window staged ahead, the forced-sync negative
    (DeviceLedger.overlap_staging=False) must measure exactly 1.0 and
    FAIL the ceiling predicate, and the overlapped history must be
    bit-exact vs the sync arm's (poisoned window included) on both the
    chain and fused partitioned-chain routes. Skip with
    --no-overlap."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import overlap_smoke as s; "
           "s.overlap_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] overlap: double-buffered staging stall ceiling + "
          "forced-sync negative (testing/overlap_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: overlap timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] overlap rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_reshard(timeout: int = 900) -> int:
    """Reshard leg: crash-safe live resharding proven LIVE
    (testing/reshard_smoke.py, 8-device virtual mesh) — a seeded
    split + migrate + merge_back completes under live traffic on a
    mesh-2 AND a mesh-8 sub-mesh with the src==dst range-digest
    witness at every flip, zero aborts, zero host fallbacks, and the
    history bit-exact vs a never-resharded oracle; the negative arm
    (an injected copy corruption) must abort PRE-FLIP with a
    FLIGHT_*_reshard_* artifact — a flip that goes through despite
    the corruption is a RED. Skip with --no-reshard."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import reshard_smoke as s; "
           "s.reshard_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] reshard: live split+migrate+merge_back with digest "
          "witness + corrupted-copy negative "
          "(testing/reshard_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: reshard timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] reshard rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_overload(timeout: int = 900) -> int:
    """Overload leg: the admission plane's SLO-driven load shedding
    proven LIVE (testing/overload_smoke.py) — a seeded 100k-session
    Zipfian overload at ~2x window capacity must keep every class's
    ADMITTED queue-wait p99 within its committed per-class budget while
    at least one class sheds, every rejection a typed ShedResult with a
    tail-kept trace (submitted == admitted + shed, zero silent drops),
    the admitted history bit-exact vs an oracle replay of only the
    admitted requests, and the shed-line-disabled negative must
    collapse past the largest budget and FAIL the gate predicate. Skip
    with --no-overload."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import overload_smoke as s; "
           "s.overload_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] overload: 100k-session Zipfian admission shedding + "
          "no-shed negative (testing/overload_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: overload timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] overload rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_telemetry(timeout: int = 900) -> int:
    """Telemetry leg: the round-10 device-telemetry plane on the fused
    partitioned-chain route (testing/telemetry_smoke.py, 8-device
    virtual mesh) — the harvested per-prepare block decoded bit-exact
    vs a host recomputation on 1/2/8-device meshes, the telemetry-lane
    census vs the committed budget's `telemetry` section, a negative
    proof that a grown pack reds perf/opbudget.check_telemetry, and
    the measured telemetry-on vs telemetry-off dispatch overhead ratio
    under the budget's `overhead_ratio_max`. Skip with
    --no-telemetry."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import telemetry_smoke as s; "
           "s.telemetry_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] telemetry: device block oracle + lane census + "
          "overhead ratio (testing/telemetry_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: telemetry timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] telemetry rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_trace_coverage(timeout: int = 900) -> int:
    """Trace-catalog coverage leg: the vopr/chaos/rebuild-style smokes
    (plus deterministic scenarios for rare events) run under recording
    tracers; RED if any catalog event (tigerbeetle_tpu/trace/event.py)
    is never emitted, or any emitted name is off-catalog (the recording
    tracer hard-errors on those). Skip with --no-trace-cov."""
    cmd = [sys.executable, "-c",
           "import sys; "
           "from tigerbeetle_tpu.testing import trace_coverage; "
           "sys.exit(trace_coverage.coverage_main())"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The reshard scenario drives a 2-shard migration; the virtual
    # mesh makes the leg's shard scenarios real multi-device.
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] trace-cov: catalog coverage "
          "(testing/trace_coverage.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: trace-cov timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] trace-cov rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_metrics(timeout: int = 600) -> int:
    """Metrics leg: perf/slo.json must load with every referenced event
    on-catalog (a dead SLO — an objective nothing can feed — is a RED),
    and a live /metrics HTTP endpoint over a real seeded serving run
    must serve Prometheus-parseable text carrying the per-route window
    histograms and the SLO series (testing/trace_coverage.py
    metrics_main). Skip with --no-metrics."""
    cmd = [sys.executable, "-c",
           "import sys; "
           "from tigerbeetle_tpu.testing import trace_coverage; "
           "sys.exit(trace_coverage.metrics_main())"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] metrics: SLO catalog check + /metrics exposition "
          "smoke", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: metrics timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] metrics rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_causality(timeout: int = 900) -> int:
    """Causality leg: causal request tracing acceptance over a REAL
    3-replica vortex cluster at sampling 1.0 — every client request
    must assemble into exactly one complete orphan-free span tree
    rooted at client_request with the commit causally attributed
    inside it, after per-pid clock-skew correction; two negative
    proofs (dropped trace-context header, dropped root span) must
    each trip the checker (testing/causality_smoke.py). Skip with
    --no-causality."""
    cmd = [sys.executable, "-c",
           "import sys; "
           "from tigerbeetle_tpu.testing import causality_smoke; "
           "sys.exit(causality_smoke.causality_main())"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] causality: causal trace assembly over a real vortex "
          "(testing/causality_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: causality timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] causality rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_bench_regression(timeout: int = 600) -> int:
    """Bench-regression leg: live serving-window p99 (seeded supervisor
    workload) vs the committed perf/latency_baseline.json, plus the
    committed BENCH_r*.json pinned p99 trajectory
    (testing/latency_smoke.py; regenerate the baseline on a healthy
    tree with `python -m tigerbeetle_tpu.testing.latency_smoke
    --write-baseline`). Skip with --no-bench-regression."""
    cmd = [sys.executable, "-c",
           "import sys; "
           "from tigerbeetle_tpu.testing import latency_smoke; "
           "sys.exit(latency_smoke.regression_main([]))"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] bench-reg: serving-window p99 vs committed baseline",
          flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: bench-reg timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] bench-reg rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_profile(timeout: int = 900) -> int:
    """Profile leg: the performance observatory proven live WITH its
    negatives (testing/observatory_smoke.py) — sampled per-dispatch
    histograms + static-cost-model roofline fractions per tier, the
    memory watermark audited green vs the committed
    perf/membudget_r*.json and the injected-leak arm RED, the seeded
    latency burn firing the page alert (typed, runbook-anchored,
    trace-tail-keeping, flight-freezing) with the alert-disabled and
    dead-rule arms, and the observatory overhead ratio under the
    membudget's profiler ceiling. Skip with --no-profile."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import observatory_smoke as s; "
           "s.observatory_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] profile: dispatch roofline + memwatch budget + "
          "burn-rate alerts (testing/observatory_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: profile timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] profile rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_static(timeout: int = 900) -> int:
    """Static leg: jaxhound 2.0's four whole-stack passes (device
    determinism, host-determinism AST lint, retrace/recompile audit vs
    the committed perf/tracebudget_r*.json head, sharding-spec
    verification) over the FULL serving-entry registry on an 8-device
    virtual mesh, plus a negative injected-violation proof per pass
    (testing/static_smoke.py). Skip with --no-static."""
    cmd = [sys.executable, "-c",
           "from tigerbeetle_tpu.testing import static_smoke as s; "
           "s.static_smoke()"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    print("[gate] static: jaxhound passes + negative proofs "
          "(testing/static_smoke.py)", flush=True)
    t0 = time.time()
    try:
        p = subprocess.run(cmd, cwd=REPO, env=env, timeout=timeout)
        rc = p.returncode
    except subprocess.TimeoutExpired:
        print(f"[gate] RED: static timed out after {timeout}s",
              flush=True)
        return 124
    print(f"[gate] static rc={rc} in {time.time() - t0:.0f}s",
          flush=True)
    return rc


def run_mesh(n_devices: int) -> int:
    # dryrun_multichip handles its own harness-proofing (re-execs into a
    # pinned virtual-CPU-mesh subprocess when needed).
    print(f"[gate] dryrun_multichip({n_devices})", flush=True)
    t0 = time.time()
    p = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; "
         f"g.dryrun_multichip({n_devices}); print('[gate] mesh ok')"],
        cwd=REPO)
    print(f"[gate] mesh rc={p.returncode} in {time.time() - t0:.0f}s",
          flush=True)
    return p.returncode


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--tier", default="quick", choices=sorted(TIER_EXPR))
    ap.add_argument("--no-mesh", action="store_true",
                    help="skip the 8-device SPMD dryrun")
    ap.add_argument("--no-opbudget", action="store_true",
                    help="skip the op-budget check + jaxhound lints")
    ap.add_argument("--no-chaos", action="store_true",
                    help="skip the fixed chaos seed set (serving "
                         "recovery path)")
    ap.add_argument("--no-rebuild", action="store_true",
                    help="skip the rebuild-from-cluster smoke + new "
                         "fuzzer seeds")
    ap.add_argument("--no-trace-cov", action="store_true",
                    help="skip the trace-catalog coverage leg (dead/"
                         "off-catalog metric detection)")
    ap.add_argument("--no-chain", action="store_true",
                    help="skip the chain-route leg (whole-window scan "
                         "dispatch differential)")
    ap.add_argument("--no-partitioned-chain", action="store_true",
                    help="skip the partitioned-chain leg (fused "
                         "sharded window route differential + "
                         "2-process multihost leg)")
    ap.add_argument("--no-overlap", action="store_true",
                    help="skip the overlap leg (double-buffered window "
                         "staging stall ceiling + forced-sync negative)")
    ap.add_argument("--no-reshard", action="store_true",
                    help="skip the live-resharding leg (seeded "
                         "split+migrate+merge_back under traffic + "
                         "corrupted-copy negative, "
                         "testing/reshard_smoke.py)")
    ap.add_argument("--no-overload", action="store_true",
                    help="skip the overload leg (admission-plane "
                         "Zipfian shed/SLO proof + no-shed negative)")
    ap.add_argument("--no-telemetry", action="store_true",
                    help="skip the telemetry leg (device block oracle "
                         "+ lane census + overhead ratio)")
    ap.add_argument("--no-metrics", action="store_true",
                    help="skip the metrics leg (SLO catalog check + "
                         "/metrics exposition smoke)")
    ap.add_argument("--no-causality", action="store_true",
                    help="skip the causality leg (causal request "
                         "tracing acceptance over a real vortex "
                         "cluster + negative proofs)")
    ap.add_argument("--no-profile", action="store_true",
                    help="skip the profile leg (dispatch roofline + "
                         "memwatch budget + burn-rate alert negatives)")
    ap.add_argument("--no-static", action="store_true",
                    help="skip the static leg (jaxhound determinism/"
                         "retrace/sharding passes + negative proofs)")
    ap.add_argument("--no-bench-regression", action="store_true",
                    help="skip the bench-regression leg (serving p99 "
                         "vs committed baseline)")
    ap.add_argument("--mesh-devices", type=int, default=8)
    ap.add_argument("--timeout", type=int, default=840,
                    help="test-tier wall clock budget (s)")
    args = ap.parse_args()

    reds = []
    rc = run_tests(args.tier, args.timeout)
    if rc != 0:
        reds.append(f"{args.tier} tier rc={rc}")
    if not args.no_opbudget:
        rc = run_opbudget()
        if rc != 0:
            reds.append(f"opbudget rc={rc}")
    if not args.no_chaos:
        rc = run_chaos()
        if rc != 0:
            reds.append(f"chaos rc={rc}")
    if not args.no_rebuild:
        rc = run_rebuild()
        if rc != 0:
            reds.append(f"rebuild rc={rc}")
    if not args.no_chain:
        rc = run_chain()
        if rc != 0:
            reds.append(f"chain rc={rc}")
    if not args.no_partitioned_chain:
        rc = run_partitioned_chain()
        if rc != 0:
            reds.append(f"partitioned-chain rc={rc}")
    if not args.no_overlap:
        rc = run_overlap()
        if rc != 0:
            reds.append(f"overlap rc={rc}")
    if not args.no_reshard:
        rc = run_reshard()
        if rc != 0:
            reds.append(f"reshard rc={rc}")
    if not args.no_overload:
        rc = run_overload()
        if rc != 0:
            reds.append(f"overload rc={rc}")
    if not args.no_telemetry:
        rc = run_telemetry()
        if rc != 0:
            reds.append(f"telemetry rc={rc}")
    if not args.no_trace_cov:
        rc = run_trace_coverage()
        if rc != 0:
            reds.append(f"trace-cov rc={rc}")
    if not args.no_metrics:
        rc = run_metrics()
        if rc != 0:
            reds.append(f"metrics rc={rc}")
    if not args.no_causality:
        rc = run_causality()
        if rc != 0:
            reds.append(f"causality rc={rc}")
    if not args.no_bench_regression:
        rc = run_bench_regression()
        if rc != 0:
            reds.append(f"bench-reg rc={rc}")
    if not args.no_profile:
        rc = run_profile()
        if rc != 0:
            reds.append(f"profile rc={rc}")
    if not args.no_static:
        rc = run_static()
        if rc != 0:
            reds.append(f"static rc={rc}")
    if not args.no_mesh:
        rc = run_mesh(args.mesh_devices)
        if rc != 0:
            reds.append(f"dryrun_multichip({args.mesh_devices}) rc={rc}")
    if reds:
        print(f"[gate] RED: {'; '.join(reds)}", flush=True)
        return 1
    print("[gate] GREEN", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
