"""On-chip serving decomposition: where does the config6 window commit
spend its time? Stages timed separately, solo on the chip:

  codec     — wire body -> SoA (host)
  dispatch  — create_transfers_window kernel call, block_until_ready
  fetch     — the window-level delta device->host fetch
  encode    — result SoA -> wire replies (host)

Writes onchip/SERVING_PROFILE_<utc>.json. Run SOLO (no concurrent bench
or pytest): contention skews every number (PERF.md doctrine).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from tigerbeetle_tpu import multi_batch  # noqa: E402
from tigerbeetle_tpu.constants import BATCH_MAX as N  # noqa: E402
from tigerbeetle_tpu.state_machine import StateMachine  # noqa: E402
from tigerbeetle_tpu.types import Account, Operation, Transfer  # noqa: E402


def main() -> None:
    import jax

    platform = jax.default_backend()
    account_count = 10_000
    sm = StateMachine(engine="device", a_cap=1 << 15, t_cap=1 << 19)
    rng = np.random.default_rng(6)
    ts = 1000
    accounts = [Account(id=i, ledger=1, code=1)
                for i in range(1, account_count + 1)]
    for lo in range(0, account_count, N):
        chunk = accounts[lo:lo + N]
        ts += len(chunk) + 10
        sm.create_accounts(chunk, ts)
    nb = N - 1

    def mk_body(base):
        dr = rng.integers(1, account_count + 1, nb, dtype=np.uint64)
        cr = rng.integers(1, account_count + 1, nb, dtype=np.uint64)
        clash = dr == cr
        cr[clash] = dr[clash] % account_count + 1
        amt = rng.integers(1, 10**6, nb)
        payload = b"".join(
            Transfer(id=int(base + i), debit_account_id=int(dr[i]),
                     credit_account_id=int(cr[i]), amount=int(amt[i]),
                     ledger=1, code=1).pack()
            for i in range(nb))
        return multi_batch.encode([payload], 128)

    W = 8
    ROUNDS = 4
    next_id = 10**7
    out = {"platform": platform, "W": W, "rounds": ROUNDS}

    # -- stage: codec (decode only; measured on one window's bodies) ----
    bodies = [mk_body(next_id + i * nb) for i in range(W)]
    next_id += W * nb
    from tigerbeetle_tpu.ops.batch import transfers_soa_from_bytes
    from tigerbeetle_tpu.state_machine import OPERATION_SPECS

    spec = OPERATION_SPECS[Operation.create_transfers]
    t0 = time.perf_counter()
    for body in bodies:
        for b in multi_batch.decode(body, spec.event_size):
            transfers_soa_from_bytes(b)
    out["codec_decode_ms_per_window"] = round(
        (time.perf_counter() - t0) * 1000, 1)

    # -- warmup: compile the window program ----------------------------
    ts += W * (nb + 10)
    wts = []
    run = ts - W * (nb + 10)
    for _ in range(W):
        run += nb + 10
        wts.append(run)
    t0 = time.perf_counter()
    sm.commit_window(Operation.create_transfers, bodies, wts)
    out["warmup_window_ms"] = round((time.perf_counter() - t0) * 1000, 1)

    # -- steady windows, stage-timed -----------------------------------
    led = sm.led
    totals = {"window_total_ms": [], "drain_ms": []}
    orig_fetch = led._delta_fetch_start
    fetch_ms = []

    def timed_fetch(n_new):
        f0 = time.perf_counter()
        r = orig_fetch(n_new)
        # issuance only: resolution (device_get) happens at drain
        fetch_ms.append((time.perf_counter() - f0) * 1000)
        return r

    led._delta_fetch_start = timed_fetch
    for _ in range(ROUNDS):
        bodies = [mk_body(next_id + i * nb) for i in range(W)]
        next_id += W * nb
        wts = []
        for _ in range(W):
            ts += nb + 10
            wts.append(ts)
        t0 = time.perf_counter()
        sm.commit_window(Operation.create_transfers, bodies, wts)
        totals["window_total_ms"].append(
            round((time.perf_counter() - t0) * 1000, 1))
        d0 = time.perf_counter()
        led.drain_mirror()
        totals["drain_ms"].append(round((time.perf_counter() - d0) * 1000, 1))
    led._delta_fetch_start = orig_fetch

    out["window_total_ms"] = totals["window_total_ms"]
    out["drain_ms"] = totals["drain_ms"]
    out["fetch_ms"] = [round(x, 1) for x in fetch_ms]
    steady = totals["window_total_ms"][1:] or totals["window_total_ms"]
    mean_total = sum(steady) / len(steady)
    out["steady_window_ms"] = round(mean_total, 1)
    out["steady_tps"] = round(W * nb / (mean_total / 1000), 1)

    # -- dispatch-only estimate: re-run the kernel on prebuilt SoA -----
    evs, tss = [], []
    for i in range(W):
        base = next_id + i * nb
        dr = rng.integers(1, account_count + 1, nb, dtype=np.uint64)
        cr = rng.integers(1, account_count + 1, nb, dtype=np.uint64)
        clash = dr == cr
        cr[clash] = dr[clash] % account_count + 1
        ev = transfers_soa_from_bytes(b"".join(
            Transfer(id=int(base + j), debit_account_id=int(dr[j]),
                     credit_account_id=int(cr[j]),
                     amount=int(rng.integers(1, 10**6)),
                     ledger=1, code=1).pack() for j in range(nb)))
        evs.append(ev)
        ts += nb + 10
        tss.append(ts)
    next_id += W * nb
    t0 = time.perf_counter()
    outs = led.create_transfers_window(evs, tss)
    out["soa_window_ms"] = round((time.perf_counter() - t0) * 1000, 1)
    d0 = time.perf_counter()
    led.drain_mirror()
    out["soa_drain_ms"] = round((time.perf_counter() - d0) * 1000, 1)

    stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    path = os.path.join(REPO, "onchip", f"SERVING_PROFILE_{stamp}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
