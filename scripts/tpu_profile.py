"""On-chip microprofiles that decide the round-3 perf strategy.

The cost model (PERF.md) says dispatch overhead, not compute, bounds the
serving kernel: ~20 us/op on a local chip, ~1 ms/op through the axon
tunnel. Three questions decide where kernel-fusion effort goes, and each
needs real-hardware evidence:

  op-cost   How does wall time scale with executed-op count? (Chains of
            K data-dependent gathers — unfusable by XLA.) Confirms or
            corrects the per-op model and measures the current regime
            (tunnel vs local).
  pallas    Does a pallas_call count as ONE dispatch? A/B of the fused
            two-choice probe (ops/pallas_kernels.py) vs the XLA lookup
            at serving shapes. If Pallas collapses its op group to one
            dispatch, megakernels win in BOTH regimes.
  scan      Does lax.scan amortize dispatch? K kernel batches inside one
            scanned program vs K separate dispatches. If scan pays once
            per program rather than per iteration-op, batch-pipelining
            beats kernel fusion through the tunnel.

Each mode runs in THIS process (callers launch fresh processes per mode;
TB_PALLAS is trace-time — see ops/pallas_kernels.py). Results append to
onchip/PROFILE_<utc>.json.

Usage: JAX_PLATFORMS=axon python scripts/tpu_profile.py [op-cost|pallas|scan|all]
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _timeit(fn, *args, warmup=2, iters=5):
    import jax

    for _ in range(warmup):
        out = fn(*args)
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def profile_op_cost() -> dict:
    """Chains of K data-dependent gathers: slope = per-op dispatch cost."""
    import jax
    import jax.numpy as jnp

    n = 8192
    table = jnp.arange(n, dtype=jnp.int32)

    def chain(k):
        @jax.jit
        def f(idx):
            x = idx
            for _ in range(k):
                x = table[(x + 1) & (n - 1)]
            return x
        return f

    out = {}
    for k in (1, 8, 32, 96):
        f = chain(k)
        idx = jnp.arange(n, dtype=jnp.int32)
        out[f"gather_chain_{k}_s"] = round(_timeit(f, idx), 6)
    ks = [1, 8, 32, 96]
    ts = [out[f"gather_chain_{k}_s"] for k in ks]
    slope = (ts[-1] - ts[0]) / (ks[-1] - ks[0])
    out["per_op_cost_us"] = round(slope * 1e6, 2)
    return out


def profile_pallas() -> dict:
    """Fused Pallas probe vs XLA two-choice lookup at serving shapes."""
    import jax
    import jax.numpy as jnp

    from tigerbeetle_tpu.ops.hash_table import (
        ht_init, ht_insert, ht_lookup)
    from tigerbeetle_tpu.ops.pallas_kernels import (
        ht_lookup_fused, probe_fusable)

    cap = 1 << 15
    table = ht_init(cap)
    m = cap // 2
    keys_hi = jnp.arange(1, m + 1, dtype=jnp.uint64)
    keys_lo = jnp.arange(1, m + 1, dtype=jnp.uint64) * jnp.uint64(7)
    table, ok = ht_insert(table, keys_hi, keys_lo,
                          jnp.arange(m, dtype=jnp.int32),
                          jnp.ones(m, dtype=bool))
    n = 8192
    q_hi = keys_hi[:n]
    q_lo = keys_lo[:n]

    xla = jax.jit(lambda t, h, l: ht_lookup(t, h, l))
    fused = jax.jit(lambda t, h, l: ht_lookup_fused(t, h, l))
    out = {
        "insert_ok": bool(ok),
        "fusable": probe_fusable(table, n),
        "xla_lookup_s": round(_timeit(xla, table, q_hi, q_lo), 6),
    }
    try:
        out["pallas_lookup_s"] = round(
            _timeit(fused, table, q_hi, q_lo), 6)
        f1, v1 = jax.jit(lambda: ht_lookup(table, q_hi, q_lo))()
        f2, v2 = jax.jit(lambda: ht_lookup_fused(table, q_hi, q_lo))()
        out["parity"] = bool(
            (f1 == f2).all() and (v1 == v2)[f1].all())
    except Exception as e:  # Mosaic lowering can fail; that IS the result.
        out["pallas_error"] = f"{type(e).__name__}: {e}"[:500]
    return out


def profile_scan() -> dict:
    """K dispatches of one gather-heavy step vs one scanned program."""
    import jax
    import jax.numpy as jnp

    n = 8192
    table = jnp.arange(n, dtype=jnp.int32)
    K = 16
    OPS = 8

    def step(x):
        for _ in range(OPS):
            x = table[(x + 1) & (n - 1)]
        return x

    jstep = jax.jit(step)

    @jax.jit
    def scanned(x):
        def body(c, _):
            return step(c), ()
        c, _ = jax.lax.scan(body, x, None, length=K)
        return c

    idx = jnp.arange(n, dtype=jnp.int32)

    def k_dispatches(x):
        for _ in range(K):
            x = jstep(x)
        return x

    t_loop = _timeit(k_dispatches, idx)
    t_scan = _timeit(scanned, idx)
    return {
        "k": K, "ops_per_step": OPS,
        "k_dispatch_s": round(t_loop, 6),
        "scan_s": round(t_scan, 6),
        "scan_speedup": round(t_loop / t_scan, 2) if t_scan > 0 else None,
    }


def main() -> None:
    mode = sys.argv[1] if len(sys.argv) > 1 else "all"
    import jax

    record = {
        "utc": time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        "mode": mode,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }
    t0 = time.time()
    if mode in ("op-cost", "all"):
        record["op_cost"] = profile_op_cost()
    if mode in ("pallas", "all"):
        record["pallas"] = profile_pallas()
    if mode in ("scan", "all"):
        record["scan"] = profile_scan()
    record["elapsed_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.join(REPO, "onchip"), exist_ok=True)
    path = os.path.join(
        REPO, "onchip", f"PROFILE_{record['utc']}_{mode}.json")
    with open(path, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(json.dumps(record))


if __name__ == "__main__":
    main()
