"""Rolling upgrade of a live cluster (VERDICT r1 missing #10).

reference: src/multiversion.zig + docs/internals/upgrades.md — the
reference re-execs into the release matching the cluster checkpoint;
this runtime upgrades by restarting processes with newer code, guarded
by release gating (multiversion.py): newer binaries may open older data
files, never the reverse, and peers' advertised releases let operators
see upgrade progress. These tests restart one replica at a time under a
live workload and assert serving continuity, release visibility, and
the downgrade refusal.
"""

from unittest import mock

import pytest

from tigerbeetle_tpu import multi_batch, multiversion
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Account, Operation, Transfer


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr,
                 amount=amt, ledger=1, code=1).pack()
        for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


class TestRollingUpgrade:
    def test_one_at_a_time_upgrade_keeps_serving(self):
        old = multiversion.RELEASE
        new = old + 1
        cluster = Cluster(seed=41, replica_count=3)
        client = cluster.client(800)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()
        nid = 10**6

        def commit_one():
            nonlocal nid
            client.request(Operation.create_transfers,
                           _transfers_body([(nid, 1, 2, 1)]))
            nid += 1
            assert cluster.run(20000, until=lambda: client.idle), \
                cluster.debug_status()

        # Upgrade replicas one at a time, committing work between each
        # restart: the cluster must keep serving throughout.
        for victim in range(3):
            commit_one()
            cluster.crash(victim)
            commit_one()  # quorum of 2 still serves
            with mock.patch.object(multiversion, "RELEASE", new):
                cluster.restart(victim)  # comes back on the new release
            commit_one()
        cluster.settle()
        # Every live replica now advertises the new release, and each
        # replica's tracker has seen the whole cluster reach it.
        for r in cluster.replicas:
            assert r.release == new
        # Pings propagate releases; after settle every tracker's view of
        # the cluster floor is the new release.
        for r in cluster.replicas:
            assert r.releases.cluster_min == new, (
                r.replica_id, r.releases.peers)
        # All the work committed during the rolling upgrade survived.
        st = cluster.replicas[0].state_machine.state
        assert st.accounts[1].debits_posted == nid - 10**6
        cluster.check_convergence()

    def test_state_sync_gates_and_stamps_checkpoint_release(self):
        """A lagging OLD-binary replica must refuse to install a
        checkpoint written by a NEWER release (running new-format data
        under an old binary bypasses the upgrade gate); once upgraded, the
        sync installs and stamps the checkpoint's release into the
        superblock so a later downgrade is refused too."""
        old = multiversion.RELEASE
        new = old + 1
        cluster = Cluster(seed=43, replica_count=3)
        client = cluster.client(802)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        # Upgrade the live majority one at a time; their checkpoints now
        # stamp the new release.
        for r in range(3):
            if r == victim:
                continue
            cluster.crash(r)
            with mock.patch.object(multiversion, "RELEASE", new):
                cluster.restart(r)
            assert cluster.run(8000, until=lambda: client.idle), \
                cluster.debug_status()
        nid = 10**6
        for k in range(40):  # > slot_count: WAL wraps past the victim
            client.request(Operation.create_transfers,
                           _transfers_body([(nid, 1, 2, 1)]))
            nid += 1
            assert cluster.run(20000, until=lambda: client.idle), \
                cluster.debug_status()
        # Old binary back up: repair can't bridge the wrap, and the sync
        # offers carry release=new — it must refuse to install them.
        cluster.restart(victim)
        cluster.run(6000)
        lagging = cluster.replicas[victim]
        assert lagging.release == old
        assert lagging.syncing is None
        assert lagging.superblock.release == old
        assert lagging.commit_min < cluster.replicas[
            (victim + 1) % 3].commit_min
        # Upgrade the victim: the same sync now installs and stamps the
        # checkpoint's release.
        cluster.crash(victim)
        with mock.patch.object(multiversion, "RELEASE", new):
            cluster.restart(victim)
        cluster.settle(ticks=8000)
        synced = cluster.replicas[victim]
        assert synced.superblock.release == new
        assert synced.state_machine.state.accounts[1].debits_posted == 40
        # The stamp makes a post-sync downgrade refuse at open.
        cluster.crash(victim)
        with pytest.raises(RuntimeError, match="upgrade"):
            cluster.restart(victim)
        with mock.patch.object(multiversion, "RELEASE", new):
            cluster.restart(victim)
        cluster.settle()
        cluster.check_convergence()

    def test_format_floor_refuses_prefloor_checkpoint(self):
        """Checkpoints below FORMAT_FLOOR (r1 files) are refused with a
        rebuild instruction instead of silently opening with the new
        index trees empty (the r2 schema bump requirement)."""
        cluster = Cluster(seed=44, replica_count=3)
        client = cluster.client(803)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()
        cluster.crash(0)
        # Forge a pre-floor data file: stamp the superblock as release
        # floor-1 (what an r1 binary's checkpoint would have written).
        r0 = cluster.replicas[0]
        sb = r0.superblock
        sb.release = multiversion.FORMAT_FLOOR - 1
        sb.store(r0.storage)
        with pytest.raises(RuntimeError, match="rebuild"):
            cluster.restart(0)

    def test_downgrade_refused_after_new_release_checkpoint(self):
        """A data file checkpointed by a newer release must refuse to
        open under the old binary (reference: the multiversion re-exec
        decision — here, the gating assertion)."""
        old = multiversion.RELEASE
        new = old + 1
        cluster = Cluster(seed=42, replica_count=3)
        client = cluster.client(801)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()
        # Upgrade replica 0 and drive enough commits to checkpoint
        # (checkpoint_interval=16) so its superblock stamps the new
        # release.
        cluster.crash(0)
        with mock.patch.object(multiversion, "RELEASE", new):
            cluster.restart(0)
        nid = 10**6
        for k in range(20):
            client.request(Operation.create_transfers,
                           _transfers_body([(nid, 1, 2, 1)]))
            nid += 1
            assert cluster.run(20000, until=lambda: client.idle), \
                cluster.debug_status()
        cluster.settle()
        assert cluster.replicas[0].superblock.release == new
        # Restarting it with the OLD binary must refuse loudly.
        cluster.crash(0)
        with pytest.raises(RuntimeError, match="upgrade"):
            cluster.restart(0)
        # And the new binary opens it fine.
        with mock.patch.object(multiversion, "RELEASE", new):
            cluster.restart(0)
        cluster.settle()
        cluster.check_convergence()
