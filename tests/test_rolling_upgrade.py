"""Rolling upgrade of a live cluster (VERDICT r1 missing #10).

reference: src/multiversion.zig + docs/internals/upgrades.md — the
reference re-execs into the release matching the cluster checkpoint;
this runtime upgrades by restarting processes with newer code, guarded
by release gating (multiversion.py): newer binaries may open older data
files, never the reverse, and peers' advertised releases let operators
see upgrade progress. These tests restart one replica at a time under a
live workload and assert serving continuity, release visibility, and
the downgrade refusal.
"""

from unittest import mock

import pytest

from tigerbeetle_tpu import multi_batch, multiversion
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Account, Operation, Transfer


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr,
                 amount=amt, ledger=1, code=1).pack()
        for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


class TestRollingUpgrade:
    def test_one_at_a_time_upgrade_keeps_serving(self):
        old = multiversion.RELEASE
        new = old + 1
        cluster = Cluster(seed=41, replica_count=3)
        client = cluster.client(800)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()
        nid = 10**6

        def commit_one():
            nonlocal nid
            client.request(Operation.create_transfers,
                           _transfers_body([(nid, 1, 2, 1)]))
            nid += 1
            assert cluster.run(20000, until=lambda: client.idle), \
                cluster.debug_status()

        # Upgrade replicas one at a time, committing work between each
        # restart: the cluster must keep serving throughout.
        for victim in range(3):
            commit_one()
            cluster.crash(victim)
            commit_one()  # quorum of 2 still serves
            with mock.patch.object(multiversion, "RELEASE", new):
                cluster.restart(victim)  # comes back on the new release
            commit_one()
        cluster.settle()
        # Every live replica now advertises the new release, and each
        # replica's tracker has seen the whole cluster reach it.
        for r in cluster.replicas:
            assert r.release == new
        # Pings propagate releases; after settle every tracker's view of
        # the cluster floor is the new release.
        for r in cluster.replicas:
            assert r.releases.cluster_min == new, (
                r.replica_id, r.releases.peers)
        # All the work committed during the rolling upgrade survived.
        st = cluster.replicas[0].state_machine.state
        assert st.accounts[1].debits_posted == nid - 10**6
        cluster.check_convergence()

    def test_downgrade_refused_after_new_release_checkpoint(self):
        """A data file checkpointed by a newer release must refuse to
        open under the old binary (reference: the multiversion re-exec
        decision — here, the gating assertion)."""
        old = multiversion.RELEASE
        new = old + 1
        cluster = Cluster(seed=42, replica_count=3)
        client = cluster.client(801)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()
        # Upgrade replica 0 and drive enough commits to checkpoint
        # (checkpoint_interval=16) so its superblock stamps the new
        # release.
        cluster.crash(0)
        with mock.patch.object(multiversion, "RELEASE", new):
            cluster.restart(0)
        nid = 10**6
        for k in range(20):
            client.request(Operation.create_transfers,
                           _transfers_body([(nid, 1, 2, 1)]))
            nid += 1
            assert cluster.run(20000, until=lambda: client.idle), \
                cluster.debug_status()
        cluster.settle()
        assert cluster.replicas[0].superblock.release == new
        # Restarting it with the OLD binary must refuse loudly.
        cluster.crash(0)
        with pytest.raises(RuntimeError, match="upgrade"):
            cluster.restart(0)
        # And the new binary opens it fine.
        with mock.patch.object(multiversion, "RELEASE", new):
            cluster.restart(0)
        cluster.settle()
        cluster.check_convergence()
