"""Differential tests: partitioned ledger state vs the oracle.

The partitioned route (parallel/partitioned.py) shards EVERY store by
account/transfer id hash and resolves each batch through the on-device
exchange + mini-state judge. These tests pin the acceptance contract:
bit-exact statuses, result timestamps, flushed canonical columns, and
epoch digests vs the sequential oracle — at mesh sizes 1, 2, and 8,
with zero host fallbacks — on exactly the windows the exchange has to
get right: two-phase pairs straddling shards, closing×balancing across
shards, and a Zipfian hot-account window where one shard owns the hot
key.
"""

import jax
import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from jax.experimental import mesh_utils
from jax.sharding import Mesh

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.batch import transfers_to_arrays
from tigerbeetle_tpu.ops.ev_layout import EV_P32_POS, XF_NCOLS, XF_P32_POS
from tigerbeetle_tpu.ops.ledger import (
    DeviceLedger, _delta_gather_body, _pad_bucket, pad_transfer_events)
from tigerbeetle_tpu.ops.state_epoch import (
    partitioned_oracle_digest, partitioned_state_digest)
from tigerbeetle_tpu.parallel.partitioned import (
    PartitionedRouter, partitioned_state_bytes, replicated_state_bytes)
from tigerbeetle_tpu.parallel.shard_utils import shard_of_int
from tigerbeetle_tpu.types import Account, AccountFlags, Transfer, \
    TransferFlags as TF

PEND = int(TF.pending)
POST = int(TF.post_pending_transfer)
VOID = int(TF.void_pending_transfer)
BAL_DR = int(TF.balancing_debit)
BAL_CR = int(TF.balancing_credit)
CLOSE_DR = int(TF.closing_debit)
DR_LIMIT = int(AccountFlags.debits_must_not_exceed_credits)
AMOUNT_MAX = (1 << 128) - 1

A_CAP, T_CAP = 1 << 9, 1 << 11
MESH_SIZES = (1, 2, 8)

# Row-pointer words are shard-/mini-scope under the partitioned layout
# (module docstring) — everything else in the flush must be bit-exact.
_XF_PTR_COL = XF_P32_POS["dr_row"][0]
_EV_PTR_COL = EV_P32_POS["dr_row"][0]
_EV_PROW_COL = EV_P32_POS["p_row"][0]  # (pstat, p_row): pstat canonical


# Compile-once caches shared across tests: the partitioned step is a
# large program, and each (mesh size, tier) pair would otherwise
# recompile per test instance.
_MESHES: dict = {}
_STEPS: dict = {}


def _mesh(n_dev):
    if len(jax.devices()) < n_dev:
        pytest.skip(f"needs {n_dev} devices")
    if n_dev not in _MESHES:
        _MESHES[n_dev] = Mesh(mesh_utils.create_device_mesh(
            (n_dev,), devices=jax.devices()[:n_dev]), ("batch",))
    return _MESHES[n_dev]


class Harness:
    """Oracle + partitioned router + single-chip ledger in lockstep;
    every batch asserts statuses/timestamps vs the oracle and the
    flushed canonical columns vs the single-chip delta gather."""

    def __init__(self, n_dev, accounts, ts0=10 ** 9):
        self.mesh = _mesh(n_dev)
        self.n_dev = n_dev
        self.oracle = StateMachineOracle()
        # The single-chip reference needs t/e caps >= N_PAD so the
        # flush-parity delta gather can slice a full padded batch.
        self.led = DeviceLedger(a_cap=A_CAP, t_cap=1 << 14)
        self.oracle.create_accounts(accounts, 50)
        self.led.create_accounts(accounts, 50)
        self.router = PartitionedRouter(self.mesh, a_cap=A_CAP,
                                        t_cap=T_CAP)
        self.router._steps = _STEPS.setdefault(n_dev, {})
        self.state = self.router.from_oracle(self.oracle)
        self.ts = ts0

    def step(self, evs, expect_statuses=None):
        self.ts += 300
        n = len(evs)
        ev = pad_transfer_events(transfers_to_arrays(evs))
        N = ev["id_lo"].shape[0]
        t0 = int(np.asarray(self.led.state["transfers"]["count"]))
        e0 = int(np.asarray(self.led.state["events"]["count"]))
        self.state, out, fb = self.router.step(self.state, ev, self.ts, n)
        assert not fb, jax.device_get(out["fb_causes"])
        want = self.oracle.create_transfers(evs, self.ts)
        self.led.create_transfers(evs, self.ts)
        st = np.asarray(out["r_status"][:n])
        rts = np.asarray(out["r_ts"][:n])
        got = [(int(rts[i]), int(st[i])) for i in range(n)]
        exp = [(r.timestamp, int(r.status)) for r in want]
        assert got == exp, list(zip(got, exp))
        if expect_statuses is not None:
            assert [r.status.name for r in want] == expect_statuses
        self._check_flush(out, t0, e0, N)
        return want

    def _check_flush(self, out, t0, e0, N):
        c = int(np.asarray(out["created_count"]))
        flush = jax.device_get(out["flush"])
        ref = jax.device_get(_delta_gather_body(
            self.led.state, t0, e0, N, N))
        for k in ("dr_id_hi", "dr_id_lo", "cr_id_hi", "cr_id_lo"):
            assert (flush[k][:c] == ref[k][:c]).all(), k
        # p_ts is only defined on ring rows referencing a pending
        # (p_row >= 0); elsewhere the gather reads row 0 of whichever
        # scope — not a canonical value.
        prow_hi = (ref["e"]["u64"][:c, _EV_PROW_COL]
                   >> np.uint64(32)).astype(np.uint32)
        has_p = prow_hi != np.uint32(0xFFFFFFFF)
        assert (flush["p_ts"][:c] == ref["p_ts"][:c])[has_p].all(), "p_ts"
        for col in range(XF_NCOLS):
            if col == _XF_PTR_COL:
                continue
            assert (flush["t"]["u64"][:c, col]
                    == ref["t"]["u64"][:c, col]).all(), ("t", col)
        ncols_e = flush["e"]["u64"].shape[1]
        for col in range(ncols_e):
            if col == _EV_PTR_COL:
                continue
            a = flush["e"]["u64"][:c, col]
            b = ref["e"]["u64"][:c, col]
            if col == _EV_PROW_COL:
                a = a & np.uint64(0xFFFFFFFF)
                b = b & np.uint64(0xFFFFFFFF)
            assert (a == b).all(), ("e", col)

    def finish(self):
        assert self.router.host_fallbacks == 0
        dd = partitioned_state_digest(self.state)
        od = partitioned_oracle_digest(self.oracle, A_CAP, self.n_dev)
        assert dd == od, (dd, od)


def _cross_shard_pairs(n_dev, count, rng):
    """(dr, cr) account-id pairs on DIFFERENT shards (any pair when
    n_dev == 1), drawn from ids 1..40."""
    pairs = []
    ids = list(range(1, 41))
    while len(pairs) < count:
        dr, cr = rng.choice(ids, 2, replace=False)
        if n_dev == 1 or shard_of_int(int(dr), n_dev) != shard_of_int(
                int(cr), n_dev):
            pairs.append((int(dr), int(cr)))
    return pairs


@pytest.mark.parametrize("n_dev", MESH_SIZES)
class TestPartitioned:
    def test_two_phase_cross_shard(self, n_dev):
        """Pending/post/void pairs whose debit and credit accounts —
        and whose pending vs post/void transfer ids — straddle shards:
        the exchange's two-phase join (pending row fetched in phase 1,
        its accounts in phase 2) is on the critical path of every
        event."""
        rng = np.random.default_rng(11)
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 41)]
        h = Harness(n_dev, accts)
        nid = 10 ** 6
        pendings = []
        for _ in range(3):
            evs = []
            for dr, cr in _cross_shard_pairs(n_dev, 60, rng):
                roll = rng.random()
                if roll < 0.5 or not pendings:
                    evs.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=cr,
                        amount=int(rng.integers(1, 60)), ledger=1,
                        code=1, flags=PEND))
                    pendings.append(nid)
                else:
                    pid = pendings.pop(0)
                    f = POST if rng.random() < 0.5 else VOID
                    evs.append(Transfer(
                        id=nid, pending_id=pid,
                        amount=AMOUNT_MAX if f == POST else 0, flags=f))
                nid += 1
            h.step(evs)
        h.finish()
        if n_dev > 1:
            assert h.router.cross_shard_transfers > 0

    def test_closing_balancing_cross_shard(self, n_dev):
        """Closing×balancing across shards: limit accounts funded from
        remote shards, balancing debits clamped against them, a closing
        pending shuts a remote account mid-window, and its void
        reopens it — the fixpoint/balancing tiers run on the
        exchange-assembled mini-state."""
        accts = [Account(id=i, ledger=1, code=1,
                         flags=DR_LIMIT if i <= 8 else 0)
                 for i in range(1, 41)]
        h = Harness(n_dev, accts)
        rng = np.random.default_rng(13)
        pairs = _cross_shard_pairs(n_dev, 16, rng)
        # Fund the limit accounts (plain tier, cross-shard rows).
        evs = [Transfer(id=1000 + i, debit_account_id=20 + i % 16,
                        credit_account_id=1 + i % 8, amount=100 + i,
                        ledger=1, code=1) for i in range(16)]
        h.step(evs)
        # Balancing debits off the limit accounts to remote credits.
        evs = [Transfer(id=2000 + i, debit_account_id=1 + i % 8,
                        credit_account_id=dr if dr > 8 else cr,
                        amount=AMOUNT_MAX, ledger=1, code=1,
                        flags=BAL_DR)
               for i, (dr, cr) in enumerate(pairs[:8])]
        h.step(evs)
        # Closing pending on a remote pair + interleaved balancing,
        # then the void reopens the closed account next batch.
        dr, cr = pairs[8]
        evs = [
            Transfer(id=3000, debit_account_id=dr,
                     credit_account_id=cr, amount=1, ledger=1, code=1,
                     flags=PEND | CLOSE_DR),
            Transfer(id=3001, debit_account_id=dr,
                     credit_account_id=cr, amount=5, ledger=1, code=1),
            Transfer(id=3002, debit_account_id=1, credit_account_id=cr,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR),
        ]
        h.step(evs)
        h.step([Transfer(id=3003, pending_id=3000, amount=0,
                         flags=VOID),
                Transfer(id=3004, debit_account_id=dr,
                         credit_account_id=cr, amount=2, ledger=1,
                         code=1)])
        h.finish()

    def test_zipfian_hot_account(self, n_dev):
        """Zipfian account draw: one shard owns the hot key, so its
        exchange lanes and write-backs concentrate there while the
        mini-state judge stays replicated — the skew-tolerance shape of
        the partitioned route."""
        rng = np.random.default_rng(17)
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 41)]
        h = Harness(n_dev, accts)
        nid = 10 ** 6
        for _ in range(3):
            draws = np.minimum(rng.zipf(1.3, size=(150, 2)), 40)
            evs = []
            for dr, cr in draws:
                dr, cr = int(dr), int(cr)
                if dr == cr:
                    cr = dr % 40 + 1
                evs.append(Transfer(
                    id=nid, debit_account_id=dr, credit_account_id=cr,
                    amount=int(rng.integers(1, 40)), ledger=1, code=1))
                nid += 1
            h.step(evs)
        h.finish()
        owned = h.router.stats()["events_owned"]
        assert sum(owned) == h.router.batches * 150

    def test_state_bytes_scale(self, n_dev):
        """Per-device resident bytes ~1/n_shards vs the replicated
        route at the same caps (the HBM-clamp removal the layout
        exists for)."""
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
        h = Harness(n_dev, accts)
        pb = partitioned_state_bytes(h.state)
        rb = replicated_state_bytes(A_CAP, T_CAP)
        assert pb <= rb // n_dev + rb // 50, (pb, rb, n_dev)


_CHAIN_STEPS: dict = {}


@pytest.mark.parametrize("n_dev", MESH_SIZES)
class TestPartitionedChain:
    """The fused default window route: ONE shard_map+lax.scan dispatch
    per eligible commit window, differential vs the oracle AND vs the
    per-batch partitioned ladder — including a poisoned window whose
    clean prefix must stay committed inside the dispatch while the
    fallen-back prepare replays per-batch, with host_fallbacks==0."""

    def _fresh(self, n_dev, accounts):
        mesh = _mesh(n_dev)
        oracle = StateMachineOracle()
        oracle.create_accounts(accounts, 50)
        router = PartitionedRouter(mesh, a_cap=A_CAP, t_cap=T_CAP)
        router._steps = _STEPS.setdefault(n_dev, {})
        router._chain_steps = _CHAIN_STEPS.setdefault(n_dev, {})
        return oracle, router, router.from_oracle(oracle)

    def _window(self, oracle, router, state, evs_list, tss):
        """step_window + per-prepare oracle parity on every result."""
        state, results = router.step_window(
            state, [transfers_to_arrays(e) for e in evs_list], tss)
        assert len(results) == len(evs_list)
        for evs, t, (st, rts) in zip(evs_list, tss, results):
            want = oracle.create_transfers(evs, t)
            exp = [(r.timestamp, int(r.status)) for r in want]
            got = [(int(rts[i]), int(st[i])) for i in range(len(evs))]
            assert got == exp, (got[:5], exp[:5])
        return state

    def test_two_phase_straddling_prepares_one_dispatch(self, n_dev):
        """Cross-shard two-phase pairs whose pending lands in an
        EARLIER prepare than its post/void, all inside one scanned
        window: the in-dispatch carry must expose prepare b's writes to
        prepare b+1 on every shard, exactly like W separate
        dispatches."""
        rng = np.random.default_rng(23)
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 41)]
        oracle, router, state = self._fresh(n_dev, accts)
        nid, ts = 10 ** 6, 10 ** 9
        pendings = []
        w, tss = [], []
        for b in range(4):
            evs = []
            for dr, cr in _cross_shard_pairs(n_dev, 12, rng):
                if b < 2 or not pendings:
                    evs.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=cr,
                        amount=int(rng.integers(1, 30)), ledger=1,
                        code=1, flags=PEND))
                    pendings.append(nid)
                else:
                    pid = pendings.pop(0)
                    f = POST if rng.random() < 0.5 else VOID
                    evs.append(Transfer(
                        id=nid, pending_id=pid,
                        amount=AMOUNT_MAX if f == POST else 0, flags=f))
                nid += 1
            ts += 300
            w.append(evs)
            tss.append(ts)
        state = self._window(oracle, router, state, w, tss)
        # The whole clean window took ONE fused dispatch.
        assert router.window_routes == {"partitioned_chain": 1}
        assert router.chain_batch_fallbacks == {}
        assert router.host_fallbacks == 0
        if n_dev > 1:
            assert router.cross_shard_transfers > 0
        dd = partitioned_state_digest(state)
        assert dd == partitioned_oracle_digest(oracle, A_CAP, n_dev)

    def test_poisoned_window_parity_vs_per_batch(self, n_dev):
        """A limit-cascade prepare (e3 headroom proof) poisons the
        chain mid-window: the prefix stays committed, prepare k replays
        per-batch (plain -> fixpoint escalation ON DEVICE), the suffix
        re-windows — and the final state is bit-identical to running
        the whole workload through the per-batch ladder, and to the
        oracle, with zero host fallbacks on both routes."""
        rng = np.random.default_rng(29)
        accts = [Account(id=i, ledger=1, code=1,
                         flags=DR_LIMIT if i <= 4 else 0)
                 for i in range(1, 41)]
        oracle, router, state = self._fresh(n_dev, accts)
        oracle_b, router_b, state_b = self._fresh(n_dev, accts)
        nid, ts = 10 ** 6, 10 ** 9
        windows = []
        for wi in range(2):
            w, tss = [], []
            for b in range(3):
                evs = [Transfer(id=nid + i, debit_account_id=dr,
                                credit_account_id=cr,
                                amount=int(rng.integers(1, 30)),
                                ledger=1, code=1)
                       for i, (dr, cr) in enumerate(
                           _cross_shard_pairs(n_dev, 8, rng))]
                nid += 8
                if wi == 0 and b == 1:
                    # Debit off a DR_LIMIT account beyond its funded
                    # credits: the plain tier's headroom proof falls
                    # back limit_only, poisoning the chain at k=1.
                    evs.append(Transfer(
                        id=nid, debit_account_id=1,
                        credit_account_id=9, amount=10 ** 6,
                        ledger=1, code=1))
                    nid += 1
                ts += 300
                w.append(evs)
                tss.append(ts)
            windows.append((w, tss))
        for w, tss in windows:
            state = self._window(oracle, router, state, w, tss)
            arrays = [transfers_to_arrays(e) for e in w]
            n_pad = _pad_bucket(max(len(e) for e in w))
            state_b, res_b = router_b._window_per_batch(
                state_b, arrays, tss, n_pad)
            for evs, t, (st, rts) in zip(w, tss, res_b):
                want = oracle_b.create_transfers(evs, t)
                got = [(int(rts[i]), int(st[i]))
                       for i in range(len(evs))]
                assert got == [(r.timestamp, int(r.status))
                               for r in want]
        assert router.host_fallbacks == 0
        assert router_b.host_fallbacks == 0
        # The poison was absorbed per-prepare, not per-window: the
        # chain route still carried the clean windows and the replayed
        # suffix, and the e3 cause landed in the chain counters.
        assert router.window_routes.get("partitioned_chain", 0) >= 2
        assert router.chain_batch_fallbacks.get("e3_limit", 0) >= 1
        assert router.escalations >= 1
        dd = partitioned_state_digest(state)
        assert dd == partitioned_state_digest(state_b)
        assert dd == partitioned_oracle_digest(oracle, A_CAP, n_dev)

    def test_flagged_window_preroutes_per_batch(self, n_dev):
        """Windows carrying flags the plain chain body cannot serve
        (balancing) pre-route to the per-batch ladder — route counters
        must say so, and parity still holds."""
        accts = [Account(id=i, ledger=1, code=1,
                         flags=DR_LIMIT if i <= 2 else 0)
                 for i in range(1, 41)]
        oracle, router, state = self._fresh(n_dev, accts)
        ts = 10 ** 9
        # Fund account 1, then a balancing debit window.
        w = [[Transfer(id=100, debit_account_id=10,
                       credit_account_id=1, amount=50, ledger=1,
                       code=1)],
             [Transfer(id=101, debit_account_id=1,
                       credit_account_id=11, amount=AMOUNT_MAX,
                       ledger=1, code=1, flags=BAL_DR),
              Transfer(id=102, debit_account_id=12,
                       credit_account_id=13, amount=3, ledger=1,
                       code=1)]]
        tss = [ts + 300, ts + 600]
        state = self._window(oracle, router, state, w[:1], tss[:1])
        state = self._window(oracle, router, state, w[1:], tss[1:])
        assert router.window_routes.get("partitioned_per_batch") == 2
        assert "partitioned_chain" not in router.window_routes
        assert router.host_fallbacks == 0
        dd = partitioned_state_digest(state)
        assert dd == partitioned_oracle_digest(oracle, A_CAP, n_dev)


class TestShardLoss:
    def test_resync_required_and_recovers(self):
        """Partitioned shard loss cannot reroute to a single chip (the
        lost range exists nowhere else): the router refuses to serve,
        and resync(oracle) rebuilds via the shard_resync recovery
        cause."""
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
        h = Harness(2, accts)
        h.step([Transfer(id=500, debit_account_id=1,
                         credit_account_id=2, amount=5, ledger=1,
                         code=1)])
        h.router.drop_device(h.mesh.devices.flat[0])
        ev = pad_transfer_events(transfers_to_arrays(
            [Transfer(id=501, debit_account_id=2, credit_account_id=3,
                      amount=1, ledger=1, code=1)]))
        with pytest.raises(RuntimeError, match="resync"):
            h.router.step(h.state, ev, h.ts + 300, 1)
        h.state = h.router.resync(h.oracle)
        assert h.router.shard_resyncs == 1
        assert not h.router.lost_devices
        h.step([Transfer(id=502, debit_account_id=2,
                         credit_account_id=3, amount=1, ledger=1,
                         code=1)])
        h.finish()
