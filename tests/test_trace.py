"""Trace subsystem tests: typed catalog enforcement, StatsD emitter
(DogStatsD line format, best-effort, aggregate-flush reset), ring
eviction self-description, wall-clock anchoring, and the cluster-wide
trace merge — including the ISSUE 5 acceptance: a 3-replica vortex run
with tracing enabled yields ONE merged Chrome/Perfetto JSON with
per-commit-stage spans from every replica on a common timeline.

ISSUE 15 adds causal request tracing: the wire trace-context block
(round trip + bit-flip degradation), deterministic identity and head
sampling, per-pid clock-skew correction (every assembled causal edge
must satisfy parent_ts <= child_ts after correction), causal assembly
over an in-process cluster, and tail retention at a 1% head rate."""

import json
import socket
import time

import pytest

from tigerbeetle_tpu.trace import (
    CATALOG,
    Event,
    EventKind,
    NullTracer,
    StatsD,
    TID_BASE,
    Tracer,
    merge_traces,
)

COMMIT_STAGES = ("commit_prefetch", "commit_execute", "commit_compact",
                 "commit_checkpoint")


# ------------------------------------------------------------- catalog

class TestCatalog:
    def test_freeform_names_are_hard_errors(self):
        t = Tracer()
        with pytest.raises(KeyError):
            t.span("commit")  # the pre-catalog free-form name
        with pytest.raises(KeyError):
            t.count("made_up_metric")
        with pytest.raises(KeyError):
            t.gauge("made_up_gauge", 1.0)

    def test_kind_and_tag_schema_enforced(self):
        t = Tracer()
        with pytest.raises(ValueError):
            t.count(Event.commit_execute)  # a span used as a counter
        with pytest.raises(ValueError):
            t.span(Event.commit_execute, op=1, bogus_tag=2)
        with pytest.raises(ValueError):
            t.gauge(Event.commits, 1.0)  # a counter used as a gauge

    def test_string_names_resolve_to_catalog(self):
        t = Tracer()
        with t.span("commit_execute", op=1, operation=2, window=1):
            pass
        assert t.events[-1]["name"] == "commit_execute"

    def test_null_tracer_accepts_anything(self):
        t = NullTracer()
        with t.span("anything", foo=1):
            pass
        t.count("anything")
        t.gauge("anything", 2.0)
        t.begin("whatever")
        t.end("whatever")

    def test_stable_tid_lanes(self):
        """Each span event owns a fixed lane range; overlapping
        occurrences land on distinct lanes within it."""
        t = Tracer()
        a = t.span(Event.grid_repair_block)
        b = t.span(Event.grid_repair_block)
        with a:
            with b:
                pass
        tids = [e["tid"] for e in t.events]
        base = TID_BASE[Event.grid_repair_block]
        assert sorted(tids) == [base, base + 1]

    def test_catalog_members_are_well_formed(self):
        for ev in Event:
            assert ev.value.doc, f"{ev.name} lacks a doc line"
            assert ev.value.slots >= 1
            assert CATALOG[ev.name] is ev
            if ev.kind is not EventKind.span:
                assert ev.slots == 1


# ------------------------------------------------------ recording tracer

class TestTracer:
    def test_counters_gauges_and_dump(self, tmp_path):
        t = Tracer(pid=3)
        with t.span(Event.commit_execute, op=1, operation=2, window=1):
            pass
        t.count(Event.commits)
        t.count(Event.commits, 2)
        t.gauge(Event.bus_pool_used, 7)
        assert t.counters["commits"] == 3
        assert t.gauges["bus_pool_used"] == 7
        assert {"commit_execute", "commits", "bus_pool_used"} <= t.emitted
        path = tmp_path / "trace.json"
        t.dump_chrome_trace(str(path))
        doc = json.loads(path.read_text())
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert spans[0]["name"] == "commit_execute"
        assert spans[0]["pid"] == 3
        assert doc["metadata"]["counters"]["commits"] == 3
        names = [e for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"]
        assert names[0]["args"]["name"] == "replica 3"

    def test_ring_eviction_is_self_describing(self):
        """ISSUE 5 satellite: the halved ring records a dropped_events
        counter AND an in-trace marker, so a truncated trace says so."""
        t = Tracer(capacity=8)
        for k in range(20):
            with t.span(Event.commit_prefetch, op=k):
                pass
        assert t.dropped_events > 0
        assert t.counters["trace_dropped_events"] == t.dropped_events
        markers = [e for e in t.events
                   if e["name"] == "trace_dropped_events"]
        assert markers and markers[0]["ph"] == "i"
        assert markers[-1]["args"]["dropped_total"] <= t.dropped_events
        assert "trace_dropped_events" in t.emitted

    def test_eviction_never_dents_histograms(self):
        """Distributions accumulate at span close BEFORE ring
        bookkeeping: a tiny ring drops span events, but the duration
        histogram still holds every sample."""
        t = Tracer(capacity=8)
        n = 50
        for k in range(n):
            with t.span(Event.commit_prefetch, op=k):
                pass
        assert t.dropped_events > 0
        assert len([e for e in t.events if e["ph"] == "X"]) < n
        assert t.histograms["commit_prefetch"].count == n
        # The interval aggregates survive eviction identically.
        assert t.aggregates.snapshot()["commit_prefetch"]["count"] == n

    def test_wall_clock_anchored_timestamps(self):
        """ISSUE 5 satellite: ts must be wall-clock comparable across
        processes — two tracers constructed apart agree on 'now'."""
        a = Tracer()
        b = Tracer()
        with a.span(Event.commit_prefetch, op=1):
            pass
        with b.span(Event.commit_prefetch, op=1):
            pass
        ts_a = a.events[0]["ts"]
        ts_b = b.events[0]["ts"]
        now_us = time.time_ns() / 1000.0
        assert abs(ts_a - now_us) < 60e6  # within a minute of wall clock
        assert 0 <= ts_b - ts_a < 10e6  # b's span started after a's

    def test_begin_end_phase_spans(self):
        t = Tracer()
        t.begin(Event.view_change, view=2)
        t.end(Event.view_change)
        t.end(Event.view_change)  # extra end is a no-op
        assert [e["name"] for e in t.events] == ["view_change"]
        assert t.events[0]["args"] == {"view": 2}


# -------------------------------------------------------------- statsd

def _udp_pair():
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(2.0)
    return sock, sock.getsockname()[1]


def _recv_lines(sock, n):
    out = []
    for _ in range(n):
        out.append(sock.recv(4096).decode())
    return out


class TestStatsD:
    def test_dogstatsd_line_format_over_loopback(self):
        """count/gauge/timing + tag rendering against a REAL loopback
        UDP socket (ISSUE 5 satellite)."""
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            s.count("commits", 2, replica=1)
            s.gauge("bus_pool_used", 7.5)
            s.timing("commit_execute", 1.25, op=3)
            lines = _recv_lines(sock, 3)
            assert lines[0] == "tb_tpu.commits:2|c|#replica:1"
            assert lines[1] == "tb_tpu.bus_pool_used:7.5|g"
            assert lines[2] == "tb_tpu.commit_execute:1.25|ms|#op:3"
            s.close()
        finally:
            sock.close()

    def test_best_effort_on_closed_socket(self):
        s = StatsD("127.0.0.1", 1)  # nothing listens; then close it too
        s.close()
        s.count("commits")  # must not raise: metrics are best-effort
        s.gauge("bus_pool_used", 1)
        s.timing("commit_execute", 1.0)

    def test_aggregate_flush_resets(self):
        """Timing aggregates flush as four gauges plus four
        histogram-derived percentile timing (|ms) lines per series on
        the emit interval and RESET after emit (reference statsd.zig
        semantics + the latency-plane percentile flush)."""
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            t = Tracer(statsd=s, emit_interval_s=0.0)  # flush every record
            with t.span(Event.commit_prefetch, op=1):
                pass
            lines = _recv_lines(sock, 8)
            byname = {ln.split(":")[0]: ln for ln in lines}
            assert "tb_tpu.trace.commit_prefetch.count" in byname
            assert byname["tb_tpu.trace.commit_prefetch.count"] \
                .endswith("|g")
            assert {"tb_tpu.trace.commit_prefetch.sum_us",
                    "tb_tpu.trace.commit_prefetch.min_us",
                    "tb_tpu.trace.commit_prefetch.max_us"} \
                <= set(byname)
            for q in ("p50", "p95", "p99", "p999"):
                assert byname[f"tb_tpu.trace.commit_prefetch.{q}"] \
                    .endswith("|ms")
            # Reset after emit: the next flush carries ONLY new spans.
            with t.span(Event.commit_prefetch, op=2):
                pass
            lines = _recv_lines(sock, 8)
            count_line = next(ln for ln in lines if ".count:" in ln)
            assert count_line == "tb_tpu.trace.commit_prefetch.count:1|g"
            assert not t.aggregates.snapshot()  # drained
            s.close()
        finally:
            sock.close()

    def test_flush_percentiles_carry_partition_tags(self):
        """window_commit's hist_tags (route/tier) ride on every flushed
        line, one series per tag class — the per-route latency feed."""
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            t = Tracer(statsd=s, emit_interval_s=0.0)
            with t.span(Event.window_commit, route="chain", tier="scan"):
                pass
            lines = _recv_lines(sock, 8)
            p99 = next(ln for ln in lines if ".p99:" in ln)
            assert p99.startswith("tb_tpu.trace.window_commit.p99:")
            assert p99.endswith("|ms|#route:chain,tier:scan")
            assert all("|#route:chain,tier:scan" in ln for ln in lines)
            s.close()
        finally:
            sock.close()

    def test_counters_emit_immediately_with_tags(self):
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            t = Tracer(statsd=s)
            t.count(Event.serving_recoveries, cause="state_digest")
            line = sock.recv(4096).decode()
            assert line == "tb_tpu.serving_recoveries:1|c|#cause:state_digest"
            s.close()
        finally:
            sock.close()


# --------------------------------------------------------------- merge

class TestMerge:
    def _doc(self, pid, ts0):
        t = Tracer(pid=pid)
        with t.span(Event.commit_execute, op=1, operation=2, window=1):
            pass
        doc = t.chrome_dict()
        for e in doc["traceEvents"]:
            if e["ph"] != "M":
                e["ts"] = ts0
        return doc

    def test_merge_rebases_and_keeps_pids(self):
        merged = merge_traces([self._doc(0, 5_000.0), self._doc(1, 6_000.0),
                               self._doc(2, 5_500.0)])
        assert merged["metadata"]["replicas"] == [0, 1, 2]
        timed = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert [e["ts"] for e in timed] == sorted(e["ts"] for e in timed)
        assert timed[0]["ts"] == 0  # rebased to the earliest event
        assert {e["pid"] for e in timed} == {0, 1, 2}

    def test_merge_renumbers_colliding_pids(self):
        merged = merge_traces([self._doc(0, 1.0), self._doc(0, 2.0)])
        assert merged["metadata"]["replicas"] == [0, 1]


# ---------------------------------------------------- in-process cluster

def test_cluster_merged_trace_has_commit_stages():
    """A traced in-process cluster merges to one timeline: every replica
    contributes prefetch/execute/compact/checkpoint spans under its own
    pid, in monotone order."""
    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Account, Operation, Transfer

    cluster = Cluster(seed=3, replica_count=3,
                      tracer_factory=lambda i: Tracer(pid=i))
    client = cluster.client(9)

    def drive(op, body):
        client.request(op, body)
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()

    drive(Operation.create_accounts, multi_batch.encode(
        [b"".join(Account(id=i, ledger=1, code=1).pack()
                  for i in (1, 2))], 128))
    interval = cluster.replicas[0].options.checkpoint_interval
    for k in range(interval + 1):
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=100 + k, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1).pack()], 128))
    merged = cluster.merged_trace()
    timed = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert [e["ts"] for e in timed] == sorted(e["ts"] for e in timed)
    for pid in (0, 1, 2):
        names = {e["name"] for e in timed if e["pid"] == pid}
        for stage in COMMIT_STAGES:
            assert stage in names, f"replica {pid} lacks {stage}"


# ------------------------------------------------- causal trace context

class TestTraceContext:
    def _ctx(self):
        from tigerbeetle_tpu.trace.context import TraceContext

        return TraceContext(trace_id=(1 << 127) | 0xDEADBEEF,
                            parent_span_id=0x1122334455667788)

    def test_pack_unpack_round_trip(self):
        from tigerbeetle_tpu.trace.context import (CTX_WIRE_SIZE,
                                                   TraceContext)

        ctx = self._ctx()
        raw = ctx.pack()
        assert len(raw) == CTX_WIRE_SIZE == 28
        assert TraceContext.unpack(raw) == ctx
        assert ctx.sampled
        child = ctx.child(0xABCD)
        assert child.trace_id == ctx.trace_id
        assert child.parent_span_id == 0xABCD
        unsampled = TraceContext(trace_id=5, flags=0)
        assert not unsampled.sampled
        assert TraceContext.unpack(unsampled.pack()) == unsampled

    def test_every_single_bit_flip_degrades_to_none(self):
        """The fuzzer's contract, exhaustively: ANY single-bit flip in
        the 28-byte block makes unpack return None (never raise), so a
        corrupt context degrades to unsampled without touching the
        frame."""
        from tigerbeetle_tpu.trace.context import (CTX_WIRE_SIZE,
                                                   TraceContext)

        raw = bytearray(self._ctx().pack())
        for bit in range(CTX_WIRE_SIZE * 8):
            raw[bit // 8] ^= 1 << (bit % 8)
            assert TraceContext.unpack(bytes(raw)) is None, f"bit {bit}"
            raw[bit // 8] ^= 1 << (bit % 8)
        assert TraceContext.unpack(bytes(raw)) is not None  # restored

    def test_header_carries_ctx_outside_checksum(self):
        """The context rides the reserved region OUT of the header
        checksum: a header packs/unpacks with its context intact, and
        zapping the context bytes leaves the header checksum VALID
        while the context reads back as None."""
        from tigerbeetle_tpu.trace.context import CTX_WIRE_SIZE
        from tigerbeetle_tpu.vsr.header import (TRACE_CTX_OFFSET, Command,
                                                Header)

        ctx = self._ctx()
        h = Header(command=Command.request, cluster=1, client=5,
                   request=3, operation=2, trace_ctx=ctx).finalize(b"xy")
        raw = h.pack()
        back = Header.unpack(raw)
        assert back.trace_ctx == ctx
        assert back.valid_checksum()
        zapped = bytearray(raw)
        zapped[TRACE_CTX_OFFSET] ^= 0xFF
        degraded = Header.unpack(bytes(zapped))
        assert degraded.trace_ctx is None
        assert degraded.valid_checksum()  # the frame survives
        assert CTX_WIRE_SIZE + TRACE_CTX_OFFSET <= len(raw)

    def test_deterministic_mint_and_head_sampling(self):
        from tigerbeetle_tpu.trace.context import (head_sampled,
                                                   mint_context,
                                                   mint_trace_id)

        assert mint_trace_id(7, 3) == mint_trace_id(7, 3)
        assert mint_trace_id(7, 3) != mint_trace_id(7, 4)
        assert mint_trace_id(7, 3, seed=1) != mint_trace_id(7, 3, seed=2)
        tid = mint_trace_id(7, 3)
        assert head_sampled(tid, 1.0) and not head_sampled(tid, 0.0)
        assert head_sampled(tid, 0.3) == head_sampled(tid, 0.3)
        hits = sum(head_sampled(mint_trace_id(1, n), 0.25)
                   for n in range(400))
        assert 40 < hits < 160  # ~100 expected; decisions, not coin flips
        # The context is ALWAYS minted; only the flag reflects the head
        # decision (tail retention needs identity on every request).
        ctx = mint_context(9, 1, head_rate=0.0)
        assert ctx.trace_id and not ctx.sampled


# ---------------------------------------------------- skew correction

def _causal_span(pid, name, ts, dur, tid, sid, parent, **extra):
    args = {"trace_id": tid, "span_id": sid, "parent_id": parent}
    args.update(extra)
    return {"name": name, "ph": "X", "ts": float(ts), "dur": float(dur),
            "pid": pid, "tid": 0, "args": args}


def _bus_span(pid, name, ts, dur, csum):
    return {"name": name, "ph": "X", "ts": float(ts), "dur": float(dur),
            "pid": pid, "tid": 0, "args": {"csum": csum}}


class TestSkewCorrection:
    """ISSUE 15 satellite: per-pid clock offsets estimated from matched
    bus send/recv pairs; after correction EVERY assembled causal edge
    satisfies parent_ts <= child_ts."""

    OFFSET_US = 80_000.0  # replica clock runs 80ms BEHIND the client

    def _doc(self):
        from tigerbeetle_tpu.trace import fmt_span_id, fmt_trace_id

        tid = fmt_trace_id(0xFEED)
        root = fmt_span_id(1)
        off = self.OFFSET_US
        events = [
            # client (pid 10): causal root + one send/recv leg.
            _causal_span(10, "client_request", 1_000, 9_000, tid, root,
                         "0" * 16, operation=2),
            _bus_span(10, "bus_send", 1_200, 10, 111),
            _bus_span(10, "bus_recv", 2_600, 10, 222),
            # replica (pid 11): its clock reads 80ms EARLY, so its raw
            # timestamps land BEFORE the client root span started.
            _bus_span(11, "bus_recv", 1_250 - off, 10, 111),
            _causal_span(11, "commit_execute", 2_000 - off, 300, tid,
                         fmt_span_id(2), root, op=1, operation=2,
                         window=1),
            _bus_span(11, "bus_send", 2_500 - off, 10, 222),
        ]
        return {"traceEvents": events, "metadata": {}}

    def test_uncorrected_edges_violate_causality(self):
        from tigerbeetle_tpu.trace.merge import assemble_traces, causal_edges

        asm = assemble_traces(self._doc(), skew_correct=False)
        edges = causal_edges(asm["traces"][0])
        assert edges, "no causal edges assembled"
        assert any(p["ts"] > c["ts"] for p, c in edges), \
            "synthetic skew did not produce a violation (vacuous test)"

    def test_corrected_edges_are_causal(self):
        from tigerbeetle_tpu.trace.merge import assemble_traces, causal_edges

        asm = assemble_traces(self._doc(), skew_correct=True)
        off = asm["clock_offsets_us"].get("11")
        assert off is not None
        assert abs(off + self.OFFSET_US) < 500, off  # ~-80ms recovered
        for t in asm["traces"]:
            for parent, child in causal_edges(t):
                assert parent["ts"] <= child["ts"], \
                    (parent["name"], parent["ts"], child["name"],
                     child["ts"])

    def test_offsets_estimated_from_matched_pairs(self):
        from tigerbeetle_tpu.trace.merge import estimate_clock_offsets

        offsets = estimate_clock_offsets(self._doc())
        assert set(offsets) == {10, 11}
        assert offsets[10] == 0.0
        assert abs(offsets[11] + self.OFFSET_US) < 500


# ------------------------------------------------------ causal assembly

def test_cluster_causal_assembly_end_to_end():
    """ISSUE 15 tentpole on the in-process cluster: every traced client
    request assembles into ONE complete span tree — client_request root
    on the client's pid, the primary's quorum wait, backup acks, and the
    commit all causally inside it, zero orphans — with a non-empty
    per-request critical path whose stages sum to the root's wall
    time."""
    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.trace.merge import assemble_traces, causal_edges
    from tigerbeetle_tpu.types import Account, Operation, Transfer

    cluster = Cluster(seed=3, replica_count=3,
                      tracer_factory=lambda i: Tracer(pid=i))
    client_tracer = Tracer(pid=90)
    client = cluster.client(7, tracer=client_tracer)

    def drive(op, body):
        client.request(op, body)
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()

    drive(Operation.create_accounts, multi_batch.encode(
        [b"".join(Account(id=i, ledger=1, code=1).pack()
                  for i in (1, 2))], 128))
    for k in range(3):
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=700 + k, debit_account_id=1, credit_account_id=2,
                      amount=1 + k, ledger=1, code=1).pack()], 128))
    asm = assemble_traces(cluster.merged_trace())
    assert asm["total"] == 4
    assert asm["complete"] == 4
    assert asm["orphan_spans"] == 0
    for t in asm["traces"]:
        root = t["root"]
        assert root is not None and root["name"] == "client_request"
        assert root["pid"] == 90
        names = {s["name"] for s in t["spans"]}
        assert {"commit_quorum", "replica_ack", "commit_execute"} <= names
        # Backups ack from their own pids: causality crosses processes.
        assert len({s["pid"] for s in t["spans"]}) >= 3
        cp = t["critical_path"]
        assert cp["total_us"] > 0
        # Stage sums cover at least the root's wall time (they can
        # exceed it: commit work runs on every replica in parallel),
        # and the unattributed remainder is never negative.
        assert sum(cp["stages"].values()) >= cp["total_us"] - 0.01
        assert cp["stages"]["network_other_us"] >= 0
        assert cp["owner"] in cp["stages"]
        # One shared clock domain: edges are causal without correction.
        for parent, child in causal_edges(t):
            assert parent["ts"] <= child["ts"] + 1_000.0


# -------------------------------------------------------- tail retention

def test_tail_retention_keeps_flagged_traces_at_one_percent_head():
    """ISSUE 15 acceptance: at a 1% head rate, 100% of the traces tail
    retention flags (SLO breach, fallback, recovery cause) stay kept;
    unflagged traces follow the deterministic head decision."""
    from tigerbeetle_tpu.trace import fmt_trace_id
    from tigerbeetle_tpu.trace.context import head_sampled, mint_context
    from tigerbeetle_tpu.trace.merge import assemble_traces

    t = Tracer(pid=0)
    n = 300
    for k in range(1, n + 1):
        ctx = mint_context(5, k, head_rate=0.01)
        t.record_span(Event.client_request, t.now_ns(), 1_000, ctx=ctx,
                      span_id=t.mint_span_id(), operation=1)
    # Flag three traces the head decision would DROP (the interesting
    # case: tail retention must override a head miss).
    dropped = [fmt_trace_id(mint_context(5, k, head_rate=0.01).trace_id)
               for k in range(1, n + 1)
               if not head_sampled(mint_context(5, k).trace_id, 0.01)]
    flagged = {dropped[0]: "slo_breach", dropped[1]: "fallback",
               dropped[2]: "state_digest"}
    for tid, reason in flagged.items():
        t.keep_trace(tid, reason)
    assert t.counters["trace_tail_keep"] == 3
    merged = merge_traces([t.chrome_dict()])
    assert set(merged["metadata"]["kept_traces"]) == set(flagged)
    asm = assemble_traces(merged, head_rate=0.01)
    by_id = {tr["trace_id"]: tr for tr in asm["traces"]}
    assert asm["total"] == n
    for tid, reason in flagged.items():
        assert by_id[tid]["kept"], tid
        assert by_id[tid]["keep_reason"] == f"tail:{reason}"
    head_kept = [tr for tr in asm["traces"]
                 if tr["keep_reason"] == "head"]
    assert asm["kept_total"] == len(head_kept) + len(flagged)
    assert len(head_kept) < n * 0.1  # ~1% head rate actually thins
    # keep_trace is idempotent: the first reason wins.
    t.keep_trace(next(iter(flagged)), "some_other_reason")
    assert t.kept_traces[next(iter(flagged))] == flagged[
        next(iter(flagged))]


# --------------------------------------------------------------- vortex

@pytest.mark.integration
def test_vortex_merged_trace(tmp_path):
    """ISSUE 5 acceptance: a 3-replica vortex run (REAL processes, real
    TCP) with tracing enabled produces one merged Chrome/Perfetto JSON
    containing prefetch/execute/compact/checkpoint spans from ALL
    replicas on a common timeline — stage names, pid-per-replica, and
    monotone timestamps checked from the loaded JSON."""
    from tigerbeetle_tpu.main import _parse_addresses
    from tigerbeetle_tpu.testing.vortex import VortexSupervisor
    from tigerbeetle_tpu.types import Account, Transfer
    from tigerbeetle_tpu.vsr.client import Client

    supervisor = VortexSupervisor(str(tmp_path), replica_count=3,
                                  seed=41, trace=True)
    try:
        client = Client(cluster=supervisor.cluster, client_id=13,
                        replica_addresses=_parse_addresses(
                            supervisor.addresses))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=1, ledger=1, code=1),
                                        Account(id=2, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
        else:
            raise AssertionError("cluster never became available")
        # Cross the checkpoint interval (16) so every replica runs all
        # four commit stages, checkpoint included.
        for k in range(17):
            client.create_transfers([Transfer(
                id=500 + k, debit_account_id=1, credit_account_id=2,
                amount=1 + k, ledger=1, code=1)])
        client.close()
    finally:
        supervisor.shutdown()  # SIGINT: each replica dumps its trace

    out = tmp_path / "cluster.trace.json"
    merged = supervisor.collect_merged_trace(str(out))
    doc = json.loads(out.read_text())
    assert doc["metadata"]["replicas"] == [0, 1, 2]
    timed = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    ts = [e["ts"] for e in timed]
    assert ts == sorted(ts), "merged timeline is not monotone"
    for pid in (0, 1, 2):
        names = {e["name"] for e in timed if e["pid"] == pid}
        for stage in COMMIT_STAGES:
            assert stage in names, \
                f"replica {pid} trace lacks {stage}: {sorted(names)}"
    # The merge wrote what it returned.
    assert merged["metadata"]["replicas"] == [0, 1, 2]
