"""Trace subsystem tests: typed catalog enforcement, StatsD emitter
(DogStatsD line format, best-effort, aggregate-flush reset), ring
eviction self-description, wall-clock anchoring, and the cluster-wide
trace merge — including the ISSUE 5 acceptance: a 3-replica vortex run
with tracing enabled yields ONE merged Chrome/Perfetto JSON with
per-commit-stage spans from every replica on a common timeline."""

import json
import socket
import time

import pytest

from tigerbeetle_tpu.trace import (
    CATALOG,
    Event,
    EventKind,
    NullTracer,
    StatsD,
    TID_BASE,
    Tracer,
    merge_traces,
)

COMMIT_STAGES = ("commit_prefetch", "commit_execute", "commit_compact",
                 "commit_checkpoint")


# ------------------------------------------------------------- catalog

class TestCatalog:
    def test_freeform_names_are_hard_errors(self):
        t = Tracer()
        with pytest.raises(KeyError):
            t.span("commit")  # the pre-catalog free-form name
        with pytest.raises(KeyError):
            t.count("made_up_metric")
        with pytest.raises(KeyError):
            t.gauge("made_up_gauge", 1.0)

    def test_kind_and_tag_schema_enforced(self):
        t = Tracer()
        with pytest.raises(ValueError):
            t.count(Event.commit_execute)  # a span used as a counter
        with pytest.raises(ValueError):
            t.span(Event.commit_execute, op=1, bogus_tag=2)
        with pytest.raises(ValueError):
            t.gauge(Event.commits, 1.0)  # a counter used as a gauge

    def test_string_names_resolve_to_catalog(self):
        t = Tracer()
        with t.span("commit_execute", op=1, operation=2, window=1):
            pass
        assert t.events[-1]["name"] == "commit_execute"

    def test_null_tracer_accepts_anything(self):
        t = NullTracer()
        with t.span("anything", foo=1):
            pass
        t.count("anything")
        t.gauge("anything", 2.0)
        t.begin("whatever")
        t.end("whatever")

    def test_stable_tid_lanes(self):
        """Each span event owns a fixed lane range; overlapping
        occurrences land on distinct lanes within it."""
        t = Tracer()
        a = t.span(Event.grid_repair_block)
        b = t.span(Event.grid_repair_block)
        with a:
            with b:
                pass
        tids = [e["tid"] for e in t.events]
        base = TID_BASE[Event.grid_repair_block]
        assert sorted(tids) == [base, base + 1]

    def test_catalog_members_are_well_formed(self):
        for ev in Event:
            assert ev.value.doc, f"{ev.name} lacks a doc line"
            assert ev.value.slots >= 1
            assert CATALOG[ev.name] is ev
            if ev.kind is not EventKind.span:
                assert ev.slots == 1


# ------------------------------------------------------ recording tracer

class TestTracer:
    def test_counters_gauges_and_dump(self, tmp_path):
        t = Tracer(pid=3)
        with t.span(Event.commit_execute, op=1, operation=2, window=1):
            pass
        t.count(Event.commits)
        t.count(Event.commits, 2)
        t.gauge(Event.bus_pool_used, 7)
        assert t.counters["commits"] == 3
        assert t.gauges["bus_pool_used"] == 7
        assert {"commit_execute", "commits", "bus_pool_used"} <= t.emitted
        path = tmp_path / "trace.json"
        t.dump_chrome_trace(str(path))
        doc = json.loads(path.read_text())
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert spans[0]["name"] == "commit_execute"
        assert spans[0]["pid"] == 3
        assert doc["metadata"]["counters"]["commits"] == 3
        names = [e for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "process_name"]
        assert names[0]["args"]["name"] == "replica 3"

    def test_ring_eviction_is_self_describing(self):
        """ISSUE 5 satellite: the halved ring records a dropped_events
        counter AND an in-trace marker, so a truncated trace says so."""
        t = Tracer(capacity=8)
        for k in range(20):
            with t.span(Event.commit_prefetch, op=k):
                pass
        assert t.dropped_events > 0
        assert t.counters["trace_dropped_events"] == t.dropped_events
        markers = [e for e in t.events
                   if e["name"] == "trace_dropped_events"]
        assert markers and markers[0]["ph"] == "i"
        assert markers[-1]["args"]["dropped_total"] <= t.dropped_events
        assert "trace_dropped_events" in t.emitted

    def test_eviction_never_dents_histograms(self):
        """Distributions accumulate at span close BEFORE ring
        bookkeeping: a tiny ring drops span events, but the duration
        histogram still holds every sample."""
        t = Tracer(capacity=8)
        n = 50
        for k in range(n):
            with t.span(Event.commit_prefetch, op=k):
                pass
        assert t.dropped_events > 0
        assert len([e for e in t.events if e["ph"] == "X"]) < n
        assert t.histograms["commit_prefetch"].count == n
        # The interval aggregates survive eviction identically.
        assert t.aggregates.snapshot()["commit_prefetch"]["count"] == n

    def test_wall_clock_anchored_timestamps(self):
        """ISSUE 5 satellite: ts must be wall-clock comparable across
        processes — two tracers constructed apart agree on 'now'."""
        a = Tracer()
        b = Tracer()
        with a.span(Event.commit_prefetch, op=1):
            pass
        with b.span(Event.commit_prefetch, op=1):
            pass
        ts_a = a.events[0]["ts"]
        ts_b = b.events[0]["ts"]
        now_us = time.time_ns() / 1000.0
        assert abs(ts_a - now_us) < 60e6  # within a minute of wall clock
        assert 0 <= ts_b - ts_a < 10e6  # b's span started after a's

    def test_begin_end_phase_spans(self):
        t = Tracer()
        t.begin(Event.view_change, view=2)
        t.end(Event.view_change)
        t.end(Event.view_change)  # extra end is a no-op
        assert [e["name"] for e in t.events] == ["view_change"]
        assert t.events[0]["args"] == {"view": 2}


# -------------------------------------------------------------- statsd

def _udp_pair():
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.bind(("127.0.0.1", 0))
    sock.settimeout(2.0)
    return sock, sock.getsockname()[1]


def _recv_lines(sock, n):
    out = []
    for _ in range(n):
        out.append(sock.recv(4096).decode())
    return out


class TestStatsD:
    def test_dogstatsd_line_format_over_loopback(self):
        """count/gauge/timing + tag rendering against a REAL loopback
        UDP socket (ISSUE 5 satellite)."""
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            s.count("commits", 2, replica=1)
            s.gauge("bus_pool_used", 7.5)
            s.timing("commit_execute", 1.25, op=3)
            lines = _recv_lines(sock, 3)
            assert lines[0] == "tb_tpu.commits:2|c|#replica:1"
            assert lines[1] == "tb_tpu.bus_pool_used:7.5|g"
            assert lines[2] == "tb_tpu.commit_execute:1.25|ms|#op:3"
            s.close()
        finally:
            sock.close()

    def test_best_effort_on_closed_socket(self):
        s = StatsD("127.0.0.1", 1)  # nothing listens; then close it too
        s.close()
        s.count("commits")  # must not raise: metrics are best-effort
        s.gauge("bus_pool_used", 1)
        s.timing("commit_execute", 1.0)

    def test_aggregate_flush_resets(self):
        """Timing aggregates flush as four gauges plus four
        histogram-derived percentile timing (|ms) lines per series on
        the emit interval and RESET after emit (reference statsd.zig
        semantics + the latency-plane percentile flush)."""
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            t = Tracer(statsd=s, emit_interval_s=0.0)  # flush every record
            with t.span(Event.commit_prefetch, op=1):
                pass
            lines = _recv_lines(sock, 8)
            byname = {ln.split(":")[0]: ln for ln in lines}
            assert "tb_tpu.trace.commit_prefetch.count" in byname
            assert byname["tb_tpu.trace.commit_prefetch.count"] \
                .endswith("|g")
            assert {"tb_tpu.trace.commit_prefetch.sum_us",
                    "tb_tpu.trace.commit_prefetch.min_us",
                    "tb_tpu.trace.commit_prefetch.max_us"} \
                <= set(byname)
            for q in ("p50", "p95", "p99", "p999"):
                assert byname[f"tb_tpu.trace.commit_prefetch.{q}"] \
                    .endswith("|ms")
            # Reset after emit: the next flush carries ONLY new spans.
            with t.span(Event.commit_prefetch, op=2):
                pass
            lines = _recv_lines(sock, 8)
            count_line = next(ln for ln in lines if ".count:" in ln)
            assert count_line == "tb_tpu.trace.commit_prefetch.count:1|g"
            assert not t.aggregates.snapshot()  # drained
            s.close()
        finally:
            sock.close()

    def test_flush_percentiles_carry_partition_tags(self):
        """window_commit's hist_tags (route/tier) ride on every flushed
        line, one series per tag class — the per-route latency feed."""
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            t = Tracer(statsd=s, emit_interval_s=0.0)
            with t.span(Event.window_commit, route="chain", tier="scan"):
                pass
            lines = _recv_lines(sock, 8)
            p99 = next(ln for ln in lines if ".p99:" in ln)
            assert p99.startswith("tb_tpu.trace.window_commit.p99:")
            assert p99.endswith("|ms|#route:chain,tier:scan")
            assert all("|#route:chain,tier:scan" in ln for ln in lines)
            s.close()
        finally:
            sock.close()

    def test_counters_emit_immediately_with_tags(self):
        sock, port = _udp_pair()
        try:
            s = StatsD("127.0.0.1", port)
            t = Tracer(statsd=s)
            t.count(Event.serving_recoveries, cause="state_digest")
            line = sock.recv(4096).decode()
            assert line == "tb_tpu.serving_recoveries:1|c|#cause:state_digest"
            s.close()
        finally:
            sock.close()


# --------------------------------------------------------------- merge

class TestMerge:
    def _doc(self, pid, ts0):
        t = Tracer(pid=pid)
        with t.span(Event.commit_execute, op=1, operation=2, window=1):
            pass
        doc = t.chrome_dict()
        for e in doc["traceEvents"]:
            if e["ph"] != "M":
                e["ts"] = ts0
        return doc

    def test_merge_rebases_and_keeps_pids(self):
        merged = merge_traces([self._doc(0, 5_000.0), self._doc(1, 6_000.0),
                               self._doc(2, 5_500.0)])
        assert merged["metadata"]["replicas"] == [0, 1, 2]
        timed = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert [e["ts"] for e in timed] == sorted(e["ts"] for e in timed)
        assert timed[0]["ts"] == 0  # rebased to the earliest event
        assert {e["pid"] for e in timed} == {0, 1, 2}

    def test_merge_renumbers_colliding_pids(self):
        merged = merge_traces([self._doc(0, 1.0), self._doc(0, 2.0)])
        assert merged["metadata"]["replicas"] == [0, 1]


# ---------------------------------------------------- in-process cluster

def test_cluster_merged_trace_has_commit_stages():
    """A traced in-process cluster merges to one timeline: every replica
    contributes prefetch/execute/compact/checkpoint spans under its own
    pid, in monotone order."""
    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Account, Operation, Transfer

    cluster = Cluster(seed=3, replica_count=3,
                      tracer_factory=lambda i: Tracer(pid=i))
    client = cluster.client(9)

    def drive(op, body):
        client.request(op, body)
        assert cluster.run(4000, until=lambda: client.idle), \
            cluster.debug_status()

    drive(Operation.create_accounts, multi_batch.encode(
        [b"".join(Account(id=i, ledger=1, code=1).pack()
                  for i in (1, 2))], 128))
    interval = cluster.replicas[0].options.checkpoint_interval
    for k in range(interval + 1):
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=100 + k, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1).pack()], 128))
    merged = cluster.merged_trace()
    timed = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert [e["ts"] for e in timed] == sorted(e["ts"] for e in timed)
    for pid in (0, 1, 2):
        names = {e["name"] for e in timed if e["pid"] == pid}
        for stage in COMMIT_STAGES:
            assert stage in names, f"replica {pid} lacks {stage}"


# --------------------------------------------------------------- vortex

@pytest.mark.integration
def test_vortex_merged_trace(tmp_path):
    """ISSUE 5 acceptance: a 3-replica vortex run (REAL processes, real
    TCP) with tracing enabled produces one merged Chrome/Perfetto JSON
    containing prefetch/execute/compact/checkpoint spans from ALL
    replicas on a common timeline — stage names, pid-per-replica, and
    monotone timestamps checked from the loaded JSON."""
    from tigerbeetle_tpu.main import _parse_addresses
    from tigerbeetle_tpu.testing.vortex import VortexSupervisor
    from tigerbeetle_tpu.types import Account, Transfer
    from tigerbeetle_tpu.vsr.client import Client

    supervisor = VortexSupervisor(str(tmp_path), replica_count=3,
                                  seed=41, trace=True)
    try:
        client = Client(cluster=supervisor.cluster, client_id=13,
                        replica_addresses=_parse_addresses(
                            supervisor.addresses))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=1, ledger=1, code=1),
                                        Account(id=2, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
        else:
            raise AssertionError("cluster never became available")
        # Cross the checkpoint interval (16) so every replica runs all
        # four commit stages, checkpoint included.
        for k in range(17):
            client.create_transfers([Transfer(
                id=500 + k, debit_account_id=1, credit_account_id=2,
                amount=1 + k, ledger=1, code=1)])
        client.close()
    finally:
        supervisor.shutdown()  # SIGINT: each replica dumps its trace

    out = tmp_path / "cluster.trace.json"
    merged = supervisor.collect_merged_trace(str(out))
    doc = json.loads(out.read_text())
    assert doc["metadata"]["replicas"] == [0, 1, 2]
    timed = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    ts = [e["ts"] for e in timed]
    assert ts == sorted(ts), "merged timeline is not monotone"
    for pid in (0, 1, 2):
        names = {e["name"] for e in timed if e["pid"] == pid}
        for stage in COMMIT_STAGES:
            assert stage in names, \
                f"replica {pid} trace lacks {stage}: {sorted(names)}"
    # The merge wrote what it returned.
    assert merged["metadata"]["replicas"] == [0, 1, 2]
