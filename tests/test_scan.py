"""LSM scan machinery: k-way merge, zig-zag intersection, seekable tree
scans, and the forest query engine (differential vs the host indexes).

reference analogs: src/lsm/k_way_merge.zig, zig_zag_merge.zig,
scan_tree.zig, scan_builder.zig, composite_key.zig.
"""

import random

from tigerbeetle_tpu.lsm.forest import Forest
from tigerbeetle_tpu.lsm.grid import Grid, MemoryDevice
from tigerbeetle_tpu.lsm.k_way_merge import k_way_merge
from tigerbeetle_tpu.lsm.query import ForestQuery
from tigerbeetle_tpu.lsm.scan import (
    TreeScan,
    composite_key,
    intersect_scans,
    union_scans,
)
from tigerbeetle_tpu.lsm.zig_zag_merge import zig_zag_intersect
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags as AFF,
    Transfer,
    TransferFlags,
)
from tigerbeetle_tpu.vsr.durable import DurableState
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage


class TestKWayMerge:
    def test_merge_dedupes_newest_first(self):
        newest = [(b"a", 1), (b"c", 1)]
        older = [(b"a", 2), (b"b", 2), (b"c", 2), (b"d", 2)]
        got = list(k_way_merge([newest, older]))
        assert got == [(b"a", 1), (b"b", 2), (b"c", 1), (b"d", 2)]

    def test_merge_random_against_sorted(self):
        rng = random.Random(5)
        sources = []
        expected = {}
        for i in range(6):
            items = sorted(
                (rng.randrange(500).to_bytes(2, "big"), (i, k))
                for k in range(rng.randrange(0, 80)))
            # dedupe within a source (sorted uniq)
            uniq = dict(items)
            sources.append(sorted(uniq.items()))
            for key, value in uniq.items():
                if key not in expected:
                    expected[key] = value
        # lowest source index wins: build expected accordingly
        expected = {}
        for i in reversed(range(len(sources))):
            for key, value in sources[i]:
                expected[key] = value
        got = dict(k_way_merge(sources))
        assert got == expected
        assert list(got) == sorted(got)


class TestZigZag:
    class _Stream:
        def __init__(self, keys):
            self.keys = sorted(keys)
            self.pos = 0

        def peek(self):
            return self.keys[self.pos] if self.pos < len(self.keys) else None

        def next(self):
            self.pos += 1

        def seek(self, key):
            while self.pos < len(self.keys) and self.keys[self.pos] < key:
                self.pos += 1

    def test_intersection(self):
        a = self._Stream([1, 3, 5, 7, 9, 11])
        b = self._Stream([2, 3, 4, 7, 11, 12])
        c = self._Stream([3, 7, 8, 11])
        assert list(zig_zag_intersect([a, b, c])) == [3, 7, 11]

    def test_random_against_set_intersection(self):
        rng = random.Random(9)
        for _ in range(30):
            sets = [set(rng.sample(range(200), rng.randrange(1, 60)))
                    for _ in range(rng.randrange(2, 5))]
            want = sorted(set.intersection(*sets))
            got = list(zig_zag_intersect(
                [self._Stream(sorted(s)) for s in sets]))
            assert got == want


def _tree_with(entries, removes=()):
    grid = Grid(MemoryDevice(8192 * 256), block_size=8192, block_count=256)
    forest = Forest(grid, {"t": (8, 8)})
    tree = forest.trees["t"]
    op = 0
    for k, v in entries:
        tree.put(k, v)
        op += 1
        if op % 7 == 0:
            tree.compact_beat(op * 32)  # scatter tables across levels
    for k in removes:
        tree.remove(k)
    return tree


class TestTreeScan:
    def test_streaming_matches_model_and_seek(self):
        rng = random.Random(3)
        model = {}
        entries = []
        for _ in range(300):
            k = rng.randrange(1000).to_bytes(8, "big")
            v = rng.randrange(2**32).to_bytes(8, "big")
            entries.append((k, v))
            model[k] = v
        removes = rng.sample(sorted(model), 20)
        tree = _tree_with(entries, removes)
        for k in removes:
            del model[k]
        lo, hi = (100).to_bytes(8, "big"), (800).to_bytes(8, "big")
        want = sorted((k, v) for k, v in model.items() if lo <= k <= hi)
        assert list(TreeScan(tree, lo, hi)) == want
        # seek jumps forward without replaying skipped keys
        scan = TreeScan(tree, lo, hi)
        mid = (400).to_bytes(8, "big")
        scan.seek(mid)
        rest = list(scan)
        assert rest == [(k, v) for k, v in want if k >= mid]

    def test_union_and_intersection_of_scans(self):
        t1 = _tree_with([(i.to_bytes(8, "big"), b"1" * 8)
                         for i in range(0, 100, 2)])
        t2 = _tree_with([(i.to_bytes(8, "big"), b"2" * 8)
                         for i in range(0, 100, 3)])
        lo, hi = (0).to_bytes(8, "big"), (99).to_bytes(8, "big")
        union = [int.from_bytes(k, "big")
                 for k, _ in union_scans([TreeScan(t1, lo, hi),
                                          TreeScan(t2, lo, hi)])]
        assert union == sorted(set(range(0, 100, 2)) | set(range(0, 100, 3)))
        inter = [int.from_bytes(k, "big")
                 for k in intersect_scans([TreeScan(t1, lo, hi),
                                           TreeScan(t2, lo, hi)])]
        assert inter == sorted(set(range(0, 100, 2)) & set(range(0, 100, 3)))


class TestForestQuery:
    def _build(self, seed=17, n=300):
        """StateMachine + DurableState flushed through checkpoints."""
        from tigerbeetle_tpu.types import AccountFlags

        rng = random.Random(seed)
        sm = StateMachine(engine="oracle")
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        ts = 10**9
        sm.create_accounts(
            [Account(id=i, ledger=1, code=rng.choice((1, 2)),
                     user_data_64=rng.choice((0, 5)),
                     flags=int(AccountFlags.history) if i % 2 else 0)
             for i in range(1, 9)], ts)
        durable.flush(sm.state)
        tid = 1000
        for batch in range(6):
            ts += 10_000
            events = []
            for _ in range(n // 6):
                dr = rng.randrange(1, 9)
                cr = rng.randrange(1, 9)
                if cr == dr:
                    cr = dr % 8 + 1
                events.append(Transfer(
                    id=tid, debit_account_id=dr, credit_account_id=cr,
                    amount=rng.randrange(1, 50), ledger=1,
                    code=rng.choice((1, 2)),
                    user_data_64=rng.choice((0, 7)),
                    flags=int(TransferFlags.pending) if rng.random() < 0.2
                    else 0))
                tid += 1
            sm.create_transfers(events, ts)
            durable.flush(sm.state)
            durable.compact_beat(batch * 32)
        durable.checkpoint(sm.state)
        return sm, durable

    def test_differential_vs_host_indexes(self):
        sm, durable = self._build()
        query = ForestQuery(durable.forest)
        filters = [
            AccountFilter(account_id=1, limit=8190,
                          flags=int(AFF.debits | AFF.credits)),
            AccountFilter(account_id=3, limit=8190, flags=int(AFF.debits)),
            AccountFilter(account_id=5, limit=8190, flags=int(AFF.credits)),
            AccountFilter(account_id=2, limit=10,
                          flags=int(AFF.debits | AFF.credits)),
            AccountFilter(account_id=4, limit=8190, code=2,
                          flags=int(AFF.debits | AFF.credits)),
            AccountFilter(account_id=6, limit=8190, user_data_64=7,
                          flags=int(AFF.debits | AFF.credits)),
            AccountFilter(account_id=7, limit=5,
                          flags=int(AFF.debits | AFF.credits | AFF.reversed)),
            AccountFilter(account_id=8, limit=8190,
                          timestamp_min=10**9 + 20_000,
                          timestamp_max=10**9 + 40_000,
                          flags=int(AFF.debits | AFF.credits)),
        ]
        for f in filters:
            want = sm.get_account_transfers(f)
            got = query.get_account_transfers(f)
            assert got == want, f"filter {f} diverged"

    def test_balances_and_query_ops_differential(self):
        from tigerbeetle_tpu.types import QueryFilter
        from tigerbeetle_tpu.types import QueryFilterFlags as QFF

        sm, durable = self._build(seed=31)
        query = ForestQuery(durable.forest)
        for f in [
            AccountFilter(account_id=1, limit=8190,
                          flags=int(AFF.debits | AFF.credits)),
            AccountFilter(account_id=3, limit=7, flags=int(AFF.debits)),
            AccountFilter(account_id=5, limit=8190, code=2,
                          flags=int(AFF.debits | AFF.credits | AFF.reversed)),
            AccountFilter(account_id=2, limit=8190,  # no history flag
                          flags=int(AFF.debits | AFF.credits)),
        ]:
            assert (query.get_account_balances(f)
                    == sm.get_account_balances(f)), f
        for f in [
            QueryFilter(limit=8190),
            QueryFilter(limit=8190, ledger=1),
            QueryFilter(limit=8190, code=2),
            QueryFilter(limit=10, user_data_64=7),
            QueryFilter(limit=5, code=1, flags=int(QFF.reversed)),
            QueryFilter(limit=8190, timestamp_min=10**9 + 20_000,
                        timestamp_max=10**9 + 40_000),
            QueryFilter(limit=8190, ledger=1, code=2),
        ]:
            assert query.query_transfers(f) == sm.query_transfers(f), f
            assert query.query_accounts(f) == sm.query_accounts(f), f

    def test_queries_survive_reopen(self):
        sm, durable = self._build(seed=23)
        root = durable.checkpoint(sm.state)
        storage = durable.grid.device.storage
        fresh = DurableState(storage)
        fresh.open(root)
        query = ForestQuery(fresh.forest)
        f = AccountFilter(account_id=1, limit=8190,
                          flags=int(AFF.debits | AFF.credits))
        assert query.get_account_transfers(f) == sm.get_account_transfers(f)
