"""Native storage engine tests: checksum parity and WAL scan differential.

The C++ engine (native/storage_engine.cpp) must agree bit-for-bit with the
Python implementations it replaces — same discipline as every other layer.
"""

import os

import pytest

from tigerbeetle_tpu import native
from tigerbeetle_tpu.vsr.checksum import _SEED, checksum
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.journal import Journal, SlotState
from tigerbeetle_tpu.vsr.storage import (
    FileStorage,
    MemoryStorage,
    TEST_LAYOUT,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def test_checksum_parity():
    rng = os.urandom
    for size in (0, 1, 63, 64, 127, 128, 129, 4096, 100_001):
        data = rng(size)
        for domain in (b"", b"hdr", b"body", b"snap"):
            assert native.checksum_native(data, _SEED + domain) == checksum(
                data, domain), (size, domain)


def _prepare(op, body, parent=0):
    header = Header(command=Command.prepare, cluster=9, op=op, parent=parent)
    return Message(header.finalize(body), body=body)


def _populate(journal):
    parent = 0
    for op in range(1, 9):
        msg = _prepare(op, os.urandom(100 * op), parent)
        journal.append(msg)
        parent = msg.header.checksum


def test_wal_scan_differential(tmp_path):
    """Same WAL bytes, classified by Python (MemoryStorage) and by the
    native scan (FileStorage) — results must agree, including fault
    classifications after corruption."""
    mem = MemoryStorage(TEST_LAYOUT)
    journal = Journal(mem)
    _populate(journal)

    # Corrupt: slot of op 3 -> body byte (faulty); slot of op 5 -> header
    # ring byte (clean via prepare); slot of op 7 -> both (unknown).
    zones = TEST_LAYOUT.zone_offsets
    psize = TEST_LAYOUT.message_size_max
    s3 = journal.slot_for_op(3)
    mem.data[zones["wal_prepares"] + s3 * psize + 260] ^= 0xFF
    s5 = journal.slot_for_op(5)
    mem.data[zones["wal_headers"] + s5 * 256 + 40] ^= 0xFF
    s7 = journal.slot_for_op(7)
    mem.data[zones["wal_prepares"] + s7 * psize + 270] ^= 0xFF
    mem.data[zones["wal_headers"] + s7 * 256 + 40] ^= 0xFF

    path = tmp_path / "wal.data"
    path.write_bytes(bytes(mem.data))

    mem2 = MemoryStorage(TEST_LAYOUT)
    mem2.data[:] = mem.data
    jp = Journal(mem2)
    expected = jp.recover()

    fs = FileStorage(str(path), TEST_LAYOUT)
    assert fs.native is not None
    jn = Journal(fs)
    got = jn.recover()
    fs.close()

    for slot, (e, g) in enumerate(zip(expected, got)):
        assert e.state == g.state, (slot, e.state, g.state)
        if e.header is not None:
            assert g.header is not None
            assert e.header.checksum == g.header.checksum, slot
    assert jp.faulty == jn.faulty  # repair set: faulty + unknown slots
    assert {s for s, x in enumerate(expected)
            if x.state == SlotState.faulty} == {journal.slot_for_op(3)}


def test_native_file_roundtrip(tmp_path):
    path = str(tmp_path / "data")
    fs = FileStorage(path, TEST_LAYOUT, create=True)
    assert fs.native is not None
    fs.write("wal_prepares", 1000, b"hello native")
    fs.sync()
    assert fs.read("wal_prepares", 1000, 12) == b"hello native"
    # beyond-EOF reads are zero-filled like the Python path
    assert fs.read("snapshot", 0, 8) == b"\x00" * 8
    fs.close()
