"""Native storage engine tests: checksum parity and WAL scan differential.

The C++ engine (native/storage_engine.cpp) must agree bit-for-bit with the
Python implementations it replaces — same discipline as every other layer.
"""

import os

import pytest

from tigerbeetle_tpu import native
from tigerbeetle_tpu.vsr.checksum import _SEED, checksum
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.journal import Journal, SlotState
from tigerbeetle_tpu.vsr.storage import (
    FileStorage,
    MemoryStorage,
    TEST_LAYOUT,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def test_checksum_parity():
    rng = os.urandom
    for size in (0, 1, 63, 64, 127, 128, 129, 4096, 100_001):
        data = rng(size)
        for domain in (b"", b"hdr", b"body", b"snap"):
            assert native.checksum_native(data, _SEED + domain) == checksum(
                data, domain), (size, domain)


def _prepare(op, body, parent=0):
    header = Header(command=Command.prepare, cluster=9, op=op, parent=parent)
    return Message(header.finalize(body), body=body)


def _populate(journal):
    parent = 0
    for op in range(1, 9):
        msg = _prepare(op, os.urandom(100 * op), parent)
        journal.append(msg)
        parent = msg.header.checksum


def test_wal_scan_differential(tmp_path):
    """Same WAL bytes, classified by Python (MemoryStorage) and by the
    native scan (FileStorage) — results must agree, including fault
    classifications after corruption."""
    mem = MemoryStorage(TEST_LAYOUT)
    journal = Journal(mem)
    _populate(journal)

    # Corrupt: slot of op 3 -> body byte (faulty); slot of op 5 -> header
    # ring byte (clean via prepare); slot of op 7 -> both (unknown).
    zones = TEST_LAYOUT.zone_offsets
    psize = TEST_LAYOUT.message_size_max
    s3 = journal.slot_for_op(3)
    mem.data[zones["wal_prepares"] + s3 * psize + 260] ^= 0xFF
    s5 = journal.slot_for_op(5)
    mem.data[zones["wal_headers"] + s5 * 256 + 40] ^= 0xFF
    s7 = journal.slot_for_op(7)
    mem.data[zones["wal_prepares"] + s7 * psize + 270] ^= 0xFF
    mem.data[zones["wal_headers"] + s7 * 256 + 40] ^= 0xFF

    path = tmp_path / "wal.data"
    path.write_bytes(bytes(mem.data))

    mem2 = MemoryStorage(TEST_LAYOUT)
    mem2.data[:] = mem.data
    jp = Journal(mem2)
    expected = jp.recover()

    fs = FileStorage(str(path), TEST_LAYOUT)
    assert fs.native is not None
    jn = Journal(fs)
    got = jn.recover()
    fs.close()

    for slot, (e, g) in enumerate(zip(expected, got)):
        assert e.state == g.state, (slot, e.state, g.state)
        if e.header is not None:
            assert g.header is not None
            assert e.header.checksum == g.header.checksum, slot
    assert jp.faulty == jn.faulty  # repair set: faulty + unknown slots
    assert {s for s, x in enumerate(expected)
            if x.state == SlotState.faulty} == {journal.slot_for_op(3)}


def test_native_file_roundtrip(tmp_path):
    path = str(tmp_path / "data")
    fs = FileStorage(path, TEST_LAYOUT, create=True)
    assert fs.native is not None
    fs.write("wal_prepares", 1000, b"hello native")
    fs.sync()
    assert fs.read("wal_prepares", 1000, 12) == b"hello native"
    # beyond-EOF reads are zero-filled like the Python path
    assert fs.read("snapshot", 0, 8) == b"\x00" * 8
    fs.close()


class TestAsyncEngine:
    """The native submission/completion IO engine (reference: the
    io_uring layer, src/io/linux.zig — submit, poll, drain barrier)."""

    def _engine(self, tmp_path):
        from tigerbeetle_tpu import native

        f = native.NativeFile(str(tmp_path / "aio.bin"), 1 << 20, True)
        return native.AsyncEngine(f), f

    def test_writes_visible_after_drain(self, tmp_path):
        from tigerbeetle_tpu import native

        if not native.available():
            pytest.skip("native engine unavailable")
        e, f = self._engine(tmp_path)
        for i in range(32):
            e.submit_write(i * 256, bytes([i]) * 256)
        e.drain(sync=True)
        for i in range(32):
            assert f.read(i * 256, 256) == bytes([i]) * 256
        e.close()

    def test_read_completion_fetch(self, tmp_path):
        from tigerbeetle_tpu import native

        if not native.available():
            pytest.skip("native engine unavailable")
        e, f = self._engine(tmp_path)
        e.submit_write(1000, b"hello world!")
        e.drain()
        rid = e.submit_read(1000, 12)
        assert e.fetch(rid, 12) == b"hello world!"
        e.close()

    def test_file_storage_async_grid_roundtrip(self, tmp_path):
        """Grid-zone writes go through the engine; overlapping cold
        reads drain first; sync() is the durability barrier."""
        from tigerbeetle_tpu import native
        from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, FileStorage

        if not native.available():
            pytest.skip("native engine unavailable")
        st = FileStorage(str(tmp_path / "data.tb"), TEST_LAYOUT, create=True)
        if st.aio is None:
            pytest.skip("async engine not active")
        blocks = {off: bytes([off % 251]) * 512 for off in
                  range(0, 8192, 512)}
        for off, data in blocks.items():
            st.write("grid", off, data)
        # Reads force a drain of overlapping pending writes.
        for off, data in blocks.items():
            assert st.read("grid", off, 512) == data
        st.write("grid", 0, b"\xAA" * 512)
        st.sync()
        assert st.read("grid", 0, 512) == b"\xAA" * 512
        # WAL/superblock zones stay synchronous (durability-ordered).
        st.write("superblock", 0, b"\x55" * 64)
        assert st.read("superblock", 0, 64) == b"\x55" * 64
        st.close()

    def test_replica_on_async_file_storage(self, tmp_path):
        """Format + restart recovery over the async-grid FileStorage."""
        from tigerbeetle_tpu import native
        from tigerbeetle_tpu.state_machine import StateMachine
        from tigerbeetle_tpu.vsr.replica import Replica
        from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, FileStorage

        if not native.available():
            pytest.skip("native engine unavailable")
        path = str(tmp_path / "r0.tb")
        st = FileStorage(path, TEST_LAYOUT, create=True)
        Replica.format(st, cluster=5, replica_id=0, replica_count=1)
        st.sync()

        class _NullBus:
            def send_to_replica(self, dst, msg):
                pass

            def send_to_client(self, cid, msg):
                pass

        class _Time:
            now = 1_700_000_000 * 10**9

            def monotonic(self):
                return self.now

            def realtime(self):
                return self.now

        r = Replica(cluster=5, replica_id=0, replica_count=1, storage=st,
                    bus=_NullBus(), time=_Time(),
                    state_machine_factory=lambda: StateMachine(
                        engine="oracle"))
        r.open()
        assert r.status == "normal"
        st.close()

    def test_sticky_write_failure_and_double_fetch(self, tmp_path):
        """A failed async write latches: every later drain reports it;
        fetching a consumed/unknown id errors instead of hanging."""
        from tigerbeetle_tpu import native

        if not native.available():
            pytest.skip("native engine unavailable")
        e, f = self._engine(tmp_path)
        e.submit_write(0, b"ok" * 8)
        rid = e.submit_read(0, 4)
        e.fetch(rid, 4)
        with pytest.raises(KeyError):
            e.fetch(rid, 4)  # already fetched: no deadlock
        with pytest.raises(KeyError):
            e.fetch(999999, 4)  # never issued
        e.drain()
        # Write beyond any plausible file bound via a bad fd engine:
        bad = native.AsyncEngine.__new__(native.AsyncEngine)
        bad.lib = e.lib
        bad.handle = e.lib.tbio_create(-1, 1)  # invalid fd: writes fail
        assert bad.handle
        bad.submit_write(0, b"x")
        with pytest.raises(RuntimeError):
            bad.drain()
        with pytest.raises(RuntimeError):
            bad.drain()  # sticky
        bad.close()
        e.close()


def test_async_write_pair_tracked():
    """Ordered tracked write pair: data2 lands strictly after data1, and
    the completion is reported through poll/fetch (the async WAL append
    primitive)."""
    import tempfile

    from tigerbeetle_tpu import native as native_mod

    if not native_mod.available():
        import pytest

        pytest.skip("native engine unavailable")
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/pairfile"
        nf = native_mod.NativeFile(path, 1 << 16, True)
        eng = native_mod.AsyncEngine(nf)
        tok = eng.submit_write_pair(0, b"A" * 512, 4096, b"B" * 64)
        assert tok > 0
        # fetch blocks until both writes land, in order.
        eng.fetch(tok)
        assert nf.read(0, 512) == b"A" * 512
        assert nf.read(4096, 64) == b"B" * 64
        # poll on a reaped token reports nothing.
        assert tok not in eng.poll()
        eng.close()
        nf.close()


def test_journal_async_append_and_recovery():
    """Async journal append: non-blocking submit, reads served from the
    pending buffer, deferred durability callback, and a clean recovery
    scan in a fresh process-equivalent (new Journal over the same file)."""
    import tempfile

    from tigerbeetle_tpu import native as native_mod
    from tigerbeetle_tpu.vsr.header import Command, Header, Message
    from tigerbeetle_tpu.vsr.journal import Journal, SlotState
    from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, FileStorage

    if not native_mod.available():
        import pytest

        pytest.skip("native engine unavailable")
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/data"
        st = FileStorage(path, TEST_LAYOUT, create=True)
        j = Journal(st)
        fired = []
        msgs = []
        for op in range(1, 4):
            h = Header(command=Command.prepare, cluster=7, replica=0,
                       view=1, op=op, operation=1)
            body = bytes([op]) * 100
            m = Message(header=h.finalize(body), body=body)
            msgs.append(m)
            durable_now = j.append(m, on_durable=lambda op=op: fired.append(op))
            assert durable_now is False  # async path engaged
            # The in-flight slot serves reads from the retained message.
            got = j.read_prepare(op)
            assert got is not None and got.header.checksum == m.header.checksum
        j.wait_all()
        assert fired == [1, 2, 3]
        assert not j._pending and not j._pending_by_slot
        # Disk now agrees with memory.
        for m in msgs:
            got = j.read_prepare(m.header.op)
            assert got is not None and got.header.checksum == m.header.checksum
        st.close()
        # Fresh journal over the same file: recovery classifies the slots.
        st2 = FileStorage(path, TEST_LAYOUT, create=False)
        j2 = Journal(st2)
        slots = j2.recover()
        for m in msgs:
            s = slots[j2.slot_for_op(m.header.op)]
            assert s.state == SlotState.clean
            assert s.header.checksum == m.header.checksum
        st2.close()


def test_journal_same_slot_serializes():
    """Two in-flight appends to one slot must not reorder: the second
    append settles the first before submitting (ring wrap / repair
    overwrite)."""
    import tempfile

    from tigerbeetle_tpu import native as native_mod
    from tigerbeetle_tpu.vsr.header import Command, Header, Message
    from tigerbeetle_tpu.vsr.journal import Journal
    from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, FileStorage

    if not native_mod.available():
        import pytest

        pytest.skip("native engine unavailable")
    with tempfile.TemporaryDirectory() as d:
        st = FileStorage(f"{d}/data", TEST_LAYOUT, create=True)
        j = Journal(st)
        fired = []
        wrap = TEST_LAYOUT.slot_count
        for op in (5, 5 + wrap):  # same slot
            h = Header(command=Command.prepare, cluster=7, replica=0,
                       view=1, op=op, operation=1)
            m = Message(header=h.finalize(b"x"), body=b"x")
            j.append(m, on_durable=lambda op=op: fired.append(op))
        # The second append settled the first but DEFERRED its callback
        # (mid-append firing could reenter the replica).
        assert fired == []
        assert j._deferred
        j.wait_all()
        assert fired == [5, 5 + wrap]  # append order preserved
        got = j.read_prepare(5 + wrap)
        assert got is not None and got.header.op == 5 + wrap
        assert j.read_prepare(5) is None  # overwritten by the wrap
        st.close()


class TestGridReadAhead:
    """Async block read-ahead through the native engine (reference:
    every read is an io_uring submission the event loop outlives,
    src/storage.zig:177): prefetch_async submits, the next read of the
    block collects the completed data, and a stale buffer (extent
    rewritten after submit) falls back to the exact synchronous read."""

    def _grid(self, tmp_path):
        from tigerbeetle_tpu import native as native_mod
        from tigerbeetle_tpu.lsm.grid import Grid
        from tigerbeetle_tpu.vsr.durable import _ZoneDevice
        from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, FileStorage

        if not native_mod.available():
            pytest.skip("native engine unavailable")
        st = FileStorage(str(tmp_path / "d"), TEST_LAYOUT, create=True)
        grid = Grid(_ZoneDevice(st, "grid"),
                    block_size=TEST_LAYOUT.grid_block_size,
                    block_count=TEST_LAYOUT.grid_block_count,
                    cache_sets=2, cache_ways=1)  # tiny: misses are real
        return st, grid

    def test_prefetch_then_read_collects_inflight(self, tmp_path):
        st, grid = self._grid(tmp_path)
        addrs = [grid.write_block(bytes([i]) * 900) for i in range(6)]
        sizes = [900] * 6
        grid.cache.clear()
        n = grid.prefetch_async(list(zip(addrs, sizes)))
        assert n == 6 and len(grid._inflight) == 6
        # Duplicate submit is a no-op while in flight.
        assert grid.prefetch_async(list(zip(addrs, sizes))) == 0
        for i, (a, s) in enumerate(zip(addrs, sizes)):
            assert grid.read_block(a, s)[:8] == bytes([i]) * 8
        assert grid.prefetch_hits == 6 and not grid._inflight
        # And the batched path collects in-flight buffers too.
        grid.cache.clear()
        grid.prefetch_async(list(zip(addrs, sizes)))
        out = grid.read_blocks(list(zip(addrs, sizes)))
        assert [o[:4] for o in out] == [bytes([i]) * 4 for i in range(6)]
        assert grid.prefetch_hits == 12
        st.close()

    def test_stale_prefetch_falls_back_to_sync_read(self, tmp_path):
        """A prefetched buffer that no longer matches the requested
        checksum (its extent was freed and rewritten between submit and
        completion) must be DISCARDED and the block re-read
        synchronously — correctness never rests on the read-ahead."""
        st, grid = self._grid(tmp_path)
        old = grid.write_block(b"\xaa" * 900)
        new = grid.write_block(b"\xbb" * 900)
        grid.cache.clear()
        # Submit a read of the OLD extent, then rebind its in-flight
        # token to the NEW block's key — exactly the state a submit-
        # then-rewrite race leaves behind (the token's buffer holds
        # bytes that do not checksum as `new`).
        assert grid.prefetch_async([(old, 900)]) == 1
        old_key = (old.checksum << 64) | old.index
        new_key = (new.checksum << 64) | new.index
        grid._inflight[new_key] = grid._inflight.pop(old_key)
        data = grid.read_block(new, 900)
        assert data[:4] == b"\xbb" * 4  # sync re-read won
        assert not grid._inflight
        st.close()

    def test_wal_tokens_unaffected_by_read_ahead(self, tmp_path):
        """Journal WAL completion tokens must keep flowing while read-
        ahead tokens sit unfetched in the engine (io_poll filters them)."""
        from tigerbeetle_tpu.vsr.header import Command, Header, Message
        from tigerbeetle_tpu.vsr.journal import Journal

        st, grid = self._grid(tmp_path)
        addr = grid.write_block(b"\xcc" * 900)
        grid.cache.clear()
        assert grid.prefetch_async([(addr, 900)]) == 1
        j = Journal(st)
        fired = []
        h = Header(command=Command.prepare, cluster=7, replica=0,
                   view=1, op=3, operation=1)
        j.append(Message(header=h.finalize(b"y"), body=b"y"),
                 on_durable=lambda: fired.append(3))
        j.wait_all()
        assert fired == [3]
        # The read-ahead is still collectable afterward.
        assert grid.read_block(addr, 900)[:4] == b"\xcc" * 4
        assert grid.prefetch_hits == 1
        st.close()
