"""C++ language client over the shared C ABI (VERDICT r1 #8).

reference pattern: per-language clients as typed wrappers over one C
client (src/clients/c/tb_client.zig), each verified by an echo test and
a sample run against a real cluster (src/clients/*/ci.zig +
testing/tmp_tigerbeetle.zig). Builds clients/cpp with g++ and drives it
against a live 3-replica cluster over TCP.
"""

import os
import shutil
import signal
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [
    pytest.mark.integration,
    pytest.mark.skipif(shutil.which("g++") is None, reason="needs g++"),
]


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


@pytest.fixture(scope="module")
def example_bin(tmp_path_factory):
    out = tmp_path_factory.mktemp("cppclient") / "example"
    subprocess.run(
        ["g++", "-O2", "-std=c++17",
         os.path.join(REPO, "clients", "cpp", "example.cpp"),
         os.path.join(REPO, "native", "tb_client.cpp"),
         "-o", str(out), "-pthread"],
        check=True, timeout=300)
    return str(out)


def test_echo(example_bin):
    p = subprocess.run([example_bin, "echo"], capture_output=True,
                       text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    assert "echo ok" in p.stdout


@pytest.fixture
def cluster3(tmp_path):
    ports = _free_ports(3)
    addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
    env = dict(os.environ)
    procs = []
    for i in range(3):
        path = tmp_path / f"r{i}.tigerbeetle"
        subprocess.run(
            [sys.executable, "-m", "tigerbeetle_tpu", "format",
             "--cluster=11", f"--replica={i}", "--replica-count=3",
             "--small", str(path)],
            check=True, cwd=REPO, env=env, timeout=120,
            stdout=subprocess.DEVNULL)
    for i in range(3):
        path = tmp_path / f"r{i}.tigerbeetle"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "tigerbeetle_tpu", "start",
             f"--addresses={addresses}", f"--replica={i}", "--cluster=11",
             "--engine=oracle", "--small", str(path)],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    try:
        yield addresses
    finally:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_cpp_client_against_cluster(example_bin, cluster3):
    # The client retries internally (hedged resends in the C layer);
    # allow a few attempts while the cluster elects.
    deadline = 120
    import time

    end = time.monotonic() + deadline
    last = None
    while time.monotonic() < end:
        p = subprocess.run([example_bin, "11", cluster3],
                           capture_output=True, text=True, timeout=90)
        last = p
        if p.returncode == 0:
            assert "cpp client ok" in p.stdout
            return
        time.sleep(2)
    raise AssertionError(
        f"cpp client never succeeded: rc={last.returncode}\n"
        f"stdout={last.stdout}\nstderr={last.stderr}")
