"""LSM-served reads with a bounded object cache (VERDICT r1 #4).

reference: src/lsm/groove.zig:885 (get through the object cache),
:996/:1339 (prefetch), src/lsm/set_associative_cache.zig:1. The serving
read path (attach_durable) must (a) return exactly what the host-index
path returns, (b) hit the LSM on cache miss, (c) bound its memory by
construction even when the data set far exceeds the cache.
"""

import numpy as np

from tigerbeetle_tpu.lsm.cache_map import ObjectCache
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags,
    ChangeEventsFilter,
    QueryFilter,
    Transfer,
    TransferFlags,
)
from tigerbeetle_tpu.vsr.durable import DurableState
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage


class TestObjectCache:
    def test_bounded_with_lru_eviction(self):
        cache = ObjectCache(sets=8, ways=2)  # capacity 16
        for k in range(100):
            cache.put(k, k * 10)
        assert len(cache) <= cache.capacity == 16
        assert cache.evictions >= 100 - 16
        # A re-put of an existing key updates in place (no eviction).
        before = cache.evictions
        live = [k for k in range(100) if cache.get(k) is not None]
        for k in live:
            cache.put(k, k * 11)
        assert cache.evictions == before
        assert all(cache.get(k) == k * 11 for k in live)

    def test_lru_within_set(self):
        cache = ObjectCache(sets=1, ways=2)
        cache.put(1, "a")
        cache.put(2, "b")
        assert cache.get(1) == "a"  # touch 1: now 2 is LRU
        cache.put(3, "c")  # evicts 2
        assert cache.get(2) is None
        assert cache.get(1) == "a" and cache.get(3) == "c"

    def test_eviction_never_loses_an_update_through_the_lsm(self):
        """The no-stash substitution's load-bearing property (see
        cache_map.py docstring): an updated-then-evicted entry must be
        re-readable with its NEW value from the layer below — updates
        only enter the cache after the durable flush, so eviction can
        never lose one (reference keeps a stash for its mid-bar window,
        src/lsm/cache_map.zig:1-40)."""
        attached, detached, _durable = _mk_attached(
            n_accounts=300, cache_sets=4, ways=2)  # capacity 8 << 300
        ids = list(range(1, 301))
        # Read a large working set (heavy eviction churn)...
        first = attached.lookup_accounts(ids)
        assert attached._acct_cache.evictions > 0
        # ...then verify every account STILL reads back with the values
        # the detached twin holds (each miss refills from the LSM;
        # nothing was lost or staled by eviction).
        again = attached.lookup_accounts(ids[:64])
        truth = detached.lookup_accounts(ids[:64])
        for got, want in zip(again, truth):
            assert got.debits_posted == want.debits_posted
            assert got.credits_posted == want.credits_posted
        assert len(first) == 300


def _mk_attached(n_accounts=300, n_transfers=2000, cache_sets=8, ways=2):
    """A durable-attached state machine with data far exceeding the
    object caches (capacity 16 each), plus an identical detached twin."""
    rng = np.random.default_rng(5)
    storage = MemoryStorage(TEST_LAYOUT)
    durable = DurableState(storage)
    attached = StateMachine(engine="oracle")
    attached.attach_durable(durable, cache_sets=cache_sets, ways=ways)
    detached = StateMachine(engine="oracle")

    accts = [Account(id=i, ledger=1, code=1 + i % 3,
                     user_data_64=i % 7)
             for i in range(1, n_accounts + 1)]
    ts = 10**9
    for sm in (attached, detached):
        sm.create_accounts(accts, ts)
    pend = int(TransferFlags.pending)
    evs = []
    for i in range(n_transfers):
        evs.append(Transfer(
            id=10**6 + i,
            debit_account_id=int(rng.integers(1, n_accounts + 1)),
            credit_account_id=int(rng.integers(1, n_accounts + 1)),
            amount=int(rng.integers(1, 100)), ledger=1,
            code=1 + i % 3, user_data_64=i % 5,
            flags=pend if i % 11 == 0 else 0))
    for e in evs:
        if e.debit_account_id == e.credit_account_id:
            e.credit_account_id = e.debit_account_id % n_accounts + 1
    flushed = durable.flush(attached.state)
    attached.cache_upsert(*flushed)
    for lo in range(0, n_transfers, 500):
        chunk = evs[lo:lo + 500]
        ts += 600
        for sm in (attached, detached):
            sm.create_transfers(chunk, ts)
        # The replica flushes + cache-upserts after every commit.
        flushed = durable.flush(attached.state)
        attached.cache_upsert(*flushed)
    return attached, detached, durable


class TestLsmServing:
    def test_reads_differential_and_bounded(self):
        attached, detached, _durable = _mk_attached()
        # Lookups: data set (300 + 2000 objects) >> cache capacity (16).
        ids = list(range(1, 301))
        got = attached.lookup_accounts(ids)
        want = detached.lookup_accounts(ids)
        assert got == want
        assert len(attached._acct_cache) <= attached._acct_cache.capacity
        assert attached._acct_cache.misses > 0, "must have hit the LSM"
        tids = [10**6 + i for i in range(0, 2000, 7)]
        assert attached.lookup_transfers(tids) == \
            detached.lookup_transfers(tids)
        assert len(attached._xfer_cache) <= attached._xfer_cache.capacity

        # Queries route through ForestQuery — exactly the host results.
        f = AccountFilter(
            account_id=17,
            flags=int(AccountFilterFlags.debits | AccountFilterFlags.credits),
            limit=8190)
        assert [t.id for t in attached.get_account_transfers(f)] == \
               [t.id for t in detached.get_account_transfers(f)]
        q = QueryFilter(code=2, user_data_64=3, limit=200)
        assert [t.id for t in attached.query_transfers(q)] == \
               [t.id for t in detached.query_transfers(q)]
        qa = QueryFilter(user_data_64=4, limit=100)
        assert [a.id for a in attached.query_accounts(qa)] == \
               [a.id for a in detached.query_accounts(qa)]
        ce = ChangeEventsFilter(limit=50)
        assert attached.get_change_events(ce) == \
            detached.get_change_events(ce)

    def test_cache_written_through_on_flush(self):
        """A cached account updated by a later batch must serve the NEW
        balances after the flush upsert (the groove write-through
        discipline: no read-side invalidation logic)."""
        attached, detached, durable = _mk_attached(
            n_accounts=10, n_transfers=0)
        a1 = attached.lookup_accounts([1])[0]  # warm the cache
        assert a1.debits_posted == 0
        ts = 10**10
        t = [Transfer(id=5_000_000, debit_account_id=1,
                      credit_account_id=2, amount=42, ledger=1, code=1)]
        for sm in (attached, detached):
            sm.create_transfers(t, ts)
        # Before the flush+upsert the cached copy is the pre-update value.
        stale = attached.lookup_accounts([1])[0]
        assert stale.debits_posted == 0
        flushed = durable.flush(attached.state)
        assert 1 in flushed[0] and 5_000_000 in flushed[1]
        attached.cache_upsert(*flushed)
        fresh = attached.lookup_accounts([1])[0]
        assert fresh.debits_posted == 42
        assert fresh == detached.lookup_accounts([1])[0]
        assert attached.lookup_transfers([5_000_000]) == \
            detached.lookup_transfers([5_000_000])


class TestBatchedPrefetch:
    def test_cold_misses_fan_out_in_few_rounds(self):
        """A cold batch of lookups must reach the device as a few batched
        fan-outs (one per level round), never one synchronous read per id
        (reference: prefetch fan-out, src/lsm/groove.zig:996,1339)."""
        attached, detached, durable = _mk_attached()
        # Pace full compaction bars so the memtables stream into L0+
        # tables — the cold path must resolve from BLOCKS, not host dicts.
        for op in range(1, 129):
            durable.compact_beat(op)
        attached._acct_cache.clear()
        attached._xfer_cache.clear()
        # A cold block cache forces device reads.
        durable.grid.cache = type(durable.grid.cache)()
        dev = durable.grid.device
        seen = {"rounds": 0, "reads": 0}
        orig_rb = dev.read_batch

        def counting_rb(reqs):
            seen["rounds"] += 1
            seen["reads"] += len(reqs)
            return orig_rb(reqs)

        dev.read_batch = counting_rb
        try:
            tids = [10**6 + i for i in range(0, 1000, 2)]
            got = attached.lookup_transfers(tids)
        finally:
            dev.read_batch = orig_rb
        assert [t.id for t in got] == tids
        assert seen["reads"] >= 10, "cold batch must actually hit the device"
        # 500 cold ids, but only a handful of fan-out rounds (levels x
        # candidate rounds), not 500 point reads.
        assert seen["rounds"] <= 10, seen

    def test_get_many_matches_get(self):
        """Tree.get_many == {k: Tree.get(k)} across memtable, immutable,
        levels, and tombstones."""
        attached, detached, durable = _mk_attached()
        for op in range(1, 97):  # flush part of the data into tables
            durable.compact_beat(op)
        tree = durable.forest.trees["transfers"]
        assert any(lv.live for lv in tree.levels), \
            "setup must produce table-resident data"
        keys = [(10**6 + i).to_bytes(16, "big") for i in range(0, 2000, 3)]
        keys += [(5).to_bytes(16, "big")]  # absent id
        batched = tree.get_many(keys)
        for k in keys:
            single = tree.get(k)
            assert batched.get(k) == single, k.hex()
