"""Differential test: full-semantics SPMD kernel on an 8-device CPU mesh.

VERDICT r1 #7: the multichip path must exercise the FULL fast-path
semantics, not the order-independent subset. The sharded step must be
bit-identical to the single-chip kernel (which the kernel-parity suite
pins against the oracle), across regular/pending/post/void/chain
batches and across consecutive batches chaining device state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.batch import transfers_to_arrays
from tigerbeetle_tpu.ops.fast_kernels import create_transfers_fast_jit
from tigerbeetle_tpu.ops.ledger import DeviceLedger, pad_transfer_events
from tigerbeetle_tpu.parallel.full_sharded import make_sharded_create_transfers
from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
VOID = int(TransferFlags.void_pending_transfer)
LINKED = int(TransferFlags.linked)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    devices = mesh_utils.create_device_mesh((N_DEV,))
    return Mesh(devices, ("batch",))


def _mixed_batches(rng, n_batches, n, base_id=10**6):
    """Batches mixing regular, linked chains, pending (no timeout), and
    post/void of prior-batch pendings — all fast-path eligible."""
    batches = []
    nid = base_id
    prior_pendings: list[int] = []
    for b in range(n_batches):
        evs = []
        nid_start = nid
        used_pids = set()
        for i in range(n):
            roll = rng.random()
            tid = nid
            nid += 1
            if roll < 0.55:
                evs.append(Transfer(
                    id=tid, debit_account_id=int(rng.integers(0, 45)),
                    credit_account_id=int(rng.integers(1, 45)),
                    amount=int(rng.integers(0, 300)), ledger=1,
                    code=int(rng.integers(0, 2)),
                    flags=LINKED if i % 7 == 0 else 0))
            elif roll < 0.8:
                evs.append(Transfer(
                    id=tid, debit_account_id=int(rng.integers(1, 41)),
                    credit_account_id=1 + int(rng.integers(1, 40)),
                    amount=int(rng.integers(1, 50)), ledger=1, code=1,
                    flags=PEND))
                prior_pendings.append(tid)
            else:
                cands = [p for p in prior_pendings
                         if p < nid_start and p not in used_pids]
                if not cands:
                    evs.append(Transfer(
                        id=tid, debit_account_id=1, credit_account_id=2,
                        amount=1, ledger=1, code=1))
                    continue
                pid = cands[int(rng.integers(0, len(cands)))]
                used_pids.add(pid)
                f = POST if rng.random() < 0.5 else VOID
                evs.append(Transfer(
                    id=tid, pending_id=pid,
                    amount=(2**128 - 1) if f == POST else 0, flags=f))
        for e in evs:
            if (e.flags & (POST | VOID)) == 0 \
                    and e.debit_account_id == e.credit_account_id:
                e.credit_account_id = e.debit_account_id % 40 + 1
        if evs[-1].flags & LINKED:
            evs[-1].flags &= ~LINKED
        batches.append(evs)
    return batches


def _tree_equal(a, b):
    eq = jax.tree.map(lambda x, y: bool((np.asarray(x) == np.asarray(y)).all()),
                      a, b)
    return all(jax.tree.leaves(eq))


class TestFullSharded:
    def test_bit_exact_vs_single_chip_and_oracle(self, mesh):
        rng = np.random.default_rng(41)
        led_single = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
        led_shard = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
        oracle = StateMachineOracle()
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 41)]
        for eng in (led_single, led_shard):
            eng.create_accounts(accts, 50)
        oracle.create_accounts(accts, 50)

        step = make_sharded_create_transfers(mesh)
        ts = 10**9
        for evs in _mixed_batches(rng, n_batches=3, n=200):
            ts += 300
            n = len(evs)
            ev = pad_transfer_events(transfers_to_arrays(evs))

            # Single-chip kernel.
            new_single, out_single = create_transfers_fast_jit(
                led_single.state, ev, np.uint64(ts), np.int32(n))
            led_single.state = new_single
            assert not bool(out_single["fallback"]), "batch must be eligible"

            # Sharded step on the same inputs.
            new_shard, out_shard = step(
                led_shard.state, ev, np.uint64(ts), np.int32(n))
            led_shard.state = new_shard

            # Bit-exact outputs and state.
            assert _tree_equal(out_single, out_shard)
            assert _tree_equal(new_single, new_shard)

            # And both match the oracle's statuses/timestamps.
            want = oracle.create_transfers(evs, ts)
            st = np.asarray(out_shard["r_status"][:n])
            rts = np.asarray(out_shard["r_ts"][:n])
            got = [(int(rts[i]), int(st[i])) for i in range(n)]
            assert got == [(r.timestamp, int(r.status)) for r in want]

        # Full-state ground truth after all batches.
        host = led_shard.to_host()
        assert host.accounts == oracle.accounts
        assert host.transfers == oracle.transfers
        assert host.pending_status == oracle.pending_status
        assert host.account_events == oracle.account_events

    def test_protocol_max_batch_bit_exact(self, mesh):
        """VERDICT r2 weak #7: tiny shapes can hide layout/padding bugs
        in the sharded kernel — run a full protocol-max batch (8190
        events + padding lanes to 8192, so each of the 8 shards carries
        1024 rows with real AND padded lanes) differentially against the
        single-chip kernel and the oracle."""
        from tigerbeetle_tpu.constants import BATCH_MAX

        rng = np.random.default_rng(43)
        led_single = DeviceLedger(a_cap=1 << 10, t_cap=1 << 15)
        led_shard = DeviceLedger(a_cap=1 << 10, t_cap=1 << 15)
        oracle = StateMachineOracle()
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 41)]
        for eng in (led_single, led_shard):
            eng.create_accounts(accts, 50)
        oracle.create_accounts(accts, 50)

        step = make_sharded_create_transfers(mesh)
        ts = 10**9
        batches = _mixed_batches(rng, n_batches=2, n=BATCH_MAX - 2)
        for evs in batches:
            ts += BATCH_MAX + 10
            n = len(evs)
            ev = pad_transfer_events(transfers_to_arrays(evs))
            assert ev["id_lo"].shape[0] % N_DEV == 0, \
                "padded batch must split evenly across the mesh"

            new_single, out_single = create_transfers_fast_jit(
                led_single.state, ev, np.uint64(ts), np.int32(n))
            led_single.state = new_single
            assert not bool(out_single["fallback"]), "batch must be eligible"

            new_shard, out_shard = step(
                led_shard.state, ev, np.uint64(ts), np.int32(n))
            led_shard.state = new_shard

            assert _tree_equal(out_single, out_shard)
            assert _tree_equal(new_single, new_shard)

            want = oracle.create_transfers(evs, ts)
            st = np.asarray(out_shard["r_status"][:n])
            rts = np.asarray(out_shard["r_ts"][:n])
            got = [(int(rts[i]), int(st[i])) for i in range(n)]
            assert got == [(r.timestamp, int(r.status)) for r in want]

    def test_fallback_flag_propagates(self, mesh):
        """An ineligible batch (E1: balancing flag) must report fallback
        with state untouched — identically to single-chip."""
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
        accts = [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
        led.create_accounts(accts, 10)
        evs = [
            Transfer(id=100, debit_account_id=1, credit_account_id=2,
                     amount=5, ledger=1, code=1,
                     flags=int(TransferFlags.balancing_debit)),
            Transfer(id=101, debit_account_id=2, credit_account_id=3,
                     amount=1, ledger=1, code=1),
        ]
        ev = pad_transfer_events(transfers_to_arrays(evs))
        step = make_sharded_create_transfers(mesh)
        # Run single-chip on a copy (fallback writes only scratch dump
        # slots, so compare against the single-chip result, which has the
        # same masked-write contract, not the pristine state).
        state_copy = jax.tree.map(jnp.array, led.state)
        new_single, out_single = create_transfers_fast_jit(
            state_copy, ev, np.uint64(10**9), np.int32(2))
        # The sharded step donates its state buffers like every
        # single-chip tier — snapshot the live rows BEFORE the call.
        before_accounts = {k: np.asarray(v).copy()
                           for k, v in led.state["accounts"].items()
                           if k != "count"}
        new_state, out = step(led.state, ev, np.uint64(10**9), np.int32(2))
        assert bool(out["fallback"]) and bool(out_single["fallback"])
        assert _tree_equal(out, out_single)
        assert _tree_equal(new_state, new_single)
        # Live (non-dump) account rows are untouched.
        for k, v in new_state["accounts"].items():
            if k == "count":
                continue
            assert (np.asarray(v)[:3] == before_accounts[k][:3]).all(), k
