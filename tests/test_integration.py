"""Integration tests: real processes, real TCP, real data files.

reference: src/integration_tests.zig + testing/tmp_tigerbeetle.zig — spawn
the actual `format`/`start` commands on temp files and port-0-style
addresses, then drive them with the client library over the network.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.main import _parse_addresses
from tigerbeetle_tpu.repl import ParseError, Statement, parse_statement
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags as AFF,
    AccountFlags,
    Operation,
    QueryFilter,
    Transfer,
    TransferFlags,
)


class TestReplParser:
    def test_create_accounts(self):
        stmt = parse_statement(
            "create_accounts id=1 code=10 ledger=700 flags=linked|history,"
            " id=2 code=10 ledger=700;")
        assert stmt.operation == Operation.create_accounts
        assert len(stmt.objects) == 2
        a = stmt.objects[0]
        assert a.id == 1 and a.code == 10 and a.ledger == 700
        assert a.flags == int(AccountFlags.linked | AccountFlags.history)
        assert stmt.objects[1].id == 2

    def test_create_transfers(self):
        stmt = parse_statement(
            "create_transfers id=0x10 debit_account_id=1 credit_account_id=2"
            " amount=10 ledger=700 code=10 flags=pending")
        t = stmt.objects[0]
        assert t.id == 16 and t.amount == 10
        assert t.flags == int(TransferFlags.pending)

    def test_lookups_and_filters(self):
        stmt = parse_statement("lookup_accounts id=1, id=2, 3;")
        assert stmt.objects == [1, 2, 3]
        stmt = parse_statement(
            "get_account_transfers account_id=1 flags=debits|credits limit=5")
        f = stmt.objects[0]
        assert f.account_id == 1 and f.limit == 5
        assert f.flags == int(AFF.debits | AFF.credits)
        stmt = parse_statement("query_accounts ledger=700 limit=3")
        assert stmt.objects[0].ledger == 700

    def test_errors(self):
        with pytest.raises(ParseError):
            parse_statement("explode id=1;")
        with pytest.raises(ParseError):
            parse_statement("create_accounts bogus_field=1;")
        with pytest.raises(ParseError):
            parse_statement("create_accounts id=zzz;")
        with pytest.raises(ParseError):
            parse_statement("create_accounts id=1 flags=warp;")
        assert parse_statement("  ;") is None


class TestReplCompletion:
    """reference: src/repl/completion.zig — operations at statement
    start, fields for the active operation, flag names inside flags=."""

    def _c(self, buffer, word):
        from tigerbeetle_tpu.repl import complete_candidates

        return complete_candidates(buffer, word)

    def test_operations_at_statement_start(self):
        got = self._c("create_", "create_")
        assert got == ["create_accounts", "create_transfers"]
        assert "query_accounts" in self._c("", "")
        assert "exit" in self._c("ex", "ex")
        # After a ';' a fresh statement starts.
        got = self._c("lookup_accounts id=1; look", "look")
        assert got == ["lookup_accounts", "lookup_transfers"]

    def test_fields_for_operation(self):
        got = self._c("create_transfers de", "de")
        assert got == ["debit_account_id="]
        got = self._c("create_accounts id=1 le", "le")
        assert got == ["ledger="]
        # Lookups complete only id=.
        assert self._c("lookup_accounts i", "i") == ["id="]
        # Unknown operation: nothing.
        assert self._c("bogus fie", "fie") == []

    def test_flag_names_inside_flags_value(self):
        got = self._c("create_transfers flags=pen", "flags=pen")
        assert got == ["flags=pending"]
        # After '|' the next flag completes with the prior ones kept.
        got = self._c("create_transfers flags=linked|pos",
                      "flags=linked|pos")
        assert got == ["flags=linked|post_pending_transfer"]
        got = self._c("query_accounts flags=rev", "flags=rev")
        assert got == ["flags=reversed"]

    def test_non_flag_values_do_not_complete(self):
        assert self._c("create_accounts id=4", "id=4") == []


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def cluster_processes(tmp_path):
    """3 real replica processes over TCP on a temp dir."""
    ports = _free_ports(3)
    addresses = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # Strip the axon hook trigger: with it set, sitecustomize imports jax
    # at INTERPRETER STARTUP in every child (tens of seconds under load),
    # racing every boot/shutdown timeout in this fixture (same discipline
    # as bench.py _pinned_env).
    env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        for i in range(3):
            path = tmp_path / f"r{i}.tigerbeetle"
            subprocess.run(
                [sys.executable, "-m", "tigerbeetle_tpu", "format",
                 "--cluster=7", f"--replica={i}", "--replica-count=3",
                 "--small", str(path)],
                check=True, cwd="/root/repo", env=env, timeout=60,
                stdout=subprocess.DEVNULL)
            # Server output goes to a FILE, not an unread pipe: a chatty
            # replica (e.g. repair warnings after its peers die) would
            # fill a 64 KiB pipe and then block at exit-time log flush —
            # the shutdown would hang on our own capture.
            log = open(tmp_path / f"r{i}.log", "wb")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "tigerbeetle_tpu", "start",
                 f"--addresses={addresses}", f"--replica={i}", "--cluster=7",
                 "--engine=oracle", "--small", str(path)],
                cwd="/root/repo", env=env,
                stdout=log, stderr=subprocess.STDOUT))
            log.close()
        yield addresses, procs, tmp_path
    finally:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.integration
def test_end_to_end_cluster(cluster_processes):
    addresses, procs, tmp_path = cluster_processes
    from tigerbeetle_tpu.vsr.client import Client

    client = Client(cluster=7, client_id=42,
                    replica_addresses=_parse_addresses(addresses))
    try:
        deadline = time.monotonic() + 60
        results = None
        while time.monotonic() < deadline:
            try:
                results = client.create_accounts([
                    Account(id=1, ledger=700, code=10),
                    Account(id=2, ledger=700, code=10),
                ])
                break
            except TimeoutError:
                continue
        assert results is not None, "cluster never became available"
        # A timed-out first attempt may have committed server-side; the
        # retried request then legitimately reports "exists".
        assert all(r.status.name in ("created", "exists") for r in results)

        results = client.create_transfers([
            Transfer(id=100, debit_account_id=1, credit_account_id=2,
                     amount=250, ledger=700, code=10),
            Transfer(id=101, debit_account_id=2, credit_account_id=1,
                     amount=50, ledger=700, code=10),
        ])
        assert [r.status.name for r in results] == ["created", "created"]

        accounts = client.lookup_accounts([1, 2])
        assert accounts[0].debits_posted == 250
        assert accounts[0].credits_posted == 50
        assert accounts[1].credits_posted == 250

        transfers = client.lookup_transfers([100, 999])
        assert len(transfers) == 1 and transfers[0].amount == 250

        # query path over the wire
        payload = client.query(
            Operation.get_account_transfers,
            AccountFilter(account_id=1, limit=10,
                          flags=int(AFF.debits | AFF.credits)))
        assert len(payload) // 128 == 2
    finally:
        client.close()


@pytest.mark.integration
def test_inspect_after_shutdown(cluster_processes):
    addresses, procs, tmp_path = cluster_processes
    from tigerbeetle_tpu.vsr.client import Client

    client = Client(cluster=7, client_id=43,
                    replica_addresses=_parse_addresses(addresses))
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=9, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
    finally:
        client.close()
    for p in procs:
        p.send_signal(signal.SIGINT)
        # Generous: SIGINT lands between bytecodes; under CPU contention
        # (parallel compiles elsewhere on the box) 10s is flaky.
        p.wait(timeout=45)
    out = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "inspect", "--small",
         str(tmp_path / "r0.tigerbeetle")],
        capture_output=True, text=True, cwd="/root/repo", timeout=60)
    assert out.returncode == 0
    assert "superblock: cluster=7" in out.stdout
    assert "journal:" in out.stdout
    # Full-file verification (reference: inspect_integrity.zig).
    out = subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "inspect", "--small",
         "--integrity", str(tmp_path / "r0.tigerbeetle")],
        capture_output=True, text=True, cwd="/root/repo", timeout=60)
    assert out.returncode == 0, out.stdout
    assert "0 fault(s)" in out.stdout


@pytest.mark.integration
def test_device_engine_real_process(tmp_path):
    """VERDICT r1 #2's literal done-criterion: `tigerbeetle_tpu start`
    (device engine is the default) + REPL-shaped requests execute via the
    vectorized fast kernels in a REAL process over TCP."""
    (port,) = _free_ports(1)
    address = f"127.0.0.1:{port}"
    path = tmp_path / "dev0.tigerbeetle"
    env = dict(os.environ)
    subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format", "--cluster=8",
         "--replica=0", "--replica-count=1", "--small", str(path)],
        check=True, cwd="/root/repo", env=env, timeout=120,
        stdout=subprocess.DEVNULL)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         f"--addresses={address}", "--replica=0", "--cluster=8",
         "--small", str(path)],  # NO --engine flag: device is the default
        cwd="/root/repo", env=env,
        # DEVNULL: an undrained pipe could fill during the first (chatty)
        # kernel compile and block the server's event loop.
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    from tigerbeetle_tpu.repl import parse_statement
    from tigerbeetle_tpu.vsr.client import Client

    client = Client(cluster=8, client_id=77,
                    replica_addresses=_parse_addresses(address))
    try:
        # The REPL statement surface drives the same client path.
        stmt = parse_statement(
            "create_accounts id=1 ledger=9 code=4, id=2 ledger=9 code=4;")
        deadline = time.monotonic() + 240  # first kernel compile is slow
        results = None
        while time.monotonic() < deadline:
            try:
                results = client.create_accounts(stmt.objects)
                break
            except TimeoutError:
                continue
        assert results is not None, "replica never served"
        assert all(r.status.name in ("created", "exists") for r in results)
        stmt = parse_statement(
            "create_transfers id=50 debit_account_id=1 credit_account_id=2 "
            "amount=9 ledger=9 code=4;")
        results = client.create_transfers(stmt.objects)
        assert [r.status.name for r in results] == ["created"]
        accounts = client.lookup_accounts([2])
        assert accounts[0].credits_posted == 9
    finally:
        client.close()
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
