"""TPU smoke tests (VERDICT r1 #10): run the kernel-parity core cases on
the REAL chip when the axon tunnel is live.

Deselected by default (pytest.ini addopts -m "not tpu"); opt in with
`pytest -m tpu`. Each test spawns a fresh subprocess with the axon
platform pinned (the session's conftest pins CPU, and a wedged tunnel
must never hang the suite): if backend init doesn't complete within the
bound, the test SKIPS with the probe diagnostics; a live chip that
produces wrong results FAILS.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
INIT_TIMEOUT_S = float(os.environ.get("TPU_SMOKE_INIT_TIMEOUT_S", "300"))

_SMOKE_SRC = r'''
import json, sys
import numpy as np
from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import Account, Transfer, TransferFlags
import jax
platform = jax.devices()[0].platform
rng = np.random.default_rng(77)
led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
sm = StateMachineOracle()
accts = [Account(id=i, ledger=1, code=1) for i in range(1, 51)]
led.create_accounts(accts, 60)
sm.create_accounts(accts, 60)
ts, nid = 10**9, 10**6
pend = int(TransferFlags.pending)
linked = int(TransferFlags.linked)
for b in range(3):
    evs = []
    for i in range(128):
        evs.append(Transfer(
            id=nid, debit_account_id=int(rng.integers(1, 51)),
            credit_account_id=1 + int(rng.integers(1, 50)),
            amount=int(rng.integers(0, 500)), ledger=1,
            code=int(rng.integers(0, 2)),
            flags=(linked if i % 9 == 0 else (pend if i % 5 == 0 else 0))))
        nid += 1
    for e in evs:
        if e.debit_account_id == e.credit_account_id:
            e.credit_account_id = e.debit_account_id % 50 + 1
    if evs[-1].flags & linked:
        evs[-1].flags &= ~linked
    ts += 200
    got = led.create_transfers(evs, ts)
    want = sm.create_transfers(evs, ts)
    if [(r.timestamp, int(r.status)) for r in got] != \
            [(r.timestamp, int(r.status)) for r in want]:
        print(json.dumps({"ok": False, "batch": b, "platform": platform}))
        sys.exit(1)
host = led.to_host()
ok = (host.accounts == sm.accounts and host.transfers == sm.transfers
      and host.account_events == sm.account_events)
print(json.dumps({"ok": bool(ok), "platform": platform,
                  "fast_batches": led.fast_batches,
                  "fallbacks": led.fallbacks}))
sys.exit(0 if ok else 1)
'''


def _probe_chip() -> dict:
    """Bounded backend-init probe (no repo code) in a fresh process."""
    sys.path.insert(0, REPO)
    try:
        from bench import probe_platform
    finally:
        sys.path.pop(0)
    return probe_platform("axon", INIT_TIMEOUT_S)


def test_kernel_parity_on_chip():
    probe = _probe_chip()
    if not probe.get("ok"):
        pytest.skip(f"TPU tunnel unavailable: {probe.get('error')} "
                    f"(elapsed {probe.get('elapsed_s')}s)")
    env = dict(os.environ, JAX_PLATFORMS="axon")
    p = subprocess.run(
        [sys.executable, "-c", _SMOKE_SRC], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=1500,
    )
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no result: rc={p.returncode}\n{p.stderr[-1200:]}"
    result = json.loads(lines[-1])
    assert result["ok"], result
    assert result["platform"] != "cpu", result


_SERVING_SRC = r'''
import json, sys
import numpy as np
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Account, Transfer
import jax
platform = jax.devices()[0].platform
sm = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 12)
rng = np.random.default_rng(11)
sm.create_accounts([Account(id=i, ledger=1, code=1)
                    for i in range(1, 21)], 30)
ts, nid = 10**9, 10**6
for b in range(3):
    evs = [Transfer(id=nid + i, debit_account_id=1 + int(rng.integers(0, 20)),
                    credit_account_id=1 + int(rng.integers(0, 20)),
                    amount=1 + int(rng.integers(0, 100)), ledger=1, code=1)
           for i in range(64)]
    for e in evs:
        if e.debit_account_id == e.credit_account_id:
            e.credit_account_id = e.debit_account_id % 20 + 1
    nid += 64
    ts += 100
    res = sm.create_transfers(evs, ts)
    if not all(r.status.name == "created" for r in res):
        print(json.dumps({"ok": False, "batch": b}))
        sys.exit(1)
total_d = sum(a.debits_posted for a in sm.state.accounts.values())
total_c = sum(a.credits_posted for a in sm.state.accounts.values())
ok = (total_d == total_c > 0 and sm.led.fallbacks == 0)
print(json.dumps({"ok": bool(ok), "platform": platform,
                  "fast": sm.led.fast_batches, "total": total_d}))
sys.exit(0 if ok else 1)
'''


def test_serving_engine_on_chip():
    """The database serving engine (device StateMachine + write-through
    mirror) on the real chip."""
    probe = _probe_chip()
    if not probe.get("ok"):
        pytest.skip(f"TPU tunnel unavailable: {probe.get('error')}")
    env = dict(os.environ, JAX_PLATFORMS="axon")
    p = subprocess.run(
        [sys.executable, "-c", _SERVING_SRC], capture_output=True,
        text=True, cwd=REPO, env=env, timeout=1500,
    )
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no result: rc={p.returncode}\n{p.stderr[-1200:]}"
    result = json.loads(lines[-1])
    assert result["ok"], result
    assert result["platform"] != "cpu", result
