"""Oracle state-machine tests: table-driven scenarios modeled on the
reference's state_machine_tests.zig (the compatibility suite the TPU kernel
must also pass, via differential testing against this oracle)."""

import pytest

from tigerbeetle_tpu.constants import NS_PER_S, U63_MAX, U128_MAX, TIMESTAMP_MAX
from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    CreateAccountStatus as AS,
    CreateTransferStatus as TS,
    Transfer,
    TransferFlags as TF,
    TransferPendingStatus,
)

TS_BASE = 10_000_000_000  # arbitrary prepare timestamp base


def make_accounts(oracle, specs, timestamp=TS_BASE):
    events = [Account(**spec) for spec in specs]
    return oracle.create_accounts(events, timestamp)


def setup_two_accounts(oracle, **kwargs):
    results = make_accounts(
        oracle,
        [
            dict(id=1, ledger=1, code=1, **kwargs),
            dict(id=2, ledger=1, code=1, **kwargs),
        ],
    )
    assert [r.status for r in results] == [AS.created, AS.created]
    return oracle


class TestCreateAccounts:
    def test_created_and_timestamps(self):
        oracle = StateMachineOracle()
        results = make_accounts(oracle, [dict(id=1, ledger=1, code=1), dict(id=2, ledger=1, code=1)])
        assert [r.status for r in results] == [AS.created, AS.created]
        # timestamp_event = timestamp - len + index + 1 (reference :3031).
        assert [r.timestamp for r in results] == [TS_BASE - 1, TS_BASE]
        assert oracle.accounts[1].timestamp == TS_BASE - 1

    def test_validation_codes(self):
        oracle = StateMachineOracle()
        results = make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, reserved=1),
                dict(id=1, ledger=1, code=1, flags=1 << 10),
                dict(id=0, ledger=1, code=1),
                dict(id=U128_MAX, ledger=1, code=1),
                dict(
                    id=1,
                    ledger=1,
                    code=1,
                    flags=int(
                        AccountFlags.debits_must_not_exceed_credits
                        | AccountFlags.credits_must_not_exceed_debits
                    ),
                ),
                dict(id=1, ledger=1, code=1, debits_pending=1),
                dict(id=1, ledger=1, code=1, debits_posted=1),
                dict(id=1, ledger=1, code=1, credits_pending=1),
                dict(id=1, ledger=1, code=1, credits_posted=1),
                dict(id=1, ledger=0, code=1),
                dict(id=1, ledger=1, code=0),
            ],
        )
        assert [r.status for r in results] == [
            AS.reserved_field,
            AS.reserved_flag,
            AS.id_must_not_be_zero,
            AS.id_must_not_be_int_max,
            AS.flags_are_mutually_exclusive,
            AS.debits_pending_must_be_zero,
            AS.debits_posted_must_be_zero,
            AS.credits_pending_must_be_zero,
            AS.credits_posted_must_be_zero,
            AS.ledger_must_not_be_zero,
            AS.code_must_not_be_zero,
        ]

    def test_exists_variants(self):
        oracle = StateMachineOracle()
        make_accounts(oracle, [dict(id=1, ledger=1, code=1, user_data_64=7)])
        results = make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, user_data_64=7, flags=int(AccountFlags.history)),
                dict(id=1, ledger=1, code=1, user_data_128=9, user_data_64=7),
                dict(id=1, ledger=1, code=1, user_data_64=8),
                dict(id=1, ledger=1, code=1, user_data_64=7, user_data_32=3),
                dict(id=1, ledger=2, code=1, user_data_64=7),
                dict(id=1, ledger=1, code=2, user_data_64=7),
                dict(id=1, ledger=1, code=1, user_data_64=7),
            ],
            timestamp=TS_BASE + 100,
        )
        assert [r.status for r in results] == [
            AS.exists_with_different_flags,
            AS.exists_with_different_user_data_128,
            AS.exists_with_different_user_data_64,
            AS.exists_with_different_user_data_32,
            AS.exists_with_different_ledger,
            AS.exists_with_different_code,
            AS.exists,
        ]
        # exists returns the original timestamp (reference :3101).
        assert results[-1].timestamp == oracle.accounts[1].timestamp

    def test_timestamp_must_be_zero(self):
        oracle = StateMachineOracle()
        results = make_accounts(oracle, [dict(id=1, ledger=1, code=1, timestamp=5)])
        assert results[0].status == AS.timestamp_must_be_zero

    def test_imported_batch_homogeneity(self):
        oracle = StateMachineOracle()
        imported = int(AccountFlags.imported)
        results = make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, flags=imported, timestamp=100),
                dict(id=2, ledger=1, code=1),  # not imported in imported batch
            ],
        )
        assert results[0].status == AS.created
        assert results[0].timestamp == 100
        assert results[1].status == AS.imported_event_expected

        results = make_accounts(
            oracle,
            [
                dict(id=3, ledger=1, code=1),
                dict(id=4, ledger=1, code=1, flags=imported, timestamp=200),
            ],
            timestamp=TS_BASE + 10,
        )
        assert results[0].status == AS.created
        assert results[1].status == AS.imported_event_not_expected

    def test_imported_timestamp_rules(self):
        oracle = StateMachineOracle()
        imported = int(AccountFlags.imported)
        results = make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, flags=imported, timestamp=0),
                dict(id=2, ledger=1, code=1, flags=imported, timestamp=TS_BASE + 50),
                dict(id=3, ledger=1, code=1, flags=imported, timestamp=1000),
                dict(id=4, ledger=1, code=1, flags=imported, timestamp=999),  # regress
                dict(id=5, ledger=1, code=1, flags=imported, timestamp=1000),  # equal = regress
            ],
        )
        assert [r.status for r in results] == [
            AS.imported_event_timestamp_out_of_range,
            AS.imported_event_timestamp_must_not_advance,
            AS.created,
            AS.imported_event_timestamp_must_not_regress,
            AS.imported_event_timestamp_must_not_regress,
        ]


class TestCreateTransfers:
    def test_simple_transfer(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        results = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1)],
            TS_BASE + 100,
        )
        assert results[0].status == TS.created
        assert results[0].timestamp == TS_BASE + 100
        assert oracle.accounts[1].debits_posted == 100
        assert oracle.accounts[2].credits_posted == 100

    def test_validation_codes(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        t0 = TS_BASE + 100
        cases = [
            (Transfer(id=1, flags=1 << 12), TS.reserved_flag),
            (Transfer(id=0), TS.id_must_not_be_zero),
            (Transfer(id=U128_MAX), TS.id_must_not_be_int_max),
            (Transfer(id=1, debit_account_id=0), TS.debit_account_id_must_not_be_zero),
            (Transfer(id=1, debit_account_id=U128_MAX), TS.debit_account_id_must_not_be_int_max),
            (Transfer(id=1, debit_account_id=1, credit_account_id=0), TS.credit_account_id_must_not_be_zero),
            (Transfer(id=1, debit_account_id=1, credit_account_id=U128_MAX), TS.credit_account_id_must_not_be_int_max),
            (Transfer(id=1, debit_account_id=1, credit_account_id=1), TS.accounts_must_be_different),
            (Transfer(id=1, debit_account_id=1, credit_account_id=2, pending_id=3), TS.pending_id_must_be_zero),
            (Transfer(id=1, debit_account_id=1, credit_account_id=2, timeout=1), TS.timeout_reserved_for_pending_transfer),
            (Transfer(id=1, debit_account_id=1, credit_account_id=2, flags=int(TF.closing_debit)), TS.closing_transfer_must_be_pending),
            (Transfer(id=1, debit_account_id=1, credit_account_id=2), TS.ledger_must_not_be_zero),
            (Transfer(id=1, debit_account_id=1, credit_account_id=2, ledger=1), TS.code_must_not_be_zero),
            # Transient failures poison the id, so use fresh ids below.
            (Transfer(id=31, debit_account_id=3, credit_account_id=2, ledger=1, code=1), TS.debit_account_not_found),
            (Transfer(id=32, debit_account_id=1, credit_account_id=3, ledger=1, code=1), TS.credit_account_not_found),
            (Transfer(id=33, debit_account_id=1, credit_account_id=2, ledger=9, code=1), TS.transfer_must_have_the_same_ledger_as_accounts),
        ]
        for i, (t, expected) in enumerate(cases):
            results = oracle.create_transfers([t], t0 + i)
            assert results[0].status == expected, f"case {i}: got {results[0].status!r}"

    def test_accounts_must_have_the_same_ledger(self):
        oracle = StateMachineOracle()
        make_accounts(oracle, [dict(id=1, ledger=1, code=1), dict(id=2, ledger=2, code=1)])
        results = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, ledger=1, code=1)],
            TS_BASE + 100,
        )
        assert results[0].status == TS.accounts_must_have_the_same_ledger

    def test_transient_error_poisons_id(self):
        """reference: state_machine.zig:3215-3252."""
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        r1 = oracle.create_transfers(
            [Transfer(id=7, debit_account_id=1, credit_account_id=99, amount=1, ledger=1, code=1)],
            TS_BASE + 100,
        )
        assert r1[0].status == TS.credit_account_not_found  # transient
        r2 = oracle.create_transfers(
            [Transfer(id=7, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 101,
        )
        assert r2[0].status == TS.id_already_failed

    def test_exists_variants(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        t = Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100,
                     user_data_64=5, ledger=1, code=1)
        assert oracle.create_transfers([t], TS_BASE + 100)[0].status == TS.created

        import dataclasses as dc
        variants = [
            (dc.replace(t, flags=int(TF.pending)), TS.exists_with_different_flags),
            (dc.replace(t, debit_account_id=2, credit_account_id=1), TS.exists_with_different_debit_account_id),
            (dc.replace(t, amount=50), TS.exists_with_different_amount),
            (dc.replace(t, user_data_128=1), TS.exists_with_different_user_data_128),
            (dc.replace(t, user_data_64=6), TS.exists_with_different_user_data_64),
            (dc.replace(t, user_data_32=1), TS.exists_with_different_user_data_32),
            (dc.replace(t, code=9), TS.exists_with_different_code),
            (t, TS.exists),
        ]
        for i, (variant, expected) in enumerate(variants):
            results = oracle.create_transfers([variant], TS_BASE + 200 + i)
            assert results[0].status == expected, f"variant {i}"
        # exists returns original transfer's timestamp.
        assert oracle.create_transfers([t], TS_BASE + 300)[0].timestamp == TS_BASE + 100

    def test_balance_limits(self):
        oracle = StateMachineOracle()
        make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, flags=int(AccountFlags.debits_must_not_exceed_credits)),
                dict(id=2, ledger=1, code=1),
            ],
        )
        # Account 1 has zero credits: any debit > 0 exceeds.
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 100,
        )
        assert r[0].status == TS.exceeds_credits
        # Fund account 1 with 100 credits, then a 100 debit is allowed, 101 is not.
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=2, credit_account_id=1, amount=100, ledger=1, code=1)],
            TS_BASE + 101,
        )
        assert r[0].status == TS.created
        r = oracle.create_transfers(
            [
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=101, ledger=1, code=1),
                Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1),
            ],
            TS_BASE + 103,
        )
        assert [x.status for x in r] == [TS.exceeds_credits, TS.created]

    def test_exceeds_debits(self):
        oracle = StateMachineOracle()
        make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1),
                dict(id=2, ledger=1, code=1, flags=int(AccountFlags.credits_must_not_exceed_debits)),
            ],
        )
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 100,
        )
        assert r[0].status == TS.exceeds_debits

    def test_overflow_codes(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        big = U128_MAX - 10
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=big, ledger=1, code=1)],
            TS_BASE + 100,
        )
        assert r[0].status == TS.created
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=11, ledger=1, code=1)],
            TS_BASE + 101,
        )
        assert r[0].status == TS.overflows_debits_posted

    def test_overflows_timeout(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        timeout = (U63_MAX - TS_BASE) // NS_PER_S + 1
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=1,
                      ledger=1, code=1, timeout=timeout, flags=int(TF.pending))],
            TS_BASE + 100,
        )
        assert r[0].status == TS.overflows_timeout


class TestLinkedChains:
    def test_chain_rollback(self):
        """All-or-nothing: a failing member rolls back the whole chain
        (reference: execute_create :3116-3150)."""
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        linked = int(TF.linked)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=10, ledger=0, code=1),  # fails
            ],
            TS_BASE + 100,
        )
        assert [x.status for x in r] == [
            TS.linked_event_failed,
            TS.linked_event_failed,
            TS.ledger_must_not_be_zero,
        ]
        # Rolled back: no transfers persisted, balances untouched.
        assert 1 not in oracle.transfers
        assert oracle.accounts[1].debits_posted == 0

    def test_chain_success(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        linked = int(TF.linked)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=5, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )
        assert [x.status for x in r] == [TS.created, TS.created]
        assert oracle.accounts[1].debits_posted == 15

    def test_chain_open(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        linked = int(TF.linked)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
            ],
            TS_BASE + 100,
        )
        assert [x.status for x in r] == [TS.linked_event_failed, TS.linked_event_chain_open]
        assert 1 not in oracle.transfers

    def test_chains_are_independent(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        linked = int(TF.linked)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10, ledger=0, code=1),  # breaks chain 1
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=7, ledger=1, code=1),  # independent
            ],
            TS_BASE + 100,
        )
        assert [x.status for x in r] == [
            TS.linked_event_failed,
            TS.ledger_must_not_be_zero,
            TS.created,
        ]
        assert oracle.accounts[1].debits_posted == 7

    def test_chain_sees_intermediate_state(self):
        """Events in a chain see prior members' effects (duplicate id inside
        chain -> exists -> breaks chain since status != created)."""
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        linked = int(TF.linked)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )
        # Duplicate id within chain: the second event sees the first one's
        # insert (flags differ by `linked`) -> exists_with_different_flags;
        # that failure breaks the chain.
        assert [x.status for x in r] == [
            TS.linked_event_failed,
            TS.exists_with_different_flags,
        ]

    def test_rollback_restores_orphans_and_limits(self):
        """After a rolled-back chain, subsequent events see pre-chain state."""
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        linked = int(TF.linked)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10, ledger=0, code=1),
                # id=1 again: chain rolled back, so id 1 was never created.
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=3, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )
        assert [x.status for x in r] == [
            TS.linked_event_failed,
            TS.ledger_must_not_be_zero,
            TS.created,
        ]
        assert oracle.transfers[1].amount == 3


class TestTwoPhase:
    def _pending(self, oracle, tid=1, amount=100, timeout=0, flags=0):
        return oracle.create_transfers(
            [Transfer(id=tid, debit_account_id=1, credit_account_id=2, amount=amount,
                      ledger=1, code=1, timeout=timeout, flags=int(TF.pending) | flags)],
            TS_BASE + 100,
        )

    def test_pending_then_post(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        assert self._pending(oracle)[0].status == TS.created
        assert oracle.accounts[1].debits_pending == 100
        assert oracle.accounts[1].debits_posted == 0

        r = oracle.create_transfers(
            [Transfer(id=2, pending_id=1, amount=U128_MAX, flags=int(TF.post_pending_transfer))],
            TS_BASE + 200,
        )
        assert r[0].status == TS.created
        assert oracle.accounts[1].debits_pending == 0
        assert oracle.accounts[1].debits_posted == 100
        assert oracle.pending_status[oracle.transfers[1].timestamp] == TransferPendingStatus.posted
        # Stored transfer inherits from pending (reference :4195-4209).
        stored = oracle.transfers[2]
        assert stored.debit_account_id == 1 and stored.credit_account_id == 2
        assert stored.ledger == 1 and stored.code == 1 and stored.amount == 100

    def test_partial_post(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        self._pending(oracle)
        r = oracle.create_transfers(
            [Transfer(id=2, pending_id=1, amount=40, flags=int(TF.post_pending_transfer))],
            TS_BASE + 200,
        )
        assert r[0].status == TS.created
        assert oracle.accounts[1].debits_posted == 40
        assert oracle.accounts[1].debits_pending == 0  # full pending amount released

    def test_void(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        self._pending(oracle)
        r = oracle.create_transfers(
            [Transfer(id=2, pending_id=1, flags=int(TF.void_pending_transfer))],
            TS_BASE + 200,
        )
        assert r[0].status == TS.created
        assert oracle.accounts[1].debits_pending == 0
        assert oracle.accounts[1].debits_posted == 0

    def test_post_validation_codes(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        self._pending(oracle)
        post = int(TF.post_pending_transfer)
        void = int(TF.void_pending_transfer)
        cases = [
            (Transfer(id=2, pending_id=1, flags=post | void), TS.flags_are_mutually_exclusive),
            (Transfer(id=2, pending_id=1, flags=post | int(TF.pending)), TS.flags_are_mutually_exclusive),
            (Transfer(id=2, pending_id=0, flags=post), TS.pending_id_must_not_be_zero),
            (Transfer(id=2, pending_id=U128_MAX, flags=post), TS.pending_id_must_not_be_int_max),
            (Transfer(id=2, pending_id=2, flags=post), TS.pending_id_must_be_different),
            (Transfer(id=2, pending_id=1, timeout=1, flags=post), TS.timeout_reserved_for_pending_transfer),
            # pending_transfer_not_found is transient: poisons its id; use a fresh one.
            (Transfer(id=99, pending_id=98, flags=post), TS.pending_transfer_not_found),
            (Transfer(id=2, pending_id=1, debit_account_id=9, flags=post), TS.pending_transfer_has_different_debit_account_id),
            (Transfer(id=2, pending_id=1, credit_account_id=9, flags=post), TS.pending_transfer_has_different_credit_account_id),
            (Transfer(id=2, pending_id=1, ledger=9, flags=post), TS.pending_transfer_has_different_ledger),
            (Transfer(id=2, pending_id=1, code=9, flags=post), TS.pending_transfer_has_different_code),
            (Transfer(id=2, pending_id=1, amount=101, flags=post), TS.exceeds_pending_transfer_amount),
            (Transfer(id=2, pending_id=1, amount=99, flags=void), TS.pending_transfer_has_different_amount),
        ]
        for i, (t, expected) in enumerate(cases):
            r = oracle.create_transfers([t], TS_BASE + 200 + i)
            assert r[0].status == expected, f"case {i}: got {r[0].status!r}"

    def test_pending_transfer_not_pending(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=5, ledger=1, code=1)],
            TS_BASE + 100,
        )
        r = oracle.create_transfers(
            [Transfer(id=2, pending_id=1, flags=int(TF.post_pending_transfer))],
            TS_BASE + 200,
        )
        assert r[0].status == TS.pending_transfer_not_pending

    def test_already_posted_and_voided(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        self._pending(oracle, tid=1)
        self._pending_2 = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10,
                      ledger=1, code=1, flags=int(TF.pending))],
            TS_BASE + 150,
        )
        post = int(TF.post_pending_transfer)
        void = int(TF.void_pending_transfer)
        assert oracle.create_transfers([Transfer(id=3, pending_id=1, amount=U128_MAX, flags=post)], TS_BASE + 200)[0].status == TS.created
        assert oracle.create_transfers([Transfer(id=4, pending_id=1, amount=U128_MAX, flags=post)], TS_BASE + 201)[0].status == TS.pending_transfer_already_posted
        assert oracle.create_transfers([Transfer(id=5, pending_id=2, flags=void)], TS_BASE + 202)[0].status == TS.created
        assert oracle.create_transfers([Transfer(id=6, pending_id=2, flags=void)], TS_BASE + 203)[0].status == TS.pending_transfer_already_voided

    def test_expiry(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        self._pending(oracle, tid=1, timeout=10)
        pending_ts = oracle.transfers[1].timestamp
        expires_at = pending_ts + 10 * NS_PER_S
        # pulse_next_timestamp starts at timestamp_min ("must scan to know",
        # reference :4915-4920); an empty scan then schedules the real expiry.
        assert oracle.pulse_needed(TS_BASE + 101)
        assert oracle.expire_pending_transfers(TS_BASE + 101) == 0
        assert oracle.pulse_next_timestamp == expires_at

        # Posting after expiry fails even before the pulse runs (reference :4145-4153).
        r = oracle.create_transfers(
            [Transfer(id=2, pending_id=1, amount=U128_MAX, flags=int(TF.post_pending_transfer))],
            expires_at + 100,
        )
        assert r[0].status == TS.pending_transfer_expired

        # Pulse expires it.
        assert oracle.pulse_needed(expires_at + 100)
        count = oracle.expire_pending_transfers(expires_at + 100)
        assert count == 1
        assert oracle.accounts[1].debits_pending == 0
        assert oracle.pending_status[pending_ts] == TransferPendingStatus.expired
        assert oracle.pulse_next_timestamp == TIMESTAMP_MAX
        r = oracle.create_transfers(
            [Transfer(id=3, pending_id=1, amount=U128_MAX, flags=int(TF.post_pending_transfer))],
            expires_at + 200,
        )
        assert r[0].status == TS.pending_transfer_expired


class TestClosingAccounts:
    def test_closing_and_reopen(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=0,
                      ledger=1, code=1, flags=int(TF.pending | TF.closing_debit))],
            TS_BASE + 100,
        )
        assert r[0].status == TS.created
        assert oracle.accounts[1].flags & AccountFlags.closed

        # Debiting a closed account fails (transient).
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 200,
        )
        assert r[0].status == TS.debit_account_already_closed

        # Voiding the closing transfer reopens.
        r = oracle.create_transfers(
            [Transfer(id=3, pending_id=1, flags=int(TF.void_pending_transfer))],
            TS_BASE + 300,
        )
        assert r[0].status == TS.created
        assert not (oracle.accounts[1].flags & AccountFlags.closed)

    def test_credit_account_already_closed(self):
        oracle = StateMachineOracle()
        setup_two_accounts(oracle)
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=0,
                      ledger=1, code=1, flags=int(TF.pending | TF.closing_credit))],
            TS_BASE + 100,
        )
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 200,
        )
        assert r[0].status == TS.credit_account_already_closed


class TestBalancing:
    def test_balancing_debit(self):
        """reference: :3841-3853 — amount clamped to what keeps debits <= credits."""
        oracle = StateMachineOracle()
        make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, flags=int(AccountFlags.debits_must_not_exceed_credits)),
                dict(id=2, ledger=1, code=1),
            ],
        )
        # Fund account 1 with 70 credits.
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=2, credit_account_id=1, amount=70, ledger=1, code=1)],
            TS_BASE + 100,
        )
        # Balancing debit of up to 100: clamps to 70.
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=100,
                      ledger=1, code=1, flags=int(TF.balancing_debit))],
            TS_BASE + 200,
        )
        assert r[0].status == TS.created
        assert oracle.transfers[2].amount == 70
        assert oracle.accounts[1].debits_posted == 70

        # Resubmit with same upper bound: exists (reference :4016-4031).
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=100,
                      ledger=1, code=1, flags=int(TF.balancing_debit))],
            TS_BASE + 300,
        )
        assert r[0].status == TS.exists
        # Lower bound than committed amount: exists_with_different_amount.
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=69,
                      ledger=1, code=1, flags=int(TF.balancing_debit))],
            TS_BASE + 400,
        )
        assert r[0].status == TS.exists_with_different_amount

    def test_balancing_credit(self):
        oracle = StateMachineOracle()
        make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1),
                dict(id=2, ledger=1, code=1, flags=int(AccountFlags.credits_must_not_exceed_debits)),
            ],
        )
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=2, credit_account_id=1, amount=30, ledger=1, code=1)],
            TS_BASE + 100,
        )
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=100,
                      ledger=1, code=1, flags=int(TF.balancing_credit))],
            TS_BASE + 200,
        )
        assert r[0].status == TS.created
        assert oracle.transfers[2].amount == 30


class TestImportedTransfers:
    def test_imported_flow(self):
        oracle = StateMachineOracle()
        imported_a = int(AccountFlags.imported)
        make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, flags=imported_a, timestamp=100),
                dict(id=2, ledger=1, code=1, flags=imported_a, timestamp=200),
            ],
        )
        imported_t = int(TF.imported)
        r = oracle.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=150),  # predates cr account
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=200),  # collides with account ts
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=300),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=250),  # regress
            ],
            TS_BASE,
        )
        assert [x.status for x in r] == [
            TS.imported_event_timestamp_must_postdate_credit_account,
            TS.imported_event_timestamp_must_not_regress,
            TS.created,
            TS.imported_event_timestamp_must_not_regress,
        ]
        assert r[2].timestamp == 300

    def test_imported_timeout_must_be_zero(self):
        oracle = StateMachineOracle()
        imported_a = int(AccountFlags.imported)
        make_accounts(
            oracle,
            [
                dict(id=1, ledger=1, code=1, flags=imported_a, timestamp=100),
                dict(id=2, ledger=1, code=1, flags=imported_a, timestamp=200),
            ],
        )
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1,
                      code=1, flags=int(TF.imported | TF.pending), timeout=5, timestamp=300)],
            TS_BASE,
        )
        assert r[0].status == TS.imported_event_timeout_must_be_zero


class TestScopeRollbackIndexes:
    def test_rolled_back_imported_account_frees_timestamp(self):
        """A rolled-back chain must also roll back the timestamp index, or a
        later imported transfer at that timestamp spuriously regresses
        (reference: groove scope_close rolls back all indexes,
        src/lsm/groove.zig:1972-1984)."""
        oracle = StateMachineOracle()
        imported = int(AccountFlags.imported)
        r = oracle.create_accounts(
            [
                Account(id=1, ledger=1, code=1, flags=imported | int(AccountFlags.linked), timestamp=500),
                Account(id=2, ledger=1, code=0, flags=imported, timestamp=600),  # fails
            ],
            TS_BASE,
        )
        assert [x.status for x in r] == [AS.linked_event_failed, AS.code_must_not_be_zero]
        oracle.create_accounts(
            [
                Account(id=3, ledger=1, code=1, flags=imported, timestamp=100),
                Account(id=4, ledger=1, code=1, flags=imported, timestamp=200),
            ],
            TS_BASE + 10,
        )
        r = oracle.create_transfers(
            [Transfer(id=9, debit_account_id=3, credit_account_id=4, amount=1,
                      ledger=1, code=1, flags=int(TF.imported), timestamp=500)],
            TS_BASE + 20,
        )
        assert r[0].status == TS.created


class TestReferenceTables:
    """Round-3 additions mirroring the remaining state_machine_tests.zig
    tables (reference line refs per test)."""

    def test_linked_chain_open_at_batch_end(self):
        """reference: "linked_event_chain_open" :1186 — a batch ending on
        a linked event fails that trailing open chain."""
        oracle = setup_two_accounts(StateMachineOracle())
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1),
             Transfer(id=2, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1, flags=int(TF.linked))],
            TS_BASE + 100)
        assert [x.status for x in r] == [
            TS.created, TS.linked_event_chain_open]
        assert 2 not in oracle.transfers
        assert oracle.accounts[1].debits_posted == 1

    def test_linked_chain_open_batch_of_one(self):
        """reference: :1225 — a single-event batch with flags.linked is an
        open chain."""
        oracle = setup_two_accounts(StateMachineOracle())
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1, flags=int(TF.linked))],
            TS_BASE + 100)
        assert [x.status for x in r] == [TS.linked_event_chain_open]
        assert not oracle.transfers

    def test_linked_chain_open_after_failed_chain(self):
        """reference: :1207 — an earlier failed chain does not absorb a
        trailing open chain; both fail independently."""
        oracle = setup_two_accounts(StateMachineOracle())
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=99,
                      amount=1, ledger=1, code=1, flags=int(TF.linked)),
             Transfer(id=2, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1),
             Transfer(id=3, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1, flags=int(TF.linked))],
            TS_BASE + 100)
        assert [x.status for x in r] == [
            TS.credit_account_not_found, TS.linked_event_failed,
            TS.linked_event_chain_open]
        assert not oracle.transfers

    def test_failed_chain_undone_within_commit(self):
        """reference: :1579 — later events in the SAME batch observe the
        rolled-back state, not the chain's intermediate effects."""
        oracle = setup_two_accounts(StateMachineOracle())
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                      amount=100, ledger=1, code=1, flags=int(TF.linked)),
             Transfer(id=2, debit_account_id=1, credit_account_id=99,
                      amount=1, ledger=1, code=1),
             # Same batch, after the rollback: balances must be pristine.
             Transfer(id=3, debit_account_id=1, credit_account_id=2,
                      amount=7, ledger=1, code=1)],
            TS_BASE + 100)
        assert [x.status for x in r] == [
            TS.linked_event_failed, TS.credit_account_not_found, TS.created]
        assert oracle.accounts[1].debits_posted == 7
        assert 1 not in oracle.transfers and 2 not in oracle.transfers

    def test_failed_transfer_does_not_exist(self):
        """reference: :1533 — a failed (non-transient) create leaves no
        object behind; the id stays usable."""
        oracle = setup_two_accounts(StateMachineOracle())
        r = oracle.create_transfers(
            [Transfer(id=5, debit_account_id=1, credit_account_id=1,
                      amount=1, ledger=1, code=1)],
            TS_BASE + 100)
        assert r[0].status == TS.accounts_must_be_different
        assert 5 not in oracle.transfers
        # Non-transient failure does not poison the id.
        r = oracle.create_transfers(
            [Transfer(id=5, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1)],
            TS_BASE + 200)
        assert r[0].status == TS.created

    def test_two_phase_amount_max_int(self):
        """reference: :1446 — pending amount=maxInt posts in full via the
        maxInt sentinel."""
        oracle = setup_two_accounts(StateMachineOracle())
        r = oracle.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                      amount=U128_MAX, ledger=1, code=1,
                      flags=int(TF.pending))],
            TS_BASE + 100)
        assert r[0].status == TS.created
        assert oracle.accounts[1].debits_pending == U128_MAX
        r = oracle.create_transfers(
            [Transfer(id=2, pending_id=1, amount=U128_MAX,
                      flags=int(TF.post_pending_transfer))],
            TS_BASE + 200)
        assert r[0].status == TS.created
        assert oracle.accounts[1].debits_pending == 0
        assert oracle.accounts[1].debits_posted == U128_MAX
        assert oracle.transfers[2].amount == U128_MAX

    def test_balancing_amount_zero(self):
        """reference: :1723 — balancing with amount=0 clamps to zero and
        still creates (a zero-amount transfer)."""
        oracle = StateMachineOracle()
        make_accounts(oracle, [
            dict(id=1, ledger=1, code=1,
                 flags=int(AccountFlags.debits_must_not_exceed_credits)),
            dict(id=2, ledger=1, code=1)])
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=2, credit_account_id=1,
                      amount=50, ledger=1, code=1)], TS_BASE + 100)
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2,
                      amount=0, ledger=1, code=1,
                      flags=int(TF.balancing_debit))],
            TS_BASE + 200)
        assert r[0].status == TS.created
        assert oracle.transfers[2].amount == 0
        assert oracle.accounts[1].debits_posted == 0

    def test_balancing_amount_max_near_full_balance(self):
        """reference: :1763 — balancing amount=maxInt against a balance
        near maxInt clamps without tripping the overflow guards."""
        oracle = StateMachineOracle()
        make_accounts(oracle, [
            dict(id=1, ledger=1, code=1),
            dict(id=2, ledger=1, code=1)])
        big = U128_MAX - 5
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=2, credit_account_id=1,
                      amount=big, ledger=1, code=1)], TS_BASE + 100)
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2,
                      amount=U128_MAX, ledger=1, code=1,
                      flags=int(TF.balancing_debit))],
            TS_BASE + 200)
        assert r[0].status == TS.created
        assert oracle.transfers[2].amount == big
        assert oracle.accounts[1].debits_posted == big

    def test_balancing_debit_and_credit_combined(self):
        """reference: :1790 — both flags clamp against BOTH accounts; the
        tighter side wins."""
        oracle = StateMachineOracle()
        make_accounts(oracle, [
            dict(id=1, ledger=1, code=1),
            dict(id=2, ledger=1, code=1),
            dict(id=3, ledger=1, code=1)])
        # Debit headroom on 1: 40 credits; credit headroom on 2: 25 debits.
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=3, credit_account_id=1,
                      amount=40, ledger=1, code=1),
             Transfer(id=2, debit_account_id=2, credit_account_id=3,
                      amount=25, ledger=1, code=1)], TS_BASE + 100)
        r = oracle.create_transfers(
            [Transfer(id=3, debit_account_id=1, credit_account_id=2,
                      amount=100, ledger=1, code=1,
                      flags=int(TF.balancing_debit | TF.balancing_credit))],
            TS_BASE + 200)
        assert r[0].status == TS.created
        assert oracle.transfers[3].amount == 25  # tighter (credit) side

    def test_balancing_with_pending(self):
        """reference: :1822 — a balancing PENDING transfer clamps against
        posted+pending and holds the clamped amount."""
        oracle = StateMachineOracle()
        make_accounts(oracle, [
            dict(id=1, ledger=1, code=1,
                 flags=int(AccountFlags.debits_must_not_exceed_credits)),
            dict(id=2, ledger=1, code=1)])
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=2, credit_account_id=1,
                      amount=60, ledger=1, code=1)], TS_BASE + 100)
        r = oracle.create_transfers(
            [Transfer(id=2, debit_account_id=1, credit_account_id=2,
                      amount=100, ledger=1, code=1,
                      flags=int(TF.balancing_debit | TF.pending))],
            TS_BASE + 200)
        assert r[0].status == TS.created
        assert oracle.transfers[2].amount == 60
        assert oracle.accounts[1].debits_pending == 60
        # A second balancing debit now has zero headroom.
        r = oracle.create_transfers(
            [Transfer(id=3, debit_account_id=1, credit_account_id=2,
                      amount=10, ledger=1, code=1,
                      flags=int(TF.balancing_debit))],
            TS_BASE + 300)
        assert r[0].status == TS.created
        assert oracle.transfers[3].amount == 0

    def test_multiple_balancing_debits_single_credit(self):
        """reference: :1853 — successive balancing debits drain the same
        funding credit until headroom is exhausted."""
        oracle = StateMachineOracle()
        make_accounts(oracle, [
            dict(id=1, ledger=1, code=1,
                 flags=int(AccountFlags.debits_must_not_exceed_credits)),
            dict(id=2, ledger=1, code=1)])
        oracle.create_transfers(
            [Transfer(id=1, debit_account_id=2, credit_account_id=1,
                      amount=100, ledger=1, code=1)], TS_BASE + 100)
        amounts = []
        for k, want in enumerate((40, 40, 20, 0)):
            r = oracle.create_transfers(
                [Transfer(id=10 + k, debit_account_id=1,
                          credit_account_id=2, amount=40, ledger=1, code=1,
                          flags=int(TF.balancing_debit))],
                TS_BASE + 200 + k * 100)
            assert r[0].status == TS.created
            amounts.append(oracle.transfers[10 + k].amount)
        assert amounts == [40, 40, 20, 0]
        assert oracle.accounts[1].debits_posted == 100

    def test_per_transfer_balance_invariant(self):
        """reference: :1915 — with flags.history, every account_events
        row carries exact post-event balances; debits-credits invariants
        hold row by row."""
        oracle = StateMachineOracle()
        make_accounts(oracle, [
            dict(id=1, ledger=1, code=1, flags=int(AccountFlags.history)),
            dict(id=2, ledger=1, code=1, flags=int(AccountFlags.history))])
        for k in range(5):
            r = oracle.create_transfers(
                [Transfer(id=100 + k, debit_account_id=1,
                          credit_account_id=2, amount=k + 1,
                          ledger=1, code=1)], TS_BASE + 100 * (k + 1))
            assert r[0].status == TS.created
        running = 0
        rows = [rec for rec in oracle.account_events
                if rec.dr_account.id == 1]
        assert len(rows) == 5
        for k, rec in enumerate(rows):
            running += k + 1
            assert rec.dr_account.debits_posted == running
            assert rec.cr_account.credits_posted == running
            assert rec.dr_account.debits_pending == 0
