"""jaxhound 2.0 static-pass unit tests (quick tier).

The full passes over the serving-entry registry are the gate's
`static` leg (testing/static_smoke.py); these tests pin the PASS
MACHINERY on small synthetic programs — every rule must RED on its
injected violation and stay clean on the paired sanctioned form — plus
the committed tracebudget file's schema and the satellite fixes
(closure-constant recursion into scan/pjit bodies, explicit
stats_unavailable instead of a silent except).
"""

import json
import os
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tigerbeetle_tpu.jaxhound import (
    core, determinism, hostdet, retrace, shardspec)
from tigerbeetle_tpu.jaxhound.registry import Entry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACEBUDGET_PATH = os.path.join(REPO, "perf", "tracebudget_r01.json")


@pytest.fixture(scope="module", autouse=True)
def _release_compiles():
    """This module compiles a few dozen throwaway fixture programs;
    drop them from jax's process-global caches afterwards so the live
    latency bench (test_metrics.py runs next in alphabetical order)
    doesn't inherit the allocation/GC pressure."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


# ------------------------------------------- closure-const recursion

def test_closure_constant_inside_scan_body_is_caught():
    """Satellite: a lookup table baked into a lax.scan BODY never
    surfaces in the top-level consts — the recursive collector must
    find it anyway."""
    table = jnp.arange(4096, dtype=jnp.int32)  # 16 KiB > 4 KiB limit

    def f(x):
        def body(c, xi):
            return c + table[xi], xi
        c, _ = jax.lax.scan(body, jnp.int32(0), x)
        return c

    cj = jax.make_jaxpr(f)(jnp.zeros(4, jnp.int32))
    big = core.closure_constants(cj)
    assert big, "oversized const inside the scan body not reported"
    assert any(size >= 4096 * 4 for _label, size in big)


def test_closure_constant_inside_nested_jit_is_caught():
    """pjit bodies keep their own const list (unlike scan, whose
    consts hoist): the nested-jit case is the one a top-level-only
    scan provably misses."""
    table = jnp.arange(4096, dtype=jnp.int32)

    @jax.jit
    def inner(x):
        return x + table[x]

    cj = jax.make_jaxpr(lambda x: inner(x) * 2)(jnp.zeros(4, jnp.int32))
    assert not cj.consts or all(
        getattr(c, "nbytes", 0) < 4096 * 4 for c in cj.consts), \
        "fixture broke: const hoisted to top level, nested case untested"
    assert core.closure_constants(cj), \
        "oversized const inside a nested jit not reported"


def test_small_consts_stay_clean():
    def f(x):
        return x + jnp.arange(8, dtype=jnp.int32)  # 32 B, under limit

    assert core.closure_constants(jax.make_jaxpr(f)(
        jnp.zeros(8, jnp.int32))) == []


# ------------------------------------------------- stats_unavailable

def test_analyze_lowered_reports_stats_unavailable():
    """Satellite: a failing cost/memory analysis must surface as an
    explicit `stats_unavailable` reason, not a silent pass."""

    class _Compiled:
        def cost_analysis(self):
            raise RuntimeError("backend says no")

        def memory_analysis(self):
            raise RuntimeError("backend says no")

    class _Lowered:
        def as_text(self):
            return ("func.func public @main() {\n"
                    "  %0 = stablehlo.constant dense<1> : tensor<i32>\n"
                    "}\n")

        def compile(self):
            return _Compiled()

    info = core.analyze_lowered(_Lowered())
    assert "stats_unavailable" in info
    assert "cost_analysis" in info["stats_unavailable"]
    assert "backend says no" in info["stats_unavailable"]


def test_analyze_lowered_real_entry_has_no_unavailable():
    low = jax.jit(lambda x: x * 2).lower(jnp.zeros(8, jnp.int32))
    info = core.analyze_lowered(low)
    assert "stats_unavailable" not in info


# ------------------------------------------------- device determinism

def test_float_psum_reds_int_psum_clean():
    mk = lambda dt: jax.make_jaxpr(  # noqa: E731
        lambda x: jax.lax.psum(x, "i"),
        axis_env=[("i", 2)])(jnp.ones(4, dt))
    red = determinism.findings_for(mk(jnp.float32), "t")
    assert any("float_collective" in f for f in red)
    assert determinism.findings_for(mk(jnp.int32), "t") == []


def test_baked_prng_key_reds_threaded_key_clean():
    baked = jax.make_jaxpr(
        lambda x: x + jax.random.uniform(jax.random.PRNGKey(0), (4,))
    )(jnp.ones(4))
    assert any("rng_no_key" in f
               for f in determinism.findings_for(baked, "t"))
    threaded = jax.make_jaxpr(
        lambda k, x: x + jax.random.uniform(k, (4,))
    )(jax.random.PRNGKey(0), jnp.ones(4))
    assert determinism.findings_for(threaded, "t") == []


def test_baked_key_inside_scan_body_reds():
    """The recursion must carry derived-ness INTO sub-jaxprs: a key
    built from a constant inside a scan body is still baked."""
    def f(x):
        def body(c, xi):
            r = jax.random.uniform(jax.random.PRNGKey(7), (4,),
                                   dtype=jnp.float32)
            return c + r.sum(), xi
        c, _ = jax.lax.scan(body, jnp.float32(0), x)
        return c

    cj = jax.make_jaxpr(f)(jnp.zeros(3, jnp.float32))
    assert any("rng_no_key" in f_ for f_ in
               determinism.findings_for(cj, "t"))


def test_host_callback_reds():
    def f(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) * 2, jax.ShapeDtypeStruct(
                (4,), jnp.float32), x)

    cj = jax.make_jaxpr(f)(jnp.ones(4, jnp.float32))
    assert any("host_callback" in f_ for f_ in
               determinism.findings_for(cj, "t"))


def test_float_scatter_dup_reds_int_and_unique_clean():
    idx = jnp.zeros((4, 1), jnp.int32)

    def add(x, u):
        return x.at[idx[:, 0]].add(u)

    red = determinism.findings_for(
        jax.make_jaxpr(add)(jnp.ones(8, jnp.float32),
                            jnp.ones(4, jnp.float32)), "t")
    assert any("float_scatter_dup" in f for f in red)
    clean_int = determinism.findings_for(
        jax.make_jaxpr(add)(jnp.ones(8, jnp.int32),
                            jnp.ones(4, jnp.int32)), "t")
    assert not any("float_scatter_dup" in f for f in clean_int)

    def add_unique(x, u):
        return x.at[idx[:, 0]].add(u, unique_indices=True,
                                   indices_are_sorted=True)

    clean_uni = determinism.findings_for(
        jax.make_jaxpr(add_unique)(jnp.ones(8, jnp.float32),
                                   jnp.ones(4, jnp.float32)), "t")
    assert not any("float_scatter_dup" in f for f in clean_uni)


# --------------------------------------------------- host determinism

def test_wall_clock_fixture_reds_and_pragma_suppresses():
    red = hostdet.scan_source(
        "import time\n\ndef f():\n    return time.time()\n", "fx.py")
    assert red == ["fx.py:4: wall_clock: time.time() read"]
    ok = hostdet.scan_source(
        "import time\n\ndef f():\n    return time.time()"
        "  # jaxhound: allow(wall_clock)\n", "fx.py")
    assert ok == []
    # A pragma for a DIFFERENT rule must not suppress.
    wrong = hostdet.scan_source(
        "import time\n\ndef f():\n    return time.time()"
        "  # jaxhound: allow(env_read)\n", "fx.py")
    assert len(wrong) == 1


def test_module_alias_and_injected_provider():
    red = hostdet.scan_source(
        "import time as _t\n\ndef f():\n    return _t.monotonic()\n",
        "fx.py")
    assert any("wall_clock" in f for f in red)
    # Injected providers (self.time.…) are the sanctioned pattern.
    ok = hostdet.scan_source(
        "class C:\n    def f(self):\n"
        "        return self.time.monotonic()\n", "fx.py")
    assert ok == []


def test_unseeded_random_reds_seeded_clean():
    red = hostdet.scan_source(
        "import random\n\ndef f():\n    return random.random()\n",
        "fx.py")
    assert any("unseeded_random" in f for f in red)
    ok = hostdet.scan_source(
        "import random\n\ndef f():\n"
        "    return random.Random(7).random()\n", "fx.py")
    assert ok == []
    red_np = hostdet.scan_source(
        "import numpy\n\ndef f():\n"
        "    return numpy.random.randint(3)\n", "fx.py")
    assert any("unseeded_random" in f for f in red_np)
    ok_np = hostdet.scan_source(
        "import numpy\n\ndef f():\n"
        "    return numpy.random.default_rng(7).integers(3)\n", "fx.py")
    assert ok_np == []


def test_set_iteration_reds_sorted_clean():
    red = hostdet.scan_source(
        "def f(xs):\n    return [x for x in set(xs)]\n", "fx.py")
    assert any("set_iteration" in f for f in red)
    ok = hostdet.scan_source(
        "def f(xs):\n    return [x for x in sorted(set(xs))]\n",
        "fx.py")
    assert ok == []


def test_env_read_reds():
    red = hostdet.scan_source(
        "import os\n\ndef f():\n    return os.environ['X']\n", "fx.py")
    assert any("env_read" in f for f in red)
    red2 = hostdet.scan_source(
        "import os\n\ndef f():\n    return os.getenv('X')\n", "fx.py")
    assert any("env_read" in f for f in red2)


def test_host_pass_over_real_scope_is_clean():
    assert hostdet.run(REPO) == []


# ------------------------------------------------------ retrace audit

def _entry(make_args, depths=(1, 2, 8, 32)):
    return Entry(name="t", route="flat", jit_fn=None, raw_fn=None,
                 make_args=make_args, depths=depths)


def test_canonical_signature_normalizes_window_axis():
    canon, fails = retrace.canonical_signature(_entry(
        lambda d: (np.zeros((d, 16), np.int32), np.uint64(5))))
    assert fails == []
    assert canon[0][0] == ("W", 16)
    # One digest regardless of which depth you look at.
    assert retrace.signature_digest(canon)


def test_polymorphic_dtype_reds():
    _, fails = retrace.canonical_signature(_entry(
        lambda d: (np.zeros(8, np.int32 if d < 8 else np.int64),)))
    assert any("polymorphic_dtype" in f for f in fails)


def test_weak_type_flap_reds():
    # A Python scalar at one depth only: weak_type flaps across W.
    _, fails = retrace.canonical_signature(_entry(
        lambda d: (7 if d == 1 else np.int32(7),)))
    assert any("weak_type_leak" in f for f in fails)


def test_non_window_axis_variation_reds():
    _, fails = retrace.canonical_signature(_entry(
        lambda d: (np.zeros((d * 2, 4), np.int32),)))
    assert any("polymorphic_shape" in f for f in fails)


def test_weak_scan_carry_reds_pinned_clean():
    def weak(x):
        def body(c, xi):
            return c + 1, xi  # Python-int carry: weak int32
        c, _ = jax.lax.scan(body, 0, x)
        return c

    cj = jax.make_jaxpr(weak)(jnp.zeros(3, jnp.int32))
    assert any("weak_carry" in f for f in retrace.weak_carries(cj, "t"))

    def pinned(x):
        def body(c, xi):
            return c + 1, xi
        c, _ = jax.lax.scan(body, jnp.int32(0), x)
        return c

    cj2 = jax.make_jaxpr(pinned)(jnp.zeros(3, jnp.int32))
    assert retrace.weak_carries(cj2, "t") == []


def test_cache_probe_counts_misses():
    calls = jax.jit(lambda x: x + 1)
    a1 = (np.zeros(8, np.int32),)
    a2 = (np.zeros(16, np.int32),)
    # same sig twice -> [<=1, 0]; new sig -> <=1. No overruns = clean.
    assert retrace.cache_probe(calls, [a1, a1, a2]) == []


def test_budget_drift_reds():
    table = {"e": {"route": "flat", "depths": [1], "n_signatures": 1,
                   "n_leaves": 2, "digest": "a" * 16}}
    import json as _json
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix="_r01.json", delete=False) as f:
        _json.dump({"entries": {"e": dict(table["e"], digest="b" * 16),
                                "gone": dict(table["e"])}}, f)
        path = f.name
    try:
        fails = retrace.check_budget({}, budget_path=path, table=table)
    finally:
        os.unlink(path)
    assert any("digest" in f for f in fails)          # drifted entry
    assert any("missing from the registry" in f for f in fails)


def test_committed_tracebudget_schema():
    """The committed pin itself: every entry carries the full schema,
    one canonical signature each, and the chain/partitioned-chain
    entries span the whole W matrix."""
    with open(TRACEBUDGET_PATH) as f:
        doc = json.load(f)
    assert doc["round"] == 1
    assert doc["matrix"]["depths"] == [1, 2, 8, 32]
    entries = doc["entries"]
    assert len(entries) >= 19
    routes = set()
    for name, e in entries.items():
        assert set(e) == {"route", "depths", "n_signatures",
                          "n_leaves", "digest"}, name
        assert e["n_signatures"] == 1, name
        assert re.fullmatch(r"[0-9a-f]{16}", e["digest"]), name
        assert e["n_leaves"] > 0, name
        routes.add(e["route"])
        if e["route"] in ("chain", "partitioned_chain"):
            assert e["depths"] == [1, 2, 8, 32], name
    assert routes >= {"flat", "chain", "sharded", "partitioned",
                      "partitioned_chain"}
    assert core.newest_tracebudget_path().endswith(
        os.path.basename(TRACEBUDGET_PATH))


def test_newest_round_path_family(tmp_path):
    """One generalized `_newest_round_path` helper behind all three
    budget-trail resolvers (op / trace / mem): each picks the highest
    committed round of ITS prefix, ignores the others' files, and the
    public helpers resolve the repo's committed heads."""
    for name in ("opbudget_r02.json", "opbudget_r11.json",
                 "tracebudget_r01.json", "membudget_r01.json",
                 "membudget_r03.json", "membudget_r02.json"):
        (tmp_path / name).write_text("{}")
    d = str(tmp_path)
    assert core._newest_round_path(d, "opbudget").endswith(
        "opbudget_r11.json")
    assert core._newest_round_path(d, "tracebudget").endswith(
        "tracebudget_r01.json")
    assert core._newest_round_path(d, "membudget").endswith(
        "membudget_r03.json")
    with pytest.raises(FileNotFoundError):
        core._newest_round_path(d, "nosuchbudget")
    # The committed heads resolve (and the membudget one is a valid
    # static-allocation budget the memwatch plane can audit against).
    for helper, prefix in (
            (core.newest_budget_path, "opbudget"),
            (core.newest_tracebudget_path, "tracebudget"),
            (core.newest_membudget_path, "membudget")):
        path = helper()
        assert os.path.basename(path).startswith(prefix + "_r"), path
        assert os.path.exists(path), path
    from tigerbeetle_tpu.trace import load_budget
    budget = load_budget()
    assert budget["components"] and budget["total_bytes"] == \
        sum(budget["components"].values())
    assert budget["profiler"]["overhead_ratio_max"] == 1.05


# ---------------------------------------------------- sharding verify

@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:8]), ("batch",))


def _sharded_jit(mesh, spec):
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding
    sh = NamedSharding(mesh, spec)
    return jax.jit(
        shard_map(lambda s: s + 1, mesh=mesh, in_specs=spec,
                  out_specs=spec),
        in_shardings=sh, out_shardings=sh, donate_argnums=0)


def test_replicated_donated_state_reds(mesh8):
    from jax.sharding import PartitionSpec as P
    x = np.zeros((8, 128), np.int64)
    fails = shardspec.verify_lowered(
        _sharded_jit(mesh8, P()).lower(x), 1, "neg")
    assert any("donated" in f for f in fails)
    assert any("SPMDShardToFullShape" in f for f in fails)


def test_batch_sharded_state_clean(mesh8):
    from jax.sharding import PartitionSpec as P
    x = np.zeros((8, 128), np.int64)
    assert shardspec.verify_lowered(
        _sharded_jit(mesh8, P("batch")).lower(x), 1, "pos") == []


def test_split_main_args_survives_quoted_shardings():
    text = ('func.func public @main(%arg0: tensor<8x4xi32> '
            '{mhlo.sharding = "{devices=[8,1]<=[8]}"}, '
            '%arg1: tensor<4xi32>) -> (tensor<4xi32>) {')
    args = shardspec.split_main_args(text)
    assert len(args) == 2
    assert "devices" in args[0] and "arg1" in args[1]


# --------------------------------------------------------------- CLI

def test_cli_host_pass_json(capsys):
    from tigerbeetle_tpu.jaxhound.cli import main
    rc = main(["--pass", "host", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["passes"]["host"]["ok"] is True


def test_cli_rejects_unknown_pass():
    from tigerbeetle_tpu.jaxhound.cli import main
    with pytest.raises(SystemExit):
        main(["--pass", "nonsense"])
