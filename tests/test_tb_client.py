"""Native C tb_client: echo mode, then a real cluster over TCP.

reference: src/clients/c/tb_client.zig (init_echo test harness) +
src/clients/python — the binding drives the same C ABI every language
client shares.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from tigerbeetle_tpu.clients import CClient, c_client_available
from tigerbeetle_tpu.types import Account, Operation, Transfer

pytestmark = pytest.mark.skipif(
    not c_client_available(), reason="native toolchain unavailable")


class TestEcho:
    def test_echo_roundtrip(self):
        client = CClient(cluster=1, replica_addresses=[], echo=True)
        try:
            for size in (0, 1, 128, 64 * 1024):
                body = os.urandom(size)
                assert client.request(Operation.create_transfers, body) == body
        finally:
            client.close()

    def test_echo_many_packets(self):
        client = CClient(cluster=1, replica_addresses=[], echo=True)
        try:
            bodies = [os.urandom(64) for _ in range(50)]
            for body in bodies:
                assert client.request(Operation.lookup_accounts, body) == body
        finally:
            client.close()

    def test_shutdown_clean(self):
        client = CClient(cluster=1, replica_addresses=[], echo=True)
        client.close()
        client.close()  # idempotent


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


@pytest.fixture
def single_replica(tmp_path):
    (port,) = _free_ports(1)
    address = f"127.0.0.1:{port}"
    path = tmp_path / "r0.tigerbeetle"
    env = dict(os.environ)
    subprocess.run(
        [sys.executable, "-m", "tigerbeetle_tpu", "format", "--cluster=9",
         "--replica=0", "--replica-count=1", "--small", str(path)],
        check=True, cwd="/root/repo", env=env, timeout=60,
        stdout=subprocess.DEVNULL)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tigerbeetle_tpu", "start",
         f"--addresses={address}", "--replica=0", "--cluster=9",
         "--engine=oracle", "--small", str(path)],
        cwd="/root/repo", env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        yield address
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


@pytest.mark.integration
def test_c_client_against_real_replica(single_replica):
    host, port = single_replica.split(":")
    client = CClient(cluster=9, replica_addresses=[(host, int(port))])
    try:
        deadline = time.monotonic() + 60
        results = None
        while time.monotonic() < deadline:
            try:
                results = client.create_accounts([
                    Account(id=1, ledger=700, code=10),
                    Account(id=2, ledger=700, code=10),
                ])
                break
            except TimeoutError:
                continue
        assert results is not None, "replica never became available"
        assert all(r.status.name in ("created", "exists") for r in results)

        results = client.create_transfers([
            Transfer(id=100, debit_account_id=1, credit_account_id=2,
                     amount=77, ledger=700, code=10)])
        assert [r.status.name for r in results] == ["created"]

        accounts = client.lookup_accounts([1, 2])
        assert accounts[0].debits_posted == 77
        assert accounts[1].credits_posted == 77
        transfers = client.lookup_transfers([100])
        assert transfers[0].amount == 77
    finally:
        client.close()
