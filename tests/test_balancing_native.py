"""Balancing transfers (balancing_debit/credit) native on device.

reference: the clamp at src/state_machine.zig:3840-3853 — the applied
amount is min(amount, available headroom), where headroom reads the
balances produced by every successful EARLIER event (including earlier
events in the same batch). Previously any balancing flag was an E1 hard
fallback to the exact host path; the balancing fixpoint tier
(ops/fast_kernels.py balancing_mode) re-derives clamped amounts per
round from the exact per-event prefix balances and resolves the whole
batch on device.
"""

import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

import numpy as np

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    Transfer,
    TransferFlags,
)

DR_LIMIT = int(AccountFlags.debits_must_not_exceed_credits)
CR_LIMIT = int(AccountFlags.credits_must_not_exceed_debits)
LINKED = int(TransferFlags.linked)
PENDING = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
VOID = int(TransferFlags.void_pending_transfer)
BAL_DR = int(TransferFlags.balancing_debit)
BAL_CR = int(TransferFlags.balancing_credit)
CLOSE_DR = int(TransferFlags.closing_debit)
AMOUNT_MAX = (1 << 128) - 1


def _pair():
    led = DeviceLedger(a_cap=1 << 12, t_cap=1 << 14)
    sm = StateMachineOracle()
    return led, sm


def _both(led, sm, events, ts):
    got = led.create_transfers(events, ts)
    want = sm.create_transfers(events, ts)
    assert ([(r.timestamp, r.status) for r in got]
            == [(r.timestamp, r.status) for r in want]), (
        [r.status.name for r in got], [r.status.name for r in want])
    return [r.status.name for r in got]


def _check_state(led, sm, acct_ids, xfer_ids=()):
    a_led = {a.id: a for a in led.lookup_accounts(list(acct_ids))}
    a_sm = {a.id: a for a in sm.lookup_accounts(list(acct_ids))}
    assert a_led == a_sm, (a_led, a_sm)
    if xfer_ids:
        x_led = led.lookup_transfers(list(xfer_ids))
        x_sm = sm.lookup_transfers(list(xfer_ids))
        assert x_led == x_sm, (x_led, x_sm)


def _setup(led, sm, accounts, fund=()):
    for eng in (led, sm):
        res = eng.create_accounts(accounts, 100)
        assert all(r.status.name == "created" for r in res)
    ts = 10**12
    for i, (dr, cr, amt) in enumerate(fund):
        _both(led, sm, [Transfer(id=900 + i, debit_account_id=dr,
                                 credit_account_id=cr, amount=amt,
                                 ledger=1, code=1)], ts)
        ts += 10
    return ts


class TestBalancingNative:
    def test_amount_max_clamps_to_headroom(self):
        """AMOUNT_MAX balancing_debit clamps to the full headroom
        (credits_posted - debits) — stored amount is the clamp, and the
        batch runs on device (no host fallback)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR)], ts)
        assert st == ["created"]
        assert led.lookup_transfers([1])[0].amount == 100
        _check_state(led, sm, [1, 2, 3], [1])
        assert led.fallbacks == 0 and led.fixpoint_batches == 1

    def test_in_batch_cascade(self):
        """A balancing transfer reads the headroom left by an EARLIER
        balancing transfer in the same batch: 60 then 40 then 0."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=60, ledger=1, code=1, flags=BAL_DR),
            Transfer(id=2, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
            Transfer(id=3, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
        ], ts)
        assert st == ["created"] * 3
        amts = [t.amount for t in led.lookup_transfers([1, 2, 3])]
        assert amts == [60, 40, 0]
        _check_state(led, sm, [1, 2, 3], [1, 2, 3])
        assert led.fallbacks == 0

    def test_balancing_credit(self):
        """balancing_credit clamps against the CREDIT account's
        debits_posted - (credits_posted + credits_pending)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(1, 2, 80)])
        # Account 1 has debits_posted=80: balancing_credit INTO account
        # 1 clamps at 80.
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=3, credit_account_id=1,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_CR)], ts)
        assert st == ["created"]
        assert led.lookup_transfers([1])[0].amount == 80
        _check_state(led, sm, [1, 2, 3], [1])
        assert led.fallbacks == 0

    def test_both_flags_min_composes(self):
        """balancing_debit AND balancing_credit: the applied amount is
        the min of both headrooms (and the nominal)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1),
                     Account(id=4, ledger=1, code=1)],
                    fund=[(2, 1, 100), (3, 4, 30)])
        # dr headroom on 1 = 100; cr headroom on 3 = 30 -> clamp 30.
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR | BAL_CR)], ts)
        assert st == ["created"]
        assert led.lookup_transfers([1])[0].amount == 30
        _check_state(led, sm, [1, 2, 3, 4], [1])
        assert led.fallbacks == 0

    def test_balancing_pending_holds_headroom(self):
        """A pending balancing transfer holds debits_pending, shrinking
        the headroom a later balancing transfer in the same batch
        sees."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=70, ledger=1, code=1,
                     flags=BAL_DR | PENDING, timeout=60),
            Transfer(id=2, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
        ], ts)
        assert st == ["created"] * 2
        amts = [t.amount for t in led.lookup_transfers([1, 2])]
        assert amts == [70, 30]
        _check_state(led, sm, [1, 2, 3], [1, 2])
        assert led.fallbacks == 0

    def test_zero_headroom_zero_amount(self):
        """No headroom at all: the transfer is still created, with
        amount 0 (reference: the clamp saturates at zero, creation
        proceeds)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=2,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR)], ts)
        assert st == ["created"]
        assert led.lookup_transfers([1])[0].amount == 0
        _check_state(led, sm, [1, 2], [1])
        assert led.fallbacks == 0

    def test_mid_batch_relief_widens_clamp(self):
        """A void earlier in the batch releases pending debits; a later
        balancing transfer's clamp must see the widened headroom."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        # Pre-batch pending holding 90 of the 100 headroom.
        st = _both(led, sm, [
            Transfer(id=800, debit_account_id=1, credit_account_id=3,
                     amount=90, ledger=1, code=1, flags=PENDING,
                     timeout=3600)], ts)
        assert st == ["created"]
        ts += 10
        st = _both(led, sm, [
            Transfer(id=801, pending_id=800, flags=VOID,
                     amount=0, ledger=1, code=1),
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
        ], ts)
        assert st == ["created"] * 2
        assert led.lookup_transfers([1])[0].amount == 100
        _check_state(led, sm, [1, 2, 3], [1, 800, 801])
        assert led.fallbacks == 0

    def test_balancing_under_limits(self):
        """Balancing + balance-limit flags on the same fixpoint: the
        clamp keeps the balancing account inside ITS limit, while the
        counterparty's limit can still fail the transfer — sequential
        statuses either way."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1, flags=DR_LIMIT),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1, flags=CR_LIMIT)],
                    fund=[(2, 1, 50)])
        # Account 3 has credits_must_not_exceed_debits with zero
        # debits: ANY positive credit breaches. The balancing clamp on
        # account 1 yields 50 > 0 -> exceeds_debits on account 3.
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR)], ts)
        assert st == ["exceeds_debits"]
        # Against a plain counterparty the same event is clamped+created.
        st = _both(led, sm, [
            Transfer(id=2, debit_account_id=1, credit_account_id=2,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR)], ts + 10)
        assert st == ["created"]
        assert led.lookup_transfers([2])[0].amount == 50
        _check_state(led, sm, [1, 2, 3], [2])
        assert led.fallbacks == 0

    def test_linked_chain_rollback(self):
        """A chain whose later member fails rolls back an earlier
        balancing transfer — including its clamped deltas."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR | LINKED),
            Transfer(id=2, debit_account_id=1, credit_account_id=99,
                     amount=1, ledger=1, code=1),  # account not found
        ], ts)
        assert st == ["linked_event_failed", "credit_account_not_found"]
        # Rolled back: headroom restored, next balancing sees 100.
        st = _both(led, sm, [
            Transfer(id=3, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR)], ts + 10)
        assert st == ["created"]
        assert led.lookup_transfers([3])[0].amount == 100
        _check_state(led, sm, [1, 2, 3], [3])
        assert led.fallbacks == 0

    def test_exists_amount_upper_bound(self):
        """Idempotent resubmission of a balancing transfer compares the
        nominal amount as an UPPER bound on the stored clamp (reference
        :4016-4031): amount >= stored -> exists; amount < stored ->
        exists_with_different_amount."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR)], ts)
        assert st == ["created"]  # stored amount 100
        # One resubmission per batch: same-id duplicates WITHIN a batch
        # are an intentional E2 exact-path fallback.
        st = []
        for k, amt in enumerate((AMOUNT_MAX, 100, 99)):
            st += _both(led, sm, [
                Transfer(id=1, debit_account_id=1, credit_account_id=3,
                         amount=amt, ledger=1, code=1, flags=BAL_DR)],
                ts + 10 * (k + 1))
        assert st == ["exists", "exists", "exists_with_different_amount"]
        _check_state(led, sm, [1, 2, 3], [1])
        assert led.fallbacks == 0

    def test_inwindow_balancing_pending_def_falls_back(self):
        """A post referencing a balancing pending created EARLIER IN THE
        SAME BATCH falls back to the exact path (the in-window
        substitution reads nominal event lanes, not the clamp) — and
        the results still match the oracle bit-for-bit."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1),
                     Account(id=3, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=3,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR | PENDING, timeout=60),
            Transfer(id=2, pending_id=1, flags=POST,
                     amount=AMOUNT_MAX, ledger=1, code=1),
        ], ts)
        assert st == ["created", "created"]
        # The post inherits the CLAMPED pending amount (100).
        assert led.lookup_transfers([2])[0].amount == 100
        _check_state(led, sm, [1, 2, 3], [1, 2])
        assert led.fallbacks == 1  # by design

    def test_closing_native_in_balancing_batch(self):
        """closing_debit in a balancing batch runs NATIVE (the balancing
        tier is closing-native: the closed-state evolution joins the
        clamp fixpoint) — results identical to the oracle, zero host
        fallbacks."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1)],
                    fund=[(2, 1, 10)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=2,
                     amount=AMOUNT_MAX, ledger=1, code=1,
                     flags=BAL_DR),
            Transfer(id=2, debit_account_id=1, credit_account_id=2,
                     amount=1, ledger=1, code=1,
                     flags=PENDING | CLOSE_DR, timeout=60),
        ], ts)
        assert st == ["created", "created"]
        _check_state(led, sm, [1, 2], [1, 2])
        assert led.fallbacks == 0  # closing is native now

    def test_seeded_fuzz_differential(self):
        """Randomized mixed batches (regular / balancing dr+cr / pending
        balancing / posts+voids of PRIOR-batch pendings / occasional
        chains / limit-flagged accounts), every batch diffed against the
        oracle and full account balances compared — all native (the
        shallow->deep ladder may escalate, but never to the host)."""
        rng = np.random.default_rng(0xBA1A)
        led, sm = _pair()
        n_acct = 10
        accts = [Account(id=i, ledger=1, code=1,
                         flags=(DR_LIMIT if i == 3
                                else CR_LIMIT if i == 7 else 0))
                 for i in range(1, n_acct + 1)]
        ts = _setup(led, sm, accts,
                    fund=[(2, 1, 500), (4, 3, 400), (6, 5, 300),
                          (8, 7, 200), (10, 9, 100)])
        next_id = 1000
        open_pendings = []  # created pending ids from PRIOR batches
        for batch in range(6):
            events = []
            created_pendings = []
            for k in range(24):
                kind = rng.integers(0, 10)
                tid = next_id
                next_id += 1
                if kind <= 1 and open_pendings:
                    pid = int(open_pendings.pop(
                        rng.integers(0, len(open_pendings))))
                    events.append(Transfer(
                        id=tid, pending_id=pid,
                        flags=POST if kind == 0 else VOID,
                        amount=AMOUNT_MAX if kind == 0 else 0,
                        ledger=1, code=1))
                    continue
                dr_i, cr_i = rng.choice(n_acct, size=2,
                                        replace=False) + 1
                flags = 0
                if kind in (2, 3):
                    flags |= BAL_DR
                elif kind in (4, 5):
                    flags |= BAL_CR
                elif kind == 6:
                    flags |= BAL_DR | BAL_CR
                amount = int(rng.integers(1, 120))
                if flags and rng.integers(0, 3) == 0:
                    amount = AMOUNT_MAX
                if kind == 7:
                    flags |= PENDING
                    created_pendings.append(tid)
                if flags & (BAL_DR | BAL_CR) and rng.integers(0, 4) == 0:
                    flags |= PENDING
                    created_pendings.append(tid)
                events.append(Transfer(
                    id=tid, debit_account_id=int(dr_i),
                    credit_account_id=int(cr_i), amount=amount,
                    ledger=1, code=1, flags=flags,
                    timeout=3600 if flags & PENDING else 0))
            _both(led, sm, events, ts)
            ts += 100
            created = {t.id for t in led.lookup_transfers(
                [e.id for e in events])}
            open_pendings.extend(i for i in created_pendings
                                 if i in created)
            _check_state(led, sm, range(1, n_acct + 1),
                         [e.id for e in events])
        assert led.fallbacks == 0
        assert led.fixpoint_batches > 0
