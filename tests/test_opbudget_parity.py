"""Differential parity for the round-6 packed/fused kernel variants.

The op-budget campaign repacked every store (32-bit columns pair-packed
into the u64 matrices), fused the role-set gathers, rebuilt the dup/join
checks on variadic sorts, and made the fixpoint tiers' application stage
reuse the fixpoint's sorted entry space. Every one of those is a
bit-exactness hazard, so this suite runs MIXED flag workloads — the
plain x balancing x closing x imported cross the issue names — through
DeviceLedger (which pre-routes each batch to the matching tier) against
the sequential oracle, asserting statuses, timestamps and the full
reconstructed host state match exactly.
"""

import random

import pytest

# Tier: jit-heavy differential suite (compiles several kernel tiers).
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags as AF,
    Transfer,
    TransferFlags as TF,
)

TS = 10_000_000_000_000


class Differ:
    def __init__(self, a_cap=1 << 12, t_cap=1 << 14):
        self.led = DeviceLedger(a_cap=a_cap, t_cap=t_cap)
        self.sm = StateMachineOracle()
        self.ts = TS

    def _step(self, fn, events):
        self.ts += len(events) + 7
        got = getattr(self.led, fn)(events, self.ts)
        want = getattr(self.sm, fn)(events, self.ts)
        assert [(r.timestamp, r.status.name) for r in got] == [
            (r.timestamp, r.status.name) for r in want
        ], fn
        return want

    def accounts(self, events):
        return self._step("create_accounts", events)

    def transfers(self, events):
        return self._step("create_transfers", events)

    def check_state(self):
        host = self.led.to_host()
        for f in ("accounts", "transfers", "pending_status", "orphaned",
                  "expiry", "pulse_next_timestamp", "commit_timestamp",
                  "accounts_key_max", "transfers_key_max",
                  "account_events"):
            assert getattr(host, f) == getattr(self.sm, f), f


def _base_accounts(d, n=16, limits=True):
    evs = []
    for i in range(1, n + 1):
        fl = 0
        if limits and i % 5 == 0:
            fl = int(AF.debits_must_not_exceed_credits)
        elif limits and i % 7 == 0:
            fl = int(AF.credits_must_not_exceed_debits)
        evs.append(Account(id=i, ledger=1, code=1, flags=fl))
    d.accounts(evs)
    # Fund everyone so limited accounts have headroom to spend.
    d.transfers([Transfer(id=10_000 + i, debit_account_id=1,
                          credit_account_id=i, amount=1_000_000,
                          ledger=1, code=1)
                 for i in range(2, n + 1)])


def test_mixed_pending_closing_balancing_stream():
    """One stream interleaving plain, pending/post/void, closing and
    balancing batches: the ledger routes each to a different packed
    kernel tier; every result and the final state must equal the
    oracle's."""
    d = Differ()
    _base_accounts(d)
    # pending + closing create (routes to the fixpoint tier).
    d.transfers([
        Transfer(id=1, debit_account_id=2, credit_account_id=3,
                 amount=100, ledger=1, code=1, flags=int(TF.pending),
                 timeout=1000),
        Transfer(id=2, debit_account_id=4, credit_account_id=5,
                 amount=50, ledger=1, code=1,
                 flags=int(TF.pending | TF.closing_debit), timeout=500),
        Transfer(id=3, debit_account_id=5, credit_account_id=6,
                 amount=10, ledger=1, code=1),
    ])
    # post/void incl. the closed account (void reopens).
    d.transfers([
        Transfer(id=4, pending_id=1, amount=(1 << 128) - 1, ledger=1,
                 code=1, flags=int(TF.post_pending_transfer)),
        Transfer(id=5, pending_id=2, amount=0, ledger=1, code=1,
                 flags=int(TF.void_pending_transfer)),
    ])
    # balancing batch (routes to the balancing tier).
    d.transfers([
        Transfer(id=6, debit_account_id=5, credit_account_id=10,
                 amount=(1 << 128) - 1, ledger=1, code=1,
                 flags=int(TF.balancing_debit)),
        Transfer(id=7, debit_account_id=7, credit_account_id=14,
                 amount=123, ledger=1, code=1),
    ])
    # plain batch again (back to the fast tier).
    d.transfers([
        Transfer(id=8, debit_account_id=3, credit_account_id=9,
                 amount=77, ledger=1, code=1),
    ])
    d.check_state()


def test_imported_batch_after_mixed_stream():
    """Imported tier over the packed layout: user timestamps, in-batch
    regress maxima chain, account-ts collision probe (the
    searchsorted method='sort' path)."""
    d = Differ()
    _base_accounts(d, n=8, limits=False)
    base = d.sm.commit_timestamp + 10
    d.transfers([
        Transfer(id=21, debit_account_id=2, credit_account_id=3,
                 amount=5, ledger=1, code=1, flags=int(TF.imported),
                 timestamp=base + 1),
        # regresses in-batch (same ts as the previous event).
        Transfer(id=22, debit_account_id=3, credit_account_id=4,
                 amount=5, ledger=1, code=1, flags=int(TF.imported),
                 timestamp=base + 1),
        Transfer(id=23, debit_account_id=4, credit_account_id=5,
                 amount=5, ledger=1, code=1, flags=int(TF.imported),
                 timestamp=base + 2),
    ])
    d.transfers([
        Transfer(id=24, debit_account_id=2, credit_account_id=5,
                 amount=1, ledger=1, code=1),
    ])
    d.check_state()


def test_inwindow_pending_chain_deaths_superbatch_shape():
    """In-window pending definition + use with a chain death: exercises
    the variadic-sort join, the packed def-view gathers, and the
    fixpoint application reusing the fixpoint's sorted entry space."""
    d = Differ()
    _base_accounts(d, n=8, limits=False)
    d.transfers([
        # def (pending) ... use (post) in ONE batch.
        Transfer(id=31, debit_account_id=2, credit_account_id=3,
                 amount=40, ledger=1, code=1, flags=int(TF.pending),
                 timeout=100),
        Transfer(id=32, pending_id=31, amount=(1 << 128) - 1, ledger=1,
                 code=1, flags=int(TF.post_pending_transfer)),
        # linked chain whose failure kills a def; its use must read
        # pending_transfer_not_found (dead-definition status).
        Transfer(id=33, debit_account_id=4, credit_account_id=5,
                 amount=10, ledger=1, code=1,
                 flags=int(TF.linked | TF.pending), timeout=50),
        Transfer(id=34, debit_account_id=99, credit_account_id=5,
                 amount=1, ledger=1, code=1),  # fails: no such account
        Transfer(id=35, pending_id=33, amount=0, ledger=1, code=1,
                 flags=int(TF.void_pending_transfer)),
    ])
    d.check_state()


@pytest.mark.parametrize("seed", range(3))
def test_mixed_flag_fuzz(seed):
    """Randomized mixed-flag stream (plain x pending x post/void x
    closing x balancing) — the round-6 analog of the fast-path fuzz
    differential, biased toward the repacked/fused code paths."""
    rng = random.Random(0xB06 + seed)
    d = Differ()
    _base_accounts(d, n=12)
    live_pending = []
    next_id = 100
    for _batch in range(6):
        evs = []
        for _ in range(rng.randrange(2, 7)):
            kind = rng.random()
            next_id += 1
            a = rng.randrange(2, 13)
            b = rng.randrange(2, 13)
            if a == b:
                b = 2 if a != 2 else 3
            if kind < 0.25:
                evs.append(Transfer(
                    id=next_id, debit_account_id=a, credit_account_id=b,
                    amount=rng.randrange(1, 500), ledger=1, code=1,
                    flags=int(TF.pending), timeout=rng.randrange(0, 50)))
                live_pending.append(next_id)
            elif kind < 0.4 and live_pending:
                pid = rng.choice(live_pending)
                post = rng.random() < 0.5
                evs.append(Transfer(
                    id=next_id, pending_id=pid,
                    amount=((1 << 128) - 1) if post else 0, ledger=1,
                    code=1,
                    flags=int(TF.post_pending_transfer if post
                              else TF.void_pending_transfer)))
            elif kind < 0.55:
                evs.append(Transfer(
                    id=next_id, debit_account_id=a, credit_account_id=b,
                    amount=(1 << 128) - 1, ledger=1, code=1,
                    flags=int(TF.balancing_debit if rng.random() < 0.5
                              else TF.balancing_credit)))
            elif kind < 0.65:
                evs.append(Transfer(
                    id=next_id, debit_account_id=a, credit_account_id=b,
                    amount=rng.randrange(1, 100), ledger=1, code=1,
                    flags=int(TF.pending | TF.closing_debit),
                    timeout=20))
                live_pending.append(next_id)
            else:
                evs.append(Transfer(
                    id=next_id, debit_account_id=a, credit_account_id=b,
                    amount=rng.randrange(1, 300), ledger=1, code=1))
        d.transfers(evs)
    d.check_state()
