"""The vectorized serving path (wire -> SoA -> kernel -> wire with
deferred mirror materialization) must be bit-identical to the oracle
engine run over the same wire bodies, and the lazily-drained mirror must
be exact at every read boundary.

Reference analog: src/state_machine.zig:2564-2669 (commit) and the VOPR
state-machine differential (-Dvopr-state-machine).
"""

import numpy as np
import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.ops.batch import (
    RESULT_WIRE,
    TRANSFER_WIRE,
    encode_create_results,
    transfers_soa_from_bytes,
)
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags,
    Operation,
    Transfer,
    TransferFlags,
)


def _mk_body(rng, base, nb, account_count, pend_frac=0.2):
    dr = rng.integers(1, account_count + 1, nb, dtype=np.uint64)
    cr = rng.integers(1, account_count + 1, nb, dtype=np.uint64)
    clash = dr == cr
    cr[clash] = dr[clash] % account_count + 1
    amt = rng.integers(1, 10**6, nb)
    flags = np.where(rng.random(nb) < pend_frac,
                     np.uint32(int(TransferFlags.pending)), np.uint32(0))
    payload = b"".join(
        Transfer(id=int(base + i), debit_account_id=int(dr[i]),
                 credit_account_id=int(cr[i]), amount=int(amt[i]),
                 ledger=1, code=1, flags=int(flags[i]),
                 timeout=3600 if flags[i] else 0).pack()
        for i in range(nb))
    return multi_batch.encode([payload], 128)


def _setup(engine, account_count=200):
    sm = StateMachine(engine=engine, a_cap=1 << 12, t_cap=1 << 14)
    ts = 1000
    accounts = [Account(id=i, ledger=1, code=1)
                for i in range(1, account_count + 1)]
    ts += len(accounts) + 10
    sm.create_accounts(accounts, ts)
    return sm, ts


def test_wire_codec_roundtrip():
    rng = np.random.default_rng(11)
    xs = [Transfer(id=(1 << 100) + i, debit_account_id=int(rng.integers(1, 99)),
                   credit_account_id=(1 << 77) + i,
                   amount=(1 << 90) + int(rng.integers(0, 10**9)),
                   pending_id=i % 3, user_data_128=(1 << 127) | i,
                   user_data_64=2**63 + i, user_data_32=7 + i, timeout=i,
                   ledger=3, code=55, flags=9, timestamp=10**15 + i)
          for i in range(17)]
    body = b"".join(t.pack() for t in xs)
    ev = transfers_soa_from_bytes(body)
    for i, t in enumerate(xs):
        assert (int(ev["id_hi"][i]) << 64) | int(ev["id_lo"][i]) == t.id
        assert (int(ev["dr_hi"][i]) << 64) | int(ev["dr_lo"][i]) \
            == t.debit_account_id
        assert (int(ev["cr_hi"][i]) << 64) | int(ev["cr_lo"][i]) \
            == t.credit_account_id
        assert (int(ev["amt_hi"][i]) << 64) | int(ev["amt_lo"][i]) == t.amount
        assert (int(ev["pid_hi"][i]) << 64) | int(ev["pid_lo"][i]) \
            == t.pending_id
        assert int(ev["ud64"][i]) == t.user_data_64
        assert int(ev["ud32"][i]) == t.user_data_32
        assert int(ev["timeout"][i]) == t.timeout
        assert int(ev["ledger"][i]) == t.ledger
        assert int(ev["code"][i]) == t.code
        assert int(ev["flags"][i]) == t.flags
        assert int(ev["ts"][i]) == t.timestamp
    assert TRANSFER_WIRE.itemsize == 128 and RESULT_WIRE.itemsize == 16
    st = np.arange(5, dtype=np.uint32)
    ts = np.arange(5, dtype=np.uint64) * 7
    enc = encode_create_results(st, ts)
    rec = np.frombuffer(enc, dtype=RESULT_WIRE)
    assert (rec["status"] == st).all() and (rec["ts"] == ts).all()


def test_device_commit_matches_oracle_commit():
    """Same wire bodies through both engines -> identical reply bytes and
    identical post-drain object state."""
    dev, ts_d = _setup("device")
    ora, ts_o = _setup("oracle")
    assert ts_d == ts_o
    ts = ts_d
    rng = np.random.default_rng(5)
    nb = 500
    next_id = 10**7
    for b in range(4):
        body = _mk_body(np.random.default_rng(100 + b), next_id, nb, 200)
        next_id += nb
        ts += nb + 10
        r_dev = dev.commit(Operation.create_transfers, body, ts)
        r_ora = ora.commit(Operation.create_transfers, body, ts)
        assert r_dev == r_ora
    # Mirror exactness at the read boundary (drains lazily).
    assert dev.state.accounts == ora.state.accounts
    assert dev.state.transfers == ora.state.transfers
    assert dev.state.pending_status == ora.state.pending_status
    assert dev.state.account_events == ora.state.account_events
    assert dev.state.orphaned == ora.state.orphaned
    assert dev.led.fallbacks == 0


def test_queries_see_deferred_batches():
    """A query immediately after a commit must observe that batch (the
    drain gate on the state property)."""
    sm, ts = _setup("device")
    nb = 64
    body = _mk_body(np.random.default_rng(1), 10**7, nb, 200, pend_frac=0.0)
    ts += nb + 10
    sm.commit(Operation.create_transfers, body, ts)
    assert sm.led._mirror_chunks, "expected a deferred chunk"
    f = AccountFilter(account_id=1, limit=100,
                      flags=int(AccountFilterFlags.debits
                                | AccountFilterFlags.credits))
    got = sm.get_account_transfers(f)
    want = [t for t in sm.state.transfers.values()
            if 1 in (t.debit_account_id, t.credit_account_id)]
    assert [t.id for t in got] == [t.id for t in want]
    assert not sm.led._mirror_chunks


def test_lookups_after_commit_drain():
    sm, ts = _setup("device")
    nb = 32
    body = _mk_body(np.random.default_rng(2), 10**7, nb, 200, pend_frac=0.0)
    ts += nb + 10
    reply = sm.commit(Operation.create_transfers, body, ts)
    rec = np.frombuffer(
        multi_batch.decode(reply, 16)[0], dtype=RESULT_WIRE)
    created_ts = [int(t) for t, s in zip(rec["ts"], rec["status"])
                  if s == 0xFFFFFFFF]  # created = maxInt(u32)
    xs = sm.lookup_transfers([10**7 + i for i in range(nb)])
    assert sorted(t.timestamp for t in xs) == sorted(created_ts)


def test_sparse_deprecated_encoding_matches():
    dev, ts = _setup("device")
    ora, _ = _setup("oracle")
    nb = 100
    rng = np.random.default_rng(3)
    # Half the events reference a missing debit account -> failures.
    payload = b"".join(
        Transfer(id=10**7 + i,
                 debit_account_id=int(rng.integers(1, 400)),
                 credit_account_id=int(rng.integers(1, 201)),
                 amount=1, ledger=1, code=1).pack()
        for i in range(nb))
    ts += nb + 10
    op = Operation.deprecated_create_transfers_sparse
    body = multi_batch.encode([payload], 128)
    r_dev = dev.commit(op, body, ts)
    r_ora = ora.commit(op, body, ts)
    assert r_dev == r_ora
