"""The SLO-grade latency plane: Prometheus exposition + endpoint, SLO
engine + burn rates, critical-path attribution, the gate's
bench-regression leg (including the injected-slowdown negative test),
devhub panels, and the scraped-vs-offline p99 parity acceptance."""

import dataclasses
import json
import urllib.request

import pytest

from tigerbeetle_tpu.metrics import (MetricsServer, parse_prometheus,
                                     render_prometheus)
from tigerbeetle_tpu.trace import Event, Tracer
from tigerbeetle_tpu.trace.histogram import REL_ERROR, Histogram
from tigerbeetle_tpu.trace.merge import critical_path, span_quantile
from tigerbeetle_tpu.trace.slo import (burn_rates, evaluate,
                                       evaluate_bench_record,
                                       load_objectives)


def _tracer_with_latency_series():
    t = Tracer(pid=0)
    for route, tier in (("chain", "scan"), ("chain", "scan"),
                        ("per_batch", "fallback")):
        with t.span(Event.window_commit, route=route, tier=tier):
            pass
    with t.span(Event.serving_dispatch, what="window"):
        pass
    t.count(Event.serving_retries, 3)
    t.gauge(Event.bus_pool_used, 7)
    t.observe(Event.serving_replay_windows, 4)
    return t


# ---------------------------------------------------------- exposition

def test_render_parse_round_trip():
    t = _tracer_with_latency_series()
    text = render_prometheus(t)
    parsed = parse_prometheus(text)  # raises on any malformed line
    assert parsed["tb_tpu_serving_retries_total"] == [({}, 3.0)]
    assert parsed["tb_tpu_bus_pool_used"] == [({}, 7.0)]
    # Span histograms carry the _us unit suffix and the partition tags.
    counts = dict((frozenset(lab.items()), v) for lab, v
                  in parsed["tb_tpu_window_commit_us_count"])
    assert counts[frozenset({("route", "chain"),
                             ("tier", "scan")}.union())] == 2.0
    assert counts[frozenset({("route", "per_batch"),
                             ("tier", "fallback")})] == 1.0
    # +Inf bucket == series count for every series.
    for lab, v in parsed["tb_tpu_window_commit_us_bucket"]:
        if lab.get("le") == "+Inf":
            assert v == counts[frozenset(
                (k, x) for k, x in lab.items() if k != "le")]
    # Histogram-kind events keep their declared unit (no _us).
    assert parsed["tb_tpu_serving_replay_windows_count"] == [({}, 1.0)]
    assert "tb_tpu_serving_replay_windows_us_count" not in parsed


def test_exemplar_render_parse_round_trip():
    """ISSUE 15 satellite: a traced span stamps its series' exemplar;
    the rendered exposition carries an OpenMetrics exemplar suffix on
    exactly one in-range bucket line per series, and parse_prometheus
    returns it (labels + value) under __exemplars__."""
    from tigerbeetle_tpu.trace.context import fmt_trace_id, mint_context

    t = _tracer_with_latency_series()  # untraced spans: no exemplars
    ctx = mint_context(7, 1)
    tid = fmt_trace_id(ctx.trace_id)
    with t.span(Event.window_commit, ctx=ctx, route="chain",
                tier="scan"):
        pass
    assert any(ex["trace_id"] == tid for ex in t.exemplars.values())
    text = render_prometheus(t)
    parsed = parse_prometheus(text)
    exemplars = parsed["__exemplars__"]["tb_tpu_window_commit_us_bucket"]
    assert len(exemplars) == 1  # one suffixed bucket line per series
    labels, ex_labels, ex_value = exemplars[0]
    assert labels["route"] == "chain" and labels["tier"] == "scan"
    assert ex_labels == {"trace_id": tid}
    # OpenMetrics: the exemplar lies within its bucket's bounds.
    assert ex_value > 0
    if labels["le"] != "+Inf":
        assert ex_value <= float(labels["le"])
    # The stripped text (no suffixes) parses to the identical series —
    # the suffix never perturbs the sample itself.
    base = parse_prometheus(
        "\n".join(ln.partition(" # ")[0] for ln in text.splitlines()))
    assert base["tb_tpu_window_commit_us_bucket"] \
        == parsed["tb_tpu_window_commit_us_bucket"]
    assert "__exemplars__" not in base


def test_exemplar_merge_keeps_slowest_sample():
    from tigerbeetle_tpu.trace.context import fmt_trace_id

    from tigerbeetle_tpu.trace.context import TraceContext

    def traced(pid, dur_us, raw_tid):
        t = Tracer(pid=pid)
        t.record_span(Event.window_commit, t.now_ns(),
                      int(dur_us * 1_000), route="chain", tier="scan",
                      ctx=TraceContext(trace_id=raw_tid))
        return t

    slow_tid = fmt_trace_id(0xABC)
    parsed = parse_prometheus(render_prometheus(
        [traced(0, 50.0, 0x123), traced(1, 9_000.0, 0xABC)]))
    exemplars = parsed["__exemplars__"]["tb_tpu_window_commit_us_bucket"]
    assert len(exemplars) == 1
    _, ex_labels, ex_value = exemplars[0]
    assert ex_labels["trace_id"] == slow_tid  # the p99 candidate wins
    assert ex_value == pytest.approx(9_000.0, rel=0.01)


def test_render_merges_tracers():
    a = _tracer_with_latency_series()
    b = _tracer_with_latency_series()
    parsed = parse_prometheus(render_prometheus([a, b]))
    assert parsed["tb_tpu_serving_retries_total"] == [({}, 6.0)]
    total = sum(v for _, v in parsed["tb_tpu_window_commit_us_count"])
    assert total == 6.0  # histograms merged losslessly across tracers


def test_metrics_server_scrape():
    t = _tracer_with_latency_series()
    srv = MetricsServer(lambda: render_prometheus(t), port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=5) as r:
            assert r.status == 200
            assert "version=0.0.4" in r.headers["Content-Type"]
            parsed = parse_prometheus(r.read().decode())
        assert "tb_tpu_window_commit_us_bucket" in parsed
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=5)
    finally:
        srv.close()


# ---------------------------------------------------------- SLO engine

def test_load_objectives_committed_file():
    cfg = load_objectives()
    names = {o.name for o in cfg["objectives"]}
    assert "chain_window_p99_ms" in names
    assert cfg["burn_window_runs"] >= 1
    assert 0.0 < cfg["burn_budget"] < 1.0


def test_dead_slo_rejected(tmp_path):
    def _write(objective):
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({"objectives": [objective]}))
        return str(p)

    with pytest.raises(ValueError, match="no_such_event"):
        load_objectives(_write({"name": "x", "event": "no_such_event",
                                "threshold": 1.0}))
    with pytest.raises(ValueError, match="counter"):
        load_objectives(_write({"name": "x", "event": "serving_retries",
                                "threshold": 1.0}))
    with pytest.raises(ValueError, match="histogram dimensions"):
        load_objectives(_write({"name": "x", "event": "window_commit",
                                "tags": {"bogus": "y"},
                                "threshold": 1.0}))
    p = tmp_path / "empty.json"
    p.write_text(json.dumps({"objectives": []}))
    with pytest.raises(ValueError, match="no objectives"):
        load_objectives(str(p))


def test_evaluate_and_breach_counter():
    t = _tracer_with_latency_series()
    cfg = load_objectives()
    rows = evaluate(t, cfg["objectives"], emit_to=t)
    by_name = {r["name"]: r for r in rows}
    # Sub-millisecond no-op spans sit far under the ms thresholds.
    assert by_name["chain_window_p99_ms"]["ok"] is True
    assert by_name["chain_window_p99_ms"]["count"] == 2
    # replay histogram: 4 windows vs the "windows"-unit threshold.
    assert by_name["recovery_replay_windows_max"]["value"] == 4
    assert "slo_breach" not in t.counters
    # Forced breach: every objective's threshold below any value.
    forced = [dataclasses.replace(o, threshold=-1.0)
              for o in cfg["objectives"]]
    rows2 = evaluate(t, forced, emit_to=t)
    breached = [r for r in rows2 if r["ok"] is False]
    assert breached and t.counters["slo_breach"] == len(breached)
    # An objective whose series is empty is unknown, not a breach.
    empty = Tracer(pid=1)
    rows3 = evaluate(empty, cfg["objectives"])
    assert all(r["ok"] is None and r["value"] is None for r in rows3)


def test_burn_rates_and_badges():
    def run(ok):
        return [{"name": "o", "ok": ok}]

    burn = burn_rates([run(True), run(False), run(False), run(True)],
                      window_runs=4, budget=0.25)["o"]
    assert burn["burn_rate"] == 0.5
    assert burn["breaches"] == 2
    assert burn["breached_now"] is False
    assert burn["badge"] is True  # burn 0.5 > budget 0.25
    # Latest-run breach raises the badge regardless of burn.
    burn2 = burn_rates([run(True)] * 7 + [run(False)],
                       window_runs=8, budget=0.5)["o"]
    assert burn2["breached_now"] is True and burn2["badge"] is True
    # Unknown runs don't consume error budget.
    burn3 = burn_rates([run(None), run(None), run(True)],
                       window_runs=8, budget=0.25)["o"]
    assert burn3["evaluated"] == 1 and burn3["badge"] is False


def test_evaluate_bench_record():
    cfg = load_objectives()
    h = Histogram()
    h.record_many([300.0] * 50)  # ms, over the 250ms chain threshold
    record = {"serving_batch_latency": {"histogram": h.to_dict(),
                                        "p99_ms": 300.0}}
    rows = {r["name"]: r
            for r in evaluate_bench_record(record, cfg["objectives"])}
    assert rows["chain_window_p99_ms"]["ok"] is False
    assert rows["window_p99_ms"]["ok"] is True  # 300 <= 400
    # No histogram: the pinned p99 is the q=0.99 fallback.
    rows2 = {r["name"]: r for r in evaluate_bench_record(
        {"serving_batch_latency": {"p99_ms": 120.0}}, cfg["objectives"])}
    assert rows2["chain_window_p99_ms"]["value"] == 120.0
    # Records without the series evaluate unknown.
    rows3 = evaluate_bench_record({}, cfg["objectives"])
    assert all(r["ok"] is None for r in rows3)


# ------------------------------------------------------- critical path

def _span(name, ts, dur, pid=0, **args):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": pid,
            "tid": 0, "args": args}


def test_critical_path_serving_windows():
    # 10 windows; the slowest is dominated by serving_dispatch.
    events = []
    t = 0.0
    for i in range(10):
        dur = 10_000.0 if i == 9 else 1_000.0
        events.append(_span("window_commit", t, dur, route="chain"))
        events.append(_span("serving_dispatch", t + 100,
                            dur * 0.8, what="window"))
        t += dur + 500.0
    cp = critical_path({"traceEvents": events}, quantile=0.9)
    assert cp["window_event"] == "window_commit"
    assert cp["windows_total"] == 10 and cp["windows_analyzed"] == 1
    assert cp["p99_owner"] == "serving_dispatch"
    assert cp["stage_share"]["serving_dispatch"] == pytest.approx(
        0.8, abs=0.02)
    assert sum(cp["stage_share"].values()) == pytest.approx(1.0, abs=0.01)


def test_critical_path_synthesized_commit_groups():
    # No window spans: per-(pid, op) commit groups become the windows,
    # and only the group's own members are attributed (an interleaved
    # neighbor op's spans must not leak in).
    events = []
    for op in range(5):
        base = op * 10_000.0
        dur = 8_000.0 if op == 4 else 1_000.0
        events.append(_span("commit_execute", base, dur * 0.25, op=op))
        events.append(_span("commit_checkpoint", base + dur * 0.25,
                            dur * 0.75, op=op))
    cp = critical_path({"traceEvents": events}, quantile=0.8)
    assert cp["window_event"] == "commit_op"
    assert cp["p99_owner"] == "commit_checkpoint"
    assert cp["windows_total"] == 5


def test_critical_path_empty():
    assert critical_path({"traceEvents": []}) is None


# --------------------------------------- live parity + regression leg

def test_endpoint_p99_matches_offline_trace():
    """Acceptance: the endpoint's per-route window histogram p99 agrees
    with the offline (merged-trace) exact quantile within the histogram
    error bound."""
    from tigerbeetle_tpu.testing.latency_smoke import measure

    t = Tracer(pid=0)
    measure(windows=6, warmup=1, tracer=t)
    parsed = parse_prometheus(render_prometheus(t))
    # The supervisor tagged every window_commit span with its route.
    routes = {lab.get("route")
              for lab, _ in parsed["tb_tpu_window_commit_us_count"]}
    assert routes and None not in routes
    exact = span_quantile(t.chrome_dict(), "window_commit", 0.99)[""]
    merged = Histogram()
    for key, (name, _tags) in t.histogram_series.items():
        if name == "window_commit":
            merged.merge(t.histograms[key])
    got_ms = merged.quantile(0.99) / 1000.0
    assert abs(got_ms - exact) / exact <= 2 * REL_ERROR


def test_bench_regression_leg_pass_and_injected_fail(monkeypatch):
    """The gate leg passes on the unmodified tree and REDs under an
    injected 2x-baseline per-window slowdown."""
    from tigerbeetle_tpu.testing import latency_smoke

    monkeypatch.delenv("TB_TPU_LATENCY_INJECT_MS", raising=False)
    assert latency_smoke.regression_main(["--windows", "6"]) == 0
    with open(latency_smoke.BASELINE_PATH) as f:
        base_p99 = json.load(f)["p99_ms"]
    monkeypatch.setenv("TB_TPU_LATENCY_INJECT_MS",
                       str(2.0 * base_p99 + 10.0))
    assert latency_smoke.regression_main(["--windows", "6"]) >= 1


def test_bench_trajectory_guard(tmp_path, monkeypatch):
    from tigerbeetle_tpu.testing import latency_smoke

    def rec(name, p99):
        (tmp_path / name).write_text(json.dumps(
            {"parsed": {"serving_batch_latency": {"p99_ms": p99}}}))

    rec("BENCH_r01.json", 80.0)
    rec("BENCH_r02.json", 90.0)
    monkeypatch.setattr(latency_smoke, "BENCH_GLOB",
                        str(tmp_path / "BENCH_r*.json"))
    assert latency_smoke.check_trajectory() == 0
    rec("BENCH_r03.json", 170.0)  # 2.1x the best prior (80)
    assert latency_smoke.check_trajectory() == 1


def test_bench_trajectory_backcompat_pre_observatory_records(tmp_path):
    """Schema stability across record generations (ISSUE 20): a
    pre-observatory BENCH record — no `profile` sub-dict anywhere —
    must audit identically to a new record that carries the full
    ##profile payload. The trajectory audit keys only on the pinned
    serving p99, and the ratio check still bites across the
    generation boundary."""
    from tigerbeetle_tpu.testing import latency_smoke

    old = {"config": {"quick": True},
           "parsed": {"serving_batch_latency": {"p99_ms": 80.0}}}
    assert "profile" not in old and "profile" not in old["parsed"]
    new = {"config": {"quick": True},
           "parsed": {"serving_batch_latency": {"p99_ms": 88.0}},
           "profile": {"cost_model": {"tiers": {}},
                       "dispatch_device_time": {}, "roofline": {},
                       "memwatch": {"reds": []}}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(old))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(new))
    bench_glob = str(tmp_path / "BENCH_r*.json")
    assert latency_smoke.check_trajectory(bench_glob) == 0
    # The guard still REDs across the boundary: a regressed NEW record
    # against an old-format best prior.
    (tmp_path / "BENCH_r03.json").write_text(json.dumps(
        dict(new, parsed={"serving_batch_latency": {"p99_ms": 170.0}})))
    assert latency_smoke.check_trajectory(bench_glob) == 1


# ------------------------------------------------------- devhub panels

def test_devhub_slo_and_critical_path_panels(tmp_path):
    from tigerbeetle_tpu import devhub

    history = str(tmp_path / "history.jsonl")
    out = str(tmp_path / "devhub.html")
    h = Histogram()
    h.record_many([300.0] * 40)  # breaches chain_window_p99_ms (250ms)
    cp = {"window_event": "window_commit", "windows_total": 40,
          "windows_analyzed": 4, "slow_quantile": 0.9,
          "threshold_ms": 200.0, "p99_ms": 310.0,
          "stage_share": {"serving_dispatch": 0.7, "other": 0.3},
          "p99_owner": "serving_dispatch"}
    devhub.record(history, {
        "value": 1.0,
        "serving_batch_latency": {"p99_ms": 300.0,
                                  "histogram": h.to_dict()},
        "trace": {"critical_path": cp},
    })
    assert devhub.render(history, out) == 1
    html_text = open(out).read()
    assert "SLOs (perf/slo.json" in html_text
    assert "BREACHED" in html_text
    assert "p99 critical path" in html_text
    assert "serving_dispatch" in html_text


# --------------------------------------------- vortex cluster scrape

@pytest.mark.integration
def test_vortex_metrics_endpoint(tmp_path):
    """Acceptance: curl /metrics on a running vortex cluster yields
    Prometheus-parseable output whose commit histograms agree with the
    offline merged trace within the histogram error bound."""
    from tigerbeetle_tpu.main import _parse_addresses
    from tigerbeetle_tpu.testing.vortex import VortexSupervisor
    from tigerbeetle_tpu.types import Account, Transfer
    from tigerbeetle_tpu.vsr.client import Client

    import time

    supervisor = VortexSupervisor(str(tmp_path), replica_count=3,
                                  seed=5, trace=True, metrics=True)
    try:
        client = Client(cluster=supervisor.cluster, client_id=13,
                        replica_addresses=_parse_addresses(
                            supervisor.addresses))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=1, ledger=1, code=1),
                                        Account(id=2, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
        else:
            raise AssertionError("cluster never became available")
        for i in range(8):
            client.create_transfers([Transfer(
                id=100 + i, debit_account_id=1, credit_account_id=2,
                amount=1 + i, ledger=1, code=1)])
        # Live scrape: parseable, and the commit pipeline fed span
        # histograms on every replica. A backup that joined late (slow
        # jax import in its process) exposes commit-free metrics until
        # it finishes replaying — wait for cluster-wide catch-up first.
        supervisor.wait_caught_up()
        for i in range(3):
            parsed = parse_prometheus(supervisor.scrape_metrics(i))
            assert parsed["tb_tpu_commit_execute_us_count"][0][1] > 0
            assert parsed["tb_tpu_commits_total"][0][1] > 0
        client.close()
    finally:
        supervisor.shutdown()
    merged = supervisor.collect_merged_trace()
    # Offline parity: the merged cluster-wide histogram p99 vs the
    # exact nearest-rank p99 over the same merged trace's spans.
    hmeta = merged["metadata"]["histograms"]["commit_execute"]
    p99_hist_ms = Histogram.from_dict(hmeta).quantile(0.99) / 1000.0
    p99_exact_ms = span_quantile(merged, "commit_execute", 0.99)[""]
    assert abs(p99_hist_ms - p99_exact_ms) / p99_exact_ms <= 2 * REL_ERROR
