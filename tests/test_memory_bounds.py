"""Memory-bounds doctrine (VERDICT r1 #9; docs/ARCHITECTURE.md:189-230).

Serving memory must not grow with history: the host account_events tail
prunes at every checkpoint (history lives in the forest's events tree),
the device event ring recycles per batch in serving mode, the object
caches are bounded by construction, and the session table / bus send
buffers carry hard caps. The soak drives enough commits that unbounded
structures would visibly grow, then asserts they didn't — with replica
convergence intact (pruning is deterministic) and history still
queryable from the LSM.
"""

import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import (
    Account,
    ChangeEventsFilter,
    Operation,
    Transfer,
)


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr,
                 amount=amt, ledger=1, code=1).pack()
        for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


class TestEventPruningSoak:
    def test_cluster_events_stay_bounded_and_converged(self):
        cluster = Cluster(seed=31, replica_count=3)
        client = cluster.client(700)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        ok = cluster.run(4000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        per_batch = 40
        n_batches = 40  # >> checkpoint_interval (16): several prunes
        nid = 10**6
        for b in range(n_batches):
            specs = [(nid + i, 1 + (i % 2), 2 - (i % 2), 1 + i)
                     for i in range(per_batch)]
            nid += per_batch
            client.request(Operation.create_transfers,
                           _transfers_body(specs))
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()
        cluster.settle()
        interval = cluster.replicas[0].options.checkpoint_interval
        # The host tail holds at most the post-checkpoint window (+ the
        # current bar), NOT the whole history.
        bound = (interval + 2) * per_batch
        total = n_batches * per_batch
        for r in cluster.replicas:
            st = r.state_machine.state
            assert len(st.account_events) <= bound, len(st.account_events)
            assert st.events_base + len(st.account_events) >= total
            # History is still fully queryable (forest-served).
            got = r.state_machine.get_change_events(
                ChangeEventsFilter(limit=5))
            assert len(got) == 5  # the OLDEST events — long since pruned
        # Deterministic pruning: replicas still byte-identical.
        cluster.check_convergence()

    def test_restarted_replica_matches_pruned_peers(self):
        cluster = Cluster(seed=32, replica_count=3)
        client = cluster.client(701)
        client.request(Operation.create_accounts, _accounts_body([1, 2]))
        ok = cluster.run(4000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        nid = 10**6
        for b in range(25):
            specs = [(nid + i, 1, 2, 1) for i in range(20)]
            nid += 20
            client.request(Operation.create_transfers,
                           _transfers_body(specs))
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()
        cluster.settle()
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        cluster.restart(victim)
        cluster.settle()
        cluster.check_convergence()


class TestDeviceServingBounds:
    def test_ring_recycles_and_mirror_prunes(self):
        from tigerbeetle_tpu.vsr.durable import DurableState
        from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

        durable = DurableState(MemoryStorage(TEST_LAYOUT))
        sm = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 14)
        sm.attach_durable(durable)
        assert sm.led.recycle_events
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in range(1, 11)], 20)
        rng = np.random.default_rng(33)
        ts, nid = 10**9, 10**6
        for b in range(30):
            evs = [Transfer(id=nid + i,
                            debit_account_id=1 + int(rng.integers(0, 10)),
                            credit_account_id=1 + int(rng.integers(0, 10)),
                            amount=1 + int(rng.integers(0, 50)),
                            ledger=1, code=1)
                   for i in range(100)]
            for e in evs:
                if e.debit_account_id == e.credit_account_id:
                    e.credit_account_id = e.debit_account_id % 10 + 1
            nid += 100
            ts += 150
            sm.create_transfers(evs, ts)
            flushed = durable.flush(sm.state)
            sm.cache_upsert(*flushed)
            # The replica prunes at checkpoints; emulate every 4 batches.
            if b % 4 == 3:
                sm.state.prune_account_events(durable.events_persisted)
        assert sm.led.fallbacks == 0
        # The device ring rewound after every consumed batch.
        assert int(np.asarray(sm.led.state["events"]["count"])) == 0
        assert sm.led._events_pushed == 0
        # The mirror tail holds only the un-pruned window.
        assert len(sm.state.account_events) <= 4 * 100
        assert sm.state.events_base + len(sm.state.account_events) == 3000
        # Caches bounded; serving still correct from the forest.
        assert len(sm._acct_cache) <= sm._acct_cache.capacity
        got = sm.get_change_events(ChangeEventsFilter(limit=3))
        assert len(got) == 3
        # Hard batches (mirror path) still work after recycling.
        # E2 same-kind duplicate id forces the exact path (balancing,
        # the previous trigger here, now runs natively).
        hard = [
            Transfer(id=nid, debit_account_id=1, credit_account_id=2,
                     amount=5, ledger=1, code=1),
            Transfer(id=nid, debit_account_id=1, credit_account_id=2,
                     amount=5, ledger=1, code=1),
        ]
        ts += 10
        res = sm.create_transfers(hard, ts)
        assert [r.status.name for r in res] == ["created", "exists"]
        assert sm.led.fallbacks == 1
        assert int(np.asarray(sm.led.state["events"]["count"])) == 0


class TestStaticAllocationLedger:
    """ISSUE 20: the static-allocation ledger (trace/memwatch.py) must
    predict the ACTUAL resident device bytes from caps alone — the
    memory-watermark plane's whole claim — on 1/2/8-device meshes, with
    partitioned per-device bytes scaling ~1/n."""

    A_CAP, T_CAP = 1 << 9, 1 << 11

    @staticmethod
    def _mesh_sizes():
        import jax

        return [s for s in (1, 2, 8) if s <= len(jax.devices())]

    def test_replicated_static_matches_device_bytes(self):
        import jax

        from tigerbeetle_tpu.serving import ServingSupervisor
        from tigerbeetle_tpu.trace import measure_ledger, static_ledger

        sup = ServingSupervisor(a_cap=self.A_CAP, t_cap=self.T_CAP)
        static = static_ledger(self.A_CAP, self.T_CAP)
        measured = measure_ledger(sup.led)
        # Every state component: predicted == measured, EXACTLY (both
        # are shape-derived; any drift means init_state grew a buffer
        # the budget trail doesn't know about).
        for name, pin in static["components"].items():
            if name.startswith("state."):
                assert measured["components"][name] == pin, \
                    (name, pin, measured["components"].get(name))
        # ... and measured == the ACTUAL device allocation (`nbytes` of
        # the live committed arrays), so the shape ledger is not a
        # parallel bookkeeping fiction.
        actual = sum(int(x.nbytes)
                     for x in jax.tree_util.tree_leaves(sup.led.state))
        state_total = sum(v for k, v in measured["components"].items()
                          if k.startswith("state."))
        assert state_total == actual, (state_total, actual)

    def test_partitioned_per_device_bytes_scale_inverse_n(self):
        import jax
        from jax.sharding import Mesh

        from tigerbeetle_tpu.oracle import StateMachineOracle
        from tigerbeetle_tpu.parallel.partitioned import PartitionedRouter
        from tigerbeetle_tpu.trace import pytree_bytes, static_ledger
        from tigerbeetle_tpu.types import Account

        rep_state = sum(
            v for k, v in static_ledger(
                self.A_CAP, self.T_CAP)["components"].items()
            if k.startswith("state."))
        sizes = [n for n in self._mesh_sizes() if n > 1]
        assert sizes, "conftest pins an 8-device virtual mesh"
        for n in sizes:
            mesh = Mesh(np.array(jax.devices()[:n]), ("batch",))
            orc = StateMachineOracle()
            orc.create_accounts(
                [Account(id=i, ledger=1, code=1) for i in range(1, 9)],
                50)
            rt = PartitionedRouter(mesh, a_cap=self.A_CAP,
                                   t_cap=self.T_CAP)
            st = rt.from_oracle(orc)
            measured = pytree_bytes(st)
            static = static_ledger(self.A_CAP, self.T_CAP, n_shards=n)
            predicted = sum(
                v for k, v in static["components"].items()
                if k.startswith("state."))
            # Static prediction within tolerance of the live sharded
            # state (cap rounding per shard is the only slack source).
            assert abs(measured - predicted) <= 0.02 * predicted, \
                (n, measured, predicted)
            # Per-device share ~1/n of the replicated-equivalent
            # footprint: the reason to shard state at all.
            per_dev = measured / n
            assert per_dev < 0.75 * rep_state, (n, per_dev, rep_state)
            assert 0.5 / n < per_dev / rep_state < 2.0 / n, \
                (n, per_dev / rep_state)
