"""Fuzz registry: every fuzzer runs clean on a couple of seeds, and the
CLI surface works (reference: src/fuzz_tests.zig + `zig build fuzz`)."""

import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.main import main
from tigerbeetle_tpu.testing import fuzz

FAST = ["ewah", "multi_batch", "superblock_quorums", "journal",
        "client_sessions", "message_bus"]


@pytest.mark.parametrize("name", FAST)
@pytest.mark.parametrize("seed", [1, 2])
def test_fast_fuzzers(name, seed):
    fuzz.run(name, seed)


@pytest.mark.parametrize("seed", [5])
def test_lsm_tree_fuzzer(seed):
    fuzz.run("lsm_tree", seed, iterations=4)


@pytest.mark.parametrize("seed", [9])
def test_state_machine_fuzzer(seed):
    fuzz.run("state_machine", seed, iterations=30)


def test_cli_list_and_unknown(capsys):
    assert main(["fuzz", "list"]) == 0
    out = capsys.readouterr().out.split()
    assert set(FAST) <= set(out)
    assert main(["fuzz", "not_a_fuzzer"]) == 1


def test_cli_run(capsys):
    assert main(["fuzz", "ewah", "3", "--iterations", "20"]) == 0
    assert "OK" in capsys.readouterr().out


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_durability_fuzzer(seed):
    """Crash-point recovery: reopening after a crash at ANY write boundary
    must succeed with balanced books."""
    fuzz.run("durability", seed, iterations=6)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_storage_faults_fuzzer(seed):
    """Zone-fault rules incl. the rebuild window: tolerated faults must
    always recover with zero silent divergence (byte-identical
    checkpoints asserted per run)."""
    fuzz.run("storage_faults", seed, iterations=2)


@pytest.mark.parametrize("seed", [4, 5])
def test_device_ledger_fuzzer(seed):
    """Mixed-eligibility DeviceLedger vs oracle: fast path <-> mirror
    regime transitions with full state + history parity."""
    fuzz.run("device_ledger", seed, iterations=15)


def test_cfo_budgeted(capsys):
    """cfo: random (fuzzer, seed) pairs under a run budget (reference:
    scripts/cfo.zig)."""
    assert main(["cfo", "--max-runs", "3", "--seed", "7"]) == 0
    assert "clean" in capsys.readouterr().out
