"""Deep limit cascades: the 8-round fixpoint escalates to the 32-round
variant on device instead of falling back to the host.

A K-wave cascade is constructed from K linked chains: chain k's first
member debits limited account L_k (which only has headroom if chain
k-1's second member's credit to L_k landed), and its second member
credits L_{k+1}. Chain 0 is poisoned, so the sequential truth unwinds
one chain per wave — resolvable only by a fixpoint with >= K rounds
(reference semantics: balance limits, src/tigerbeetle.zig:34-42; chain
rollback, src/state_machine.zig:3116-3150).
"""

import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

import numpy as np

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags as AF,
    Transfer,
    TransferFlags as TF,
)

TS = 10_000_000_000_000


def _cascade_events(k_chains, first_id=10_000):
    """k_chains linked pairs forming a k-wave limit cascade. Account
    layout: FUND (id 1, unlimited) and limited accounts L_1..L_{k+1}
    (ids 2..k+2), each with debits_must_not_exceed_credits and a
    pre-batch credit of 10. Chain k (0-based): [debit L_{k+1} by 20,
    credit L_{k+2} by 10]. Chain 0's second member is poisoned (missing
    account). Truth: chain 0 rolls back; L_2 never gets its relief
    credit, so chain 1's debit of 20 > 10+10 breaches; chain 1 rolls
    back; and so on — one chain per wave."""
    events = []
    tid = first_id
    for k in range(k_chains):
        dr_acct = 2 + k  # L_{k+1}
        cr_acct = 3 + k  # L_{k+2}
        poison = 999_999 if k == 0 else cr_acct
        events.append(Transfer(id=tid, debit_account_id=dr_acct,
                               credit_account_id=1, ledger=1, code=1,
                               amount=20, flags=TF.linked))
        events.append(Transfer(id=tid + 1, debit_account_id=1,
                               credit_account_id=poison, ledger=1,
                               code=1, amount=10))
        tid += 2
    return events


def _setup(n_limited):
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
    sm = StateMachineOracle()
    accounts = [Account(id=1, ledger=1, code=1)]
    accounts += [Account(id=i, ledger=1, code=1,
                         flags=AF.debits_must_not_exceed_credits)
                 for i in range(2, n_limited + 2)]
    led.create_accounts(accounts, TS)
    sm.create_accounts(accounts, TS)
    # Fund every limited account with credit 10 (headroom for one debit
    # of 20 only WITH the in-batch relief credit of 10).
    funds = [Transfer(id=100 + i, debit_account_id=1,
                      credit_account_id=i, ledger=1, code=1, amount=10)
             for i in range(2, n_limited + 2)]
    ts = TS + 1000
    led.create_transfers(funds, ts)
    sm.create_transfers(funds, ts)
    return led, sm


def _diff(led, sm, events, ts):
    got = led.create_transfers(events, ts)
    want = sm.create_transfers(events, ts)
    assert [(r.timestamp, r.status.name) for r in got] == \
           [(r.timestamp, r.status.name) for r in want]


def test_shallow_cascade_stays_in_first_tier():
    led, sm = _setup(8)
    _diff(led, sm, _cascade_events(4), TS + 5000)
    assert led.fallbacks == 0
    assert led.deep_fixpoint_batches == 0
    assert led.fixpoint_batches >= 1


def test_deep_cascade_escalates_on_device():
    """12 waves > the 8-round budget: must resolve via the 32-round
    variant, never the host."""
    led, sm = _setup(16)
    _diff(led, sm, _cascade_events(12), TS + 5000)
    assert led.fallbacks == 0, "escalation must not touch the host path"
    assert led.deep_fixpoint_batches == 1


def test_warm_kernels_is_inert():
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
    led.create_accounts([Account(id=1, ledger=1, code=1),
                         Account(id=2, ledger=1, code=1)], TS)
    before = {k: np.asarray(v).copy()
              for k, v in led.state["transfers"].items() if k != "count"}
    count_before = int(led.state["transfers"]["count"])
    led.warm_kernels(256)
    assert int(led.state["transfers"]["count"]) == count_before
    for k, v in before.items():
        np.testing.assert_array_equal(
            np.asarray(led.state["transfers"][k]), v)
    # Ledger still fully functional afterward.
    res = led.create_transfers(
        [Transfer(id=50, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=5)], TS + 100)
    assert res[0].status.name == "created"
