"""TCP bus: static message pool + suspend/resume backpressure
(reference: src/message_pool.zig:107, src/message_bus.zig:1217-1223 —
overload turns into TCP backpressure on clients, not reply drops)."""

import socket
import time

from tigerbeetle_tpu.vsr import message_bus as mb
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.message_bus import MessageBus

CLUSTER = 7


def _mk_server(on_message):
    bus = MessageBus(
        cluster=CLUSTER, on_message=on_message,
        replica_addresses=[("127.0.0.1", 0)], replica_id=0, listen=True)
    return bus


def _request(client_id: int, request: int, body: bytes = b"") -> bytes:
    h = Header(command=Command.request, cluster=CLUSTER, client=client_id,
               request=request, operation=128)
    return Message(h.finalize(body), body=body).pack()


def test_pool_watermark_suspends_and_resumes_client_reads(monkeypatch):
    """Flood a bus past the pool's high watermark with a client that does
    not drain its replies: the bus must SUSPEND reading that client (no
    reply drops), then resume once the client drains below the low
    watermark."""
    # Small pool so the test is fast.
    monkeypatch.setattr(mb, "MESSAGE_POOL_SIZE", 40)
    monkeypatch.setattr(mb, "POOL_SUSPEND_AT", 30)
    monkeypatch.setattr(mb, "POOL_RESUME_AT", 15)

    received = []
    replies: list = []
    server = _mk_server(lambda m: received.append(m))
    host, port = server.listen_address

    cli = socket.create_connection((host, port))
    cli.setblocking(True)
    # One request identifies the connection as a client peer.
    cli.sendall(_request(42, 1))
    deadline = time.time() + 5
    while not received and time.time() < deadline:
        server.poll(0.05)
    assert received, "request did not arrive"
    conn = server.by_peer[("client", 42)]

    # Big bodies: the queue must exceed kernel socket buffering, or the
    # flush legitimately drains the pool and resumes.
    reply_body = b"x" * (512 * 1024)
    rh = Header(command=Command.reply, cluster=CLUSTER, client=42,
                request=1, replica=0)
    reply = Message(rh.finalize(reply_body), body=reply_body)
    # Queue replies up to the high watermark WITHOUT the client reading.
    for _ in range(30):
        server.send_to_client(42, reply)
    assert server.dropped_client == 0, "suspension must preempt drops"
    assert conn.read_suspended, "client reads must suspend at the watermark"
    # Beyond the watermark, client enqueues drop: the headroom up to
    # MESSAGE_POOL_SIZE is RESERVED for replica traffic (a wedged client
    # must never starve consensus messages of pool slots).
    server.send_to_client(42, reply)
    assert server.dropped_client == 1
    server.poll(0.02)  # one flush round: kernel buffers fill, queue stays
    assert conn.read_suspended

    # While suspended, inbound client bytes are NOT read.
    cli.sendall(_request(42, 2))
    for _ in range(10):
        server.poll(0.02)
    assert len(received) == 1, "suspended connection must not be read"

    # The client drains: flushes release pool slots and reads resume.
    cli.setblocking(False)
    got = 0
    deadline = time.time() + 10
    while time.time() < deadline and (conn.read_suspended or got == 0):
        try:
            chunk = cli.recv(1 << 20)
            got += len(chunk)
        except BlockingIOError:
            pass
        server.poll(0.02)
    assert got > 0
    assert not conn.read_suspended, "reads must resume below low watermark"
    # The request sent during suspension is now delivered.
    deadline = time.time() + 5
    while len(received) < 2 and time.time() < deadline:
        try:
            cli.recv(1 << 20)
        except BlockingIOError:
            pass
        server.poll(0.02)
    assert len(received) == 2
    server.close()
    cli.close()


def test_replica_traffic_never_suspended(monkeypatch):
    """Replica peers are exempt from suspension (VSR liveness rides on
    them; its delivery contract tolerates drops instead)."""
    monkeypatch.setattr(mb, "MESSAGE_POOL_SIZE", 8)
    monkeypatch.setattr(mb, "POOL_SUSPEND_AT", 6)
    monkeypatch.setattr(mb, "POOL_RESUME_AT", 3)

    received = []
    server = _mk_server(lambda m: received.append(m))
    host, port = server.listen_address
    peer = socket.create_connection((host, port))
    hello = Header(command=Command.ping, cluster=CLUSTER, replica=2)
    peer.sendall(Message(hello.finalize()).pack())
    deadline = time.time() + 5
    while not received and time.time() < deadline:
        server.poll(0.05)
    conn = server.by_peer[("replica", 2)]

    pong = Header(command=Command.pong, cluster=CLUSTER, replica=0)
    msg = Message(pong.finalize())
    for _ in range(20):  # far past the tiny pool
        server.send_to_replica(2, msg)
    assert not conn.read_suspended
    assert server.dropped_replica > 0  # drops, never suspension
    server.close()
    peer.close()


class TestAdaptiveHedging:
    """reference: src/vsr/client.zig:734 — the hedge/resend battery is
    RTT-driven, not a fixed constant."""

    def _client(self):
        from tigerbeetle_tpu.vsr.client import Client

        return Client.__new__(Client)  # logic-only: no bus

    def test_hedge_tracks_rtt_ewma_with_clamps(self):
        from tigerbeetle_tpu.vsr import client as C

        c = self._client()
        c._hedge_override = None
        c.rtt_ewma_s = None
        # Unknown cluster: maximum patience before fan-out.
        assert c.hedge_delay_s() == C.HEDGE_MAX_S
        c._observe_rtt(0.05)
        assert c.rtt_ewma_s == 0.05
        assert abs(c.hedge_delay_s() - 0.2) < 1e-9  # 4x RTT
        # Fast cluster converges down; floor applies.
        for _ in range(60):
            c._observe_rtt(0.0005)
        assert c.hedge_delay_s() == C.HEDGE_MIN_S
        # Degraded link: ceiling applies.
        for _ in range(60):
            c._observe_rtt(3.0)
        assert c.hedge_delay_s() == C.HEDGE_MAX_S

    def test_override_pins_delay(self):
        c = self._client()
        c._hedge_override = 0.1
        c.rtt_ewma_s = 0.5
        assert c.hedge_delay_s() == 0.1

    def test_resend_backoff_exponential_with_jitter(self):
        from tigerbeetle_tpu.vsr import client as C

        c = self._client()
        c.client_id = 7
        delays = [c._resend_delay_s(a) for a in range(6)]
        # Monotone growth to the cap.
        assert all(b >= a for a, b in zip(delays, delays[1:]))
        assert delays[0] >= C.RESEND_BASE_S
        assert delays[-1] <= C.RESEND_MAX_S * 1.25
        # Different clients land on different phases.
        c2 = self._client()
        c2.client_id = 8
        assert c2._resend_delay_s(0) != c._resend_delay_s(0)
