"""TCP bus: static message pool + suspend/resume backpressure
(reference: src/message_pool.zig:107, src/message_bus.zig:1217-1223 —
overload turns into TCP backpressure on clients, not reply drops)."""

import socket
import time

from tigerbeetle_tpu.vsr import message_bus as mb
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.message_bus import MessageBus

CLUSTER = 7


def _mk_server(on_message):
    bus = MessageBus(
        cluster=CLUSTER, on_message=on_message,
        replica_addresses=[("127.0.0.1", 0)], replica_id=0, listen=True)
    return bus


def _request(client_id: int, request: int, body: bytes = b"") -> bytes:
    h = Header(command=Command.request, cluster=CLUSTER, client=client_id,
               request=request, operation=128)
    return Message(h.finalize(body), body=body).pack()


def test_pool_watermark_suspends_and_resumes_client_reads(monkeypatch):
    """Flood a bus past the pool's high watermark with a client that does
    not drain its replies: the bus must SUSPEND reading that client (no
    reply drops), then resume once the client drains below the low
    watermark."""
    # Small pool so the test is fast.
    monkeypatch.setattr(mb, "MESSAGE_POOL_SIZE", 40)
    monkeypatch.setattr(mb, "POOL_SUSPEND_AT", 30)
    monkeypatch.setattr(mb, "POOL_RESUME_AT", 15)

    received = []
    replies: list = []
    server = _mk_server(lambda m: received.append(m))
    host, port = server.listen_address

    cli = socket.create_connection((host, port))
    cli.setblocking(True)
    # One request identifies the connection as a client peer.
    cli.sendall(_request(42, 1))
    deadline = time.time() + 5
    while not received and time.time() < deadline:
        server.poll(0.05)
    assert received, "request did not arrive"
    conn = server.by_peer[("client", 42)]

    # Big bodies: the queue must exceed kernel socket buffering, or the
    # flush legitimately drains the pool and resumes.
    reply_body = b"x" * (512 * 1024)
    rh = Header(command=Command.reply, cluster=CLUSTER, client=42,
                request=1, replica=0)
    reply = Message(rh.finalize(reply_body), body=reply_body)
    # Queue replies up to the high watermark WITHOUT the client reading.
    for _ in range(30):
        server.send_to_client(42, reply)
    assert server.dropped_client == 0, "suspension must preempt drops"
    assert conn.read_suspended, "client reads must suspend at the watermark"
    # Beyond the watermark, client enqueues drop: the headroom up to
    # MESSAGE_POOL_SIZE is RESERVED for replica traffic (a wedged client
    # must never starve consensus messages of pool slots).
    server.send_to_client(42, reply)
    assert server.dropped_client == 1
    server.poll(0.02)  # one flush round: kernel buffers fill, queue stays
    assert conn.read_suspended

    # While suspended, inbound client bytes are NOT read.
    cli.sendall(_request(42, 2))
    for _ in range(10):
        server.poll(0.02)
    assert len(received) == 1, "suspended connection must not be read"

    # The client drains: flushes release pool slots and reads resume.
    cli.setblocking(False)
    got = 0
    deadline = time.time() + 10
    while time.time() < deadline and (conn.read_suspended or got == 0):
        try:
            chunk = cli.recv(1 << 20)
            got += len(chunk)
        except BlockingIOError:
            pass
        server.poll(0.02)
    assert got > 0
    assert not conn.read_suspended, "reads must resume below low watermark"
    # The request sent during suspension is now delivered.
    deadline = time.time() + 5
    while len(received) < 2 and time.time() < deadline:
        try:
            cli.recv(1 << 20)
        except BlockingIOError:
            pass
        server.poll(0.02)
    assert len(received) == 2
    server.close()
    cli.close()


def test_replica_traffic_never_suspended(monkeypatch):
    """Replica peers are exempt from suspension (VSR liveness rides on
    them; its delivery contract tolerates drops instead)."""
    monkeypatch.setattr(mb, "MESSAGE_POOL_SIZE", 8)
    monkeypatch.setattr(mb, "POOL_SUSPEND_AT", 6)
    monkeypatch.setattr(mb, "POOL_RESUME_AT", 3)

    received = []
    server = _mk_server(lambda m: received.append(m))
    host, port = server.listen_address
    peer = socket.create_connection((host, port))
    hello = Header(command=Command.ping, cluster=CLUSTER, replica=2)
    peer.sendall(Message(hello.finalize()).pack())
    deadline = time.time() + 5
    while not received and time.time() < deadline:
        server.poll(0.05)
    conn = server.by_peer[("replica", 2)]

    pong = Header(command=Command.pong, cluster=CLUSTER, replica=0)
    msg = Message(pong.finalize())
    for _ in range(20):  # far past the tiny pool
        server.send_to_replica(2, msg)
    assert not conn.read_suspended
    assert server.dropped_replica > 0  # drops, never suspension
    server.close()
    peer.close()
