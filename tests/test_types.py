"""Data-model tests: sizes, codec round-trips, enum codes, precedence order.

Modeled on the reference's inline comptime asserts (src/tigerbeetle.zig:28-32,
111-115, 193-214, 401-423) and unit tests.
"""

import pytest

from tigerbeetle_tpu.constants import BATCH_MAX, U128_MAX
from tigerbeetle_tpu.types import (
    Account,
    AccountBalance,
    AccountFilter,
    AccountFlags,
    ChangeEventsFilter,
    CREATE_ACCOUNT_PRECEDENCE,
    CREATE_TRANSFER_PRECEDENCE,
    CreateAccountResult,
    CreateAccountStatus,
    CreateTransferResult,
    CreateTransferStatus,
    Operation,
    QueryFilter,
    Transfer,
    TransferFlags,
)


def test_sizes():
    assert len(Account().pack()) == 128
    assert len(Transfer().pack()) == 128
    assert len(AccountBalance().pack()) == 128
    assert len(AccountFilter().pack()) == 128
    assert len(QueryFilter().pack()) == 64
    assert len(ChangeEventsFilter().pack()) == 64
    assert len(CreateAccountResult().pack()) == 16
    assert len(CreateTransferResult().pack()) == 16
    assert BATCH_MAX == 8190


def test_account_roundtrip():
    a = Account(
        id=(1 << 127) + 5,
        debits_pending=1,
        debits_posted=(1 << 100),
        credits_pending=3,
        credits_posted=4,
        user_data_128=U128_MAX - 1,
        user_data_64=2**64 - 2,
        user_data_32=7,
        ledger=700,
        code=17,
        flags=int(AccountFlags.history | AccountFlags.closed),
        timestamp=999,
    )
    assert Account.unpack(a.pack()) == a


def test_transfer_roundtrip():
    t = Transfer(
        id=123456789012345678901234567890,
        debit_account_id=1,
        credit_account_id=2,
        amount=U128_MAX,
        pending_id=42,
        user_data_128=5,
        user_data_64=6,
        user_data_32=7,
        timeout=3600,
        ledger=1,
        code=1,
        flags=int(TransferFlags.pending | TransferFlags.linked),
        timestamp=1234,
    )
    assert Transfer.unpack(t.pack()) == t


def test_transfer_field_offsets():
    """Wire layout byte-for-byte (reference extern struct field order)."""
    t = Transfer(id=1, debit_account_id=2, credit_account_id=3, amount=4,
                 pending_id=5, user_data_128=6, user_data_64=7, user_data_32=8,
                 timeout=9, ledger=10, code=11, flags=12, timestamp=13)
    raw = t.pack()
    assert int.from_bytes(raw[0:16], "little") == 1
    assert int.from_bytes(raw[16:32], "little") == 2
    assert int.from_bytes(raw[32:48], "little") == 3
    assert int.from_bytes(raw[48:64], "little") == 4
    assert int.from_bytes(raw[64:80], "little") == 5
    assert int.from_bytes(raw[80:96], "little") == 6
    assert int.from_bytes(raw[96:104], "little") == 7
    assert int.from_bytes(raw[104:108], "little") == 8
    assert int.from_bytes(raw[108:112], "little") == 9
    assert int.from_bytes(raw[112:116], "little") == 10
    assert int.from_bytes(raw[116:118], "little") == 11
    assert int.from_bytes(raw[118:120], "little") == 12
    assert int.from_bytes(raw[120:128], "little") == 13


def test_status_wire_codes():
    """Spot-check wire codes against reference values (tigerbeetle.zig:153-319)."""
    assert CreateAccountStatus.linked_event_failed == 1
    assert CreateAccountStatus.exists == 21
    assert CreateAccountStatus.imported_event_timestamp_must_not_regress == 26
    assert CreateAccountStatus.created == (1 << 32) - 1

    assert CreateTransferStatus.linked_event_failed == 1
    assert CreateTransferStatus.exists == 46
    assert CreateTransferStatus.id_already_failed == 68
    assert CreateTransferStatus.exceeds_credits == 54
    assert CreateTransferStatus.exceeds_debits == 55
    assert CreateTransferStatus.exists_with_different_ledger == 67
    assert CreateTransferStatus.created == (1 << 32) - 1


def test_status_codes_dense():
    """Codes 1..max must be gap-free (reference comptime asserts :193-214)."""
    account_codes = {int(s) for s in CreateAccountStatus} - {0, (1 << 32) - 1}
    assert account_codes == set(range(1, 27))
    transfer_codes = {int(s) for s in CreateTransferStatus} - {0, (1 << 32) - 1}
    assert transfer_codes == set(range(1, 69))


def test_precedence_order():
    """Precedence = declaration order, not numeric order."""
    P = CREATE_TRANSFER_PRECEDENCE
    # imported_event_expected (code 56) outranks timestamp_must_be_zero (code 3).
    assert P[CreateTransferStatus.imported_event_expected] < P[CreateTransferStatus.timestamp_must_be_zero]
    # exists checks outrank flags_are_mutually_exclusive.
    assert P[CreateTransferStatus.exists] < P[CreateTransferStatus.flags_are_mutually_exclusive]
    # exceeds_credits is almost last.
    assert P[CreateTransferStatus.exceeds_credits] > P[CreateTransferStatus.overflows_timeout]
    assert P[CreateTransferStatus.linked_event_failed] == 0
    assert CREATE_ACCOUNT_PRECEDENCE[CreateAccountStatus.linked_event_failed] == 0
    # created ranks last in both.
    assert P[CreateTransferStatus.created] == max(P.values())


def test_transient_statuses():
    assert CreateTransferStatus.debit_account_not_found.transient()
    assert CreateTransferStatus.exceeds_credits.transient()
    assert CreateTransferStatus.debit_account_already_closed.transient()
    assert not CreateTransferStatus.exists.transient()
    assert not CreateTransferStatus.linked_event_failed.transient()
    assert not CreateTransferStatus.overflows_debits.transient()


def test_balance_limit_predicates():
    a = Account(
        flags=int(AccountFlags.debits_must_not_exceed_credits),
        debits_pending=10,
        debits_posted=20,
        credits_posted=100,
    )
    assert not a.debits_exceed_credits(70)
    assert a.debits_exceed_credits(71)
    assert not a.credits_exceed_debits(10**30)  # flag not set


def test_operation_codes():
    assert Operation.pulse == 128
    assert Operation.create_accounts == 146
    assert Operation.create_transfers == 147
    assert Operation.create_transfers.is_batchable()
    assert Operation.create_transfers.is_multi_batch()
    assert not Operation.get_change_events.is_multi_batch()
    assert not Operation.pulse.is_batchable()
