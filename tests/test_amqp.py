"""AMQP 0.9.1 protocol + CDC AMQP sink against an in-process mini broker.

reference: src/amqp.zig + src/amqp/{protocol,spec}.zig (own protocol
implementation, no client library) and src/cdc/runner.zig (publish with
confirms). The broker here implements the server side of the same subset,
so both directions of the codec are exercised honestly over a real socket.
"""

import json
import socket
import struct
import threading

from tigerbeetle_tpu import amqp
from tigerbeetle_tpu.amqp import (
    BASIC_ACK,
    BASIC_GET,
    BASIC_GET_EMPTY,
    BASIC_GET_OK,
    BASIC_PUBLISH,
    CHANNEL_OPEN,
    CHANNEL_OPEN_OK,
    CONFIRM_SELECT,
    CONFIRM_SELECT_OK,
    CONNECTION_CLOSE,
    CONNECTION_CLOSE_OK,
    CONNECTION_OPEN,
    CONNECTION_OPEN_OK,
    CONNECTION_START,
    CONNECTION_START_OK,
    CONNECTION_TUNE,
    CONNECTION_TUNE_OK,
    EXCHANGE_DECLARE,
    EXCHANGE_DECLARE_OK,
    FRAME_BODY,
    FRAME_HEADER,
    PROTOCOL_HEADER,
    QUEUE_BIND,
    QUEUE_BIND_OK,
    QUEUE_DECLARE,
    QUEUE_DECLARE_OK,
    RESOURCE_LOCKED,
    Frame,
    content_frames,
    field_table,
    longstr,
    method_frame,
    shortstr,
)


class MiniBroker:
    """Multi-connection AMQP 0.9.1 server: handshake, declarations
    (incl. exclusive queues), publishes (stored + routed to queues via
    the default exchange), confirms, basic.get/ack, purge — the server
    half of everything the CDC runner speaks."""

    def __init__(self):
        self.listener = socket.socket()
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(8)
        self.port = self.listener.getsockname()[1]
        self.lock = threading.Lock()
        self.messages = []  # every publish: (exchange, routing_key, body)
        # queue name -> list of (delivery_tag, body); unacked get-issued
        # messages by tag.
        self.queues: dict[str, list] = {}
        self.unacked: dict[int, tuple[str, bytes]] = {}
        self.exclusive: dict[str, int] = {}  # queue -> owner conn id
        self.declared_exchanges = []
        self.declared_queues = []
        self.bindings = []
        self.auth = None
        self.next_tag = 0
        self._conn_seq = 0
        self.thread = threading.Thread(target=self._accept, daemon=True)
        self.thread.start()

    def _accept(self):
        while True:
            try:
                sock, _ = self.listener.accept()
            except OSError:
                return
            self._conn_seq += 1
            threading.Thread(target=self._serve,
                             args=(sock, self._conn_seq),
                             daemon=True).start()

    def _route(self, exchange, routing_key, body):
        with self.lock:
            self.messages.append((exchange, routing_key, body))
            if exchange == "" and routing_key in self.queues:
                self.queues[routing_key].append(body)

    def _serve(self, sock, conn_id):
        rx = bytearray()

        def recv_frame():
            while True:
                got = Frame.parse(rx)
                if got is not None:
                    return got
                try:
                    chunk = sock.recv(64 * 1024)
                except OSError:
                    return None
                if not chunk:
                    return None
                rx.extend(chunk)

        try:
            header = b""
            while len(header) < 8:
                got = sock.recv(8 - len(header))
                if not got:
                    return
                header += got
            assert header == PROTOCOL_HEADER, header
            sock.sendall(method_frame(
                0, CONNECTION_START,
                struct.pack(">BB", 0, 9) + field_table({"product": "mini"})
                + longstr(b"PLAIN") + longstr(b"en_US")))

            delivery_tag = 0
            pending = None
            body_size = 0
            body = b""
            while True:
                got = recv_frame()
                if got is None:
                    break
                method = got.method
                if method == CONNECTION_START_OK:
                    args = got.args()
                    args.table()
                    mechanism = args.shortstr()
                    response = args.longstr()
                    self.auth = (mechanism, response)
                    sock.sendall(method_frame(
                        0, CONNECTION_TUNE,
                        struct.pack(">HIH", 0, 128 * 1024, 0)))
                elif method == CONNECTION_TUNE_OK:
                    pass
                elif method == CONNECTION_OPEN:
                    sock.sendall(method_frame(0, CONNECTION_OPEN_OK,
                                              b"\x00"))
                elif method == CHANNEL_OPEN:
                    sock.sendall(method_frame(
                        got.channel, CHANNEL_OPEN_OK, longstr(b"")))
                elif method == EXCHANGE_DECLARE:
                    args = got.args()
                    args.u16()
                    self.declared_exchanges.append(
                        (args.shortstr(), args.shortstr()))
                    sock.sendall(method_frame(got.channel,
                                              EXCHANGE_DECLARE_OK))
                elif method == QUEUE_DECLARE:
                    args = got.args()
                    args.u16()
                    name = args.shortstr()
                    flags = args.u8()
                    exclusive = bool(flags & 0b100)
                    with self.lock:
                        owner = self.exclusive.get(name)
                        if owner is not None and owner != conn_id:
                            sock.sendall(method_frame(
                                0, CONNECTION_CLOSE,
                                struct.pack(">H", RESOURCE_LOCKED)
                                + shortstr("RESOURCE_LOCKED")
                                + struct.pack(">HH", *QUEUE_DECLARE)))
                            break
                        if exclusive:
                            self.exclusive[name] = conn_id
                        self.declared_queues.append(name)
                        self.queues.setdefault(name, [])
                    sock.sendall(method_frame(
                        got.channel, QUEUE_DECLARE_OK,
                        shortstr(name) + struct.pack(">II", 0, 0)))
                elif method == QUEUE_BIND:
                    args = got.args()
                    args.u16()
                    self.bindings.append(
                        (args.shortstr(), args.shortstr(),
                         args.shortstr()))
                    sock.sendall(method_frame(got.channel, QUEUE_BIND_OK))
                elif method == CONFIRM_SELECT:
                    sock.sendall(method_frame(got.channel,
                                              CONFIRM_SELECT_OK))
                elif method == BASIC_GET:
                    args = got.args()
                    args.u16()
                    name = args.shortstr()
                    with self.lock:
                        store = self.queues.get(name, [])
                        if store:
                            msg = store.pop(0)
                            self.next_tag += 1
                            tag = self.next_tag
                            self.unacked[tag] = (name, msg, conn_id)
                        else:
                            msg = None
                    if msg is None:
                        sock.sendall(method_frame(
                            got.channel, BASIC_GET_EMPTY, shortstr("")))
                    else:
                        sock.sendall(
                            method_frame(
                                got.channel, BASIC_GET_OK,
                                struct.pack(">QB", tag, 0)
                                + shortstr("") + shortstr(name)
                                + struct.pack(">I", 0))
                            + content_frames(got.channel, msg,
                                             128 * 1024))
                elif method == BASIC_ACK:
                    args = got.args()
                    tag = args.u64()
                    with self.lock:
                        self.unacked.pop(tag, None)
                elif method == BASIC_PUBLISH:
                    args = got.args()
                    args.u16()
                    pending = (args.shortstr(), args.shortstr())
                elif method == CONNECTION_CLOSE:
                    sock.sendall(method_frame(0, CONNECTION_CLOSE_OK))
                    break
                elif got.type == FRAME_HEADER and pending is not None:
                    _, _, body_size, _ = struct.unpack_from(
                        ">HHQH", got.payload)
                    body = b""
                    if body_size == 0:
                        delivery_tag += 1
                        self._route(*pending, b"")
                        sock.sendall(method_frame(
                            got.channel, BASIC_ACK,
                            struct.pack(">QB", delivery_tag, 0)))
                        pending = None
                elif got.type == FRAME_BODY and pending is not None:
                    body += got.payload
                    if len(body) >= body_size:
                        delivery_tag += 1
                        self._route(*pending, body)
                        sock.sendall(method_frame(
                            got.channel, BASIC_ACK,
                            struct.pack(">QB", delivery_tag, 0)))
                        pending = None
        finally:
            # AMQP connection-death semantics: exclusive queues die with
            # their connection, and this connection's unacked (checked
            # out) messages return to the FRONT of their queues.
            with self.lock:
                for name in [n for n, c in self.exclusive.items()
                             if c == conn_id]:
                    del self.exclusive[name]
                for tag in [t for t, (_, _, c) in self.unacked.items()
                            if c == conn_id]:
                    name, msg, _ = self.unacked.pop(tag)
                    self.queues.setdefault(name, []).insert(0, msg)
            sock.close()

    def close(self):
        self.listener.close()


class TestAmqpClient:
    def test_handshake_declare_publish_confirm(self):
        broker = MiniBroker()
        client = amqp.AmqpClient("127.0.0.1", broker.port,
                                 user="svc", password="secret")
        try:
            client.exchange_declare("tb.cdc", "topic")
            client.queue_declare("audit")
            client.queue_bind("audit", "tb.cdc", "cdc.#")
            client.confirm_select()
            client.publish("tb.cdc", "cdc.single_phase", b"hello")
            client.publish("tb.cdc", "cdc.two_phase_pending", b"x" * 300_000)
            client.wait_confirms()
        finally:
            client.close()
            broker.close()
        assert broker.auth == ("PLAIN", b"\x00svc\x00secret")
        assert ("tb.cdc", "topic") in broker.declared_exchanges
        assert "audit" in broker.declared_queues
        assert ("audit", "tb.cdc", "cdc.#") in broker.bindings
        assert broker.messages[0] == ("tb.cdc", "cdc.single_phase", b"hello")
        ex, rk, body = broker.messages[1]
        assert rk == "cdc.two_phase_pending" and body == b"x" * 300_000

    def test_wait_confirms_out_of_order_and_multiple(self):
        """Acks may arrive out of order and with `multiple` set; a nack is
        a delivery failure (AMQP 0.9.1 publisher-confirms semantics)."""
        client = amqp.AmqpClient.__new__(amqp.AmqpClient)
        client.confirm_mode = True
        client.outstanding = {1, 2, 3}
        acks = [
            amqp.Frame(amqp.FRAME_METHOD, 1,
                       struct.pack(">HHQB", 60, 80, 3, 0)),  # ack tag 3
            amqp.Frame(amqp.FRAME_METHOD, 1,
                       struct.pack(">HHQB", 60, 80, 2, 1)),  # ack <=2
        ]
        client._recv_frame = lambda: acks.pop(0)
        client.wait_confirms()
        assert client.outstanding == set()

        client.outstanding = {1}
        nack = amqp.Frame(amqp.FRAME_METHOD, 1,
                          struct.pack(">HHQB", 60, 120, 1, 0))
        client._recv_frame = lambda: nack
        try:
            client.wait_confirms()
            assert False, "nack must raise"
        except amqp.ProtocolError as e:
            assert "nacked" in str(e)

    def test_frame_roundtrip_and_parse_publishes(self):
        raw = (method_frame(1, BASIC_PUBLISH,
                            struct.pack(">H", 0) + shortstr("e")
                            + shortstr("k") + b"\x00")
               + amqp.content_frames(1, b"payload"))
        got = list(amqp.parse_publishes(raw))
        assert got == [("e", "k", b"payload")]


class TestAmqpCommand:
    def test_cdc_pump_from_live_replica(self, tmp_path):
        """format -> start -> commit transfers -> `amqp --once` pumps the
        change events into the broker (reference: `tigerbeetle amqp`)."""
        import os
        import signal
        import subprocess
        import sys
        import time

        from tigerbeetle_tpu.main import _parse_addresses, main
        from tigerbeetle_tpu.types import Account, Transfer
        from tigerbeetle_tpu.vsr.client import Client

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        address = f"127.0.0.1:{port}"
        path = tmp_path / "r0.tigerbeetle"
        subprocess.run(
            [sys.executable, "-m", "tigerbeetle_tpu", "format", "--cluster=4",
             "--replica=0", "--replica-count=1", "--small", str(path)],
            check=True, cwd="/root/repo", timeout=60,
            stdout=subprocess.DEVNULL)
        proc = subprocess.Popen(
            [sys.executable, "-m", "tigerbeetle_tpu", "start",
             f"--addresses={address}", "--replica=0", "--cluster=4",
             "--engine=oracle", "--small", str(path)],
            cwd="/root/repo", env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        broker = MiniBroker()
        try:
            client = Client(cluster=4, client_id=5,
                            replica_addresses=_parse_addresses(address))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    client.create_accounts([Account(id=1, ledger=1, code=1),
                                            Account(id=2, ledger=1, code=1)])
                    break
                except TimeoutError:
                    continue
            client.create_transfers([
                Transfer(id=10, debit_account_id=1, credit_account_id=2,
                         amount=9, ledger=1, code=1)])
            client.close()
            rc = main(["amqp", f"--addresses={address}", "--cluster=4",
                       f"--amqp=127.0.0.1:{broker.port}", "--once"])
            assert rc == 0
        finally:
            broker.close()
            proc.send_signal(signal.SIGINT)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        cdc = [(ex, rk, b) for ex, rk, b in broker.messages
               if rk.startswith("cdc.")]
        assert len(cdc) == 1
        record = json.loads(cdc[0][2])
        assert record["transfer_id"] == 10 and record["transfer_amount"] == 9
        # The watermark went to the broker-resident progress queue.
        progress = [b for ex, rk, b in broker.messages
                    if rk == "tb.internal.progress.4"]
        assert len(progress) == 1
        assert json.loads(progress[0])["timestamp_processed"] > 0


class TestCdcAmqpSink:
    def test_runner_publishes_change_events_with_confirms(self):
        from tigerbeetle_tpu.cdc import AmqpSink, CDCRunner
        from tigerbeetle_tpu.state_machine import StateMachine
        from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

        sm = StateMachine(engine="oracle")
        ts = 10**9
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in (1, 2)], ts)
        ts += 1000
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1),
             Transfer(id=11, debit_account_id=1, credit_account_id=2,
                      amount=3, ledger=1, code=1,
                      flags=int(TransferFlags.pending))], ts)

        broker = MiniBroker()
        sink = AmqpSink("127.0.0.1", broker.port)
        try:
            runner = CDCRunner(sm, sink)
            published = runner.run_until_idle()
        finally:
            sink.close()
            broker.close()
        assert published == 2
        keys = [rk for _, rk, _ in broker.messages]
        assert keys == ["cdc.single_phase", "cdc.two_phase_pending"]
        record = json.loads(broker.messages[0][2])
        assert record["transfer_amount"] == 5
        assert record["type"] == "single_phase"

    def _sm(self, n):
        from tigerbeetle_tpu.state_machine import StateMachine
        from tigerbeetle_tpu.types import Account, Transfer

        sm = StateMachine(engine="oracle")
        ts = 10**9
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in (1, 2)], ts)
        for i in range(1, n + 1):
            sm.create_transfers(
                [Transfer(id=i, debit_account_id=1, credit_account_id=2,
                          amount=i, ledger=1, code=1)], ts + 1000 * i)
        return sm

    def test_broker_progress_queue_survives_runner_crash(self):
        """The watermark lives IN the broker (the reference's
        progress-tracker queue, cdc/runner.zig:34): runner A publishes
        two batches and dies; runner B recovers the watermark with
        basic.get and resumes exactly after the confirmed stream."""
        from tigerbeetle_tpu.cdc import AmqpProgress, AmqpSink, CDCRunner

        broker = MiniBroker()
        try:
            sm = self._sm(6)
            sink_a = AmqpSink("127.0.0.1", broker.port, cluster=7)
            prog_a = AmqpProgress("127.0.0.1", broker.port, cluster=7)
            runner_a = CDCRunner(sm, sink_a, batch_limit=2,
                                 progress=prog_a, pipeline=False)
            assert runner_a.recover() == 0
            runner_a.poll()
            runner_a.poll()  # events 1-4 confirmed, then "crash"
            sink_a.close()
            prog_a.close()

            sink_b = AmqpSink("127.0.0.1", broker.port, cluster=7)
            prog_b = AmqpProgress("127.0.0.1", broker.port, cluster=7)
            runner_b = CDCRunner(sm, sink_b, batch_limit=2,
                                 progress=prog_b, pipeline=False)
            watermark = runner_b.recover()
            assert watermark > 0
            assert runner_b.run_until_idle() == 2  # only 5, 6 remain
            sink_b.close()
            prog_b.close()
        finally:
            broker.close()
        cdc_bodies = [json.loads(b) for ex, rk, b in broker.messages
                      if rk.startswith("cdc.")]
        assert [r["transfer_id"] for r in cdc_bodies] == [1, 2, 3, 4, 5, 6]
        # Progress queue holds exactly one (newest) watermark message —
        # the runner's checkout returns to the queue as its connection
        # dies (broker-side requeue runs moments after close returns).
        import time as _t
        for _ in range(200):
            if len(broker.queues.get("tb.internal.progress.7", [])) == 1:
                break
            _t.sleep(0.01)
        assert len(broker.queues["tb.internal.progress.7"]) == 1

    def test_locker_queue_excludes_second_runner(self):
        """Two CDC runners for one cluster: the second's exclusive
        locker declare must fail (cdc/runner.zig:35 locker queue)."""
        import pytest

        from tigerbeetle_tpu.amqp import ProtocolError
        from tigerbeetle_tpu.cdc import AmqpSink

        broker = MiniBroker()
        try:
            first = AmqpSink("127.0.0.1", broker.port, cluster=9,
                             lock=True)
            with pytest.raises(ProtocolError, match="405"):
                AmqpSink("127.0.0.1", broker.port, cluster=9, lock=True)
            first.close()
            # Lock released with the connection: a successor acquires it.
            third = AmqpSink("127.0.0.1", broker.port, cluster=9,
                             lock=True)
            third.close()
        finally:
            broker.close()

    def test_pipelined_amqp_runner_overlaps_and_delivers_in_order(self):
        from tigerbeetle_tpu.cdc import AmqpProgress, AmqpSink, CDCRunner

        broker = MiniBroker()
        try:
            sm = self._sm(9)
            sink = AmqpSink("127.0.0.1", broker.port, cluster=3)
            prog = AmqpProgress("127.0.0.1", broker.port, cluster=3)
            runner = CDCRunner(sm, sink, batch_limit=2, progress=prog,
                               pipeline=True)
            runner.recover()
            assert runner.run_until_idle() == 9
            runner.close()
            sink.close()
            prog.close()
        finally:
            broker.close()
        cdc_bodies = [json.loads(b) for ex, rk, b in broker.messages
                      if rk.startswith("cdc.")]
        assert [r["transfer_id"] for r in cdc_bodies] == list(range(1, 10))
