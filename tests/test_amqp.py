"""AMQP 0.9.1 protocol + CDC AMQP sink against an in-process mini broker.

reference: src/amqp.zig + src/amqp/{protocol,spec}.zig (own protocol
implementation, no client library) and src/cdc/runner.zig (publish with
confirms). The broker here implements the server side of the same subset,
so both directions of the codec are exercised honestly over a real socket.
"""

import json
import socket
import struct
import threading

from tigerbeetle_tpu import amqp
from tigerbeetle_tpu.amqp import (
    BASIC_ACK,
    BASIC_PUBLISH,
    CHANNEL_OPEN,
    CHANNEL_OPEN_OK,
    CONFIRM_SELECT,
    CONFIRM_SELECT_OK,
    CONNECTION_CLOSE,
    CONNECTION_CLOSE_OK,
    CONNECTION_OPEN,
    CONNECTION_OPEN_OK,
    CONNECTION_START,
    CONNECTION_START_OK,
    CONNECTION_TUNE,
    CONNECTION_TUNE_OK,
    EXCHANGE_DECLARE,
    EXCHANGE_DECLARE_OK,
    FRAME_BODY,
    FRAME_HEADER,
    PROTOCOL_HEADER,
    QUEUE_BIND,
    QUEUE_BIND_OK,
    QUEUE_DECLARE,
    QUEUE_DECLARE_OK,
    Frame,
    field_table,
    longstr,
    method_frame,
    shortstr,
)


class MiniBroker:
    """Single-connection AMQP 0.9.1 server: handshake, declarations,
    publishes (stored), confirms."""

    def __init__(self):
        self.listener = socket.socket()
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(1)
        self.port = self.listener.getsockname()[1]
        self.messages = []  # (exchange, routing_key, body)
        self.declared_exchanges = []
        self.declared_queues = []
        self.bindings = []
        self.auth = None
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        sock, _ = self.listener.accept()
        rx = bytearray()

        def recv_frame():
            while True:
                got = Frame.parse(rx)
                if got is not None:
                    return got
                chunk = sock.recv(64 * 1024)
                if not chunk:
                    return None
                rx.extend(chunk)

        header = b""
        while len(header) < 8:
            header += sock.recv(8 - len(header))
        assert header == PROTOCOL_HEADER, header
        sock.sendall(method_frame(
            0, CONNECTION_START,
            struct.pack(">BB", 0, 9) + field_table({"product": "mini"})
            + longstr(b"PLAIN") + longstr(b"en_US")))

        delivery_tag = 0
        pending = None
        body_size = 0
        body = b""
        while True:
            got = recv_frame()
            if got is None:
                break
            method = got.method
            if method == CONNECTION_START_OK:
                args = got.args()
                args.table()
                mechanism = args.shortstr()
                response = args.longstr()
                self.auth = (mechanism, response)
                sock.sendall(method_frame(0, CONNECTION_TUNE, struct.pack(
                    ">HIH", 0, 128 * 1024, 0)))
            elif method == CONNECTION_TUNE_OK:
                pass
            elif method == CONNECTION_OPEN:
                sock.sendall(method_frame(0, CONNECTION_OPEN_OK, b"\x00"))
            elif method == CHANNEL_OPEN:
                sock.sendall(method_frame(
                    got.channel, CHANNEL_OPEN_OK, longstr(b"")))
            elif method == EXCHANGE_DECLARE:
                args = got.args()
                args.u16()
                self.declared_exchanges.append(
                    (args.shortstr(), args.shortstr()))
                sock.sendall(method_frame(got.channel, EXCHANGE_DECLARE_OK))
            elif method == QUEUE_DECLARE:
                args = got.args()
                args.u16()
                name = args.shortstr()
                self.declared_queues.append(name)
                sock.sendall(method_frame(
                    got.channel, QUEUE_DECLARE_OK,
                    shortstr(name) + struct.pack(">II", 0, 0)))
            elif method == QUEUE_BIND:
                args = got.args()
                args.u16()
                self.bindings.append(
                    (args.shortstr(), args.shortstr(), args.shortstr()))
                sock.sendall(method_frame(got.channel, QUEUE_BIND_OK))
            elif method == CONFIRM_SELECT:
                sock.sendall(method_frame(got.channel, CONFIRM_SELECT_OK))
            elif method == BASIC_PUBLISH:
                args = got.args()
                args.u16()
                pending = (args.shortstr(), args.shortstr())
            elif method == CONNECTION_CLOSE:
                sock.sendall(method_frame(0, CONNECTION_CLOSE_OK))
                break
            elif got.type == FRAME_HEADER and pending is not None:
                _, _, body_size, _ = struct.unpack_from(">HHQH", got.payload)
                body = b""
                if body_size == 0:
                    self._deliver(sock, got.channel, pending, b"")
                    delivery_tag += 1
                    pending = None
            elif got.type == FRAME_BODY and pending is not None:
                body += got.payload
                if len(body) >= body_size:
                    delivery_tag += 1
                    self.messages.append((*pending, body))
                    sock.sendall(method_frame(
                        got.channel, BASIC_ACK,
                        struct.pack(">QB", delivery_tag, 0)))
                    pending = None
        sock.close()

    def _deliver(self, sock, channel, pending, body):
        self.messages.append((*pending, body))
        sock.sendall(method_frame(channel, BASIC_ACK,
                                  struct.pack(">QB", 1, 0)))

    def close(self):
        self.listener.close()


class TestAmqpClient:
    def test_handshake_declare_publish_confirm(self):
        broker = MiniBroker()
        client = amqp.AmqpClient("127.0.0.1", broker.port,
                                 user="svc", password="secret")
        try:
            client.exchange_declare("tb.cdc", "topic")
            client.queue_declare("audit")
            client.queue_bind("audit", "tb.cdc", "cdc.#")
            client.confirm_select()
            client.publish("tb.cdc", "cdc.single_phase", b"hello")
            client.publish("tb.cdc", "cdc.two_phase_pending", b"x" * 300_000)
            client.wait_confirms()
        finally:
            client.close()
            broker.close()
        assert broker.auth == ("PLAIN", b"\x00svc\x00secret")
        assert ("tb.cdc", "topic") in broker.declared_exchanges
        assert "audit" in broker.declared_queues
        assert ("audit", "tb.cdc", "cdc.#") in broker.bindings
        assert broker.messages[0] == ("tb.cdc", "cdc.single_phase", b"hello")
        ex, rk, body = broker.messages[1]
        assert rk == "cdc.two_phase_pending" and body == b"x" * 300_000

    def test_wait_confirms_out_of_order_and_multiple(self):
        """Acks may arrive out of order and with `multiple` set; a nack is
        a delivery failure (AMQP 0.9.1 publisher-confirms semantics)."""
        client = amqp.AmqpClient.__new__(amqp.AmqpClient)
        client.confirm_mode = True
        client.outstanding = {1, 2, 3}
        acks = [
            amqp.Frame(amqp.FRAME_METHOD, 1,
                       struct.pack(">HHQB", 60, 80, 3, 0)),  # ack tag 3
            amqp.Frame(amqp.FRAME_METHOD, 1,
                       struct.pack(">HHQB", 60, 80, 2, 1)),  # ack <=2
        ]
        client._recv_frame = lambda: acks.pop(0)
        client.wait_confirms()
        assert client.outstanding == set()

        client.outstanding = {1}
        nack = amqp.Frame(amqp.FRAME_METHOD, 1,
                          struct.pack(">HHQB", 60, 120, 1, 0))
        client._recv_frame = lambda: nack
        try:
            client.wait_confirms()
            assert False, "nack must raise"
        except amqp.ProtocolError as e:
            assert "nacked" in str(e)

    def test_frame_roundtrip_and_parse_publishes(self):
        raw = (method_frame(1, BASIC_PUBLISH,
                            struct.pack(">H", 0) + shortstr("e")
                            + shortstr("k") + b"\x00")
               + amqp.content_frames(1, b"payload"))
        got = list(amqp.parse_publishes(raw))
        assert got == [("e", "k", b"payload")]


class TestAmqpCommand:
    def test_cdc_pump_from_live_replica(self, tmp_path):
        """format -> start -> commit transfers -> `amqp --once` pumps the
        change events into the broker (reference: `tigerbeetle amqp`)."""
        import os
        import signal
        import subprocess
        import sys
        import time

        from tigerbeetle_tpu.main import _parse_addresses, main
        from tigerbeetle_tpu.types import Account, Transfer
        from tigerbeetle_tpu.vsr.client import Client

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        address = f"127.0.0.1:{port}"
        path = tmp_path / "r0.tigerbeetle"
        subprocess.run(
            [sys.executable, "-m", "tigerbeetle_tpu", "format", "--cluster=4",
             "--replica=0", "--replica-count=1", "--small", str(path)],
            check=True, cwd="/root/repo", timeout=60,
            stdout=subprocess.DEVNULL)
        proc = subprocess.Popen(
            [sys.executable, "-m", "tigerbeetle_tpu", "start",
             f"--addresses={address}", "--replica=0", "--cluster=4",
             "--engine=oracle", "--small", str(path)],
            cwd="/root/repo", env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        broker = MiniBroker()
        try:
            client = Client(cluster=4, client_id=5,
                            replica_addresses=_parse_addresses(address))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    client.create_accounts([Account(id=1, ledger=1, code=1),
                                            Account(id=2, ledger=1, code=1)])
                    break
                except TimeoutError:
                    continue
            client.create_transfers([
                Transfer(id=10, debit_account_id=1, credit_account_id=2,
                         amount=9, ledger=1, code=1)])
            client.close()
            rc = main(["amqp", f"--addresses={address}", "--cluster=4",
                       f"--amqp=127.0.0.1:{broker.port}", "--once"])
            assert rc == 0
        finally:
            broker.close()
            proc.send_signal(signal.SIGINT)
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        assert len(broker.messages) == 1
        record = json.loads(broker.messages[0][2])
        assert record["transfer_id"] == 10 and record["transfer_amount"] == 9


class TestCdcAmqpSink:
    def test_runner_publishes_change_events_with_confirms(self):
        from tigerbeetle_tpu.cdc import AmqpSink, CDCRunner
        from tigerbeetle_tpu.state_machine import StateMachine
        from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

        sm = StateMachine(engine="oracle")
        ts = 10**9
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in (1, 2)], ts)
        ts += 1000
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1),
             Transfer(id=11, debit_account_id=1, credit_account_id=2,
                      amount=3, ledger=1, code=1,
                      flags=int(TransferFlags.pending))], ts)

        broker = MiniBroker()
        sink = AmqpSink("127.0.0.1", broker.port)
        try:
            runner = CDCRunner(sm, sink)
            published = runner.run_until_idle()
        finally:
            sink.close()
            broker.close()
        assert published == 2
        keys = [rk for _, rk, _ in broker.messages]
        assert keys == ["cdc.single_phase", "cdc.two_phase_pending"]
        record = json.loads(broker.messages[0][2])
        assert record["transfer_amount"] == 5
        assert record["type"] == "single_phase"
