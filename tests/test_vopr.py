"""VOPR-style deterministic whole-cluster simulation.

reference: src/vopr.zig + src/testing/cluster.zig — a seed drives random
workload AND random faults (crashes, restarts, partitions, packet loss);
at the end the cluster must converge to byte-identical state, and every
client-visible reply must be consistent with a single commit order.
"""

import random

import pytest

# Tier: randomized cluster soak (see pytest.ini) — slow+soak,
# run when touching VOPR/consensus, not per snapshot.
pytestmark = [pytest.mark.slow, pytest.mark.soak]

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.cluster import Cluster, MS, NetworkOptions
from tigerbeetle_tpu.types import (
    Account,
    CreateTransferResult,
    Operation,
    Transfer,
)


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr, amount=amt,
                 ledger=1, code=1).pack() for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


@pytest.mark.parametrize("seed,engine", [
    (101, "kernel"), (202, "kernel"), (303, "kernel"), (404, "kernel"),
    # The serving (device) engine under the same chaos: crashes,
    # partitions, restarts — regime transitions + write-through mirror
    # + NACK all under fire (round-2 soak in test form).
    (515, "device"), (626, "device"),
    # Soak-found liveness seeds: a replica stranded on a deposed
    # primary's multi-op suffix with no canonical anchor — recovers via
    # stalled-repair start_view re-solicitation + checkpoint rollback.
    (446681642, "oracle"), (866557783, "oracle"),
    # Soak-found: same-log_view DVCs conflicting at an op (unrepaired
    # reused-op leftovers) — resolved by the hash-chain walk-down merge.
    (517731180, "oracle"),
    # Soak-found: a rolled-back quarantine range re-executing its stale
    # fork (shared ancestry defeats the parent tripwire) — suspects now
    # execute only after replacement or forward-chain confirmation.
    (834858532, "oracle"),
])
def test_vopr_swarm(seed, engine):
    rng = random.Random(seed)
    replica_count = rng.choice([3, 5])
    factory = (StateMachine if engine == "kernel"
               else (lambda: StateMachine(engine="device", a_cap=1 << 10,
                                          t_cap=1 << 13)))
    cluster = Cluster(
        seed=seed, replica_count=replica_count,
        state_machine_factory=factory,
        network=NetworkOptions(
            loss_probability=rng.choice([0.0, 0.02, 0.10]),
            duplicate_probability=rng.choice([0.0, 0.05]),
            delay_min_ns=1 * MS,
            delay_max_ns=rng.choice([10 * MS, 50 * MS])))
    client = cluster.client(1)

    client.request(Operation.create_accounts, _accounts_body(range(1, 11)))
    ok = cluster.run(20_000, until=lambda: client.idle)
    assert ok, cluster.debug_status()

    # Random workload interleaved with faults. At most a minority of
    # replicas is ever down (liveness requires a replication quorum).
    max_down = (replica_count - 1) // 2
    next_id = 1000
    accepted = []
    sent = []

    def down_count():
        cut = {e[1] for e in cluster.partitioned if e[0] == "replica"}
        return len(cluster.crashed | cut)

    for step in range(12):
        roll = rng.random()
        if roll < 0.25 and down_count() < max_down:
            victim = rng.randrange(replica_count)
            if victim not in cluster.crashed:
                cluster.crash(victim)
        elif roll < 0.4 and cluster.crashed:
            cluster.restart(rng.choice(sorted(cluster.crashed)))
        elif roll < 0.5 and down_count() < max_down:
            cluster.partition(("replica", rng.randrange(replica_count)))
        elif roll < 0.6:
            cluster.heal()

        specs = []
        for _ in range(rng.randrange(1, 8)):
            dr = rng.randrange(1, 11)
            cr = rng.randrange(1, 11)
            if cr == dr:
                cr = dr % 10 + 1
            specs.append((next_id, dr, cr, rng.randrange(1, 100)))
            next_id += 1
        sent.append(specs)
        client.request(Operation.create_transfers, _transfers_body(specs))
        ok = cluster.run(60_000, until=lambda: client.idle)
        assert ok, f"step {step}: no progress: {cluster.debug_status()}"
        (payload,) = multi_batch.decode(client.replies[-1].body, 16)
        results = [CreateTransferResult.unpack(payload[i:i + 16])
                   for i in range(0, len(payload), 16)]
        accepted.append(sum(1 for r in results
                            if r.status.name == "created"))

    for r in sorted(cluster.crashed):
        cluster.restart(r)
    cluster.settle(ticks=60_000)

    # The replicated state machine must reflect exactly the accepted events.
    state = cluster.replicas[0].state_machine.state
    total = sum(a.debits_posted for a in state.accounts.values())
    expected = sum(
        amt for specs, acc in zip(sent, accepted)
        for (_, _, _, amt) in specs[:acc])
    # accepted transfers are a prefix-free subset; recompute exactly:
    created_ids = {t.id for t in state.transfers.values()}
    expected = sum(amt for specs in sent
                   for (tid, _, _, amt) in specs if tid in created_ids)
    assert total == expected
    assert sum(a.credits_posted for a in state.accounts.values()) == total


class TestIdPermutation:
    def test_roundtrip_bijective(self):
        from tigerbeetle_tpu.testing.workload import IdPermutation

        perm = IdPermutation(42)
        seen = set()
        for v in list(range(2000)) + [2**64, 2**127, (1 << 128) - 5]:
            i = perm.encode(v)
            assert 0 < i < (1 << 128) - 1  # valid transfer id range
            # encode remaps only the two illegal ids (0 and maxInt), which
            # these inputs never produce — the strict roundtrip must hold.
            assert perm.decode(i) == v
            seen.add(i)
        assert len(seen) == 2003  # injective over the sample


@pytest.mark.parametrize("seed,engine", [
    (11, "kernel"), (23, "kernel"),
    # Device-engine soaks: batches mix pendings with SUCCESSFUL
    # posts/voids of pendings created earlier in the SAME batch, so the
    # kernel's in-window pending resolution (and its fixpoint
    # escalation) runs under crash/partition chaos with every reply
    # audited (VERIFY mode on: the sim's extra-check doctrine).
    (301, "device"), (302, "device"), (303, "device"),
])
def test_vopr_workload_auditor(seed, engine):
    """Swarm run where every reply is audited against the outcome encoded
    in its transfer ids (reference: workload/auditor pair — replies are
    verifiable in O(1) memory, testing/id.zig IdPermutation)."""
    from tigerbeetle_tpu.testing.workload import Auditor, Workload

    rng = random.Random(seed)
    factory = (StateMachine if engine == "kernel"
               else (lambda: StateMachine(engine="device", a_cap=1 << 10,
                                          t_cap=1 << 13)))
    cluster = Cluster(
        seed=seed, replica_count=3,
        state_machine_factory=factory,
        network=NetworkOptions(
            loss_probability=rng.choice([0.0, 0.05]),
            duplicate_probability=0.02,
            delay_min_ns=1 * MS, delay_max_ns=30 * MS))
    client = cluster.client(1)
    workload = Workload(seed, account_ids=list(range(1, 9)))
    auditor = Auditor(workload.permutation)

    payload = b"".join(a.pack() for a in workload.accounts())
    client.request(Operation.create_accounts,
                   multi_batch.encode([payload], 128))
    assert cluster.run(20_000, until=lambda: client.idle)

    for step in range(10):
        if rng.random() < 0.2 and not cluster.crashed:
            cluster.crash(rng.randrange(3))
        elif cluster.crashed and rng.random() < 0.5:
            cluster.restart(rng.choice(sorted(cluster.crashed)))
        events = workload.batch()
        body = multi_batch.encode([b"".join(t.pack() for t in events)], 128)
        client.request(Operation.create_transfers, body)
        ok = cluster.run(60_000, until=lambda: client.idle)
        assert ok, f"step {step}: {cluster.debug_status()}"
        (payload,) = multi_batch.decode(client.replies[-1].body, 16)
        results = [CreateTransferResult.unpack(payload[i:i + 16])
                   for i in range(0, len(payload), 16)]
        auditor.check(events, results)

    for r in sorted(cluster.crashed):
        cluster.restart(r)
    cluster.settle(ticks=60_000)
    assert auditor.checked > 0


class TestZipfian:
    def test_distribution_is_hot_headed(self):
        from tigerbeetle_tpu.utils import ZipfianGenerator

        zipf = ZipfianGenerator(1000, theta=0.99, seed=3)
        draws = zipf.draw(50_000)
        assert draws.min() >= 0 and draws.max() < 1000
        # Zipf(0.99) over 1000 items: the hottest ~10 items take >30%.
        hot_share = (draws < 10).mean()
        assert hot_share > 0.3, hot_share
        # ...but the tail is still reachable.
        assert (draws > 500).any()

    def test_grow_preserves_stream(self):
        from tigerbeetle_tpu.utils import ZipfianGenerator

        zipf = ZipfianGenerator(100, seed=5).grow(200)
        draws = zipf.draw(10_000)
        assert draws.max() >= 100  # new items reachable


@pytest.mark.parametrize("seed", [71, 72])
def test_vopr_clock_drift_and_partition_modes(seed):
    """Swarm with per-replica clock drift and the reference's partition
    modes (packet_simulator.zig {uniform_size, uniform_partition,
    isolate_single}): the cluster must still converge byte-identically."""
    rng = random.Random(seed)
    cluster = Cluster(
        seed=seed, replica_count=3,
        clock_drift_ppm_max=200, clock_offset_ns_max=50 * MS,
        network=NetworkOptions(loss_probability=0.02,
                               delay_min_ns=1 * MS, delay_max_ns=20 * MS))
    client = cluster.client(1)
    client.request(Operation.create_accounts, _accounts_body([1, 2]))
    assert cluster.run(20_000, until=lambda: client.idle)
    next_id = 500
    for step in range(8):
        if step % 3 == 1:
            cluster.partition_mode(rng.choice(
                ("isolate_single", "uniform_size", "uniform_partition")))
        elif step % 3 == 2:
            cluster.heal()
        client.request(Operation.create_transfers, _transfers_body(
            [(next_id, 1, 2, step + 1)]))
        next_id += 1
        ok = cluster.run(60_000, until=lambda: client.idle)
        assert ok, f"step {step}: {cluster.debug_status()}"
    cluster.settle(ticks=60_000)
    state = cluster.replicas[0].state_machine.state
    assert state.accounts[1].debits_posted == sum(
        t.amount for t in state.transfers.values())


@pytest.mark.parametrize("seed", [1000, 1013, 1018, 1038])
def test_vopr_storm_regression_seeds(seed):
    """Seeds that historically exposed consensus bugs (stale-prepare
    execution after view changes with empty/holey suffixes, restart replay
    beyond commit_max, canonical staleness across views, repair never
    pulling committed tail ops). Locked as regressions; the VOPR liveness
    contract applies: progress is required only once faults heal."""
    rng = random.Random(seed)
    n = rng.choice((3, 3, 5))
    cluster = Cluster(
        seed=seed, replica_count=n,
        standby_count=rng.choice((0, 0, 1)),
        clock_drift_ppm_max=rng.choice((0, 200, 500)),
        clock_offset_ns_max=rng.choice((0, 80 * MS)),
        network=NetworkOptions(
            loss_probability=rng.choice((0.0, 0.03, 0.08)),
            duplicate_probability=rng.choice((0.0, 0.05)),
            delay_min_ns=1 * MS,
            delay_max_ns=rng.choice((10 * MS, 40 * MS))))
    client = cluster.client(1)
    client.request(Operation.create_accounts, _accounts_body(range(1, 6)))
    assert cluster.run(30_000, until=lambda: client.idle)
    next_id = 500
    for step in range(14):
        roll = rng.random()
        if roll < 0.25:
            cluster.partition_mode(rng.choice(
                ("isolate_single", "uniform_size", "uniform_partition")))
        elif roll < 0.45:
            cluster.heal()
        elif roll < 0.55 and len(cluster.crashed) < (n - 1) // 2:
            victim = rng.randrange(n)
            if victim not in cluster.crashed:
                cluster.crash(victim)
        elif cluster.crashed and roll < 0.75:
            cluster.restart(rng.choice(sorted(cluster.crashed)))
        specs = [(next_id + k, rng.randrange(1, 6), rng.randrange(1, 6),
                  rng.randrange(1, 50)) for k in range(rng.randrange(1, 5))]
        next_id += len(specs)
        body = multi_batch.encode([b"".join(
            Transfer(id=i, debit_account_id=dr,
                     credit_account_id=cr if cr != dr else dr % 5 + 1,
                     amount=a, ledger=1, code=1).pack()
            for i, dr, cr, a in specs)], 128)
        client.request(Operation.create_transfers, body)
        if not cluster.run(40_000, until=lambda: client.idle):
            cluster.heal()
            for r in sorted(cluster.crashed):
                cluster.restart(r)
            assert cluster.run(100_000, until=lambda: client.idle), \
                f"step {step}: {cluster.debug_status()}"
    for r in sorted(cluster.crashed):
        cluster.restart(r)
    cluster.settle(ticks=100_000)
