"""VOPR-style deterministic whole-cluster simulation.

reference: src/vopr.zig + src/testing/cluster.zig — a seed drives random
workload AND random faults (crashes, restarts, partitions, packet loss);
at the end the cluster must converge to byte-identical state, and every
client-visible reply must be consistent with a single commit order.
"""

import random

import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.testing.cluster import Cluster, MS, NetworkOptions
from tigerbeetle_tpu.types import (
    Account,
    CreateTransferResult,
    Operation,
    Transfer,
)


def _accounts_body(ids):
    payload = b"".join(Account(id=i, ledger=1, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr, amount=amt,
                 ledger=1, code=1).pack() for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


@pytest.mark.parametrize("seed", [101, 202, 303, 404])
def test_vopr_swarm(seed):
    rng = random.Random(seed)
    replica_count = rng.choice([3, 5])
    cluster = Cluster(
        seed=seed, replica_count=replica_count,
        network=NetworkOptions(
            loss_probability=rng.choice([0.0, 0.02, 0.10]),
            duplicate_probability=rng.choice([0.0, 0.05]),
            delay_min_ns=1 * MS,
            delay_max_ns=rng.choice([10 * MS, 50 * MS])))
    client = cluster.client(1)

    client.request(Operation.create_accounts, _accounts_body(range(1, 11)))
    ok = cluster.run(20_000, until=lambda: client.idle)
    assert ok, cluster.debug_status()

    # Random workload interleaved with faults. At most a minority of
    # replicas is ever down (liveness requires a replication quorum).
    max_down = (replica_count - 1) // 2
    next_id = 1000
    accepted = []
    sent = []

    def down_count():
        cut = {e[1] for e in cluster.partitioned if e[0] == "replica"}
        return len(cluster.crashed | cut)

    for step in range(12):
        roll = rng.random()
        if roll < 0.25 and down_count() < max_down:
            victim = rng.randrange(replica_count)
            if victim not in cluster.crashed:
                cluster.crash(victim)
        elif roll < 0.4 and cluster.crashed:
            cluster.restart(rng.choice(sorted(cluster.crashed)))
        elif roll < 0.5 and down_count() < max_down:
            cluster.partition(("replica", rng.randrange(replica_count)))
        elif roll < 0.6:
            cluster.heal()

        specs = []
        for _ in range(rng.randrange(1, 8)):
            dr = rng.randrange(1, 11)
            cr = rng.randrange(1, 11)
            if cr == dr:
                cr = dr % 10 + 1
            specs.append((next_id, dr, cr, rng.randrange(1, 100)))
            next_id += 1
        sent.append(specs)
        client.request(Operation.create_transfers, _transfers_body(specs))
        ok = cluster.run(60_000, until=lambda: client.idle)
        assert ok, f"step {step}: no progress: {cluster.debug_status()}"
        (payload,) = multi_batch.decode(client.replies[-1].body, 16)
        results = [CreateTransferResult.unpack(payload[i:i + 16])
                   for i in range(0, len(payload), 16)]
        accepted.append(sum(1 for r in results
                            if r.status.name == "created"))

    for r in sorted(cluster.crashed):
        cluster.restart(r)
    cluster.settle(ticks=60_000)

    # The replicated state machine must reflect exactly the accepted events.
    state = cluster.replicas[0].state_machine.state
    total = sum(a.debits_posted for a in state.accounts.values())
    expected = sum(
        amt for specs, acc in zip(sent, accepted)
        for (_, _, _, amt) in specs[:acc])
    # accepted transfers are a prefix-free subset; recompute exactly:
    created_ids = {t.id for t in state.transfers.values()}
    expected = sum(amt for specs in sent
                   for (tid, _, _, amt) in specs if tid in created_ids)
    assert total == expected
    assert sum(a.credits_posted for a in state.accounts.values()) == total
