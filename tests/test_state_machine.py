"""StateMachine shell tests: queries, history, change events, wire codec.

Host analog of the reference's state_machine_tests.zig query scenarios plus
multi_batch.zig round-trip tests.
"""

import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import OPERATION_SPECS, StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountBalance,
    AccountFilter,
    AccountFilterFlags as AFF,
    AccountFlags as AF,
    ChangeEvent,
    ChangeEventType,
    ChangeEventsFilter,
    CreateTransferResult,
    Operation,
    QueryFilter,
    QueryFilterFlags as QFF,
    Transfer,
    TransferFlags as TF,
)

TS = 10**13


def _setup(engine="kernel"):
    sm = StateMachine(engine=engine)
    res = sm.create_accounts([
        Account(id=1, ledger=1, code=10, user_data_64=7),
        Account(id=2, ledger=1, code=10, flags=int(AF.history)),
        Account(id=3, ledger=1, code=20, user_data_64=7),
        Account(id=4, ledger=2, code=10),
    ], TS)
    assert all(r.status.name == "created" for r in res)
    res = sm.create_transfers([
        Transfer(id=101, debit_account_id=1, credit_account_id=2, amount=10,
                 ledger=1, code=5, user_data_64=77),
        Transfer(id=102, debit_account_id=2, credit_account_id=3, amount=20,
                 ledger=1, code=5),
        Transfer(id=103, debit_account_id=3, credit_account_id=1, amount=30,
                 ledger=1, code=6, user_data_64=77),
        Transfer(id=104, debit_account_id=1, credit_account_id=2, amount=40,
                 ledger=1, code=6, flags=int(TF.pending)),
    ], TS + 100)
    assert all(r.status.name == "created" for r in res)
    return sm


@pytest.mark.parametrize("engine", ["kernel", "oracle"])
def test_get_account_transfers(engine):
    sm = _setup(engine)
    f = AccountFilter(account_id=2, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    got = [t.id for t in sm.get_account_transfers(f)]
    assert got == [101, 102, 104]

    f = AccountFilter(account_id=2, limit=100, flags=int(AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 104]

    f = AccountFilter(account_id=2, limit=100,
                      flags=int(AFF.debits | AFF.credits | AFF.reversed))
    assert [t.id for t in sm.get_account_transfers(f)] == [104, 102, 101]

    f = AccountFilter(account_id=1, limit=100, user_data_64=77,
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 103]

    f = AccountFilter(account_id=1, limit=2,
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 103]

    # invalid filters -> empty
    assert sm.get_account_transfers(
        AccountFilter(account_id=0, limit=10, flags=int(AFF.debits))) == []
    assert sm.get_account_transfers(
        AccountFilter(account_id=1, limit=0, flags=int(AFF.debits))) == []
    assert sm.get_account_transfers(
        AccountFilter(account_id=1, limit=10)) == []  # neither side
    assert sm.get_account_transfers(
        AccountFilter(account_id=1, limit=10, timestamp_min=5, timestamp_max=4,
                      flags=int(AFF.debits))) == []


@pytest.mark.parametrize("engine", ["kernel", "oracle"])
def test_get_account_balances(engine):
    sm = _setup(engine)
    f = AccountFilter(account_id=2, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    balances = sm.get_account_balances(f)
    # Account 2 is touched by transfers 101 (cr +10 posted), 102 (dr 20),
    # 104 (cr pending 40).
    assert [(b.credits_posted, b.debits_posted, b.credits_pending)
            for b in balances] == [(10, 0, 0), (10, 20, 0), (10, 20, 40)]
    # Non-history account -> empty.
    f = AccountFilter(account_id=1, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    assert sm.get_account_balances(f) == []


@pytest.mark.parametrize("engine", ["kernel", "oracle"])
def test_query_accounts_and_transfers(engine):
    sm = _setup(engine)
    got = [a.id for a in sm.query_accounts(QueryFilter(limit=10, code=10))]
    assert got == [1, 2, 4]
    got = [a.id for a in sm.query_accounts(
        QueryFilter(limit=10, code=10, ledger=1))]
    assert got == [1, 2]
    got = [a.id for a in sm.query_accounts(
        QueryFilter(limit=10, user_data_64=7,
                    flags=int(QFF.reversed)))]
    assert got == [3, 1]
    got = [a.id for a in sm.query_accounts(QueryFilter(limit=2))]
    assert got == [1, 2]

    got = [t.id for t in sm.query_transfers(QueryFilter(limit=10, code=6))]
    assert got == [103, 104]
    got = [t.id for t in sm.query_transfers(QueryFilter(limit=10))]
    assert got == [101, 102, 103, 104]
    assert sm.query_transfers(QueryFilter(limit=0)) == []


@pytest.mark.parametrize("engine", ["kernel", "oracle"])
def test_change_events(engine):
    sm = _setup(engine)
    # post the pending transfer, then expire nothing
    res = sm.create_transfers(
        [Transfer(id=105, pending_id=104, amount=(1 << 128) - 1,
                  flags=int(TF.post_pending_transfer))], TS + 200)
    assert res[0].status.name == "created"
    events = sm.get_change_events(ChangeEventsFilter(limit=100))
    assert [e.type for e in events] == [
        ChangeEventType.single_phase,
        ChangeEventType.single_phase,
        ChangeEventType.single_phase,
        ChangeEventType.two_phase_pending,
        ChangeEventType.two_phase_posted,
    ]
    assert events[0].transfer_id == 101
    assert events[0].debit_account_id == 1
    assert events[0].credit_account_id == 2
    assert events[0].debit_account_debits_posted == 10
    assert events[4].transfer_pending_id == 104
    assert events[4].transfer_amount == 40
    # round-trip the wire format
    raw = events[0].pack()
    assert len(raw) == 384
    assert ChangeEvent.unpack(raw) == events[0]
    # limit + range
    sub = sm.get_change_events(ChangeEventsFilter(limit=2))
    assert len(sub) == 2
    assert sm.get_change_events(ChangeEventsFilter(limit=0)) == []


def test_change_events_expiry():
    sm = StateMachine()
    sm.create_accounts([Account(id=1, ledger=1, code=1),
                        Account(id=2, ledger=1, code=1)], TS)
    sm.create_transfers(
        [Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=5,
                  ledger=1, code=1, flags=int(TF.pending), timeout=1)],
        TS + 100)
    assert sm.pulse_needed(TS + 100 + 2 * 10**9)
    sm.commit(Operation.pulse, b"", TS + 100 + 2 * 10**9)
    events = sm.get_change_events(ChangeEventsFilter(limit=10))
    assert [e.type for e in events] == [
        ChangeEventType.two_phase_pending,
        ChangeEventType.two_phase_expired,
    ]
    assert events[1].transfer_id == 10  # the pending transfer itself
    assert events[1].transfer_pending_id == 0


def test_multi_batch_timestamps_advance_per_batch():
    """Each inner batch consumes one timestamp per event (reference:
    execute_multi_batch advances the execute timestamp per batch)."""
    sm = StateMachine()
    accounts = b"".join(Account(id=i, ledger=1, code=1).pack() for i in (1, 2))
    sm.commit(Operation.create_accounts, multi_batch.encode([accounts], 128), TS)
    t1 = Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=1,
                  ledger=1, code=1).pack()
    t2 = Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=2,
                  ledger=1, code=1).pack()
    out = sm.commit(Operation.create_transfers,
                    multi_batch.encode([t1, t2], 128), TS + 100)
    r1, r2 = [CreateTransferResult.unpack(p)
              for p in multi_batch.decode(out, 16)]
    assert r1.status.name == r2.status.name == "created"
    assert r1.timestamp == TS + 99 and r2.timestamp == TS + 100
    assert len(sm.state.transfer_by_timestamp) == 2


def test_malformed_bodies_rejected():
    import pytest as _pytest

    from tigerbeetle_tpu.state_machine import ProtocolError

    sm = StateMachine()
    assert not sm.input_valid(Operation.create_accounts, b"\x01" * 100)
    with _pytest.raises(ProtocolError):
        sm.commit(Operation.deprecated_create_transfers_unbatched,
                  b"\x00" * 100, TS)
    # two filters in a single-filter op
    f = AccountFilter(account_id=1, limit=10, flags=int(AFF.debits)).pack()
    body = multi_batch.encode([f + f], 128)
    assert not sm.input_valid(Operation.get_account_transfers, body)
    # junk between payload and trailer
    good = multi_batch.encode([f], 128)
    bad = good[:-128] + b"\x99" * 16 + good[-128:-16] + good[-16:]
    assert not sm.input_valid(Operation.get_account_transfers, bad)


def test_multi_batch_roundtrip():
    for element_size in (8, 16, 64, 128):
        batches = [b"\x01" * element_size * 3, b"", b"\x02" * element_size]
        body = multi_batch.encode(batches, element_size)
        assert len(body) % element_size == 0 or element_size == 8
        out = multi_batch.decode(body, element_size)
        assert out == batches
    # single batch
    body = multi_batch.encode([b"\x07" * 128], 128)
    assert multi_batch.decode(body, 128) == [b"\x07" * 128]
    # malformed
    with pytest.raises(ValueError):
        multi_batch.decode(b"", 128)
    with pytest.raises(ValueError):
        multi_batch.decode(b"\x00\x00", 128)


def test_wire_commit_path():
    sm = StateMachine()
    accounts = b"".join(
        Account(id=i, ledger=1, code=1).pack() for i in (1, 2))
    body = multi_batch.encode([accounts], 128)
    out = sm.commit(Operation.create_accounts, body, TS)
    results = multi_batch.decode(out, 16)
    assert len(results[0]) == 32  # two dense CreateAccountResults

    transfers = b"".join(
        Transfer(id=100 + i, debit_account_id=1, credit_account_id=2,
                 amount=10, ledger=1, code=1).pack() for i in range(3))
    body = multi_batch.encode([transfers], 128)
    out = sm.commit(Operation.create_transfers, body, TS + 100)
    (payload,) = multi_batch.decode(out, 16)
    assert len(payload) == 48
    r = CreateTransferResult.unpack(payload[:16])
    assert r.status.name == "created"

    # lookups via wire
    ids = (100).to_bytes(16, "little") + (999).to_bytes(16, "little")
    body = multi_batch.encode([ids], 16)
    out = sm.commit(Operation.lookup_transfers, body, TS + 200)
    (payload,) = multi_batch.decode(out, 128)
    assert len(payload) == 128  # only id 100 found
    assert Transfer.unpack(payload).id == 100

    # deprecated sparse create: one bad event -> single {index, result} pair
    bad = Transfer(id=0, debit_account_id=1, credit_account_id=2,
                   amount=1, ledger=1, code=1).pack()
    good = Transfer(id=200, debit_account_id=1, credit_account_id=2,
                    amount=1, ledger=1, code=1).pack()
    body = multi_batch.encode([bad + good], 128)
    out = sm.commit(Operation.deprecated_create_transfers_sparse, body, TS + 300)
    (payload,) = multi_batch.decode(out, 8)
    assert len(payload) == 8
    import struct as _s

    index, code = _s.unpack("<II", payload)
    assert index == 0 and code == 5  # id_must_not_be_zero

    # get_account_transfers via wire
    f = AccountFilter(account_id=1, limit=10,
                      flags=int(AFF.debits | AFF.credits))
    body = multi_batch.encode([f.pack()], 128)
    out = sm.commit(Operation.get_account_transfers, body, TS + 400)
    (payload,) = multi_batch.decode(out, 128)
    assert len(payload) // 128 == 4
