"""Pipelined commit windows (DeviceLedger.submit_window /
resolve_windows): depth-N in-flight windows with chained force_fallback
poisoning must be bit-identical to the synchronous window path — incl.
a fallback mid-pipeline, write-through capture, flush columns, and the
event-ring reset mode.

Reference analog: the primary pipelines up to 8 prepares
(src/config.zig:155); a failed prepare poisons the pipeline suffix."""

import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.ops.batch import transfers_to_arrays
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Account, Operation, Transfer, TransferFlags

PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
U128MAX = (1 << 128) - 1


def _mk_led(t_cap=1 << 13):
    led = DeviceLedger(a_cap=1 << 10, t_cap=t_cap)
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    return led


def _windows(rng, n_windows, k=3, n=64, base=10**6, with_pend=False,
             poison_window=None):
    """n_windows windows of k batches each; optionally a batch with a
    duplicate id (hard fallback) inside window `poison_window`."""
    out = []
    nid = base
    ts = 10**12
    pend_pool = []
    for w in range(n_windows):
        evs, tss = [], []
        for b in range(k):
            batch = []
            for i in range(n):
                dr = int(rng.integers(1, 65))
                if with_pend and pend_pool and i % 5 == 0:
                    batch.append(Transfer(
                        id=nid, pending_id=pend_pool.pop(0),
                        amount=U128MAX, ledger=1, code=1, flags=POST))
                else:
                    f = PEND if (with_pend and i % 4 == 0) else 0
                    batch.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=dr % 64 + 1,
                        amount=int(rng.integers(1, 100)), ledger=1,
                        code=1, flags=f, timeout=10 if f else 0))
                    if f:
                        pend_pool.append(nid)
                nid += 1
            if poison_window == w and b == k // 2:
                # duplicate id within the batch: hard fallback (E2)
                batch[-1] = Transfer(
                    id=batch[0].id, debit_account_id=1,
                    credit_account_id=2, amount=1, ledger=1, code=1)
            ts += n + 10
            evs.append(batch)
            tss.append(ts)
        out.append((evs, tss))
    return out


def _state_eq(a, b):
    assert a.accounts == b.accounts
    assert a.transfers == b.transfers
    assert a.pending_status == b.pending_status
    assert a.expiry == b.expiry
    assert set(a.orphaned) == set(b.orphaned)
    assert a.pulse_next_timestamp == b.pulse_next_timestamp
    assert a.commit_timestamp == b.commit_timestamp


@pytest.mark.parametrize("with_pend,poison", [
    (False, None), (True, None), (False, 1), (True, 2)])
def test_pipeline_matches_sync(with_pend, poison):
    rng = np.random.default_rng(3)
    windows = _windows(rng, 4, with_pend=with_pend, poison_window=poison)
    led_p = _mk_led()
    led_s = _mk_led()

    # Pipelined, depth 2. A host-regime stretch (after a hard-fallback
    # redo) makes submit_window return None — the caller then resolves
    # and takes the synchronous path, exactly like the serving driver.
    pending = []
    results_p = []
    for evs, tss in windows:
        arrays = [transfers_to_arrays(b) for b in evs]
        tk = led_p.submit_window(arrays, tss)
        if tk is None:
            led_p.resolve_windows()
            while pending:
                results_p.append(pending.pop(0).results)
            results_p.append(
                ("sync", led_p.create_transfers_window(arrays, tss)))
            continue
        pending.append(tk)
        if len(pending) > 1:
            led_p.resolve_windows(count=1)
            # a fallback resolves the whole pipeline; collect in order
            while pending and pending[0].results is not None:
                results_p.append(pending.pop(0).results)
    led_p.resolve_windows()
    for tk in pending:
        results_p.append(tk.results)

    # Synchronous windows.
    results_s = []
    for evs, tss in windows:
        out = led_s.create_transfers_window(
            [transfers_to_arrays(b) for b in evs], tss)
        results_s.append(out)

    assert len(results_p) == len(results_s)
    for (kind_res), outs_s in zip(results_p, results_s):
        _, outs_p = kind_res
        for (st_p, ts_p), (st_s, ts_s) in zip(outs_p, outs_s):
            np.testing.assert_array_equal(np.asarray(st_p),
                                          np.asarray(st_s))
            np.testing.assert_array_equal(np.asarray(ts_p),
                                          np.asarray(ts_s))
    _state_eq(led_p.to_host(), led_s.to_host())


def test_pipeline_ring_reset_serving_mode():
    """Serving mode (recycle_events): the ring-reset kernel variants
    keep the event ring bounded per window with no host barrier."""
    from tigerbeetle_tpu.oracle import StateMachineOracle

    rng = np.random.default_rng(5)
    windows = _windows(rng, 5, with_pend=True, base=2 * 10**6)

    def mk_serving():
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13,
                           write_through=StateMachineOracle())
        led.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
        led.recycle_events = True
        led.retain_flush_columns = True
        return led

    led_p = mk_serving()
    led_s = mk_serving()

    pending = []
    for evs, tss in windows:
        tk = led_p.submit_window(
            [transfers_to_arrays(b) for b in evs], tss)
        assert tk is not None
        pending.append(tk)
        if len(pending) > 1:
            led_p.resolve_windows(count=1)
            pending = [t for t in pending if t.results is None]
    led_p.resolve_windows()
    for evs, tss in windows:
        led_s.create_transfers_window(
            [transfers_to_arrays(b) for b in evs], tss)
    led_p.drain_mirror()
    led_s.drain_mirror()
    cols_p = led_p.take_flush_columns()
    cols_s = led_s.take_flush_columns()
    assert len(cols_p) == len(cols_s)
    for cp, cs in zip(cols_p, cols_s):
        assert cp[3] == cs[3]  # n_new per chunk
        if cp[3]:
            for key in ("id_hi", "id_lo", "ts", "flags"):
                np.testing.assert_array_equal(
                    np.asarray(cp[0][key]), np.asarray(cs[0][key]))
    _state_eq(led_p.mirror, led_s.mirror)


def test_reads_resolve_pipeline():
    from tigerbeetle_tpu.oracle import StateMachineOracle

    rng = np.random.default_rng(9)
    windows = _windows(rng, 2, base=3 * 10**6)
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13,
                       write_through=StateMachineOracle())
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    tk = led.submit_window(
        [transfers_to_arrays(b) for b in windows[0][0]], windows[0][1])
    assert tk is not None
    some_id = windows[0][0][0][0].id
    # A mirror read (drain boundary) must resolve the pipeline first.
    state = led.mirror
    led.drain_mirror()
    assert tk.results is not None, "drain must resolve in-flight windows"
    assert state.transfers[some_id].id == some_id


def test_statemachine_pipelined_replies_match_sync():
    sm_p = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 13)
    sm_s = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 13)
    accts = [Account(id=i, ledger=1, code=1) for i in range(1, 65)]
    for sm in (sm_p, sm_s):
        sm.create_accounts(accts, 120)
    rng = np.random.default_rng(17)
    nid = 5 * 10**6
    ts = 10**12
    op = Operation.create_transfers
    all_replies_p, all_replies_s = [], []
    recs = []
    for w in range(3):
        bodies, tss = [], []
        for b in range(2):
            evs = []
            for i in range(128):
                dr = int(rng.integers(1, 65))
                evs.append(Transfer(
                    id=nid, debit_account_id=dr,
                    credit_account_id=dr % 64 + 1,
                    amount=int(rng.integers(1, 100)), ledger=1, code=1))
                nid += 1
            ts += 200
            bodies.append(multi_batch.encode(
                [b"".join(e.pack() for e in evs)], 128))
            tss.append(ts)
        rec = sm_p.submit_commit_window(op, bodies, tss)
        assert rec is not None
        recs.append(rec)
        all_replies_s.extend(sm_s.commit_window(op, bodies, tss))
    sm_p.resolve_commit_windows()
    for rec in recs:
        all_replies_p.extend(rec["replies"])
    assert all_replies_p == all_replies_s
    assert sm_p.state.transfers == sm_s.state.transfers


def test_pipeline_balancing_windows():
    """Balancing windows ride the pipelined serving path natively (the
    balancing ring super tier): results and final state bit-identical
    to the sync window path AND to an oracle fed the same prepares —
    including a poisoned window mid-pipeline (the prev_fb chain through
    the balancing branch) and clamped amounts in the write-through
    flush columns."""
    from tigerbeetle_tpu.oracle import StateMachineOracle

    BAL_DR = int(TransferFlags.balancing_debit)
    BAL_CR = int(TransferFlags.balancing_credit)

    rng = np.random.default_rng(7)
    nid = 3 * 10**6
    ts = 10**12
    windows = []
    for w in range(4):
        evs, tss = [], []
        for b in range(3):
            batch = []
            for i in range(48):
                dr = int(rng.integers(1, 65))
                flags = (BAL_DR if i % 3 == 0
                         else (BAL_CR if i % 7 == 0 else 0))
                amt = (U128MAX if (flags and i % 6 == 0)
                       else int(rng.integers(1, 100)))
                batch.append(Transfer(
                    id=nid, debit_account_id=dr,
                    credit_account_id=dr % 64 + 1, amount=amt,
                    ledger=1, code=1, flags=flags))
                nid += 1
            if w == 2 and b == 1:
                # duplicate id within the batch: hard fallback (E2)
                batch[-1] = Transfer(
                    id=batch[0].id, debit_account_id=1,
                    credit_account_id=2, amount=1, ledger=1, code=1)
            ts += 70
            evs.append(batch)
            tss.append(ts)
        windows.append((evs, tss))

    def mk_serving():
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13,
                           write_through=StateMachineOracle())
        led.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
        led.recycle_events = True
        led.retain_flush_columns = True
        return led

    led_p = mk_serving()
    led_s = mk_serving()
    orc = StateMachineOracle()
    r = orc.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    assert all(x.status.name == "created" for x in r)

    pending = []
    for evs, tss in windows:
        arrays = [transfers_to_arrays(b) for b in evs]
        tk = led_p.submit_window(arrays, tss)
        if tk is None:
            led_p.resolve_windows()
            pending.clear()
            led_p.create_transfers_window(arrays, tss)
            continue
        pending.append(tk)
        if len(pending) > 1:
            led_p.resolve_windows(count=1)
            pending = [t for t in pending if t.results is None]
    led_p.resolve_windows()
    for evs, tss in windows:
        led_s.create_transfers_window(
            [transfers_to_arrays(b) for b in evs], tss)
        for b, tsb in zip(evs, tss):
            orc.create_transfers(b, tsb)

    led_p.drain_mirror()
    led_s.drain_mirror()
    cols_p = led_p.take_flush_columns()
    cols_s = led_s.take_flush_columns()
    assert len(cols_p) == len(cols_s)
    for cp, cs in zip(cols_p, cols_s):
        assert cp[3] == cs[3]  # n_new per chunk
        if cp[3]:
            # Clamped (not nominal) amounts must flow through capture.
            for key in ("id_hi", "id_lo", "ts", "flags",
                        "amt_hi", "amt_lo"):
                np.testing.assert_array_equal(
                    np.asarray(cp[0][key]), np.asarray(cs[0][key]))
    _state_eq(led_p.mirror, led_s.mirror)
    _state_eq(led_p.mirror, orc)
