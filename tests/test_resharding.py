"""Elastic shards (ISSUE 19): the pure control-plane pieces of
crash-safe live resharding — host/device bit-identity of the ownership
hash and overlay routing at range edges, ownership-table validation,
plan membership, conflict detection, and the hot-range detector's
verdicts (including the degenerate single-hot-account case). The
staged protocol itself is exercised end to end by reshard_smoke, the
chaos scenario, and the supervisor integration tests."""

from functools import partial
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from tigerbeetle_tpu.jaxhound import core as jh_core
from tigerbeetle_tpu.jaxhound import determinism
from tigerbeetle_tpu.parallel import shard_utils as su
from tigerbeetle_tpu.parallel.resharding import (
    HotRangeDetector, ReshardController, ReshardPlan)

U64MAX = (1 << 64) - 1
U128MAX = (1 << 128) - 1

# 128-bit ids at the limb boundaries: 0, the u64 edge (lo saturated,
# hi empty), the first hi-only id, the top of the id space.
EDGE_IDS = [0, 1, 2, U64MAX - 1, U64MAX, U64MAX + 1, (1 << 127),
            (1 << 127) + 1, U128MAX - 1, U128MAX]


def _split(ids):
    hi = np.array([(i >> 64) & U64MAX for i in ids], dtype=np.uint64)
    lo = np.array([i & U64MAX for i in ids], dtype=np.uint64)
    return hi, lo


def _fuzz_ids(seed, n=256):
    rng = np.random.default_rng(seed)
    hi = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    lo = rng.integers(0, 1 << 64, n, dtype=np.uint64)
    return [int(h) << 64 | int(l) for h, l in zip(hi, lo)]


# ------------------------------------------------- hash bit-identity


def test_shard_hash_host_device_identity():
    """`shard_of_int` (host python-int path: oracle partitioning,
    digest packs, range membership) and `shard_of_id` (traced device
    path: routing kernels) must agree bit-for-bit on every id — the
    whole resharding protocol hangs on the two views never skewing."""
    ids = EDGE_IDS + _fuzz_ids(7)
    hi, lo = _split(ids)
    h_dev = np.asarray(jax.jit(su.mix_id)(hi, lo))
    for i, want in zip(ids, h_dev.tolist()):
        assert su.mix_int(i) == want, hex(i)
    for n_shards in (1, 2, 8):
        dev = np.asarray(jax.jit(
            partial(su.shard_of_id, n_shards=n_shards))(hi, lo))
        for i, got in zip(ids, dev.tolist()):
            assert su.shard_of_int(i, n_shards) == got, \
                (hex(i), n_shards)


def test_overlay_bit_identity_all_modes():
    """Jitted `owner_read`/`writes_here` vs host `owner_read_int`/
    `write_owners_int` under a three-entry overlay covering all three
    modes, with entry bounds placed EXACTLY on sampled hashes so the
    inclusive lo/hi edges are exercised, not just interior points."""
    n_shards = 8
    ids = EDGE_IDS + _fuzz_ids(11)
    hs = sorted(su.mix_int(i) for i in ids)
    # Bounds at actual sampled hashes: ids landing exactly on lo/hi.
    entries = (
        (hs[5], hs[60], 0, 1, su.OVERLAY_DOUBLE_WRITE),
        (hs[20], hs[120], 1, 2, su.OVERLAY_MIGRATED),
        (hs[80], hs[240], 2, 3, su.OVERLAY_RETURNING),
    )
    hi, lo = _split(ids)
    own_dev = np.asarray(jax.jit(
        lambda kh, kl: su.owner_read(kh, kl, n_shards, entries))(
            hi, lo))
    for i, got in zip(ids, own_dev.tolist()):
        assert su.owner_read_int(i, n_shards, entries) == got, hex(i)
    for me in range(n_shards):
        w_dev = np.asarray(jax.jit(
            lambda kh, kl, m=me: su.writes_here(
                kh, kl, n_shards, np.int32(m), entries))(hi, lo))
        for i, got in zip(ids, w_dev.tolist()):
            want = me in su.write_owners_int(i, n_shards, entries)
            assert bool(got) == want, (hex(i), me)
    # Semantics spot-checks on one in-range id per mode.
    for (elo, ehi, src, dst, mode) in entries:
        member = next(i for i in ids
                      if elo <= su.mix_int(i) <= ehi
                      and su.shard_of_int(i, n_shards) == src)
        owner = su.owner_read_int(member, n_shards, entries)
        writers = su.write_owners_int(member, n_shards, entries)
        if mode == su.OVERLAY_DOUBLE_WRITE:
            assert owner == src and set(writers) == {src, dst}
        elif mode == su.OVERLAY_MIGRATED:
            assert owner == dst and writers == (dst,)
        else:  # RETURNING
            assert owner == dst and set(writers) == {src, dst}


def test_empty_overlay_identical_lowering():
    """With no overlay, `owner_read` IS `shard_of_id` — same jaxpr, so
    idle windows pay zero routing overhead for reshard-readiness."""
    hi, lo = _split(EDGE_IDS)
    jp_base = jax.make_jaxpr(
        lambda kh, kl: su.shard_of_id(kh, kl, 8))(hi, lo)
    jp_over = jax.make_jaxpr(
        lambda kh, kl: su.owner_read(kh, kl, 8, ()))(hi, lo)
    assert str(jp_base) == str(jp_over)


def test_overlay_lowering_jaxhound_clean():
    """The overlay-routed lowering stays deterministic (no PRNG, no
    nondeterministic scatter) and gather-free — jaxhound's static
    lints, the same gate the partitioned step functions pass."""
    entries = (
        (0, 1 << 62, 0, 1, su.OVERLAY_DOUBLE_WRITE),
        (1 << 63, U64MAX, 2, 3, su.OVERLAY_RETURNING),
    )

    def routed(kh, kl):
        return (su.owner_read(kh, kl, 8, entries),
                su.writes_here(kh, kl, 8, np.int32(3), entries))

    hi, lo = _split(EDGE_IDS + _fuzz_ids(3, 64))
    cj = jax.make_jaxpr(routed)(hi, lo)
    assert determinism.findings_for(cj, "overlay_route") == []
    assert jh_core.state_gathers(cj) == []


# ------------------------------------------- ownership-table semantics


def test_ownership_table_generations_and_validation():
    t0 = su.OwnershipTable(4)
    assert not t0.active and t0.generation == 0
    t1 = t0.with_entry(0, 1 << 32, 1, 2, su.OVERLAY_DOUBLE_WRITE)
    assert t1.active and t1.generation == 1
    t2 = t1.transition(t1.entries[0], su.OVERLAY_MIGRATED)
    assert t2.generation == 2
    assert t2.entries[0][4] == su.OVERLAY_MIGRATED
    t3 = t2.without_entry(t2.entries[0])
    assert t3.generation == 3 and not t3.active

    with pytest.raises(AssertionError):   # src == dst
        t0.with_entry(0, 10, 1, 1, su.OVERLAY_DOUBLE_WRITE)
    with pytest.raises(AssertionError):   # bad mode
        t0.with_entry(0, 10, 1, 2, 9)
    with pytest.raises(AssertionError):   # lo > hi
        t0.with_entry(10, 0, 1, 2, su.OVERLAY_DOUBLE_WRITE)
    with pytest.raises(AssertionError):   # same-src overlap
        t1.with_entry(1 << 32, 1 << 33, 1, 3, su.OVERLAY_MIGRATED)
    # Overlap across DIFFERENT sources is fine (disjoint id sets: an
    # id belongs to an entry only if its base owner == src).
    t1.with_entry(0, 1 << 32, 2, 3, su.OVERLAY_MIGRATED)
    with pytest.raises(AssertionError):   # non-power-of-two mesh
        su.OwnershipTable(3)


def test_reshard_plan_validation_and_membership():
    with pytest.raises(AssertionError):
        ReshardPlan(lo=10, hi=0, src=0, dst=1, kind="migrate")
    with pytest.raises(AssertionError):
        ReshardPlan(lo=0, hi=10, src=1, dst=1, kind="migrate")
    with pytest.raises(AssertionError):
        ReshardPlan(lo=0, hi=10, src=0, dst=1, kind="shuffle")

    mid = 1 << 63
    plan = ReshardPlan(lo=0, hi=mid, src=0, dst=1, kind="migrate")
    for i in EDGE_IDS + _fuzz_ids(5, 64):
        h = su.mix_int(i)
        want = h <= mid and (h & 7) == 0
        assert plan.in_range(i, 8) == want, hex(i)


# ------------------------------------------------ conflict detection


def _soa(ids):
    """A minimal SoA ev dict: transfer ids only, zero pid/dr/cr."""
    hi, lo = _split(ids)
    z = np.zeros(len(ids), dtype=np.uint64)
    return {"id_hi": hi, "id_lo": lo, "pid_hi": z, "pid_lo": z,
            "dr_hi": z, "dr_lo": z, "cr_hi": z, "cr_lo": z}


def test_conflicts_hashes_ids_in_both_batch_forms():
    """`conflicts` freezes the copy-stage range against BOTH batch
    representations — SoA ev dicts and Transfer objects — hashing ids
    bit-identically with the device in each. Regression: the object
    branch must hash the raw id, never treat it AS the hash."""
    ctl = ReshardController(SimpleNamespace(n_shards=2))
    a = next(i for i in _fuzz_ids(17) if su.mix_int(i) < U64MAX)
    h = su.mix_int(a)
    src = h & 1
    ctl.stage = "copy"

    def set_plan(lo, hi):
        ctl.plan = ReshardPlan(lo=lo, hi=hi, src=src, dst=1 - src,
                               kind="migrate")

    set_plan(h, h)          # the single-hash range
    # SoA: id / pid / dr / cr columns all checked; zeros filtered.
    assert ctl.conflicts([_soa([a])])
    soa = _soa([0])
    for k in ("pid", "dr", "cr"):
        d = dict(soa)
        ahi, alo = _split([a])
        d[f"{k}_hi"], d[f"{k}_lo"] = ahi, alo
        assert ctl.conflicts([d]), k
    assert not ctl.conflicts([_soa([0])])        # zero ids filtered
    out = next(i for i in _fuzz_ids(19) if su.mix_int(i) != h)
    assert not ctl.conflicts([_soa([out])])

    # Transfer objects: same ids, same verdicts. The id column not
    # under test carries `out` (known out of range), so only `field`
    # decides the verdict.
    def obj(i, field="id"):
        kw = dict(id=out, pending_id=0, debit_account_id=0,
                  credit_account_id=0)
        kw[field] = i
        return SimpleNamespace(**kw)

    assert ctl.conflicts([[obj(a)]])
    assert ctl.conflicts([[obj(a, "pending_id")]])
    assert ctl.conflicts([[obj(a, "debit_account_id")]])
    assert ctl.conflicts([[obj(a, "credit_account_id")]])
    assert not ctl.conflicts([[obj(out)]])
    # THE regression: an object whose raw id equals an in-range HASH
    # value but whose own hash is out of range must not conflict.
    if su.mix_int(h) != h:
        assert not ctl.conflicts([[obj(h)]])

    # Inclusive boundaries at both ends of wider ranges.
    set_plan(0, h)
    assert ctl.conflicts([_soa([a])])
    set_plan(h, U64MAX)
    assert ctl.conflicts([_soa([a])])
    set_plan(h + 1, U64MAX)
    assert not ctl.conflicts([_soa([a])])

    # Only the copy stage freezes: double-write serves the range live.
    set_plan(h, h)
    for stage in ("idle", "double_write", "flip", "done"):
        ctl.stage = stage
        assert not ctl.conflicts([_soa([a])]), stage
    ctl.stage = "copy"
    assert not ctl.conflicts([])


# ------------------------------------------------- hot-range detector


def _acct_window(accounts, n_events):
    """One SoA window: dr cycles through `accounts`, cr stays zero
    (zero ids are filtered from the histogram)."""
    ids = [accounts[i % len(accounts)] for i in range(n_events)]
    hi, lo = _split(ids)
    z = np.zeros(n_events, dtype=np.uint64)
    return {"dr_hi": hi, "dr_lo": lo, "cr_hi": z, "cr_lo": z}


def _accounts_on_shard(shard, n_shards, k, start=1):
    out, i = [], start
    while len(out) < k:
        if su.shard_of_int(i, n_shards) == shard:
            out.append(i)
        i += 1
    return out


def test_hot_range_detector_verdicts():
    n_shards = 2
    # Under-sampled: below min_events, never a verdict.
    det = HotRangeDetector(n_shards=n_shards)
    det.observe_window([_acct_window([1], 16)])
    assert det.propose() is None

    # Balanced: load split across shards, no proposal.
    det = HotRangeDetector(n_shards=n_shards)
    a0 = _accounts_on_shard(0, n_shards, 4)
    a1 = _accounts_on_shard(1, n_shards, 4)
    det.observe_window([_acct_window(a0 + a1, 128)])
    assert det.propose() is None

    # Splittable skew: several accounts share one hot shard — a split
    # plan moves the cold half of the range to the coldest shard.
    det = HotRangeDetector(n_shards=n_shards)
    det.observe_window([_acct_window(a0, 128)])
    v = det.propose()
    assert v is not None and v["verdict"] == "split", v
    plan = v["plan"]
    assert plan.kind == "split" and plan.src == 0 and plan.dst == 1
    assert plan.lo == 0
    assert any(plan.in_range(i, n_shards) for i in a0)
    assert not all(plan.in_range(i, n_shards) for i in a0), \
        "a split that moves the WHOLE shard isolates nothing"

    # Anti-thrash cooldown: no immediate re-proposal.
    assert det.propose() is None


def test_hot_range_detector_unsplittable_single_account():
    """Degenerate case: ONE account carries the shard. No hash range
    smaller than the whole shard isolates it, so the detector must
    emit the `unsplittable` verdict (naming the account hash and the
    AT2-lane remedy) instead of proposing a thrashing split."""
    n_shards = 2
    hot_acct = 7
    det = HotRangeDetector(n_shards=n_shards)
    det.observe_window([_acct_window([hot_acct], 128)])
    v = det.propose()
    assert v is not None and v["verdict"] == "unsplittable", v
    assert v["shard"] == su.shard_of_int(hot_acct, n_shards)
    assert v["hot_hash"] == su.mix_int(hot_acct)
    assert v["fraction"] == 1.0
    assert "AT2" in v["note"]
    # Anti-thrash: the verdict sets the cooldown too — no churn of
    # repeated verdicts (or worse, plans) for a load placement can't
    # fix.
    assert det.propose() is None
    det.observe_window([_acct_window([hot_acct], 128)])
    assert det.propose() is None  # still cooling down


def test_hot_range_detector_object_batches():
    """The detector folds Transfer-object windows too (serving path
    hands it the same batches the router dispatches)."""
    det = HotRangeDetector(n_shards=2)
    batch = [SimpleNamespace(debit_account_id=7, credit_account_id=0)
             for _ in range(128)]
    det.observe_window([batch])
    v = det.propose()
    assert v is not None and v["verdict"] == "unsplittable"
    assert v["shard"] == su.shard_of_int(7, 2)
