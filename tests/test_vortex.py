"""Vortex: chaos over real processes + real TCP + fault proxy.

reference: src/vortex.zig — the non-deterministic counterpart of VOPR.
Bounded for CI: one short storm, then heal, audit, shutdown, verify data
files. (The reference runs vortex for hours in CI; the harness supports
that by raising the step count.)
"""

import time

import pytest

from tigerbeetle_tpu.main import _parse_addresses
from tigerbeetle_tpu.testing.vortex import VortexSupervisor
from tigerbeetle_tpu.types import Account, Transfer
from tigerbeetle_tpu.vsr.client import Client


@pytest.mark.integration
def test_vortex_storm(tmp_path):
    supervisor = VortexSupervisor(str(tmp_path), replica_count=3, seed=7)
    committed = []
    try:
        client = Client(cluster=supervisor.cluster, client_id=9,
                        replica_addresses=_parse_addresses(
                            supervisor.addresses))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=1, ledger=1, code=1),
                                        Account(id=2, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
        else:
            raise AssertionError("cluster never became available")

        tid = 100
        for step in range(12):
            fault = supervisor.random_fault(max_down=1)
            amount = step + 1
            try:
                results = client.create_transfers([Transfer(
                    id=tid, debit_account_id=1, credit_account_id=2,
                    amount=amount, ledger=1, code=1)])
                if results[0].status.name in ("created", "exists"):
                    committed.append((tid, amount))
            except TimeoutError:
                # Unknown outcome: the transfer may or may not have
                # committed. Resolve it after healing.
                committed.append((tid, None))
            tid += 1
            if step == 5:
                supervisor.heal_all()  # mid-run heal keeps liveness honest
        supervisor.heal_all()

        # Audit: every known-committed transfer present; unknowns resolved.
        # Unknown-outcome prepares may still commit DURING the audit (a
        # healing view change adopts them), so transfers and accounts are
        # re-read until two consecutive observations agree — a consistent
        # snapshot of the settled cluster.
        deadline = time.monotonic() + 120
        snapshot = prev = None
        while time.monotonic() < deadline:
            try:
                transfers = {t.id: t for t in client.lookup_transfers(
                    [t for t, _ in committed])}
                accounts = {a.id: a for a in client.lookup_accounts([1, 2])}
            except TimeoutError:
                continue
            obs = (sorted(transfers), accounts[1].debits_posted,
                   accounts[2].credits_posted)
            if obs == prev:
                snapshot = (transfers, accounts)
                break
            prev = obs
        assert snapshot is not None, "cluster did not settle"
        transfers, accounts = snapshot
        total = 0
        for tid_, amount in committed:
            if amount is not None:
                assert tid_ in transfers, f"committed transfer {tid_} lost"
                total += transfers[tid_].amount
            elif tid_ in transfers:
                total += transfers[tid_].amount
        assert accounts[1].debits_posted == total
        assert accounts[2].credits_posted == total
        client.close()
    finally:
        supervisor.shutdown()
    supervisor.verify_data_files()
