"""Vortex: chaos over real processes + real TCP + fault proxy.

reference: src/vortex.zig — the non-deterministic counterpart of VOPR.
Bounded for CI: one short storm, then heal, audit, shutdown, verify data
files. (The reference runs vortex for hours in CI; the harness supports
that by raising the step count.)
"""

import time

import pytest

from tigerbeetle_tpu.main import _parse_addresses
from tigerbeetle_tpu.testing.vortex import VortexSupervisor
from tigerbeetle_tpu.types import Account, Transfer
from tigerbeetle_tpu.vsr.client import Client


@pytest.mark.integration
def test_vortex_storm(tmp_path):
    supervisor = VortexSupervisor(str(tmp_path), replica_count=3, seed=7)
    committed = []
    try:
        client = Client(cluster=supervisor.cluster, client_id=9,
                        replica_addresses=_parse_addresses(
                            supervisor.addresses))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=1, ledger=1, code=1),
                                        Account(id=2, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
        else:
            raise AssertionError("cluster never became available")

        tid = 100
        for step in range(12):
            fault = supervisor.random_fault(max_down=1)
            amount = step + 1
            try:
                results = client.create_transfers([Transfer(
                    id=tid, debit_account_id=1, credit_account_id=2,
                    amount=amount, ledger=1, code=1)])
                if results[0].status.name in ("created", "exists"):
                    committed.append((tid, amount))
            except TimeoutError:
                # Unknown outcome: the transfer may or may not have
                # committed. Resolve it after healing.
                committed.append((tid, None))
            tid += 1
            if step == 5:
                supervisor.heal_all()  # mid-run heal keeps liveness honest
        supervisor.heal_all()

        # Audit: every known-committed transfer present; unknowns resolved.
        # Unknown-outcome prepares may still commit DURING the audit (a
        # healing view change adopts them), so transfers and accounts are
        # re-read until two consecutive observations agree — a consistent
        # snapshot of the settled cluster.
        deadline = time.monotonic() + 120
        snapshot = prev = None
        while time.monotonic() < deadline:
            try:
                transfers = {t.id: t for t in client.lookup_transfers(
                    [t for t, _ in committed])}
                accounts = {a.id: a for a in client.lookup_accounts([1, 2])}
            except TimeoutError:
                continue
            obs = (sorted(transfers), accounts[1].debits_posted,
                   accounts[2].credits_posted)
            if obs == prev:
                snapshot = (transfers, accounts)
                break
            prev = obs
        assert snapshot is not None, "cluster did not settle"
        transfers, accounts = snapshot
        total = 0
        for tid_, amount in committed:
            if amount is not None:
                assert tid_ in transfers, f"committed transfer {tid_} lost"
                total += transfers[tid_].amount
            elif tid_ in transfers:
                total += transfers[tid_].amount
        assert accounts[1].debits_posted == total
        assert accounts[2].credits_posted == total
        client.close()
    finally:
        supervisor.shutdown()
    supervisor.verify_data_files()


@pytest.mark.integration
def test_vortex_rebuild_from_cluster(tmp_path):
    """ISSUE 4 acceptance: destroy one replica's data file under live
    client traffic; a crash injected mid-rebuild restarts the rebuild
    cleanly; `recover --from-cluster` rebuilds the file; the rebuilt
    replica rejoins and its state-epoch forest digest is bit-identical
    to a healthy peer's at the same checkpoint, with zero committed-op
    divergence."""
    supervisor = VortexSupervisor(str(tmp_path), replica_count=3, seed=23)
    committed = []
    victim = 2
    try:
        client = Client(cluster=supervisor.cluster, client_id=11,
                        replica_addresses=_parse_addresses(
                            supervisor.addresses))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            try:
                client.create_accounts([Account(id=1, ledger=1, code=1),
                                        Account(id=2, ledger=1, code=1)])
                break
            except TimeoutError:
                continue
        else:
            raise AssertionError("cluster never became available")

        def pump(tid_lo, tid_hi):
            for tid in range(tid_lo, tid_hi):
                try:
                    res = client.create_transfers([Transfer(
                        id=tid, debit_account_id=1, credit_account_id=2,
                        amount=1, ledger=1, code=1)])
                    if res[0].status.name in ("created", "exists"):
                        committed.append((tid, 1))
                except TimeoutError:
                    committed.append((tid, None))  # unknown outcome

        # Drive past the 32-slot WAL window so the rebuild MUST take the
        # state-sync path (peers cannot serve op 1 from their WAL).
        pump(100, 148)
        supervisor.destroy_data_file(victim)
        pump(148, 160)  # live client load while the data file is gone
        # Crash injection: the first rebuild attempt is SIGKILLed. If it
        # was mid-install, the superblock's sync_op record marks the file
        # rebuild-only; either way the re-run must complete cleanly.
        supervisor.run_rebuild(victim, crash_after_s=1.5)
        assert supervisor.run_rebuild(victim) == 0
        supervisor.start_replica(victim)
        pump(160, 172)  # the rebuilt replica follows live traffic

        # Settle audit (zero committed-op divergence): reread until two
        # consecutive observations agree, then check every known-commit.
        deadline = time.monotonic() + 120
        snapshot = prev = None
        while time.monotonic() < deadline:
            try:
                transfers = {t.id: t for t in client.lookup_transfers(
                    [t for t, _ in committed])}
                accounts = {a.id: a for a in client.lookup_accounts([1, 2])}
            except TimeoutError:
                continue
            obs = (sorted(transfers), accounts[1].debits_posted)
            if obs == prev:
                snapshot = (transfers, accounts)
                break
            prev = obs
        assert snapshot is not None, "cluster did not settle"
        transfers, accounts = snapshot
        total = 0
        for tid, amount in committed:
            if amount is not None:
                assert tid in transfers, f"committed transfer {tid} lost"
                total += transfers[tid].amount
            elif tid in transfers:
                total += transfers[tid].amount
        assert accounts[1].debits_posted == total
        assert accounts[2].credits_posted == total
        # Give idle heartbeats a moment to level every replica's commit
        # so all three land on the same checkpoint at shutdown.
        time.sleep(2.0)
        client.close()
    finally:
        supervisor.shutdown()
    supervisor.verify_data_files()
    digests = {i: supervisor.forest_digest(i) for i in range(3)}
    ck_v, digest_v = digests[victim]
    peers_same = [i for i in (0, 1) if digests[i][0] == ck_v]
    assert peers_same, f"no healthy peer at the rebuilt checkpoint: {digests}"
    for i in peers_same:
        assert digests[i][1] == digest_v, \
            f"forest digest divergence r{i} vs rebuilt r{victim}: {digests}"
