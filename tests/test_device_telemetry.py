"""Device telemetry plane + flight recorder (round 10).

Host-side units: TEL_LAYOUT decode, the pack's lane order, the
router's telemetry absorption (synthetic blocks — the device-true
bit-exactness is testing/telemetry_smoke.py's gate leg), and the
flight recorder's ring/dump/merge contract."""

import json

import numpy as np
import pytest


class _StubTracer:
    """Records (event, value, tags) calls — enough surface for
    _absorb_telemetry and FlightRecorder.dump."""

    def __init__(self):
        self.observed = []
        self.counted = []

    def observe(self, event, value, **tags):
        self.observed.append((str(event), value, tags))

    def count(self, event, value=1, **tags):
        self.counted.append((str(event), value, tags))

    def span(self, event, **tags):
        import contextlib

        return contextlib.nullcontext()


def _mk_tel(rows):
    """rows: list of per-prepare dicts keyed by TEL_LAYOUT name ->
    [1, W, TEL_WORDS] u32 block (single shard)."""
    from tigerbeetle_tpu.parallel.partitioned import TEL_LAYOUT

    arr = np.zeros((1, len(rows), len(TEL_LAYOUT)), np.uint32)
    for w, row in enumerate(rows):
        for k, v in row.items():
            arr[0, w, TEL_LAYOUT.index(k)] = v
    return arr


# --------------------------------------------------------------- decode


def test_decode_telemetry_layout_roundtrip():
    from tigerbeetle_tpu.parallel.partitioned import (
        TEL_LAYOUT, TEL_WORDS, decode_telemetry)

    rng = np.random.default_rng(3)
    tel = rng.integers(0, 1 << 16, (2, 3, TEL_WORDS), dtype=np.uint32)
    d = decode_telemetry(tel)
    assert set(d) == set(TEL_LAYOUT)
    for i, name in enumerate(TEL_LAYOUT):
        np.testing.assert_array_equal(d[name], tel[..., i])


def test_telemetry_pack_preserves_word_order():
    from tigerbeetle_tpu.parallel.partitioned import (
        TEL_WORDS, _telemetry_pack)

    out = np.asarray(_telemetry_pack(*range(TEL_WORDS)))
    np.testing.assert_array_equal(out, np.arange(TEL_WORDS))
    assert out.dtype == np.uint32


def test_tel_causes_cover_fallback_taxonomy():
    # Every kernel fb_cause (plus the two exchange breaches and the
    # scan's transitive poison) must be encodable — a new cause key
    # must be added to TEL_CAUSES or the decode reads code_<n>.
    from tigerbeetle_tpu.parallel.partitioned import TEL_CAUSES

    for name in ("e1_hard_flags", "e2_collision", "e3_limit",
                 "e4_overflow", "e5_void_closing", "closing",
                 "capacity", "forced", "shard_capacity",
                 "exchange_overflow"):
        assert name in TEL_CAUSES


# ------------------------------------------------------- router absorb


def _router(telemetry=True, tracer=None):
    import jax
    from jax.sharding import Mesh

    from tigerbeetle_tpu.parallel.partitioned import PartitionedRouter

    mesh = Mesh(np.array(jax.devices()[:1]), ("batch",))
    return PartitionedRouter(mesh, telemetry=telemetry, tracer=tracer)


def test_absorb_telemetry_aggregates_and_summary():
    from tigerbeetle_tpu.parallel.partitioned import TEL_CAUSES

    tracer = _StubTracer()
    rt = _router(tracer=tracer)
    tel = _mk_tel([
        dict(fix_rounds=0, poison_cause=0, xchg1_occupancy=4,
             xchg1_capacity=16, xchg2_occupancy=8, xchg2_capacity=32,
             cross_shard_transfers=3, ring_occupancy=7,
             writeback_transfers=7, events_owned=8),
        dict(fix_rounds=2, poison_cause=TEL_CAUSES.index("e3_limit") + 1,
             xchg1_occupancy=8, xchg1_capacity=16, xchg2_occupancy=16,
             xchg2_capacity=32, ring_occupancy=7, events_owned=9,
             shard_capacity_hit=1),
    ])
    s = rt._absorb_telemetry(tel)
    assert s["prepares"] == 2
    assert s["fix_rounds"] == [0, 2]
    assert s["poison_causes"] == [None, "e3_limit"]
    assert s["exchange_occupancy_pct"] == [25.0, 25.0, 50.0, 50.0]
    assert s["cross_shard_transfers"] == 3
    assert s["writeback_rows"] == 7
    assert s["events_owned"] == [17]
    assert s["ring_occupancy"] == [7]
    assert s["shard_capacity_hits"] == 1
    assert rt.device_poison_causes == {"e3_limit": 1}
    assert rt.writeback_rows == 7
    assert rt.shard_capacity_hits == 1
    assert rt._tel_rounds.count == 2
    assert rt._tel_hist.count == 4
    events = {e for e, _, _ in tracer.observed} | \
        {e for e, _, _ in tracer.counted}
    for name in ("device_fixpoint_rounds", "device_exchange_occupancy",
                 "device_ring_occupancy", "device_poison_cause",
                 "device_writeback_rows"):
        assert any(name in e for e in events), (name, events)


def test_absorb_telemetry_empty_and_2d():
    rt = _router()
    assert rt._absorb_telemetry(np.zeros((1, 0, 12), np.uint32)) is None
    s = rt._absorb_telemetry(np.zeros((1, 12), np.uint32))
    assert s["prepares"] == 1


def test_stats_telemetry_section_toggle():
    rt = _router()
    tel = rt.stats()["telemetry"]
    for key in ("device_poison_causes", "writeback_rows",
                "shard_capacity_hits", "exchange_occupancy",
                "fixpoint_rounds", "flight_windows", "flight_dumps"):
        assert key in tel
    assert _router(telemetry=False).stats()["telemetry"] is None


# ------------------------------------------------------ flight recorder


def test_flight_ring_bounded():
    from tigerbeetle_tpu.trace import FlightRecorder

    fr = FlightRecorder(capacity=4)
    for w in range(10):
        fr.record(window=w, route="partitioned_chain")
    assert fr.seq == 10
    recs = fr.records
    assert [r["window"] for r in recs] == [6, 7, 8, 9]
    assert [r["seq"] for r in recs] == [6, 7, 8, 9]


def test_flight_dump_artifact_and_histograms(tmp_path):
    from tigerbeetle_tpu.trace import FlightRecorder

    tracer = _StubTracer()
    fr = FlightRecorder(capacity=8, pid=3, tracer=tracer,
                        out_dir=str(tmp_path))
    fr.record(window=0, route="partitioned_chain",
              telemetry={"fix_rounds": [0, 2],
                         "exchange_occupancy_pct": [25.0, 50.0]},
              prepares=2)
    fr.record(window=1, route="epoch_verified", epoch_digest="abc123")
    path = fr.dump("unit_test")
    assert path and path.endswith("FLIGHT_3_unit_test_000002.json")
    assert fr.last_dump_path == path
    assert fr.dumps == 1
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit_test"
    assert doc["pid"] == 3
    assert doc["windows_recorded"] == 2
    assert len(doc["records"]) == 2
    assert doc["records"][0]["telemetry"]["fix_rounds"] == [0, 2]
    assert doc["records"][1]["epoch_digest"] == "abc123"
    assert doc["histograms"]["fix_rounds"]["count"] == 2
    assert doc["histograms"]["exchange_occupancy_pct"]["count"] == 2
    assert any("flight_recorder_dump" in e for e, _, t in tracer.counted
               if t.get("reason") == "unit_test")


def test_flight_dump_never_raises_on_io_failure():
    from tigerbeetle_tpu.trace import FlightRecorder

    fr = FlightRecorder()
    fr.record(window=0, route="x")
    path = fr.dump("io_fail",
                   path="/nonexistent_dir_tb_tpu/flight.json")
    assert path == ""
    assert fr.dumps == 1
    assert fr.last_dump_path is None


def test_flight_merge_lossless(tmp_path):
    from tigerbeetle_tpu.trace import FlightRecorder, Histogram
    from tigerbeetle_tpu.trace.flight_recorder import merge_flight_records

    paths = []
    for pid, rounds in ((0, [1.0, 2.0]), (1, [3.0, 4.0, 5.0])):
        fr = FlightRecorder(pid=pid, out_dir=str(tmp_path))
        for w, r in enumerate(rounds):
            fr.record(window=w, route="partitioned_chain",
                      telemetry={"fix_rounds": [r],
                                 "exchange_occupancy_pct": []})
        paths.append(fr.dump("mirror_divergence"))
    merged = merge_flight_records(paths)
    assert merged["replicas"] == [0, 1]
    assert merged["reasons"] == ["mirror_divergence"]
    assert [(r["pid"], r["seq"]) for r in merged["records"]] == \
        [(0, 0), (0, 1), (1, 0), (1, 1), (1, 2)]
    h = Histogram.from_dict(merged["histograms"]["fix_rounds"])
    assert h.count == 5
    # The merged histogram equals one built from the union of samples.
    ref = Histogram()
    for v in (1.0, 2.0, 3.0, 4.0, 5.0):
        ref.record(v)
    assert h.to_dict() == ref.to_dict()
    # Merge accepts pre-loaded dicts too.
    docs = [json.load(open(p)) for p in paths]
    assert merge_flight_records(docs)["records"] == merged["records"]


def test_new_catalog_events_registered():
    from tigerbeetle_tpu.trace import Event

    for name in ("device_fixpoint_rounds", "device_poison_cause",
                 "device_exchange_occupancy", "device_ring_occupancy",
                 "device_writeback_rows", "flight_recorder_dump"):
        assert hasattr(Event, name), name


def test_serving_stats_expose_flight():
    # ServingSupervisor wires a recorder by default and surfaces its
    # counters; constructing one must not require a device ledger.
    from tigerbeetle_tpu.trace import FlightRecorder

    fr = FlightRecorder(capacity=2)
    fr.record(window=0, route="recovery", cause="dispatch_exhausted")
    assert fr.records[0]["detail"]["cause"] == "dispatch_exhausted"
