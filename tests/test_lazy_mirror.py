"""Lazy columnar mirror (ops/lazy_mirror.py): the serving drain registers
chunks without building objects, and every observable value matches the
sequential oracle (the old eager drain's contract).

Reference analog: the groove object cache materializes on demand
(src/lsm/groove.zig:885); commit itself never builds host objects
(src/state_machine.zig:2564 "commit is the cheap part")."""

import numpy as np
import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.constants import BATCH_MAX
from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.lazy_mirror import (LazyEventList, LazyEventRecord,
                                             LazyTransferDict)
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (Account, Operation, Transfer,
                                   TransferFlags)


def _mixed_workload(rng, n_accounts, batches, batch):
    """Create/pending/post/void/closing mix as per-batch Transfer lists."""
    pend = int(TransferFlags.pending)
    post = int(TransferFlags.post_pending_transfer)
    void = int(TransferFlags.void_pending_transfer)
    out = []
    next_id = 10**6
    pending_ids = []
    for _ in range(batches):
        events = []
        # Post/void targets come from PRIOR batches only, so the fast
        # kernel keeps the batch (same-batch pending references fall
        # back to the host path and would defeat the laziness assertions).
        prior_pending = list(pending_ids)
        for _ in range(batch):
            tid = next_id
            next_id += 1
            roll = rng.random()
            if roll < 0.5:
                events.append(Transfer(
                    id=tid, debit_account_id=int(rng.integers(1, n_accounts + 1)),
                    credit_account_id=int(rng.integers(1, n_accounts + 1)) % n_accounts + 1,
                    amount=int(rng.integers(0, 1000)), ledger=1, code=1))
            elif roll < 0.75:
                events.append(Transfer(
                    id=tid, debit_account_id=int(rng.integers(1, n_accounts + 1)),
                    credit_account_id=int(rng.integers(1, n_accounts + 1)) % n_accounts + 1,
                    amount=int(rng.integers(1, 1000)), ledger=1, code=1,
                    flags=pend, timeout=int(rng.integers(0, 50))))
                pending_ids.append(tid)
            elif roll < 0.95 and prior_pending:
                target = prior_pending[int(rng.integers(0, len(prior_pending)))]
                events.append(Transfer(
                    id=tid, pending_id=target,
                    amount=int(rng.integers(0, 500)) if rng.random() < 0.5 else 0,
                    ledger=1, code=1,
                    flags=post if rng.random() < 0.5 else void))
            else:
                # Zero-amount regular create (exercises the no-op
                # account-update condition in apply_account_finals).
                events.append(Transfer(
                    id=tid, debit_account_id=int(rng.integers(1, n_accounts + 1)),
                    credit_account_id=int(rng.integers(1, n_accounts + 1)) % n_accounts + 1,
                    amount=0, ledger=1, code=1))
        out.append(events)
    return out


@pytest.fixture(scope="module")
def engines():
    """Device serving engine + sequential oracle over the same workload,
    with fixups so clashing dr/cr never occur."""
    rng = np.random.default_rng(42)
    n_accounts = 40
    sm = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 13)
    oracle = StateMachineOracle()
    accounts = [Account(id=i, ledger=1, code=1)
                for i in range(1, n_accounts + 1)]
    sm.create_accounts(accounts, 500)
    oracle.create_accounts(accounts, 500)
    ts = 10**12
    for events in _mixed_workload(rng, n_accounts, batches=6, batch=256):
        for ev in events:  # keep dr != cr after the modular fixup
            if ev.debit_account_id == ev.credit_account_id:
                ev.credit_account_id = ev.debit_account_id % n_accounts + 1
        ts += len(events) + 5
        body = b"".join(e.pack() for e in events)
        sm.commit(Operation.create_transfers,
                  multi_batch.encode([body], 128), ts)
        oracle.create_transfers(events, ts)
    return sm, oracle


def test_drain_is_lazy(engines):
    sm, _ = engines
    sm.led.drain_mirror()
    transfers = sm._state.transfers
    assert isinstance(transfers, LazyTransferDict)
    assert transfers._lazy, "drain should leave rows unmaterialized"
    lazy_before = len(transfers._lazy)
    some_id = next(iter(transfers._lazy))
    obj = transfers[some_id]
    assert obj.id == some_id
    assert len(transfers._lazy) == lazy_before - 1, \
        "a point read must materialize exactly one row"


def test_lazy_dict_mutation_semantics(engines):
    sm, _ = engines
    sm.led.drain_mirror()
    transfers = sm._state.transfers
    # Fabricate a lazy-backed dict copy to exercise del/pop/contains.
    if not transfers._lazy:
        pytest.skip("all rows already materialized by earlier test order")
    some_id = next(iter(transfers._lazy))
    assert some_id in transfers
    assert some_id in set(transfers.keys())
    n = len(transfers)
    transfers.dirty.discard(some_id)
    popped = transfers.pop(some_id)
    assert popped.id == some_id
    assert some_id in transfers.dirty, "pop must mark the durable channel"
    assert len(transfers) == n - 1
    assert some_id not in transfers
    # Reinsert (fallback-style) and delete.
    transfers[some_id] = popped
    del transfers[some_id]
    assert some_id not in transfers
    # Restore for later tests.
    transfers[some_id] = popped


def test_mirror_matches_oracle(engines):
    sm, oracle = engines
    state = sm.state  # drains
    assert state.accounts == oracle.accounts
    assert state.transfers == oracle.transfers  # materialize_all via __eq__
    assert not state.transfers._lazy
    assert state.pending_status == oracle.pending_status
    assert state.expiry == oracle.expiry
    assert set(state.orphaned) == set(oracle.orphaned)
    assert state.transfer_by_timestamp == oracle.transfer_by_timestamp
    assert state.transfers_key_max == oracle.transfers_key_max
    assert state.commit_timestamp == oracle.commit_timestamp
    assert state.pulse_next_timestamp == oracle.pulse_next_timestamp


def test_account_events_match_oracle(engines):
    sm, oracle = engines
    events = sm.state.account_events
    assert isinstance(events, LazyEventList)
    assert len(events) == len(oracle.account_events)
    assert events == oracle.account_events
    # Element access yields record-compatible objects.
    rec = events[0]
    assert rec == oracle.account_events[0]
    assert events[-1] == oracle.account_events[-1]
    sl = events[3:17]
    assert sl == oracle.account_events[3:17]


def test_lazy_event_list_surface():
    lst = LazyEventList()
    assert not lst and len(lst) == 0 and lst == []

    class _FakeChunk:
        def event(self, k):
            return ("ev", k)

    c = _FakeChunk()
    lst.extend_lazy(c, 5)
    lst.append("real-0")
    lst.extend_lazy(c, 3)
    assert len(lst) == 9
    assert lst[5] == "real-0"
    assert isinstance(lst[0], LazyEventRecord)
    # Prefix prune (durable flush) trims into the first lazy segment.
    del lst[:2]
    assert len(lst) == 7
    assert lst[3] == "real-0"
    # Suffix deletion (scope rollback).
    del lst[6:]
    assert len(lst) == 6
    items = list(lst)
    assert items[3] == "real-0" and len(items) == 6
