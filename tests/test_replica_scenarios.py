"""Hard scripted cluster scenarios, round 3 (reference:
src/vsr/replica_test.zig — the exact fault sequences randomized
simulation rarely hits). These complement tests/test_consensus_scenarios
(message-level single-replica scripts) with full-cluster scripts:
storage corruption + crash/restart + partitions + checkpoint crossings.

Reference cases ported (replica_test.zig line refs at each test):
WAL prepare/header corruption flavors, corrupt reply slot, misdirected
write, repair-during-view-change of a committed op, backup checkpoint
fast-forward, checkpoint-crossing catch-up, duel of the primaries.
"""

import pytest

from tests.test_vsr import (
    _create_accounts_body,
    _create_transfers_body,
    _drive,
)
from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.testing.cluster import MS, Cluster, NetworkOptions
from tigerbeetle_tpu.types import Operation, Transfer
from tigerbeetle_tpu.vsr.header import HEADER_SIZE
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT


def _wal_prepare_off(storage, op: int) -> int:
    slot = op % storage.layout.slot_count
    return storage.layout.zone_offsets["wal_prepares"] \
        + slot * storage.layout.message_size_max


def _wal_header_off(storage, op: int) -> int:
    slot = op % storage.layout.slot_count
    return storage.layout.zone_offsets["wal_headers"] + slot * HEADER_SIZE


def _flip(storage, off: int, n: int = 64) -> None:
    for i in range(n):
        storage.data[off + i] ^= 0xFF


def _setup(seed, n_transfers=6, **kw):
    cluster = Cluster(seed=seed, replica_count=3, **kw)
    client = cluster.client(40 + seed)
    _drive(cluster, client, [
        (Operation.create_accounts, _create_accounts_body([1, 2])),
        (Operation.create_transfers, _create_transfers_body(
            [(100 + k, 1, 2, 1) for k in range(n_transfers)])),
    ])
    cluster.settle()
    return cluster, client


def _assert_converged_balance(cluster, want_debits):
    for i, r in enumerate(cluster.replicas):
        if i in cluster.crashed:
            continue
        a1 = r.state_machine.state.accounts[1]
        assert a1.debits_posted == want_debits, (i, a1)
    cluster.check_convergence()


class TestWalCorruption:
    def test_corrupt_committed_prepare_restart_repairs(self):
        """replica_test.zig:131 ("corrupt checkpoint…head"): a backup's
        COMMITTED prepare is corrupted on disk; after restart, recovery
        classifies the slot faulty and repairs the body from peers —
        state must still converge."""
        cluster, client = _setup(21)
        primary = cluster.replicas[0].primary_index()
        victim = (primary + 1) % 3
        cluster.crash(victim)
        st = cluster.storages[victim]
        _flip(st, _wal_prepare_off(st, 2) + HEADER_SIZE + 16)
        cluster.restart(victim)
        cluster.settle()
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(300, 1, 2, 5)]))])
        cluster.settle()
        _assert_converged_balance(cluster, 6 + 5)

    def test_corrupt_wal_header_restart_repairs(self):
        """replica_test.zig:171: a corrupted redundant header with an
        intact prepare classifies the slot recoverable; restart + repair
        must converge."""
        cluster, client = _setup(22)
        primary = cluster.replicas[0].primary_index()
        victim = (primary + 2) % 3
        cluster.crash(victim)
        st = cluster.storages[victim]
        _flip(st, _wal_header_off(st, 2), n=32)
        cluster.restart(victim)
        cluster.settle()
        _assert_converged_balance(cluster, 6)

    def test_corrupt_right_of_head_uncommitted(self):
        """replica_test.zig:75 (corrupt right of head): corruption in an
        uncommitted suffix slot beyond the head is harmless garbage —
        recovery must not execute or propagate it."""
        cluster, client = _setup(23)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        st = cluster.storages[victim]
        # Beyond the current head op (2 requests committed => ops ~1..2).
        _flip(st, _wal_prepare_off(st, 9))
        _flip(st, _wal_header_off(st, 9), n=32)
        cluster.restart(victim)
        cluster.settle()
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(301, 1, 2, 2)]))])
        cluster.settle()
        _assert_converged_balance(cluster, 6 + 2)

    def test_misdirected_write_detected_and_repaired(self):
        """A misdirected write (reference storage fault model,
        testing/storage.zig): replica's slot A holds a VALID prepare for
        the wrong op. Recovery must detect the op/slot mismatch rather
        than serve the wrong body; repair restores convergence."""
        cluster, client = _setup(24)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        st = cluster.storages[victim]
        # Copy slot(op=1)'s prepare+header into slot(op=2): valid bytes,
        # wrong slot.
        p1, p2 = _wal_prepare_off(st, 1), _wal_prepare_off(st, 2)
        h1, h2 = _wal_header_off(st, 1), _wal_header_off(st, 2)
        msz = st.layout.message_size_max
        st.data[p2:p2 + msz] = st.data[p1:p1 + msz]
        st.data[h2:h2 + HEADER_SIZE] = st.data[h1:h1 + HEADER_SIZE]
        cluster.restart(victim)
        cluster.settle()
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(302, 1, 2, 3)]))])
        cluster.settle()
        _assert_converged_balance(cluster, 6 + 3)


class TestReplyRepair:
    def test_corrupt_reply_slot_repaired_on_retry(self):
        """replica_test.zig:704 (corrupt reply): a request commits but
        its reply is lost in flight; the primary's stored reply bytes are
        then corrupted on disk. The client's retry (same request number)
        must be answered via peer reply repair, not garbage."""
        cluster, client = _setup(25)
        cluster.settle()
        primary = cluster.replicas[0].primary_index()
        # Drop replies to the client while the request commits.
        orig_post = cluster._post

        def drop_replies(src, dst, raw):
            if dst[0] == "client":
                return
            orig_post(src, dst, raw)

        cluster._post = drop_replies
        client.request(Operation.create_transfers,
                       _create_transfers_body([(303, 1, 2, 4)]))
        cluster.run(1200)  # commits cluster-wide; reply never delivered
        assert not client.idle
        # Corrupt the primary's on-disk reply zone and bounce it so the
        # in-memory copy is gone too.
        cluster.crash(primary)
        st = cluster.storages[primary]
        off = st.layout.zone_offsets["client_replies"]
        for s in range(st.layout.clients_max):
            _flip(st, off + s * st.layout.message_size_max, n=128)
        cluster.restart(primary)
        cluster._post = orig_post
        # The client keeps retrying the SAME request: the (possibly new)
        # primary must serve the reply — repaired from a peer if its own
        # bytes are torn.
        ok = cluster.run(8000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster.settle()
        _assert_converged_balance(cluster, 6 + 4)


class TestCheckpointCrossing:
    def test_backup_fast_forwards_one_checkpoint(self):
        """replica_test.zig:568: a partitioned backup misses a whole
        checkpoint interval; after healing it must catch up (repair or
        state sync) and converge on the post-checkpoint state."""
        cluster, client = _setup(26)
        primary = cluster.replicas[0].primary_index()
        lagger = (primary + 1) % 3
        cluster.partition(("replica", lagger))
        # checkpoint_interval=16: drive well past one checkpoint.
        for k in range(20):
            _drive(cluster, client, [
                (Operation.create_transfers,
                 _create_transfers_body([(400 + k, 1, 2, 1)]))])
        assert any(r.superblock.op_checkpoint > 0
                   for i, r in enumerate(cluster.replicas) if i != lagger)
        cluster.heal(("replica", lagger))
        cluster.settle(4000)
        _assert_converged_balance(cluster, 6 + 20)

    def test_backup_crash_before_checkpoint_primary_prepares_on(self):
        """replica_test.zig:801: a backup crashes just before the
        checkpoint boundary; the primary checkpoints and keeps preparing;
        the restarted backup crosses the checkpoint on catch-up."""
        cluster, client = _setup(27)
        primary = cluster.replicas[0].primary_index()
        victim = (primary + 2) % 3
        cluster.crash(victim)
        for k in range(20):
            _drive(cluster, client, [
                (Operation.create_transfers,
                 _create_transfers_body([(500 + k, 1, 2, 1)]))])
        cluster.restart(victim)
        cluster.settle(4000)
        _assert_converged_balance(cluster, 6 + 20)

    def test_lagging_replica_syncs_across_two_checkpoints(self):
        """replica_test.zig:1121 (partition, lag, sync): two full
        checkpoints pass while a replica is partitioned — beyond WAL
        repair reach if the ring wrapped; catch-up must still converge
        byte-for-byte."""
        cluster, client = _setup(28)
        lagger = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.partition(("replica", lagger))
        for k in range(36):
            _drive(cluster, client, [
                (Operation.create_transfers,
                 _create_transfers_body([(600 + k, 1, 2, 1)]))])
        cluster.heal(("replica", lagger))
        cluster.settle(6000)
        _assert_converged_balance(cluster, 6 + 36)


class TestViewChangeHard:
    def test_repair_during_view_change_committed_op_not_nacked(self):
        """replica_test.zig:650: a COMMITTED op is corrupt on the new
        primary at view-change time. It must repair the body from peers —
        never nack-truncate a committed op."""
        cluster, client = _setup(29)
        old_primary = cluster.replicas[0].primary_index()
        new_primary = (old_primary + 1) % 3
        committed = cluster.replicas[new_primary].commit_min
        assert committed >= 2
        # Corrupt committed op 2 on the soon-to-be primary's WAL.
        cluster.crash(new_primary)
        st = cluster.storages[new_primary]
        _flip(st, _wal_prepare_off(st, 2) + HEADER_SIZE + 8)
        cluster.restart(new_primary)
        cluster.settle()
        # Force the view change onto it.
        cluster.crash(old_primary)
        cluster.run(4000, until=lambda: all(
            r.status == "normal" and r.view > 0
            for i, r in enumerate(cluster.replicas)
            if i not in cluster.crashed))
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(304, 1, 2, 7)]))])
        cluster.restart(old_primary)
        cluster.settle(4000)
        _assert_converged_balance(cluster, 6 + 7)

    def test_duel_of_the_primaries(self):
        """replica_test.zig:902: the deposed primary comes back mid-view-
        change still believing it leads; exactly one view survives and no
        fork is possible."""
        cluster, client = _setup(30)
        primary = cluster.replicas[0].primary_index()
        cluster.partition(("replica", primary))
        # The two live replicas elect a new view.
        cluster.run(4000, until=lambda: all(
            r.view > 0 and r.status == "normal"
            for i, r in enumerate(cluster.replicas) if i != primary))
        # The old primary rejoins, still in view 0, and tries to drive
        # its own prepare; the duel must resolve to ONE view.
        cluster.heal(("replica", primary))
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(305, 1, 2, 9)]))])
        cluster.settle(4000)
        views = {r.view for r in cluster.replicas}
        assert len(views) == 1 and views.pop() > 0
        _assert_converged_balance(cluster, 6 + 9)

    def test_asymmetric_partition_send_only_primary(self):
        """replica_test.zig:479 (partition primary-all, send-only): the
        primary can SEND but not RECEIVE — it cannot gather acks, so the
        cluster must eventually elect around it and stay live."""
        cluster, client = _setup(31)
        primary = cluster.replicas[0].primary_index()
        # Drop everything INBOUND to the primary from replicas (send-only
        # partition): filter at the post hook.
        orig_post = cluster._post

        def drop_inbound(src, dst, raw):
            if (dst == ("replica", primary)
                    and src[0] == "replica"):
                return
            orig_post(src, dst, raw)

        cluster._post = drop_inbound
        client.request(Operation.create_transfers,
                       _create_transfers_body([(306, 1, 2, 11)]))
        ok = cluster.run(8000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        cluster._post = orig_post
        cluster.settle(4000)
        _assert_converged_balance(cluster, 6 + 11)
