"""`inspect` hardening (ISSUE 4 satellite): the WAL-slot and superblock
dumps must render against a deliberately corrupted data file — every bad
checksum FLAGGED in the output, never raised. Each zone is corrupted in
turn; `main(["inspect", ...])` runs in-process so a crash surfaces as a
test failure, not a subprocess exit code.
"""

import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.main import main
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import Account, Operation, Transfer
from tigerbeetle_tpu.vsr.header import HEADER_SIZE
from tigerbeetle_tpu.vsr.replica import Replica
from tigerbeetle_tpu.vsr.storage import (
    SUPERBLOCK_COPY_SIZE,
    TEST_LAYOUT,
    FileStorage,
)
from tigerbeetle_tpu.vsr.superblock import SuperBlock


class _NullBus:
    def send_to_replica(self, dst, msg):
        pass

    def send_to_client(self, client, msg):
        pass


class _Time:
    now = 1_700_000_000 * 10**9

    def monotonic(self):
        self.now += 1_000_000
        return self.now

    def realtime(self):
        return self.now


def _encode(payloads):
    return multi_batch.encode([b"".join(payloads)], 128)


def _build_data_file(path) -> None:
    """Single-replica data file with commits across a checkpoint, so the
    WAL, snapshot, and grid zones all hold real content."""
    storage = FileStorage(str(path), layout=TEST_LAYOUT, create=True)
    Replica.format(storage, cluster=1, replica_id=0, replica_count=1)
    replica = Replica(
        cluster=1, replica_id=0, replica_count=1, storage=storage,
        bus=_NullBus(), time=_Time(),
        state_machine_factory=lambda: StateMachine(engine="oracle"))
    replica.open()
    replica._primary_prepare(
        Operation.create_accounts,
        _encode([Account(id=i, ledger=1, code=1).pack() for i in (1, 2)]))
    replica.tick()  # async WAL appends ack (and commit) at poll_io
    for k in range(20):  # crosses checkpoint_interval=16
        replica._primary_prepare(
            Operation.create_transfers,
            _encode([Transfer(id=100 + k, debit_account_id=1,
                              credit_account_id=2, amount=1,
                              ledger=1, code=1).pack()]))
        replica.tick()
    replica.journal.wait_all()
    replica.tick()
    assert replica.superblock.op_checkpoint > 0
    storage.sync()
    storage.close()


def _flip(path, offset: int, n: int = 8) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        data = bytearray(f.read(n))
        for i in range(len(data)):
            data[i] ^= 0xFF
        f.seek(offset)
        f.write(bytes(data))


ZONES = TEST_LAYOUT.zone_offsets


@pytest.fixture()
def data_file(tmp_path):
    path = tmp_path / "r0.tigerbeetle"
    _build_data_file(path)
    return str(path)


def _active_snapshot_offset(path) -> int:
    storage = FileStorage(path, layout=TEST_LAYOUT)
    sb = SuperBlock.load(storage)
    storage.close()
    return ZONES["snapshot"] \
        + sb.snapshot_slot * TEST_LAYOUT.snapshot_size_max


class TestInspectCorruptZones:
    def test_clean_file_renders_ok(self, data_file, capsys):
        assert main(["inspect", "--small", data_file]) == 0
        out = capsys.readouterr().out
        assert "superblock: cluster=1" in out
        assert "root=ok" in out
        assert "CORRUPT" not in out

    def test_all_superblock_copies_corrupt(self, data_file, capsys):
        for copy in range(4):
            _flip(data_file,
                  ZONES["superblock"] + copy * SUPERBLOCK_COPY_SIZE + 4)
        assert main(["inspect", "--small", data_file]) == 1
        out = capsys.readouterr().out
        assert out.count("CORRUPT (bad checksum)") >= 4
        assert "no quorum" in out

    def test_one_superblock_copy_corrupt_still_opens(self, data_file,
                                                     capsys):
        _flip(data_file, ZONES["superblock"] + 4)
        assert main(["inspect", "--small", data_file]) == 0
        out = capsys.readouterr().out
        assert "superblock copy 0: CORRUPT" in out
        assert "superblock: cluster=1" in out  # quorum survives

    def test_wal_header_corrupt_recovers_from_prepare(self, data_file,
                                                      capsys):
        # A torn redundant header with an intact prepare is legitimately
        # recovered (not a fault) — the dump must render it, not die.
        slot = 2  # op 2's slot in the 32-slot ring
        _flip(data_file, ZONES["wal_headers"] + slot * HEADER_SIZE + 4)
        assert main(["inspect", "--small", data_file]) == 0
        out = capsys.readouterr().out
        assert f"wal slot {slot:4d}: op=2" in out

    def test_wal_both_rings_corrupt_flagged(self, data_file, capsys):
        slot = 2
        _flip(data_file, ZONES["wal_headers"] + slot * HEADER_SIZE + 4)
        _flip(data_file, ZONES["wal_prepares"]
              + slot * TEST_LAYOUT.message_size_max + 4)
        assert main(["inspect", "--small", data_file]) == 0
        out = capsys.readouterr().out
        assert f"wal slot {slot:4d}: no valid header " \
               "CORRUPT (unrecognizable)" in out

    def test_wal_prepare_corrupt_flagged(self, data_file, capsys):
        slot = 3
        _flip(data_file, ZONES["wal_prepares"]
              + slot * TEST_LAYOUT.message_size_max + HEADER_SIZE + 8)
        assert main(["inspect", "--small", data_file]) == 0
        out = capsys.readouterr().out
        assert "faulty" in out
        assert f"wal slot {slot:4d}:" in out
        assert "CORRUPT (bad checksum)" in out

    def test_snapshot_root_corrupt_flagged(self, data_file, capsys):
        _flip(data_file, _active_snapshot_offset(data_file) + 16)
        assert main(["inspect", "--small", data_file]) == 1
        out = capsys.readouterr().out
        assert "root=CORRUPT" in out
        # The WAL dump still renders below the corrupt root.
        assert "journal:" in out

    def test_grid_corrupt_integrity_flags_not_raises(self, data_file,
                                                     capsys):
        # Carpet-bomb the first bytes of many grid blocks: --integrity
        # must enumerate faults (and a failed state rebuild) tolerantly.
        bs = TEST_LAYOUT.grid_block_size
        for block in range(0, 64):
            _flip(data_file, ZONES["grid"] + block * bs + 1, n=4)
        assert main(["inspect", "--small", "--integrity",
                     data_file]) == 1
        out = capsys.readouterr().out
        assert "CORRUPT" in out or "FAILED" in out

    def test_zeroed_file_renders_no_quorum(self, data_file, capsys):
        storage = FileStorage(data_file, layout=TEST_LAYOUT)
        storage.erase()
        storage.close()
        assert main(["inspect", "--small", data_file]) == 1
        out = capsys.readouterr().out
        assert "no quorum" in out

    def test_mid_rebuild_record_rendered(self, data_file, capsys):
        storage = FileStorage(data_file, layout=TEST_LAYOUT)
        sb = SuperBlock.load(storage)
        sb.sync_op = 48
        sb.store(storage)
        storage.close()
        assert main(["inspect", "--small", data_file]) == 0
        out = capsys.readouterr().out
        assert "MID-REBUILD" in out
        # ...and a normal open refuses the file outright.
        storage = FileStorage(data_file, layout=TEST_LAYOUT)
        replica = Replica(
            cluster=1, replica_id=0, replica_count=1, storage=storage,
            bus=_NullBus(), time=_Time(),
            state_machine_factory=lambda: StateMachine(engine="oracle"))
        with pytest.raises(RuntimeError, match="mid-rebuild"):
            replica.open()
        storage.close()
