"""In-batch / in-window pending resolution (ops/fast_kernels.py
_dup_and_pend_join + the dependency fixpoint): a post/void whose pending
was created EARLIER in the same batch or commit window resolves on
device, bit-identically to the sequential oracle.

Reference: post_or_void_pending_transfer resolves against the groove,
which already contains same-batch creations
(src/state_machine.zig:4053-4112); failure statuses follow the same
precedence order (src/tigerbeetle.zig:220)."""

import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (Account, AccountFlags, Transfer,
                                   TransferFlags)

PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
VOID = int(TransferFlags.void_pending_transfer)
LINKED = int(TransferFlags.linked)
U128MAX = (1 << 128) - 1


def _mk_pair(a_cap=1 << 10, t_cap=1 << 12, accounts=None):
    dev = StateMachine(engine="device", a_cap=a_cap, t_cap=t_cap)
    orc = StateMachine(engine="oracle")
    accounts = accounts or [Account(id=i, ledger=1, code=1)
                            for i in range(1, 101)]
    for sm in (dev, orc):
        res = sm.create_accounts(accounts, 120)
        assert all(r.status.name == "created" for r in res)
    return dev, orc


def _diff_batch(dev, orc, events, ts):
    rd = dev.create_transfers(events, ts)
    ro = orc.create_transfers(events, ts)
    got = [(r.timestamp, r.status.name) for r in rd]
    want = [(r.timestamp, r.status.name) for r in ro]
    assert got == want, f"status divergence:\n dev={got}\n orc={want}"
    return [r.status.name for r in rd]


def _assert_state_parity(dev, orc):
    ds, os_ = dev.state, orc.state
    assert ds.accounts == os_.accounts
    assert ds.transfers == os_.transfers
    assert ds.pending_status == os_.pending_status
    assert ds.expiry == os_.expiry
    assert set(ds.orphaned) == set(os_.orphaned)
    assert ds.pulse_next_timestamp == os_.pulse_next_timestamp
    assert ds.commit_timestamp == os_.commit_timestamp


class TestInBatchPending:
    def test_pend_then_post_same_batch(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=1000, debit_account_id=1, credit_account_id=2,
                     amount=100, ledger=1, code=1, flags=PEND, timeout=60),
            Transfer(id=1001, debit_account_id=3, credit_account_id=4,
                     amount=5, ledger=1, code=1),
            Transfer(id=1002, pending_id=1000, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
        ]
        st = _diff_batch(dev, orc, events, 10**12)
        assert st == ["created", "created", "created"]
        assert dev.led.fallbacks == 0, "must stay on device"
        _assert_state_parity(dev, orc)
        a1 = dev.lookup_accounts([1])[0]
        assert a1.debits_posted == 100 and a1.debits_pending == 0

    def test_pend_then_void_sentinel_amounts(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=2000, debit_account_id=1, credit_account_id=2,
                     amount=77, ledger=1, code=1, flags=PEND, timeout=9),
            Transfer(id=2001, pending_id=2000, amount=0,
                     ledger=1, code=1, flags=VOID),
        ]
        st = _diff_batch(dev, orc, events, 2 * 10**12)
        assert st == ["created", "created"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)

    def test_post_of_failed_pend_is_not_found(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=3000, debit_account_id=1, credit_account_id=999,
                     amount=10, ledger=1, code=1, flags=PEND),
            Transfer(id=3001, pending_id=3000, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
        ]
        st = _diff_batch(dev, orc, events, 3 * 10**12)
        assert st == ["credit_account_not_found",
                      "pending_transfer_not_found"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)

    def test_post_before_pend_is_not_found(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=4001, pending_id=4000, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
            Transfer(id=4000, debit_account_id=1, credit_account_id=2,
                     amount=10, ledger=1, code=1, flags=PEND),
        ]
        st = _diff_batch(dev, orc, events, 4 * 10**12)
        assert st == ["pending_transfer_not_found", "created"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)

    def test_post_of_chain_rolled_back_pend(self):
        dev, orc = _mk_pair()
        events = [
            # Linked chain: pend + a failing member -> pend rolls back.
            Transfer(id=5000, debit_account_id=1, credit_account_id=2,
                     amount=10, ledger=1, code=1, flags=PEND | LINKED),
            Transfer(id=5001, debit_account_id=1, credit_account_id=999,
                     amount=1, ledger=1, code=1),
            Transfer(id=5002, pending_id=5000, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
        ]
        st = _diff_batch(dev, orc, events, 5 * 10**12)
        assert st == ["linked_event_failed", "credit_account_not_found",
                      "pending_transfer_not_found"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)

    def test_use_is_own_chains_first_failure(self):
        """The def was still applied when its same-chain use evaluated:
        the use keeps ITS OWN failure code (which then breaks the chain
        and rolls the def back) — NOT pending_transfer_not_found, which
        being transient would wrongly poison the use's id."""
        dev, orc = _mk_pair()
        events = [
            Transfer(id=9100, debit_account_id=1, credit_account_id=2,
                     amount=10, ledger=1, code=1, flags=PEND | LINKED),
            Transfer(id=9101, pending_id=9100, amount=50,
                     ledger=1, code=1, flags=VOID),
        ]
        st = _diff_batch(dev, orc, events, 95 * 10**11)
        assert st == ["linked_event_failed",
                      "exceeds_pending_transfer_amount"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)
        # exceeds_pending_transfer_amount is NOT transient: the id must
        # stay usable.
        retry = [Transfer(id=9101, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1, code=1)]
        st2 = _diff_batch(dev, orc, retry, 96 * 10**11)
        assert st2 == ["created"]
        _assert_state_parity(dev, orc)

    def test_post_of_post_is_not_pending(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=6000, debit_account_id=1, credit_account_id=2,
                     amount=10, ledger=1, code=1, flags=PEND),
            Transfer(id=6001, pending_id=6000, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
            Transfer(id=6002, pending_id=6001, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
        ]
        st = _diff_batch(dev, orc, events, 6 * 10**12)
        assert st == ["created", "created",
                      "pending_transfer_not_pending"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)

    def test_double_post_same_pid_falls_back_correctly(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=7000, debit_account_id=1, credit_account_id=2,
                     amount=10, ledger=1, code=1, flags=PEND),
            Transfer(id=7001, pending_id=7000, amount=U128MAX,
                     ledger=1, code=1, flags=POST),
            Transfer(id=7002, pending_id=7000, amount=0,
                     ledger=1, code=1, flags=VOID),
        ]
        st = _diff_batch(dev, orc, events, 7 * 10**12)
        assert st == ["created", "created",
                      "pending_transfer_already_posted"]
        _assert_state_parity(dev, orc)  # host fallback is fine here

    def test_partial_post_amount_in_batch(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=8000, debit_account_id=1, credit_account_id=2,
                     amount=100, ledger=1, code=1, flags=PEND),
            Transfer(id=8001, pending_id=8000, amount=40,
                     ledger=1, code=1, flags=POST),
        ]
        st = _diff_batch(dev, orc, events, 8 * 10**12)
        assert st == ["created", "created"]
        assert dev.led.fallbacks == 0
        _assert_state_parity(dev, orc)
        a1 = dev.lookup_accounts([1])[0]
        assert a1.debits_posted == 40 and a1.debits_pending == 0

    def test_ud_and_ledger_inheritance_from_inbatch_pend(self):
        dev, orc = _mk_pair()
        events = [
            Transfer(id=9000, debit_account_id=1, credit_account_id=2,
                     amount=10, user_data_128=7, user_data_64=8,
                     user_data_32=9, ledger=1, code=3, flags=PEND),
            Transfer(id=9001, pending_id=9000, amount=U128MAX,
                     ledger=0, code=0, flags=POST),
        ]
        st = _diff_batch(dev, orc, events, 9 * 10**12)
        assert st == ["created", "created"]
        assert dev.led.fallbacks == 0
        t = dev.state.transfers[9001]
        to = orc.state.transfers[9001]
        assert (t.user_data_128, t.user_data_64, t.user_data_32,
                t.ledger, t.code) == (7, 8, 9, 1, 3)
        assert t == to
        _assert_state_parity(dev, orc)

    def test_limits_with_inbatch_releases(self):
        limit = int(AccountFlags.debits_must_not_exceed_credits)
        accounts = [Account(id=1, ledger=1, code=1, flags=limit),
                    Account(id=2, ledger=1, code=1)]
        dev, orc = _mk_pair(accounts=accounts)
        # Fund the limited account, then alternate pend/void so the
        # limit headroom depends on in-batch releases.
        seed = [Transfer(id=100, debit_account_id=2, credit_account_id=1,
                         amount=100, ledger=1, code=1)]
        _diff_batch(dev, orc, seed, 10**12)
        events = []
        nid = 10_000
        for k in range(12):
            events.append(Transfer(
                id=nid, debit_account_id=1, credit_account_id=2,
                amount=60, ledger=1, code=1, flags=PEND))
            events.append(Transfer(
                id=nid + 1, pending_id=nid, amount=0,
                ledger=1, code=1, flags=VOID))
            nid += 2
        _diff_batch(dev, orc, events, 2 * 10**12)
        assert dev.led.fallbacks == 0, \
            "limit cascade with in-batch releases must stay on device"
        _assert_state_parity(dev, orc)


class TestInWindowPending:
    def test_window_pend_then_post_batches(self):
        """The config4 shape: one prepare creates pendings, the next
        posts/voids them — windowed in ONE dispatch."""
        from tigerbeetle_tpu.ops.batch import transfers_to_arrays

        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        seq = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 65)]
        for eng in (led, seq):
            eng.create_accounts(accts, 120)
        rng = np.random.default_rng(11)
        n = 256
        nid = 10**6
        batches, tss = [], []
        ts = 10**12
        for b in range(4):
            evs = []
            if b % 2 == 0:
                base = nid
                for i in range(n):
                    dr = int(rng.integers(1, 65))
                    evs.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=dr % 64 + 1,
                        amount=int(rng.integers(1, 100)), ledger=1,
                        code=1, flags=PEND,
                        timeout=int(rng.integers(0, 30))))
                    nid += 1
            else:
                for i in range(n):
                    even = i % 2 == 0
                    evs.append(Transfer(
                        id=nid, pending_id=base + i,
                        amount=U128MAX if even else 0,
                        ledger=1, code=1, flags=POST if even else VOID))
                    nid += 1
            ts += n + 10
            batches.append([transfers_to_arrays(evs), evs])
            tss.append(ts)

        outs = led.create_transfers_window(
            [b[0] for b in batches], tss)
        assert led.window_fallbacks == 0, \
            "pend->post window must resolve on device"
        assert led.fallbacks == 0
        # Sequential truth: same batches one dispatch at a time.
        for (ev_arrays, evs), ts_b in zip(batches, tss):
            seq.create_transfers(evs, ts_b)
        for (st, ts_out), (_, evs), ts_b in zip(outs, batches, tss):
            pass
        host_w = led.to_host()
        host_s = seq.to_host()
        assert host_w.accounts == host_s.accounts
        assert host_w.transfers == host_s.transfers
        assert host_w.pending_status == host_s.pending_status
        assert host_w.expiry == host_s.expiry
        assert host_w.pulse_next_timestamp == host_s.pulse_next_timestamp

    def test_window_mixed_with_failures(self):
        from tigerbeetle_tpu.ops.batch import transfers_to_arrays

        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        seq = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 33)]
        for eng in (led, seq):
            eng.create_accounts(accts, 120)
        rng = np.random.default_rng(13)
        nid = 5 * 10**6
        ts = 10**12
        batches, tss, raw = [], [], []
        pend_pool = []
        for b in range(5):
            evs = []
            fresh = []
            for i in range(64):
                roll = rng.random()
                if roll < 0.4:
                    dr = int(rng.integers(1, 40))  # some not_found
                    evs.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=dr % 32 + 1,
                        amount=int(rng.integers(1, 50)), ledger=1,
                        code=1, flags=PEND,
                        timeout=int(rng.integers(0, 5))))
                    fresh.append(nid)
                elif roll < 0.8 and pend_pool:
                    target = pend_pool.pop(0)
                    even = i % 2 == 0
                    evs.append(Transfer(
                        id=nid, pending_id=target,
                        amount=U128MAX if even else 0,
                        ledger=1, code=1, flags=POST if even else VOID))
                else:
                    evs.append(Transfer(
                        id=nid, debit_account_id=int(rng.integers(1, 33)),
                        credit_account_id=int(rng.integers(1, 33)) % 32 + 1,
                        amount=int(rng.integers(0, 50)), ledger=1, code=1))
                nid += 1
            pend_pool.extend(fresh)
            ts += 80
            batches.append(transfers_to_arrays(evs))
            raw.append(evs)
            tss.append(ts)
        outs = led.create_transfers_window(batches, tss)
        for evs, ts_b in zip(raw, tss):
            seq.create_transfers(evs, ts_b)
        host_w = led.to_host()
        host_s = seq.to_host()
        assert host_w.accounts == host_s.accounts
        assert host_w.transfers == host_s.transfers
        assert host_w.pending_status == host_s.pending_status
        assert set(host_w.orphaned) == set(host_s.orphaned)
        assert host_w.pulse_next_timestamp == host_s.pulse_next_timestamp


class TestFuzzInBatchPending:
    @pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
    def test_fuzz_mixed_pend_post_batches(self, seed):
        dev, orc = _mk_pair(a_cap=1 << 10, t_cap=1 << 14)
        rng = np.random.default_rng(seed)
        nid = 10**6
        known_ids = []
        ts = 10**12
        for b in range(4):
            events = []
            batch_ids = []
            for i in range(128):
                roll = rng.random()
                if roll < 0.35:
                    dr = int(rng.integers(1, 110))  # some invalid
                    cr = int(rng.integers(1, 110))
                    if dr == cr:
                        cr = dr % 100 + 1
                    events.append(Transfer(
                        id=nid, debit_account_id=dr, credit_account_id=cr,
                        amount=int(rng.integers(0, 1000)), ledger=1,
                        code=1, flags=PEND,
                        timeout=int(rng.integers(0, 10))))
                elif roll < 0.7 and (batch_ids or known_ids):
                    pool = batch_ids if ((rng.random() < 0.6 and batch_ids)
                                         or not known_ids) else known_ids
                    target = pool[int(rng.integers(0, len(pool)))]
                    even = rng.random() < 0.5
                    events.append(Transfer(
                        id=nid, pending_id=target,
                        amount=(U128MAX if even
                                else int(rng.integers(0, 500))),
                        ledger=1, code=1,
                        flags=POST if even else VOID))
                else:
                    dr = int(rng.integers(1, 101))
                    events.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=dr % 100 + 1,
                        amount=int(rng.integers(0, 1000)),
                        ledger=1, code=1,
                        flags=LINKED if rng.random() < 0.1 else 0))
                batch_ids.append(nid)
                nid += 1
            ts += 200
            _diff_batch(dev, orc, events, ts)
            known_ids.extend(batch_ids)
            if len(known_ids) > 400:
                del known_ids[:200]
        _assert_state_parity(dev, orc)
