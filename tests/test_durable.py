"""DurableState: incremental LSM checkpoints under the replica.

reference analogs: checkpoint/resume via copy-on-write grid + superblock
flip (docs/internals/data_file.md:63-94), storage determinism
(storage_checker.zig:55 byte-identical checkpoints)."""

import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.cluster import Cluster, NetworkOptions
from tigerbeetle_tpu.types import (
    Account,
    Operation,
    Transfer,
    TransferFlags,
)
from tigerbeetle_tpu.vsr import snapshot as snapshot_codec
from tigerbeetle_tpu.vsr.durable import DurableState
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

MS = 1_000_000


def _rich_state():
    """State covering every persisted container: two-phase, expiry,
    orphaned ids, account events."""
    sm = StateMachine(engine="oracle")
    ts = 1000
    sm.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)], timestamp=ts)
    ts += 100
    sm.create_transfers(
        [Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=50,
                  ledger=1, code=1),
         Transfer(id=11, debit_account_id=1, credit_account_id=2, amount=5,
                  ledger=1, code=1, flags=int(TransferFlags.pending),
                  timeout=3600),
         Transfer(id=12, debit_account_id=2, credit_account_id=3, amount=7,
                  ledger=1, code=1, flags=int(TransferFlags.pending))],
        timestamp=ts)
    ts += 100
    sm.create_transfers(
        [Transfer(id=13, debit_account_id=0, credit_account_id=2, amount=1,
                  ledger=1, code=1),  # fails (non-transient)
         Transfer(id=14, pending_id=12, ledger=1, code=1,
                  flags=int(TransferFlags.post_pending_transfer)),
         Transfer(id=15, debit_account_id=1, credit_account_id=9, amount=1,
                  ledger=1, code=1)],  # transient: orphaned id
        timestamp=ts)
    return sm


class TestDurableRoundtrip:
    def test_checkpoint_open_roundtrip(self):
        sm = _rich_state()
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        root = durable.checkpoint(sm.state)
        assert len(root) <= TEST_LAYOUT.snapshot_size_max

        durable2 = DurableState(storage)
        restored = durable2.open(root)
        assert (snapshot_codec.encode(restored)
                == snapshot_codec.encode(sm.state))
        assert restored.orphaned == {15}
        assert not restored.accounts.dirty and not restored.transfers.dirty

    def test_incremental_flush_only_writes_dirty(self):
        sm = _rich_state()
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        durable.checkpoint(sm.state)
        # After a checkpoint nothing is dirty: a second flush writes nothing.
        trees = durable.forest.trees
        before = {name: len(t.memtable) for name, t in trees.items()}
        durable.flush(sm.state)
        after = {name: len(t.memtable) for name, t in trees.items()}
        assert before == after == {name: 0 for name in trees}
        # One more transfer dirties exactly the touched objects.
        sm.create_transfers(
            [Transfer(id=20, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1)], timestamp=10_000)
        durable.flush(sm.state)
        assert len(trees["transfers"].memtable) == 1
        assert len(trees["accounts"].memtable) == 2
        assert len(trees["events"].memtable) == 1

    def test_failed_linked_chain_rollback_flush(self):
        """A rolled-back linked chain leaves dirty keys whose objects were
        removed again — flush must skip them, not crash, and must not write
        tombstones for objects that were never persisted."""
        sm = _rich_state()
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        durable.checkpoint(sm.state)
        results = sm.create_transfers(
            [Transfer(id=30, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1,
                      flags=int(TransferFlags.linked | TransferFlags.pending),
                      timeout=60),
             Transfer(id=31, debit_account_id=1, credit_account_id=99,
                      amount=1, ledger=1, code=1)],
            timestamp=50_000)
        assert results[0].status.name == "linked_event_failed"
        root = durable.checkpoint(sm.state)
        restored = DurableState(storage).open(root)
        assert 30 not in restored.transfers
        assert (snapshot_codec.encode(restored)
                == snapshot_codec.encode(sm.state))
        # The rolled-back pending row never reached the trees: no tombstone.
        assert durable.forest.trees["transfers"].get(
            (30).to_bytes(16, "big")) is None

    def test_root_blob_stays_small_as_state_grows(self):
        sm = StateMachine(engine="oracle")
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        ts = 1000
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in (1, 2)], timestamp=ts)
        sizes = []
        for round_i in range(8):
            ts += 200
            sm.create_transfers(
                [Transfer(id=100 + round_i * 64 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1, code=1)
                 for k in range(64)], timestamp=ts)
            sizes.append(len(durable.checkpoint(sm.state)))
        # Incremental: the root references manifests, not data; growth is
        # table-count bound, far below the object count.
        assert sizes[-1] < 8192
        restored = DurableState(storage).open(durable.checkpoint(sm.state))
        assert len(restored.transfers) == 8 * 64


class TestClusterDurability:
    def test_many_checkpoints_and_restart_replay_determinism(self):
        """Run past several checkpoint/bar boundaries, crash + restart a
        replica mid-interval, and require byte-identical grids (settle()
        runs the storage checker)."""
        cluster = Cluster(seed=42, replica_count=3)
        client = cluster.client(1)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        tid = 100
        for batch in range(20):
            body = multi_batch.encode(
                [b"".join(Transfer(id=tid + k, debit_account_id=1,
                                   credit_account_id=2, amount=1,
                                   ledger=1, code=1).pack()
                          for k in range(3))], 128)
            tid += 3
            drive(Operation.create_transfers, body)
            if batch == 10:
                victim = (cluster.replicas[0].primary_index() + 1) % 3
                cluster.crash(victim)
            if batch == 14:
                cluster.restart(victim)
        cluster.settle()
        assert all(r.superblock.op_checkpoint > 0 for r in cluster.replicas)
        a1 = cluster.replicas[0].state_machine.state.accounts[1]
        assert a1.debits_posted == 60

    @pytest.mark.parametrize("seed", [21, 22])
    def test_chaos_with_checkpoints(self, seed):
        cluster = Cluster(
            seed=seed, replica_count=3,
            network=NetworkOptions(loss_probability=0.05,
                                   duplicate_probability=0.05,
                                   delay_min_ns=1 * MS,
                                   delay_max_ns=30 * MS))
        client = cluster.client(7)
        body_accounts = multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128)
        client.request(Operation.create_accounts, body_accounts)
        ok = cluster.run(4000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        for k in range(25):
            body = multi_batch.encode(
                [Transfer(id=1000 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128)
            client.request(Operation.create_transfers, body)
            ok = cluster.run(6000, until=lambda: client.idle)
            assert ok, cluster.debug_status()
        cluster.settle()
        assert all(r.superblock.op_checkpoint > 0 for r in cluster.replicas)


def _policy_flush(sm, durable):
    """The replica's flush policy (vsr/replica.py): columns against a
    quiescent mirror, else drain + object path."""
    led = sm.led
    cols = led.take_flush_columns() if led is not None else None
    raw = sm.raw_state
    if cols and (raw.accounts.dirty or raw.transfers.dirty
                 or raw.pending_status.dirty or raw.expiry.dirty
                 or raw.orphaned.dirty
                 or durable.events_persisted < (
                     raw.events_base + len(raw.account_events))):
        sm.state  # drain
        cols = None
    flushed = durable.flush(raw, flush_columns=cols)
    sm.cache_upsert(*flushed)
    return flushed


def test_vectorized_column_flush_matches_object_flush():
    """durable.flush's vectorized transfer path (device-engine columns)
    must produce byte-identical trees to the object path (oracle engine)
    over the same commits."""
    import numpy as np

    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.state_machine import StateMachine
    from tigerbeetle_tpu.types import Operation, TransferFlags
    from tigerbeetle_tpu.vsr.durable import DurableState
    from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

    def build(engine):
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        sm = StateMachine(engine=engine, a_cap=1 << 12, t_cap=1 << 14)
        sm.attach_durable(durable)
        ts = 1000
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 60)]
        ts += len(accts) + 10
        sm.create_accounts(accts, ts)
        _policy_flush(sm, durable)
        rng = np.random.default_rng(5)
        nb = 300
        next_id = 10**7
        pend = int(TransferFlags.pending)
        post = int(TransferFlags.post_pending_transfer)
        for b in range(3):
            evs = []
            for i in range(nb):
                tid = next_id
                next_id += 1
                if b == 2 and i % 5 == 0:
                    evs.append(Transfer(
                        id=tid, pending_id=10**7 + nb + i,
                        amount=(1 << 128) - 1, flags=post))
                else:
                    dr = int(rng.integers(1, 60))
                    cr = dr % 59 + 1
                    evs.append(Transfer(
                        id=tid, debit_account_id=dr, credit_account_id=cr,
                        amount=int(rng.integers(1, 1000)), ledger=1, code=1,
                        user_data_128=(1 << 100) + i, user_data_64=i % 7,
                        user_data_32=i % 5,
                        flags=pend if i % 4 == 0 else 0,
                        timeout=60 if i % 4 == 0 else 0))
            payload = b"".join(e.pack() for e in evs)
            body = multi_batch.encode([payload], 128)
            ts += nb + 10
            sm.commit(Operation.create_transfers, body, ts)
            _policy_flush(sm, durable)
        return durable

    dev = build("device")
    ora = build("oracle")
    for name in dev.forest.trees:
        t_dev = dev.forest.trees[name]
        t_ora = ora.forest.trees[name]
        assert t_dev.memtable == t_ora.memtable, f"tree {name} diverged"


def test_column_flush_hard_batch_interleave_matches_oracle():
    """The hard-regime handoff (review scenario): a closing transfer runs
    on the mirror between fast-path chunks; the policy flush must drain
    and serialize through ONE authority — trees must match the oracle
    twin exactly across the handoff."""
    import numpy as np

    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.state_machine import StateMachine
    from tigerbeetle_tpu.types import Operation, TransferFlags
    from tigerbeetle_tpu.vsr.durable import DurableState
    from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

    def build(engine):
        storage = MemoryStorage(TEST_LAYOUT)
        durable = DurableState(storage)
        sm = StateMachine(engine=engine, a_cap=1 << 12, t_cap=1 << 14)
        sm.attach_durable(durable)
        ts = 1000
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 40)]
        ts += len(accts) + 10
        sm.create_accounts(accts, ts)
        _policy_flush(sm, durable)
        rng = np.random.default_rng(9)
        next_id = 10**7

        def commit(evs):
            nonlocal ts
            payload = b"".join(e.pack() for e in evs)
            ts += len(evs) + 10
            sm.commit(Operation.create_transfers,
                      multi_batch.encode([payload], 128), ts)
            _policy_flush(sm, durable)

        def fast_batch(n):
            nonlocal next_id
            evs = []
            for i in range(n):
                dr = int(rng.integers(1, 40))
                evs.append(Transfer(
                    id=next_id, debit_account_id=dr,
                    credit_account_id=dr % 39 + 1,
                    amount=int(rng.integers(1, 100)), ledger=1, code=1))
                next_id += 1
            commit(evs)

        fast_batch(50)
        # HARD batch: closing flags route to the mirror (hard regime).
        commit([Transfer(id=next_id, debit_account_id=5,
                         credit_account_id=6, amount=1, ledger=1, code=1,
                         flags=int(TransferFlags.closing_debit
                                   | TransferFlags.pending))])
        next_id += 1
        # Fast batches again (regime probe -> fast path resumes).
        for _ in range(10):
            fast_batch(20)
        return durable

    dev = build("device")
    ora = build("oracle")
    for name in dev.forest.trees:
        assert dev.forest.trees[name].memtable == \
            ora.forest.trees[name].memtable, f"tree {name} diverged"


def test_cache_invalidated_after_column_flush():
    """Review scenario: a cached account must never serve its pre-chunk
    balance after a column-path flush (cache invalidation contract)."""
    import numpy as np

    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.state_machine import StateMachine
    from tigerbeetle_tpu.types import Operation
    from tigerbeetle_tpu.vsr.durable import DurableState
    from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

    storage = MemoryStorage(TEST_LAYOUT)
    durable = DurableState(storage)
    sm = StateMachine(engine="device", a_cap=1 << 12, t_cap=1 << 14)
    sm.attach_durable(durable)
    ts = 1000
    sm.create_accounts([Account(id=1, ledger=1, code=1),
                        Account(id=2, ledger=1, code=1)], ts)
    _policy_flush(sm, durable)
    got = sm.lookup_accounts([1])  # caches account 1 (balance 0)
    assert got and got[0].debits_posted == 0
    payload = Transfer(id=10, debit_account_id=1, credit_account_id=2,
                       amount=77, ledger=1, code=1).pack()
    ts += 20
    sm.commit(Operation.create_transfers,
              multi_batch.encode([payload], 128), ts)
    _policy_flush(sm, durable)
    got = sm.lookup_accounts([1])
    assert got and got[0].debits_posted == 77, \
        "stale cached balance after column flush"
