"""Client binding generator: drift, layout parity, enum completeness.

reference: the per-language binding codegen under src/clients/ — the
reference CI regenerates bindings and fails on drift; the layout-parity
test here is the analog of its comptime size/offset asserts.
"""

import os

from tigerbeetle_tpu import types as T
from tigerbeetle_tpu.clients import codegen

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLayouts:
    def test_struct_sizes(self):
        assert codegen.struct_size("Account") == 128
        assert codegen.struct_size("Transfer") == 128
        assert codegen.struct_size("AccountBalance") == 128
        assert codegen.struct_size("AccountFilter") == 128
        assert codegen.struct_size("QueryFilter") == 64
        assert codegen.struct_size("CreateAccountResult") == 16
        assert codegen.struct_size("CreateTransferResult") == 16

    def test_layout_matches_types_pack(self):
        """Byte-for-byte: each field's slice at the generator's offset must
        equal the field's own encoding in types.py's pack() output — the
        property the generated Go/Node marshallers are built on."""
        for name, fields in codegen.LAYOUTS.items():
            cls = codegen.PY_CLASSES[name]
            # Distinct sentinel per field (within each field's range).
            sentinels = {}
            for i, (field, kind) in enumerate(fields):
                if kind.startswith("pad"):
                    continue
                bits = 128 if kind == "u128" else int(kind[1:])
                sentinels[field] = (0x0101010101010101 * (i + 1)) % (1 << bits)
            kwargs = dict(sentinels)
            if name.endswith("Result"):
                # status is an enum field on the Python class.
                enum_cls = (T.CreateAccountStatus if "Account" in name
                            else T.CreateTransferStatus)
                kwargs["status"] = enum_cls.linked_event_failed
                sentinels["status"] = int(enum_cls.linked_event_failed)
                kwargs.pop("reserved", None)
                sentinels["reserved"] = 0
            if name == "Account":
                pass  # reserved is a real (zero-required) wire field
            packed = cls(**{k: v for k, v in kwargs.items()
                            if k in cls.__dataclass_fields__}).pack()
            assert len(packed) == codegen.struct_size(name), name
            for field, kind, off in codegen.offsets(name):
                size = codegen.field_size(kind)
                got = packed[off:off + size]
                if kind.startswith("pad"):
                    assert got == b"\x00" * size, (name, field)
                    continue
                want_val = sentinels.get(field, 0)
                if field in cls.__dataclass_fields__:
                    want = want_val.to_bytes(size, "little")
                else:
                    want = (0).to_bytes(size, "little")
                assert got == want, (name, field, got.hex(), want.hex())

    def test_unpack_round_trip_at_offsets(self):
        """The generated unpackers read the same offsets pack writes."""
        t = T.Transfer(id=(1 << 127) | 5, debit_account_id=2,
                       credit_account_id=3, amount=(1 << 64) + 7,
                       pending_id=9, user_data_128=11, user_data_64=13,
                       user_data_32=17, timeout=19, ledger=23, code=29,
                       flags=int(T.TransferFlags.pending), timestamp=31)
        raw = t.pack()
        off = dict((f, o) for f, _, o in codegen.offsets("Transfer"))
        assert int.from_bytes(raw[off["id"]:off["id"] + 16],
                              "little") == t.id
        assert int.from_bytes(raw[off["amount"]:off["amount"] + 16],
                              "little") == t.amount
        assert int.from_bytes(raw[off["flags"]:off["flags"] + 2],
                              "little") == t.flags


class TestGeneratedSources:
    def test_committed_sources_match_generator(self):
        """Drift check: clients/go + clients/node must be exactly what the
        generator emits (regenerate with `python -m tigerbeetle_tpu
        clients`)."""
        for rel, want in codegen.generate_all().items():
            path = os.path.join(REPO, "clients", rel)
            assert os.path.exists(path), f"missing generated file: {rel}"
            with open(path) as f:
                assert f.read() == want, f"stale generated file: {rel}"

    def test_status_enums_complete(self):
        go_types = codegen.generate_go()["go/tigerbeetle/types.go"]
        node_types = codegen.generate_node()["node/lib/types.js"]
        for status in T.CreateTransferStatus:
            go_name = "CreateTransferStatus" + "".join(
                p.capitalize() for p in status.name.split("_"))
            assert f"{go_name} CreateTransferStatus = {int(status)}" \
                in go_types, status.name
            assert f"{status.name}: {int(status)}," in node_types, status.name
        for op in T.Operation:
            assert f"{op.name}: {int(op)}," in node_types, op.name

    def test_generated_c_abi_matches_native(self):
        """The addon/cgo extern declarations must cover exactly the tbp_*
        functions native/tb_client.cpp exports."""
        with open(os.path.join(REPO, "native", "tb_client.cpp")) as f:
            native = f.read()
        exported = set(codegen.C_ABI_FUNCTIONS)
        for fn in exported:
            assert fn in native, fn
        go_client = codegen.generate_go()["go/tigerbeetle/client.go"]
        addon = codegen.generate_node()["node/addon/addon.c"]
        for fn in exported - {"tbp_client_packet_free"}:
            assert fn in go_client, fn
            assert fn in addon, fn


class TestConformance:
    """The offline conformance contract (clients/conformance.json) must
    stay regenerable, self-consistent, and byte-true to types.py."""

    def test_committed_conformance_matches_generator(self):
        with open(os.path.join(REPO, "clients", "conformance.json")) as f:
            committed = f.read()
        assert committed == codegen.generate_conformance()

    def test_struct_vectors_decode_with_types(self):
        import json

        doc = json.loads(codegen.generate_conformance())
        for vec in doc["struct_vectors"]:
            cls = codegen.PY_CLASSES[vec["struct"]]
            obj = cls.unpack(bytes.fromhex(vec["encoded_hex"]))
            for field, want in vec["fields"].items():
                got = getattr(obj, field)
                assert int(got) == int(want), (vec["struct"], field)

    def test_vector_offsets_agree_with_layout(self):
        import json

        doc = json.loads(codegen.generate_conformance())
        layouts = doc["structs"]
        for vec in doc["struct_vectors"]:
            raw = bytes.fromhex(vec["encoded_hex"])
            spec = layouts[vec["struct"]]
            assert len(raw) == spec["size"]
            for f in spec["fields"]:
                if f["kind"].startswith("pad"):
                    continue
                want = int(vec["fields"].get(f["name"], 0))
                got = int.from_bytes(
                    raw[f["offset"]:f["offset"] + f["size"]], "little")
                assert got == want, (vec["struct"], f["name"])

    def test_multi_batch_vectors_decode(self):
        import json

        from tigerbeetle_tpu import multi_batch

        doc = json.loads(codegen.generate_conformance())
        for vec in doc["multi_batch_vectors"]:
            payloads = [bytes.fromhex(p) for p in vec["payloads_hex"]]
            body = bytes.fromhex(vec["encoded_hex"])
            assert multi_batch.decode(body, vec["element_size"]) == payloads


class TestGeneratedSyntax:
    """Offline structural gate for all six generated languages (this
    image has none of their toolchains; reference compiles per-language
    in CI, src/scripts/ci.zig:56): comment/string-aware delimiter
    balance + required symbols — the generator's characteristic
    failure class is an unbalanced emission from template escaping."""

    def test_all_generated_sources_structurally_valid(self):
        from tigerbeetle_tpu.clients.syntax_check import check_generated

        files = codegen.generate_all()
        checked = check_generated(files)
        # Every language's main sources were actually covered.
        assert any(p.endswith(".go") for p in checked)
        assert any(p.endswith(".js") for p in checked)
        assert any(p.endswith(".java") for p in checked)
        assert any(p.endswith(".cs") for p in checked)
        assert any(p.endswith(".rb") for p in checked)
        assert any(p.endswith(".rs") for p in checked)
        assert len(checked) >= 20

    def test_required_abi_symbols_present(self):
        from tigerbeetle_tpu.clients.syntax_check import check_source

        files = codegen.generate_all()
        for rel, symbols in (
                ("go/tigerbeetle/client.go", codegen.C_ABI_FUNCTIONS),
                ("rust/src/client.rs", codegen.C_ABI_FUNCTIONS),
                ("ruby/lib/tigerbeetle_tpu/client.rb",
                 codegen.C_ABI_FUNCTIONS)):
            lang = {"go": "go", "rs": "rust", "rb": "ruby"}[
                rel.rsplit(".", 1)[1]]
            check_source(files[rel], lang, required_symbols=symbols)

    def test_checker_rejects_broken_emission(self):
        import pytest

        from tigerbeetle_tpu.clients.syntax_check import (
            SyntaxIssue,
            check_source,
        )

        with pytest.raises(SyntaxIssue, match="unclosed"):
            check_source("fn main() { let x = (1;", "rust")
        with pytest.raises(SyntaxIssue, match="unterminated string"):
            check_source('let s = "oops;', "node")
        with pytest.raises(SyntaxIssue, match="missing"):
            check_source("package x", "go",
                         required_symbols=("tbp_client_init",))
        # Balanced code with braces inside strings/comments is clean.
        check_source('// {{{ \nlet s = "}}}"; fn f() {}', "rust")
        check_source("s = '{{{' # }}}\n", "ruby")
