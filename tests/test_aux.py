"""Aux subsystem tests: EWAH, Marzullo clock, tracer/statsd, AOF, CDC,
multiversion, clock sampling in the cluster."""

import json
import random

import pytest

from tigerbeetle_tpu import ewah
from tigerbeetle_tpu.aof import AOF, recover as aof_recover
from tigerbeetle_tpu.cdc import CDCRunner, CallbackSink, JsonlSink
from tigerbeetle_tpu.multiversion import RELEASE, ReleaseTracker
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.trace import NullTracer, StatsD, Tracer
from tigerbeetle_tpu.types import Account, ChangeEventsFilter, Operation, Transfer
from tigerbeetle_tpu.vsr.clock import Clock, Interval, marzullo
from tigerbeetle_tpu.vsr.header import Command, Header, Message


class TestEwah:
    def test_roundtrip_random(self):
        rng = random.Random(7)
        for _ in range(20):
            words = []
            for _ in range(rng.randrange(0, 50)):
                roll = rng.random()
                if roll < 0.4:
                    words.extend([0] * rng.randrange(1, 20))
                elif roll < 0.6:
                    words.extend([(1 << 64) - 1] * rng.randrange(1, 20))
                else:
                    words.append(rng.getrandbits(64) | 1)
            assert ewah.decode(ewah.encode(words)) == words

    def test_compression_and_bitset(self):
        words = [0] * 1000 + [0xDEADBEEF] + [(1 << 64) - 1] * 1000
        blob = ewah.encode(words)
        assert len(blob) < len(words) * 8 // 100  # >100x on runs
        bits = [i % 7 == 0 for i in range(1000)]
        assert ewah.decode_bitset(ewah.encode_bitset(bits)) == bits


class TestMarzullo:
    def test_overlap(self):
        best = marzullo([Interval(0, 10), Interval(5, 15), Interval(8, 12),
                         Interval(100, 110)])
        assert best.lo == 8 and best.hi == 10

    def test_disjoint_majority(self):
        best = marzullo([Interval(0, 1), Interval(0, 2), Interval(10, 11)])
        assert best.lo == 0 and best.hi == 1

    def test_clock_learn(self):
        class T:
            def realtime(self):
                return 1000

            def monotonic(self):
                return 1000

        clock = Clock(0, 3, T())
        assert clock.offset() is None  # no quorum yet
        # rtt 100 -> offset 40 +- 50: interval [-10, 90] OVERLAPS our own
        # zero-offset interval, so 2 of 3 sources agree = quorum.
        clock.learn(1, 900, 990, 1000)
        iv = clock.offset()
        assert iv is not None
        # Own [0,0] against peer [-10,90]: the overlap is exactly [0,0].
        assert iv.lo <= 0 <= iv.hi
        assert clock.realtime_synchronized() is not None
        # A peer sample DISJOINT from every other source is not
        # agreement, even though two sources were sampled (reference
        # clock.zig: the smallest interval must be consistent with a
        # replica quorum).
        lonely = Clock(0, 3, T())
        lonely.learn(1, 900, 1040, 1000)  # offset 90 +- 50: [40, 140]
        assert lonely.offset() is None


class TestTracer:
    def test_spans_and_chrome_dump(self, tmp_path):
        from tigerbeetle_tpu.trace import Event

        tracer = Tracer()
        with tracer.span(Event.commit_execute, op=1, operation=2,
                         window=1):
            pass
        tracer.count(Event.commits)
        tracer.count(Event.commits, 2)
        tracer.gauge(Event.bus_pool_used, 3)
        assert tracer.counters["commits"] == 3
        assert tracer.gauges["bus_pool_used"] == 3
        path = tmp_path / "trace.json"
        tracer.dump_chrome_trace(str(path))
        doc = json.loads(path.read_text())
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert spans[0]["name"] == "commit_execute"

    def test_statsd_datagram_format(self):
        captured = []

        class FakeSock:
            def sendto(self, data, addr):
                captured.append(data.decode())

            def setblocking(self, flag):
                pass

            def close(self):
                pass

        statsd = StatsD()
        statsd.sock = FakeSock()
        statsd.count("commits", 2, replica=1)
        statsd.timing("commit", 1.5)
        assert captured[0] == "tb_tpu.commits:2|c|#replica:1"
        assert captured[1] == "tb_tpu.commit:1.5|ms"

    def test_null_tracer_is_silent(self):
        tracer = NullTracer()
        with tracer.span("anything"):
            pass
        tracer.count("x")


def _prepare(op, operation, body, ts):
    header = Header(command=Command.prepare, cluster=1, op=op,
                    operation=int(operation), timestamp=ts)
    return Message(header.finalize(body), body=body)


class TestAOF:
    def test_append_iterate_recover(self, tmp_path):
        from tigerbeetle_tpu import multi_batch

        path = str(tmp_path / "a.aof")
        aof = AOF(path)
        sm = StateMachine()
        ts = 10**13
        body1 = multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack() for i in (1, 2))],
            128)
        sm.commit(Operation.create_accounts, body1, ts)
        aof.append(_prepare(1, Operation.create_accounts, body1, ts))
        body2 = multi_batch.encode(
            [Transfer(id=9, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1).pack()], 128)
        sm.commit(Operation.create_transfers, body2, ts + 100)
        aof.append(_prepare(2, Operation.create_transfers, body2, ts + 100))
        aof.close()

        msgs = list(AOF.iterate(path))
        assert [m.header.op for m in msgs] == [1, 2]

        recovered = StateMachine()
        applied = aof_recover(path, recovered)
        assert applied == 2
        assert recovered.state.accounts == sm.state.accounts
        assert recovered.state.transfers == sm.state.transfers

    def test_torn_tail_stops_iteration(self, tmp_path):
        path = str(tmp_path / "torn.aof")
        aof = AOF(path)
        body = b""
        aof.append(_prepare(1, Operation.pulse, b"", 10**13))
        aof.close()
        with open(path, "ab") as f:
            f.write(b"TBTPUAOF\xff\xff")  # torn frame
        assert len(list(AOF.iterate(path))) == 1


class TestCDC:
    def test_runner_watermark(self, tmp_path):
        from tigerbeetle_tpu import multi_batch

        sm = StateMachine()
        ts = 10**13
        sm.create_accounts([Account(id=1, ledger=1, code=1),
                            Account(id=2, ledger=1, code=1)], ts)
        sm.create_transfers(
            [Transfer(id=i, debit_account_id=1, credit_account_id=2,
                      amount=i, ledger=1, code=1) for i in (1, 2, 3)],
            ts + 100)
        seen = []
        runner = CDCRunner(sm, CallbackSink(seen.append), batch_limit=2)
        assert runner.run_until_idle() == 3
        assert [e.transfer_id for e in seen] == [1, 2, 3]
        # New events after the watermark only.
        sm.create_transfers(
            [Transfer(id=4, debit_account_id=2, credit_account_id=1,
                      amount=9, ledger=1, code=1)], ts + 200)
        assert runner.poll() == 1
        assert seen[-1].transfer_id == 4

        jsonl = tmp_path / "events.jsonl"
        sink = JsonlSink(str(jsonl))
        runner2 = CDCRunner(sm, sink)
        assert runner2.run_until_idle() == 4
        sink.close()
        lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
        assert len(lines) == 4 and lines[0]["transfer_id"] == 1
        assert lines[0]["type"] == "single_phase"


class TestAOFContiguity:
    def test_reopen_dedupes_and_blocks_gaps(self, tmp_path):
        path = str(tmp_path / "c.aof")
        aof = AOF(path)
        aof.append(_prepare(1, Operation.pulse, b"", 10**13))
        aof.append(_prepare(2, Operation.pulse, b"", 10**13 + 1))
        aof.close()
        # Reopen: last_op recovered; duplicate appends are no-ops.
        aof2 = AOF(path)
        assert aof2.last_op == 2
        aof2.append(_prepare(2, Operation.pulse, b"", 10**13 + 1))
        aof2.append(_prepare(3, Operation.pulse, b"", 10**13 + 2))
        with pytest.raises(RuntimeError):
            aof2.append(_prepare(7, Operation.pulse, b"", 10**13 + 9))
        aof2.close()
        assert [m.header.op for m in AOF.iterate(path)] == [1, 2, 3]

    def test_recover_rejects_gapped_aof(self, tmp_path):
        path = str(tmp_path / "gap.aof")
        aof = AOF(path)
        aof.append(_prepare(1, Operation.pulse, b"", 10**13))
        aof.last_op = 4  # simulate a gap on disk
        aof.append(_prepare(5, Operation.pulse, b"", 10**13 + 9))
        aof.close()
        with pytest.raises(ValueError):
            aof_recover(path, StateMachine())


class TestCDCCrashResume:
    def _sm(self, n=7):
        sm = StateMachine()
        ts = 10**13
        sm.create_accounts([Account(id=1, ledger=1, code=1),
                            Account(id=2, ledger=1, code=1)], ts)
        for i in range(1, n + 1):
            sm.create_transfers(
                [Transfer(id=i, debit_account_id=1, credit_account_id=2,
                          amount=i, ledger=1, code=1)], ts + 100 * i)
        return sm

    def test_file_progress_resumes_after_crash(self, tmp_path):
        """Kill the runner mid-stream; a fresh runner recovers the
        durable watermark and resumes without losing events (reference:
        cdc/runner.zig progress-queue recovery)."""
        from tigerbeetle_tpu.cdc import FileProgress

        sm = self._sm(7)
        progress = FileProgress(str(tmp_path / "cdc.progress"))
        seen_a = []
        runner_a = CDCRunner(sm, CallbackSink(seen_a.append),
                             batch_limit=2, progress=progress,
                             pipeline=False)
        assert runner_a.recover() == 0
        runner_a.poll()  # one batch: events 1,2 — then "crash"
        assert [e.transfer_id for e in seen_a] == [1, 2]
        del runner_a

        seen_b = []
        runner_b = CDCRunner(sm, CallbackSink(seen_b.append),
                             batch_limit=2,
                             progress=FileProgress(
                                 str(tmp_path / "cdc.progress")))
        runner_b.recover()
        assert runner_b.run_until_idle() == 5
        runner_b.close()
        assert [e.transfer_id for e in seen_b] == [3, 4, 5, 6, 7]

    def test_crash_after_flush_before_store_duplicates_not_skips(
            self, tmp_path):
        """A crash BETWEEN sink flush and watermark store must replay the
        batch (at-least-once: duplicates allowed, gaps never)."""
        from tigerbeetle_tpu.cdc import FileProgress

        sm = self._sm(4)

        class StoreCrash(FileProgress):
            def __init__(self, path):
                super().__init__(path)
                self.crash = True

            def store(self, timestamp):
                if self.crash:
                    raise RuntimeError("crashed before progress store")
                super().store(timestamp)

        progress = StoreCrash(str(tmp_path / "cdc.progress"))
        seen = []
        runner = CDCRunner(sm, CallbackSink(seen.append), batch_limit=2,
                           progress=progress, pipeline=False)
        with pytest.raises(RuntimeError):
            runner.poll()
        assert [e.transfer_id for e in seen] == [1, 2]  # published...
        # ...but the durable watermark never moved:
        runner2 = CDCRunner(sm, CallbackSink(seen.append), batch_limit=2,
                            progress=FileProgress(
                                str(tmp_path / "cdc.progress")))
        runner2.recover()
        assert runner2.run_until_idle() == 4
        runner2.close()
        # 1,2 delivered twice (at-least-once), 3,4 once; no gaps.
        assert [e.transfer_id for e in seen] == [1, 2, 1, 2, 3, 4]

    def test_pipelined_matches_serial(self, tmp_path):
        """The dual-buffer overlap must deliver the identical ordered
        stream the serial pump does."""
        sm = self._sm(9)
        serial, piped = [], []
        r1 = CDCRunner(sm, CallbackSink(serial.append), batch_limit=2,
                       pipeline=False)
        assert r1.run_until_idle() == 9
        r2 = CDCRunner(sm, CallbackSink(piped.append), batch_limit=2,
                       pipeline=True)
        assert r2.run_until_idle() == 9
        r2.close()
        assert [e.transfer_id for e in piped] == \
            [e.transfer_id for e in serial]
        assert r2.timestamp_processed == r1.timestamp_processed

    def test_pipelined_flush_failure_holds_watermark(self):
        sm = self._sm(4)

        class FlakySink:
            def __init__(self):
                self.fail = True
                self.events = []

            def publish(self, event):
                self.events.append(event)

            def flush(self):
                if self.fail:
                    self.fail = False
                    raise OSError("broker down")

        sink = FlakySink()
        runner = CDCRunner(sm, sink, batch_limit=2, pipeline=True)
        with pytest.raises(OSError):
            runner.run_until_idle()
        assert runner.timestamp_processed == 0
        assert runner.run_until_idle() == 4  # full replay from watermark
        runner.close()


class TestCDCFlushFailure:
    def test_watermark_holds_until_flush_succeeds(self):
        sm = StateMachine()
        ts = 10**13
        sm.create_accounts([Account(id=1, ledger=1, code=1),
                            Account(id=2, ledger=1, code=1)], ts)
        sm.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2,
                      amount=1, ledger=1, code=1)], ts + 100)

        class FlakySink:
            def __init__(self):
                self.fail = True
                self.events = []

            def publish(self, event):
                self.events.append(event)

            def flush(self):
                if self.fail:
                    self.fail = False
                    raise OSError("disk full")

        sink = FlakySink()
        runner = CDCRunner(sm, sink)
        with pytest.raises(OSError):
            runner.poll()
        assert runner.timestamp_processed == 0  # watermark held
        assert runner.poll() == 1  # re-read and delivered
        assert runner.timestamp_processed > 0


def test_release_gating_enforced_at_open():
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.vsr.superblock import SuperBlock

    cluster = Cluster(seed=6, replica_count=1)
    cluster.run(50)
    storage = cluster.storages[0]
    sb = SuperBlock.load(storage)
    sb.release = RELEASE + 1  # written by a future release
    sb.store(storage)
    cluster.crash(0)
    with pytest.raises(RuntimeError, match="release"):
        cluster.restart(0)


def test_clock_samples_expire():
    class T:
        def __init__(self):
            self.now = 10**12

        def realtime(self):
            return self.now

        def monotonic(self):
            return self.now

    t = T()
    clock = Clock(0, 3, t)
    # offset 0 +- 50 (agrees with our own zero interval).
    clock.learn(1, t.now - 100, t.now - 50, t.now)
    assert clock.offset() is not None
    t.now += clock.window_ns + 1
    assert clock.offset() is None  # stale sample no longer counts


class TestMultiversion:
    def test_release_gating(self):
        tracker = ReleaseTracker()
        tracker.observe(1, RELEASE)
        tracker.observe(2, RELEASE + 1)
        assert tracker.cluster_min == RELEASE
        assert tracker.compatible(RELEASE)
        assert not tracker.compatible(RELEASE + 1)


def test_cluster_clock_and_release_sampling():
    """Pings flow in the simulator: clocks learn offsets, releases spread."""
    from tigerbeetle_tpu.testing.cluster import Cluster

    cluster = Cluster(seed=5, replica_count=3)
    cluster.run(200)
    for r in cluster.replicas:
        assert r.releases.peers, "release observations missing"
        assert r.clock.samples, "clock samples missing"
        assert r.clock.realtime_synchronized() is not None


class TestDevhub:
    def test_record_and_render(self, tmp_path):
        from tigerbeetle_tpu import devhub

        history = str(tmp_path / "h.jsonl")
        out = str(tmp_path / "devhub.html")
        for v in (100.0, 200.0, 150.0):
            devhub.record(history, {"value": v, "config2_10k_tps": v * 2})
        assert devhub.render(history, out) == 3
        html = open(out).read()
        assert "polyline" in html and "300" in html

    def test_torn_history_line_skipped(self, tmp_path):
        from tigerbeetle_tpu import devhub

        history = tmp_path / "h.jsonl"
        history.write_text('{"value": 1.0}\n{"val')  # torn tail
        assert devhub.load(str(history)) == [{"value": 1.0}]

    def test_regression_flagged_against_trailing_median(self, tmp_path):
        """reference: the devhub run is the nightly perf gate
        (src/scripts/devhub.zig:174-237) — a drop beyond tolerance vs
        the trailing median must surface."""
        from tigerbeetle_tpu import devhub

        entries = [{"value": 300_000 + i * 1000,
                    "serving_batch_latency": {"sustained_tps": 70_000,
                                              "p99_ms": 90.0}}
                   for i in range(8)]
        # Healthy latest: no flags.
        assert devhub.regressions(entries + [
            {"value": 301_000,
             "serving_batch_latency": {"sustained_tps": 71_000,
                                       "p99_ms": 91.0}}]) == {}
        # Throughput drop + latency spike: both flagged.
        got = devhub.regressions(entries + [
            {"value": 150_000,
             "serving_batch_latency": {"sustained_tps": 30_000,
                                       "p99_ms": 200.0}}])
        assert set(got) == {"value", "serving_sustained_tps",
                            "serving_p99_ms"}
        assert got["value"]["ratio"] < 0.9
        assert got["serving_p99_ms"]["ratio"] > 1.1

    def test_render_surfaces_cfo_failing_seeds(self, tmp_path):
        from tigerbeetle_tpu import devhub

        history = str(tmp_path / "h.jsonl")
        devhub.record(history, {"value": 1.0,
                                "config5_oracle_parity": True})
        cfo_dir = tmp_path / "cfo"
        cfo_dir.mkdir()
        (cfo_dir / "CFO_r04.json").write_text(json.dumps({
            "runs_clean": 10, "runs_failing": 1, "elapsed_s": 5.0,
            "failing": [{"kind": "vopr", "name": "vopr", "seed": 777,
                         "error": "AssertionError(...)",
                         "reproduce": "python -m tigerbeetle_tpu cfo "
                                      "--kind vopr --seed 777 "
                                      "--max-runs 1"}]}))
        out = str(tmp_path / "d.html")
        devhub.render(history, out, cfo_dir=str(cfo_dir))
        doc = open(out).read()
        assert "continuous fuzzing" in doc and "777" in doc
        assert "--kind vopr --seed 777" in doc
        assert "oracle parity: 1/1" in doc


class TestJaxhound:
    def test_report_accounts_kernel(self):
        import re

        from tigerbeetle_tpu.jaxhound import report

        lines = report("create_accounts_fast")
        header = next(line for line in lines if "HLO instructions" in line)
        count = int(re.search(r"(\d+) HLO instructions", header).group(1))
        assert count > 50  # the kernel is large; 0 means the parser broke
        assert any("stablehlo." in line for line in lines)  # histogram rows


class TestMultiversionCli:
    def test_compatible_data_file(self, tmp_path):
        from tigerbeetle_tpu.main import main

        path = str(tmp_path / "r0.tb")
        assert main(["format", "--cluster=1", "--replica=0",
                     "--replica-count=1", "--small", path]) == 0
        assert main(["multiversion", "--small", path]) == 0


class TestClusterConfigEnforcement:
    def test_mismatched_fingerprint_peer_is_dropped(self):
        """reference: ConfigCluster must match across the cluster
        (src/config.zig:153-163); pings carry a fingerprint and a
        mismatched peer's traffic is refused."""
        from tests.test_nack import _FakeTime, _CaptureBus, _mk_replica
        from tigerbeetle_tpu.vsr.header import Command, Header, Message

        r, bus, _ = _mk_replica(0, replica_count=3)
        fp = r._config_fp
        good = Header(command=Command.ping, cluster=0xABCD01, replica=1,
                      view=0, timestamp=123, context=fp)
        r.on_message(Message(good.finalize()))
        assert bus.of(Command.pong), "matching peer must get a pong"
        bus.sent.clear()
        bad = Header(command=Command.ping, cluster=0xABCD01, replica=2,
                     view=0, timestamp=124, context=fp ^ 0x1)
        r.on_message(Message(bad.finalize()))
        assert not bus.of(Command.pong), "mismatched peer must be dropped"
        # Fingerprint-less pings (legacy / handshake hello) stay accepted
        # for unflagged peers...
        legacy = Header(command=Command.ping, cluster=0xABCD01, replica=1,
                        view=0, timestamp=125)
        r.on_message(Message(legacy.finalize()))
        assert bus.of(Command.pong)
        # ...but must NOT un-gate a flagged peer (reconnect handshake
        # would otherwise reopen the gate every connection churn).
        bus.sent.clear()
        hello = Header(command=Command.ping, cluster=0xABCD01, replica=2,
                       view=0, timestamp=126)
        r.on_message(Message(hello.finalize()))
        assert not bus.of(Command.pong)
        assert 2 in r._config_mismatch

    def test_mismatched_peer_consensus_traffic_gated(self):
        """The mismatch flag gates ALL replica traffic (prepare etc.),
        not just pongs — and a matching ping clears it."""
        from tests.test_nack import _mk_replica, _prepare_msg
        from tigerbeetle_tpu.vsr.header import Command, Header, Message

        r, bus, _ = _mk_replica(1, replica_count=3)
        r.status = "normal"
        fp = r._config_fp
        bad_ping = Header(command=Command.ping, cluster=0xABCD01, replica=0,
                          view=0, timestamp=1, context=fp ^ 0x2)
        r.on_message(Message(bad_ping.finalize()))
        assert 0 in r._config_mismatch
        # A prepare from the flagged primary is dropped.
        m = _prepare_msg(1)
        r.on_message(m)
        assert r.op == 0 and r.journal.read_prepare(1) is None
        # The peer upgrades (matching ping): flag clears, traffic flows.
        good_ping = Header(command=Command.ping, cluster=0xABCD01, replica=0,
                           view=0, timestamp=2, context=fp)
        r.on_message(Message(good_ping.finalize()))
        assert 0 not in r._config_mismatch
        r.on_message(m)
        assert r.op == 1 and r.journal.read_prepare(1) is not None


class TestCommitMetrics:
    def test_per_op_timing_table(self):
        """reference: per-op timings recorded at commit
        (src/state_machine.zig:729-780, :2637-2667)."""
        from tigerbeetle_tpu import multi_batch
        from tigerbeetle_tpu.state_machine import StateMachine
        from tigerbeetle_tpu.types import Account, Operation

        sm = StateMachine(engine="oracle")
        body = multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128)
        sm.commit(Operation.create_accounts, body, 100)
        lookup = multi_batch.encode([(1).to_bytes(16, "little")], 16)
        sm.commit(Operation.lookup_accounts, lookup, 200)
        sm.commit(Operation.lookup_accounts, lookup, 300)
        m = sm.metrics
        assert m["create_accounts"]["count"] == 1
        assert m["lookup_accounts"]["count"] == 2
        assert m["lookup_accounts"]["total_ns"] >= \
            m["lookup_accounts"]["max_ns"] > 0
