"""Snapshot-testing utility (reference: stdx.Snap, src/stdx/stdx.zig:16)
and snapshot coverage of stable renderings."""

import subprocess
import sys
import textwrap

import pytest

from tigerbeetle_tpu.testing.snap import snap


class TestSnapCore:
    def test_match_passes(self):
        snap("a\nb\n", expected="""\
        a
        b
        """)

    def test_mismatch_shows_diff(self):
        with pytest.raises(AssertionError) as e:
            snap("actual\n", expected="""\
            expected
            """)
        assert "-expected" in str(e.value) and "+actual" in str(e.value)

    def test_update_rewrites_source(self, tmp_path):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        test_src = textwrap.dedent('''\
            from tigerbeetle_tpu.testing.snap import snap

            def check():
                # Two stale snaps, the first shrinking: the rewriter must
                # track line deltas so the second still lands correctly.
                snap("one\\n", expected="""\\
                stale line a
                stale line b
                stale line c
                """)
                snap("x\\ny\\nz\\n", expected="""\\
                stale
                """)
                snap("no trailing newline", expected="""\\
                stale
                """)

            check()
            print("ok")
        ''')
        path = tmp_path / "snapped.py"
        path.write_text(test_src)
        env = {"PATH": "/usr/bin:/bin", "PYTHONPATH": repo}
        # First run with SNAP_UPDATE=1 rewrites every literal in place.
        p = subprocess.run([sys.executable, str(path)],
                           env={**env, "SNAP_UPDATE": "1"},
                           capture_output=True, text=True)
        assert p.returncode == 0, p.stderr
        text = path.read_text()
        assert "one" in text
        assert "stale line" not in text and '"""\\\nstale' not in text
        # Second run (no update) passes against the rewritten literals —
        # including the no-trailing-newline value (convergence).
        p = subprocess.run([sys.executable, str(path)], env=env,
                           capture_output=True, text=True)
        assert p.returncode == 0, p.stderr


class TestSnapshots:
    """Snapshot assertions over stable user-facing renderings."""

    def test_account_repr_layout(self):
        from tigerbeetle_tpu.types import Account, AccountFlags

        a = Account(id=7, debits_posted=250, credits_posted=50,
                    ledger=700, code=10,
                    flags=int(AccountFlags.history))
        got = "\n".join(
            f"{f}={getattr(a, f)}" for f in (
                "id", "debits_pending", "debits_posted", "credits_pending",
                "credits_posted", "ledger", "code", "flags"))
        snap(got + "\n", expected="""\
        id=7
        debits_pending=0
        debits_posted=250
        credits_pending=0
        credits_posted=50
        ledger=700
        code=10
        flags=8
        """)

    def test_operation_wire_codes(self):
        from tigerbeetle_tpu.types import Operation

        live = [op for op in Operation if not op.name.startswith("deprec")]
        got = "\n".join(f"{int(op)} {op.name}" for op in live)
        snap(got + "\n", expected="""\
        128 pulse
        137 get_change_events
        140 lookup_accounts
        141 lookup_transfers
        142 get_account_transfers
        143 get_account_balances
        144 query_accounts
        145 query_transfers
        146 create_accounts
        147 create_transfers
        """)
