"""Full 33-tree forest schema (reference: src/state_machine.zig:45-90
tree_ids — accounts 9, transfers 14, transfers_pending 2, account_events 8)
and the queries/cleanup the new trees serve."""

from tigerbeetle_tpu.lsm.query import ForestQuery
from tigerbeetle_tpu.lsm.scan import TreeScan, composite_key
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags,
    AccountFlags,
    ChangeEventsFilter,
    CreateTransferStatus,
    Transfer,
    TransferFlags,
)
from tigerbeetle_tpu.vsr.durable import SCHEMA, DurableState
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

TS_MAX = (1 << 63) - 1


CREATED = CreateTransferStatus.created


def _mk():
    sm = StateMachine(engine="oracle")
    storage = MemoryStorage(TEST_LAYOUT)
    durable = DurableState(storage)
    return sm, durable, storage


def _count(tree, key_min: bytes, key_max: bytes) -> int:
    return sum(1 for _ in TreeScan(tree, key_min, key_max))


class TestFullForestSchema:
    def test_schema_has_33_trees(self):
        # reference: 4 grooves / 33 trees with fixed ids 1..33
        # (src/state_machine.zig:45-90).
        assert len(SCHEMA) == 33

    def test_closed_index_tracks_reopen(self):
        sm, durable, storage = _mk()
        ts = 1000
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in (1, 2)], ts)
        ts += 100
        closing = [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                            amount=0, ledger=1, code=1,
                            flags=int(TransferFlags.pending
                                      | TransferFlags.closing_debit))]
        res = sm.create_transfers(closing, ts)
        assert res[0].status == CREATED
        durable.flush(sm.state)
        trees = durable.forest.trees
        a1_ts = sm.state.accounts[1].timestamp
        key = composite_key(1, a1_ts, 1)
        assert trees["acct_by_closed"].get(key) == b"\x01"
        assert trees["xfer_by_closing"].get(
            composite_key(1, sm.state.transfers[10].timestamp, 1)) == b"\x01"

        ts += 100
        void = [Transfer(id=11, pending_id=10, ledger=1, code=1,
                         flags=int(TransferFlags.void_pending_transfer))]
        res = sm.create_transfers(void, ts)
        assert res[0].status == CREATED
        assert not (sm.state.accounts[1].flags & AccountFlags.closed)
        durable.flush(sm.state)
        assert trees["acct_by_closed"].get(key) is None  # reopened

    def test_amount_and_imported_indexes(self):
        sm, durable, storage = _mk()
        imported = int(AccountFlags.imported)
        sm.create_accounts(
            [Account(id=1, ledger=1, code=1, flags=imported, timestamp=100),
             Account(id=2, ledger=1, code=1, flags=imported, timestamp=101)],
            timestamp=1000)
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=777, ledger=1, code=1,
                      flags=int(TransferFlags.imported), timestamp=500)],
            timestamp=2000)
        assert 10 in sm.state.transfers
        durable.flush(sm.state)
        trees = durable.forest.trees
        assert _count(trees["acct_by_imported"],
                      composite_key(1, 1, 1), composite_key(1, TS_MAX, 1)) == 2
        assert trees["xfer_by_amount"].get(
            composite_key(777, 500, 16)) == b"\x01"
        assert trees["xfer_by_imported"].get(
            composite_key(1, 500, 1)) == b"\x01"

    def test_account_timestamp_event_index(self):
        sm, durable, storage = _mk()
        hist = int(AccountFlags.history)
        sm.create_accounts(
            [Account(id=1, ledger=1, code=1, flags=hist),
             Account(id=2, ledger=1, code=1)], 1000)
        ts = 2000
        for i in range(4):
            sm.create_transfers(
                [Transfer(id=100 + i, debit_account_id=1,
                          credit_account_id=2, amount=5 + i,
                          ledger=1, code=1)], ts)
            ts += 100
        durable.flush(sm.state)
        q = ForestQuery(durable.forest)
        a1_ts = sm.state.accounts[1].timestamp
        rows = q.account_history_events(a1_ts)
        # Only account 1 has history: one index row per event, debit side.
        assert len(rows) == 4
        assert [r.debits_posted for r in rows] == [5, 11, 18, 26]
        # Exactly the rows get_account_balances serves for the account.
        f = AccountFilter(
            account_id=1, limit=8190,
            flags=int(AccountFilterFlags.debits | AccountFilterFlags.credits))
        assert [(b.timestamp, b.debits_posted)
                for b in q.get_account_balances(f)] == \
               [(r.timestamp, r.debits_posted) for r in rows]
        # The no-history account contributed no index rows.
        a2_ts = sm.state.accounts[2].timestamp
        assert q.account_history_events(a2_ts) == []

    def test_expired_event_indexes(self):
        sm, durable, storage = _mk()
        sm.create_accounts(
            [Account(id=1, ledger=7, code=1), Account(id=2, ledger=7, code=1)],
            1000)
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=50, ledger=7, code=1,
                      flags=int(TransferFlags.pending), timeout=1)],
            2_000_000_000)
        expired = sm.state.expire_pending_transfers(10_000_000_000)
        assert expired == 1
        durable.flush(sm.state)
        q = ForestQuery(durable.forest)
        rec = q.expiry_event_of_pending(10)
        assert rec is not None and rec.transfer_pending.id == 10
        assert [r.transfer_pending.id
                for r in q.expired_events_by_account(1, "dr")] == [10]
        assert [r.transfer_pending.id
                for r in q.expired_events_by_account(2, "cr")] == [10]
        trees = durable.forest.trees
        assert _count(trees["ev_by_ledger_expired"],
                      composite_key(7, 1, 4),
                      composite_key(7, TS_MAX, 4)) == 1
        # Pending-status index has one row per event (2 creates + 1 pending
        # + 1 expiry here).
        assert _count(trees["ev_by_pstat"],
                      composite_key(0, 1, 1),
                      composite_key(4, TS_MAX, 1)) == len(
                          sm.state.account_events)

    def test_prunable_index_and_prune_job(self):
        sm, durable, storage = _mk()
        hist = int(AccountFlags.history)
        sm.create_accounts(
            [Account(id=1, ledger=1, code=1, flags=hist),
             Account(id=2, ledger=1, code=1),
             Account(id=3, ledger=1, code=1)], 1000)
        ts = 2000
        # 1<->2 events keep history (account 1); 2<->3 events are prunable.
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1)], ts)
        sm.create_transfers(
            [Transfer(id=11, debit_account_id=2, credit_account_id=3,
                      amount=6, ledger=1, code=1)], ts + 100)
        durable.flush(sm.state)
        trees = durable.forest.trees
        n_events = len(sm.state.account_events)
        assert _count(trees["events"], bytes(8), b"\xff" * 8) == n_events
        prunable = _count(trees["ev_by_prunable"], bytes(8), b"\xff" * 8)
        assert prunable == 1  # only the 2->3 transfer event
        q = ForestQuery(durable.forest)
        before = q.get_change_events(ChangeEventsFilter(limit=100))
        pruned = durable.prune_events(TS_MAX)
        assert pruned == 1
        assert _count(trees["events"], bytes(8), b"\xff" * 8) == n_events - 1
        assert _count(trees["ev_by_prunable"], bytes(8), b"\xff" * 8) == 0
        after = q.get_change_events(ChangeEventsFilter(limit=100))
        assert len(after) == len(before) - 1
        # History rows survive: the account_timestamp index still serves.
        a1_ts = sm.state.accounts[1].timestamp
        assert len(q.account_history_events(a1_ts)) == 1

    def test_checkpoint_after_prune_still_opens(self):
        """A checkpoint taken after prune_events must restore (the meta
        events count is monotonic; the tree holds fewer rows) — and
        further flushes must persist exactly the new tail."""
        sm, durable, storage = _mk()
        sm.create_accounts(
            [Account(id=1, ledger=1, code=1),
             Account(id=2, ledger=1, code=1)], 1000)
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1)], 2000)
        durable.flush(sm.state)
        assert durable.prune_events(TS_MAX) == len(sm.state.account_events)
        root = durable.checkpoint(sm.state)

        durable2 = DurableState(storage)
        restored = durable2.open(root)  # load_events=True must not raise
        assert restored.account_events == []
        assert restored.events_base == len(sm.state.account_events)
        # New events after restore land in the tree exactly once.
        restored.create_transfers(
            [Transfer(id=11, debit_account_id=1, credit_account_id=2,
                      amount=6, ledger=1, code=1)], 3000)
        durable2.flush(restored)
        trees = durable2.forest.trees
        assert _count(trees["events"], bytes(8), b"\xff" * 8) == 1

    def test_closed_index_writes_only_on_transitions(self):
        """Balance churn on never-closed accounts must not touch
        acct_by_closed (write-amp guard)."""
        sm, durable, storage = _mk()
        sm.create_accounts(
            [Account(id=1, ledger=1, code=1),
             Account(id=2, ledger=1, code=1)], 1000)
        ts = 2000
        for i in range(5):
            sm.create_transfers(
                [Transfer(id=100 + i, debit_account_id=1,
                          credit_account_id=2, amount=1,
                          ledger=1, code=1)], ts)
            ts += 100
            durable.flush(sm.state)
        assert durable.forest.trees["acct_by_closed"].memtable == {}

    def test_checkpoint_roundtrip_with_full_schema(self):
        sm, durable, storage = _mk()
        hist = int(AccountFlags.history)
        sm.create_accounts(
            [Account(id=1, ledger=1, code=1, flags=hist),
             Account(id=2, ledger=1, code=1)], 1000)
        sm.create_transfers(
            [Transfer(id=10, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1,
                      flags=int(TransferFlags.pending), timeout=1)], 2000)
        sm.state.expire_pending_transfers(10**12)
        root = durable.checkpoint(sm.state)
        durable2 = DurableState(storage)
        durable2.open(root)
        q = ForestQuery(durable2.forest)
        assert q.expiry_event_of_pending(10) is not None
        a1_ts = sm.state.accounts[1].timestamp
        assert len(q.account_history_events(a1_ts)) == 2  # create + expiry
