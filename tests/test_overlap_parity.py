"""Double-buffered window staging (ISSUE 16): the overlapped pipeline
(stage_window ahead of submit_window) must be bit-identical to the
synchronous staging path on every route — statuses, timestamps, flush
columns, digests — including a window poisoned mid-pipeline and a
chaos bit-flip recovery that must drain staged-but-undispatched windows
WITHOUT committing them. Staging is an optimization, never a semantic:
a staged pack is consumed only on exact identity match (same event
arrays, timestamps, route, pad bucket), else dropped and re-packed
inline."""

import numpy as np
import pytest

from tigerbeetle_tpu.ops.batch import transfers_to_arrays
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)
U128MAX = (1 << 128) - 1

# The jit-heavy differential tests ride the slow tier like their
# sibling suite (test_window_pipeline.py); the small staging-identity
# test stays in the quick tier.
slow = pytest.mark.slow


def _mk_led(t_cap=1 << 13):
    led = DeviceLedger(a_cap=1 << 10, t_cap=t_cap)
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    return led


def _windows(rng, n_windows, k=3, n=64, base=10**6, with_pend=False,
             poison_window=None):
    """n_windows windows of k batches each; optionally a duplicate-id
    batch (hard fallback) inside window `poison_window`."""
    out = []
    nid = base
    ts = 10**12
    pend_pool = []
    for w in range(n_windows):
        evs, tss = [], []
        for b in range(k):
            batch = []
            for i in range(n):
                dr = int(rng.integers(1, 65))
                if with_pend and pend_pool and i % 5 == 0:
                    batch.append(Transfer(
                        id=nid, pending_id=pend_pool.pop(0),
                        amount=U128MAX, ledger=1, code=1, flags=POST))
                else:
                    f = PEND if (with_pend and i % 4 == 0) else 0
                    batch.append(Transfer(
                        id=nid, debit_account_id=dr,
                        credit_account_id=dr % 64 + 1,
                        amount=int(rng.integers(1, 100)), ledger=1,
                        code=1, flags=f, timeout=10 if f else 0))
                    if f:
                        pend_pool.append(nid)
                nid += 1
            if poison_window == w and b == k // 2:
                # duplicate id within the batch: hard fallback (E2)
                batch[-1] = Transfer(
                    id=batch[0].id, debit_account_id=1,
                    credit_account_id=2, amount=1, ledger=1, code=1)
            ts += n + 10
            evs.append(batch)
            tss.append(ts)
        out.append((evs, tss))
    return out


def _state_eq(a, b):
    assert a.accounts == b.accounts
    assert a.transfers == b.transfers
    assert a.pending_status == b.pending_status
    assert a.expiry == b.expiry
    assert set(a.orphaned) == set(b.orphaned)
    assert a.pulse_next_timestamp == b.pulse_next_timestamp
    assert a.commit_timestamp == b.commit_timestamp


def _run_staged(led, windows, depth=2):
    """The overlapped serving pattern at ledger level: submit k, stage
    k+1 (its pack overlaps the blocking resolve), resolve oldest. The
    SAME prepare-dict objects must be staged and submitted — staging
    is consumed on identity, exactly like the serving drivers."""
    arrs = [[transfers_to_arrays(b) for b in evs]
            for evs, _tss in windows]
    results = []
    pending = []
    for i, (_evs, tss) in enumerate(windows):
        arrays = arrs[i]
        tk = led.submit_window(arrays, tss)
        if tk is None:
            led.resolve_windows()
            while pending:
                results.append(pending.pop(0).results)
            results.append(
                ("sync", led.create_transfers_window(arrays, tss)))
            continue
        pending.append(tk)
        if i + 1 < len(windows):
            led.stage_window(arrs[i + 1], windows[i + 1][1])
        if len(pending) >= depth:
            led.resolve_windows(count=1)
            while pending and pending[0].results is not None:
                results.append(pending.pop(0).results)
    led.resolve_windows()
    for tk in pending:
        results.append(tk.results)
    led.shutdown_staging()
    return results


def test_stage_identity_hit_and_miss():
    """Quick tier: a staged pack is consumed only on exact identity
    match (prepare-dict identity, not equality); a mismatched stage is
    a counted miss whose inline re-pack is bit-identical; forced-sync
    staging measures a stall fraction of exactly 1.0 (the overlap gate
    leg's negative)."""
    led = _mk_led()
    led_sync = _mk_led()
    led_sync.overlap_staging = False
    rng = np.random.default_rng(23)
    (w0, t0), (w1, t1) = _windows(rng, 2, k=2, n=8)

    a0 = [transfers_to_arrays(b) for b in w0]
    a0_twin = [transfers_to_arrays(b) for b in w0]  # equal, new dicts
    a1 = [transfers_to_arrays(b) for b in w1]
    # Stage equal-but-distinct prepare dicts: identity mismatch ->
    # counted miss, the stage is dropped, the inline pack serves.
    assert led.stage_window(a0_twin, t0)
    tk0 = led.submit_window(a0, t0)
    assert tk0 is not None
    assert led.staging_stats["misses"] == 1
    assert led.staging_stats["staged"] == 0
    # Stage + submit the SAME objects: identity hit.
    assert led.stage_window(a1, t1)
    tk1 = led.submit_window(a1, t1)
    assert tk1 is not None
    led.resolve_windows()
    assert led.staging_stats["staged"] == 1
    assert led.staging_summary()["windows"] == 2

    # Forced-sync arm: stage_window refuses, stall fraction is 1.0.
    assert not led_sync.stage_window(a0, t0)
    for w, t in ((w0, t0), (w1, t1)):
        arrays = [transfers_to_arrays(b) for b in w]
        assert led_sync.submit_window(arrays, t) is not None
    led_sync.resolve_windows()
    sm = led_sync.staging_summary()
    assert sm["overlap"] is False and sm["staged"] == 0
    assert sm["host_stall_fraction"] == 1.0

    # Bit-exact regardless of staging path.
    for tk in (tk0, tk1):
        assert tk.results is not None
    _state_eq(led.to_host(), led_sync.to_host())
    led.shutdown_staging()
    led_sync.shutdown_staging()


@slow
@pytest.mark.parametrize("with_pend,poison", [
    (False, None), (True, 2)])
def test_overlap_matches_sync(with_pend, poison):
    """Overlapped pipeline vs synchronous windows: statuses, ts, final
    state — incl. a hard-fallback window mid-pipeline whose redo must
    not consume a stale staged pack."""
    rng = np.random.default_rng(3)
    windows = _windows(rng, 4, with_pend=with_pend,
                       poison_window=poison)
    led_p = _mk_led()
    led_s = _mk_led()
    led_s.overlap_staging = False

    results_p = _run_staged(led_p, windows)
    results_s = []
    for evs, tss in windows:
        results_s.append(led_s.create_transfers_window(
            [transfers_to_arrays(b) for b in evs], tss))

    assert len(results_p) == len(results_s)
    for kind_res, outs_s in zip(results_p, results_s):
        _, outs_p = kind_res
        for (st_p, ts_p), (st_s, ts_s) in zip(outs_p, outs_s):
            np.testing.assert_array_equal(np.asarray(st_p),
                                          np.asarray(st_s))
            np.testing.assert_array_equal(np.asarray(ts_p),
                                          np.asarray(ts_s))
    _state_eq(led_p.to_host(), led_s.to_host())
    st = led_p.staging_stats
    assert st["staged"] >= 1, st
    # Clean runs consume every stage; a poisoned run may drop stages
    # (route-hysteresis flip after the redo) but must count them.
    assert st["staged"] + st["misses"] == st["windows"] - 1 \
        or poison is not None, st


@slow
def test_overlap_flush_columns_serving_mode():
    """Serving mode (write-through + ring recycle): the overlapped
    pipeline's drained flush columns and mirror are bit-identical to
    the sync path's."""
    from tigerbeetle_tpu.oracle import StateMachineOracle

    rng = np.random.default_rng(5)
    windows = _windows(rng, 4, with_pend=True, base=2 * 10**6)

    def mk_serving(overlap):
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13,
                           write_through=StateMachineOracle())
        led.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in range(1, 65)],
            120)
        led.recycle_events = True
        led.retain_flush_columns = True
        led.overlap_staging = overlap
        return led

    led_p = mk_serving(True)
    led_s = mk_serving(False)
    _run_staged(led_p, windows)
    for evs, tss in windows:
        led_s.create_transfers_window(
            [transfers_to_arrays(b) for b in evs], tss)
    led_p.drain_mirror()
    led_s.drain_mirror()
    cols_p = led_p.take_flush_columns()
    cols_s = led_s.take_flush_columns()
    assert len(cols_p) == len(cols_s)
    for cp, cs in zip(cols_p, cols_s):
        assert cp[3] == cs[3]  # n_new per chunk
        if cp[3]:
            for key in ("id_hi", "id_lo", "ts", "flags"):
                np.testing.assert_array_equal(
                    np.asarray(cp[0][key]), np.asarray(cs[0][key]))
    _state_eq(led_p.mirror, led_s.mirror)
    assert led_p.staging_stats["staged"] >= 1


@slow
def test_overlap_partitioned_chain():
    """The fused partitioned-chain route (attach mode): overlapped
    staging vs sync staging vs the oracle — results and sharded state
    digests bit-identical, including a window poisoned by a limit
    cascade (per-prepare fallback mid-pipeline under staging)."""
    import jax
    from jax.sharding import Mesh

    from tigerbeetle_tpu.oracle import StateMachineOracle
    from tigerbeetle_tpu.ops.state_epoch import (
        partitioned_oracle_digest, partitioned_state_digest)
    from tigerbeetle_tpu.parallel.partitioned import PartitionedRouter
    from tigerbeetle_tpu.types import AccountFlags

    A_CAP, T_CAP = 1 << 9, 1 << 11
    n_dev = len(jax.devices())
    dr_limit = int(AccountFlags.debits_must_not_exceed_credits)
    accts = [Account(id=i, ledger=1, code=1,
                     flags=(dr_limit if i <= 4 else 0))
             for i in range(1, 41)]
    rng = np.random.default_rng(13)
    nid, ts = 10**6, 10**9
    windows = []
    for w in range(4):
        batches, tss = [], []
        for b in range(3):
            n = 8
            dr = rng.integers(5, 41, n)
            cr = rng.integers(5, 41, n)
            clash = dr == cr
            cr[clash] = dr[clash] % 36 + 5
            batch = [Transfer(id=nid + i, debit_account_id=int(dr[i]),
                              credit_account_id=int(cr[i]),
                              amount=int(rng.integers(1, 30)),
                              ledger=1, code=1) for i in range(n)]
            nid += n
            if w == 1 and b == 1:
                # DR-limit cascade: poisons the fused chain at this
                # prepare; the clean prefix stays committed on device.
                batch.append(Transfer(id=nid, debit_account_id=1,
                                      credit_account_id=9,
                                      amount=10**9, ledger=1, code=1))
                nid += 1
            ts += 300
            batches.append(batch)
            tss.append(ts)
        windows.append((batches, tss))

    steps, chain_steps = {}, {}
    digests, results, oracles = [], [], []
    for overlap in (True, False):
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("batch",))
        orc = StateMachineOracle()
        orc.create_accounts(accts, 50)
        router = PartitionedRouter(mesh, a_cap=A_CAP, t_cap=T_CAP)
        router._steps = steps
        router._chain_steps = chain_steps
        led = DeviceLedger(a_cap=A_CAP, t_cap=T_CAP)
        led.attach_partitioned(router, router.from_oracle(orc))
        led.overlap_staging = overlap
        # Same prepare-dict objects staged and submitted (identity).
        arrs = [[transfers_to_arrays(b) for b in batches]
                for batches, _tss in windows]
        tickets = []
        for i, (_batches, tss) in enumerate(windows):
            tk = led.submit_window(arrs[i], tss)
            assert tk is not None
            tickets.append(tk)
            if i + 1 < len(windows):
                led.stage_window(arrs[i + 1], windows[i + 1][1])
            if len(led._tickets) >= 2:
                led.resolve_windows(count=1)
        led.resolve_windows()
        norm = []
        for tk in tickets:
            _kind, pairs = tk.results
            norm.append([[(int(t), int(s))
                          for s, t in zip(st.tolist(), ts_.tolist())]
                         for st, ts_ in pairs])
        results.append(norm)
        if overlap:
            assert led.staging_stats["staged"] >= 1, led.staging_stats
        else:
            assert led.staging_stats["staged"] == 0, led.staging_stats
        digests.append(partitioned_state_digest(led.partitioned_state))
        oracles.append(orc)
        led.shutdown_staging()

    assert results[0] == results[1]
    assert digests[0] == digests[1]
    # Oracle parity: statuses/ts and final sharded digest.
    orc = oracles[0]
    want = []
    for batches, tss in windows:
        want.append([[(r.timestamp, int(r.status))
                      for r in orc.create_transfers(b, t)]
                     for b, t in zip(batches, tss)])
    assert results[0] == want
    assert digests[0] == partitioned_oracle_digest(orc, A_CAP, n_dev)


@slow
def test_bitflip_recovery_drains_staged_without_commit():
    """Chaos bit-flip mid-pipeline: the epoch verify catches the
    corruption, recovery replays the LOGGED windows from the oracle
    (in-flight windows adopt the replay's answers), and a window that
    was STAGED but never dispatched dies with the quarantined ledger —
    its transfers never commit, and serving continues cleanly on the
    rebuilt ledger."""
    from tigerbeetle_tpu.serving import ServingSupervisor
    from tigerbeetle_tpu.testing.chaos import inject_state_bitflip

    rng = np.random.default_rng(41)
    windows = _windows(rng, 4, k=2, n=32, base=4 * 10**6)
    accts = [Account(id=i, ledger=1, code=1) for i in range(1, 65)]

    def run(faulted):
        sup = ServingSupervisor(a_cap=1 << 10, t_cap=1 << 13,
                                epoch_interval=100)
        sup.create_accounts(accts, 120)
        for batches, tss in windows[:3]:
            sup.submit_transfers_window(batches, tss)
        staged_batches, staged_tss = windows[3]
        if faulted:
            # Corrupt a digest-covered live cell, then stage (but never
            # submit) window 3 on the doomed ledger.
            f = {"target": "transfers_u64", "row_pick": 0,
                 "col_pick": 0, "bit": 7}
            assert inject_state_bitflip(sup.led, f), f
            assert sup.led.stage_window(
                [transfers_to_arrays(b) for b in staged_batches],
                staged_tss)
            old_led = sup.led
            # Divergence found -> recovers inside, returns False.
            assert not sup.verify_epoch()
            assert sup.last_recovery is not None
            assert sup.last_recovery["cause"] == "state_digest", \
                sup.last_recovery
            assert sup.counters["recoveries"], sup.counters
            assert sup.counters["checksum_mismatches"] >= 1
            assert sup.led is not old_led, "ledger not quarantined"
            # The staged-but-undispatched pack died with the old
            # ledger's stager: nothing from window 3 committed anywhere.
            assert old_led._staged is None and old_led._stager is None
            assert sup.led._staged is None
            for b in staged_batches:
                for ev in b:
                    assert ev.id not in sup.led.mirror.transfers
                    assert ev.id not in sup.epoch_base.transfers
        else:
            assert sup.verify_epoch()
            assert not sup.counters["recoveries"], sup.counters
        # Serving continues: window 3 submits cleanly afterwards.
        sup.submit_transfers_window(staged_batches, staged_tss)
        sup.drain_pipeline()
        assert sup.verify_epoch()
        hist = list(sup.history)
        sup.led.shutdown_staging()
        return hist

    hist_f = run(faulted=True)
    hist_c = run(faulted=False)
    # Authoritative history bit-exact vs the unfaulted run: recovery
    # replay changed nothing observable, and window 3's results come
    # from its REAL post-recovery dispatch, not the dead stage.
    assert hist_f == hist_c


# --------------------------------------------------------------------------
# Admission plane × staging (ISSUE 18, satellite 3): shedding decisions
# landing mid-window must never leak into committed state — the admitted
# history stays bit-exact vs an oracle replay of ONLY the admitted
# requests, and a window that was STAGE-AHEAD-packed but shed before
# submit never commits a single transfer.


def _mk_admission_plane(**kw):
    from tigerbeetle_tpu.admission import (
        AdmissionClass, AdmissionPlane, VirtualClock)
    from tigerbeetle_tpu.serving import ServingSupervisor

    clock = VirtualClock()
    sup = ServingSupervisor(a_cap=1 << 8, t_cap=1 << 11,
                            epoch_interval=4, sleep=lambda s: None,
                            seed=11)
    classes = (
        AdmissionClass("critical", 0, slo_ms=100.0, deadline_ms=400.0),
        AdmissionClass("batch", 1, slo_ms=200.0, deadline_ms=800.0),
    )
    # prepare_max=4 with 2-event requests -> every window is >=2
    # prepares, the pipelined route's staging-eligibility floor
    # (DeviceLedger._window_plan requires len(evs) > 1).
    args = dict(classes=classes, prepare_max=4, window_prepares=2,
                session_credits=100, max_queue=256, clock=clock,
                seed=11)
    args.update(kw)
    plane = AdmissionPlane(sup, **args)
    plane.open_accounts(
        [Account(id=i, ledger=1, code=1) for i in (1, 2)], 1_000)
    return plane, sup, clock


def _adm_evs(n, start):
    return [Transfer(id=start + i, debit_account_id=1,
                     credit_account_id=2, amount=1, ledger=1, code=1)
            for i in range(n)]


@slow
def test_shed_mid_window_history_bit_exact():
    """Overloaded plane with the shed line slamming shut mid-run: the
    supervisor's committed history equals an oracle replay of exactly
    the admitted requests, and no shed request's transfers ever reach
    the committed mirror."""
    plane, sup, clock = _mk_admission_plane(
        stage_ahead=True, session_credits=1)
    reqs, nid = [], 10**5
    for t in range(8):
        for sid in range(1, 5):
            cls = "critical" if sid == 1 else "batch"
            # Second submit in the same tick: typed no_credit shed.
            reqs.append(plane.submit(sid, _adm_evs(2, nid), cls=cls))
            reqs.append(
                plane.submit(sid, _adm_evs(2, nid + 2), cls=cls))
            nid += 4
        if t == 4:
            # The shed line slams shut mid-run: queued AND stage-ahead
            # batch-class members shed as "shed_line".
            plane.force_shed_level(1)
        if t == 6:
            plane.force_shed_level(None)
        plane.pump()
        clock.advance(0.05)
    plane.drain()
    cons = plane.conservation()
    assert cons["ok"] and cons["queued"] == 0 and cons["staged"] == 0
    shed = [r for r in reqs if r.state == "shed"]
    admitted = [r for r in reqs if r.state == "admitted"]
    assert shed and admitted
    assert {r.shed.reason for r in shed} >= {"no_credit", "shed_line"}
    # Bit-exactness under shedding: committed history == oracle replay
    # of the admitted script alone.
    hist, _oracle = plane.oracle_history()
    assert hist == sup.history
    assert sup.verify_epoch()
    # Zero leakage: no shed transfer committed; every admitted one did.
    shed_ids = {ev.id for r in shed for ev in r.transfers}
    adm_ids = {ev.id for r in admitted for ev in r.transfers}
    assert not shed_ids & set(sup.led.mirror.transfers)
    assert adm_ids <= set(sup.led.mirror.transfers)
    sup.led.shutdown_staging()


@slow
def test_staged_but_shed_window_never_commits():
    """A stage-ahead window whose members are shed between prestage and
    submit is abandoned: the staged pack is never dispatched, its
    transfers appear in neither the mirror nor the verified epoch base,
    and the pack itself dies with shutdown_staging — the same
    never-committed guarantee the recovery drain gives a quarantined
    stage."""
    plane, sup, clock = _mk_admission_plane(stage_ahead=True)
    nid = 2 * 10**5
    for sid in range(1, 9):
        plane.submit(sid, _adm_evs(2, nid), cls="batch")
        nid += 2
    # One pump: window 1 (8 events) dispatches, window 2 (8 events) is
    # packed onto the ledger's background stager.
    plane.pump()
    clock.advance(0.02)
    assert plane._staged_next is not None
    staged_reqs = list(plane._staged_next[3])
    staged_ids = {ev.id for r in staged_reqs for ev in r.transfers}
    assert staged_ids
    # Gate the batch class before the staged window submits: every
    # staged member sheds as "shed_line"; the pack is never dispatched.
    plane.force_shed_level(1)
    plane.pump()
    assert all(r.state == "shed" and r.shed.reason == "shed_line"
               for r in staged_reqs)
    plane.drain()
    assert plane.conservation()["ok"]
    hist, _oracle = plane.oracle_history()
    assert hist == sup.history
    assert sup.verify_epoch()
    assert not staged_ids & set(sup.led.mirror.transfers)
    assert not staged_ids & set(sup.epoch_base.transfers)
    # The abandoned pack dies with the stager, never having committed.
    sup.led.shutdown_staging()
    assert sup.led._staged is None


# --------------------------------------------------------------------------
# Elastic shards (ISSUE 19, satellite fix): quarantine/resync × staging.


def test_resync_tears_down_staging_first(tmp_path, monkeypatch):
    """A pack staged under the pre-quarantine ownership map must die
    with the resync: `PartitionedRouter.resync` shuts the attached
    ledger's staging down BEFORE rebuilding, so the stale pack — whose
    route and pad bucket would still match by identity — can never be
    consumed against the rebuilt state."""
    import jax
    from jax.sharding import Mesh

    from tigerbeetle_tpu.oracle import StateMachineOracle
    from tigerbeetle_tpu.parallel.partitioned import PartitionedRouter

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    monkeypatch.setenv("TB_TPU_FLIGHT_DIR", str(tmp_path))
    mesh = Mesh(np.array(jax.devices()[:2]), ("batch",))
    orc = StateMachineOracle()
    orc.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 17)], 50)
    router = PartitionedRouter(mesh, a_cap=1 << 8, t_cap=1 << 10)
    led = DeviceLedger(a_cap=1 << 8, t_cap=1 << 10)
    led.attach_partitioned(router, router.from_oracle(orc))

    nid, ts = 10**6, 10**9
    batches, tss = [], []
    for _b in range(2):   # W >= 2: the staging-eligibility floor
        batches.append(
            [Transfer(id=nid + i, debit_account_id=i % 16 + 1,
                      credit_account_id=(i + 1) % 16 + 1, amount=1,
                      ledger=1, code=1) for i in range(8)])
        nid += 8
        ts += 100
        tss.append(ts)
    evs = [transfers_to_arrays(b) for b in batches]
    assert led.stage_window(evs, tss)
    assert led._staged is not None and led._stager is not None

    # Quarantine: the router refuses to serve a lost range...
    router.drop_device(mesh.devices.flat[0])
    with pytest.raises(RuntimeError):
        led.create_transfers_window(evs, tss)
    # ...and the resync rebuild tears the stale stage down first.
    state = router.resync(orc)
    assert led._staged is None and led._stager is None
    assert router.shard_resyncs == 1 and not router.lost_devices
    # Serving resumes cleanly on the rebuilt state: the same window
    # re-packs inline (no stage to hit) and commits with oracle parity.
    led._part_state = state
    out = led.create_transfers_window(evs, tss)
    got = [[(int(t), int(s)) for s, t in zip(st.tolist(), ts_.tolist())]
           for st, ts_ in out]
    want = [[(r.timestamp, int(r.status))
             for r in orc.create_transfers(b, t)]
            for b, t in zip(batches, tss)]
    assert got == want
