"""VSR layer tests: journal recovery, superblock quorum, snapshot codec,
and deterministic cluster simulation (normal path, view change, crash
recovery, packet chaos). reference test strategy: SURVEY.md §4."""

import dataclasses

import pytest

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.testing.cluster import Cluster, NetworkOptions, MS
from tigerbeetle_tpu.types import Account, Operation, Transfer
from tigerbeetle_tpu.vsr import snapshot as snapshot_codec
from tigerbeetle_tpu.vsr.checksum import checksum
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.journal import Journal, SlotState
from tigerbeetle_tpu.vsr.storage import MemoryStorage, TEST_LAYOUT
from tigerbeetle_tpu.vsr.superblock import SuperBlock


def _prepare(op: int, body: bytes = b"", parent: int = 0) -> Message:
    header = Header(command=Command.prepare, cluster=7, op=op, parent=parent)
    return Message(header.finalize(body), body=body)


class TestHeader:
    def test_roundtrip_and_checksums(self):
        msg = _prepare(5, b"hello world")
        raw = msg.pack()
        back = Message.unpack(raw)
        assert back.valid()
        assert back.header.op == 5 and back.body == b"hello world"
        # Corrupt one body byte -> body checksum fails, header still valid.
        bad = bytearray(raw)
        bad[-1] ^= 0xFF
        corrupt = Message.unpack(bytes(bad))
        assert corrupt.header.valid_checksum()
        assert not corrupt.valid()
        # Corrupt the header -> header checksum fails.
        bad = bytearray(raw)
        bad[40] ^= 0x01
        assert not Message.unpack(bytes(bad)).header.valid_checksum()


class TestJournal:
    def test_append_read_recover(self):
        storage = MemoryStorage()
        journal = Journal(storage)
        parent = 0
        for op in range(1, 6):
            msg = _prepare(op, f"body{op}".encode(), parent)
            journal.append(msg)
            parent = msg.header.checksum
        assert journal.read_prepare(3).body == b"body3"
        assert journal.read_prepare(9) is None

        # Fresh journal over the same storage: recovery must find all 5.
        journal2 = Journal(storage)
        slots = journal2.recover()
        clean_ops = sorted(s.header.op for s in slots
                           if s.state == SlotState.clean and s.header)
        assert clean_ops[-5:] == [1, 2, 3, 4, 5]
        assert journal2.read_prepare(4).body == b"body4"

    def test_recover_torn_prepare(self):
        storage = MemoryStorage()
        journal = Journal(storage)
        msg = _prepare(1, b"payload")
        journal.append(msg)
        # Tear the prepare body (simulate partial write), keep the header.
        zones = storage.layout.zone_offsets
        slot = journal.slot_for_op(1)
        pos = (zones["wal_prepares"] + slot * journal.prepare_size_max
               + 258)  # inside the 7-byte body
        storage.data[pos] ^= 0xFF
        journal2 = Journal(storage)
        slots = journal2.recover()
        slot = slots[journal2.slot_for_op(1)]
        assert slot.state == SlotState.faulty
        assert slot.header.op == 1  # known from the redundant header
        assert journal2.read_prepare(1) is None

    def test_recover_torn_header(self):
        storage = MemoryStorage()
        journal = Journal(storage)
        msg = _prepare(1, b"payload")
        journal.append(msg)
        zones = storage.layout.zone_offsets
        storage.data[zones["wal_headers"] + 256 + 10] ^= 0xFF  # slot 1 header
        journal2 = Journal(storage)
        journal2.recover()
        # Prepare ring intact: slot recovers clean from the prepare itself.
        assert journal2.read_prepare(1).body == b"payload"


class TestSuperBlock:
    def test_quorum_pick(self):
        storage = MemoryStorage()
        sb = SuperBlock(cluster=1, replica_id=0, replica_count=3)
        sb.store(storage)
        sb.commit_min = 42
        sb.store(storage)
        loaded = SuperBlock.load(storage)
        assert loaded.sequence == 2 and loaded.commit_min == 42

    def test_torn_update_falls_back(self):
        storage = MemoryStorage()
        sb = SuperBlock(cluster=1, replica_id=0, replica_count=3)
        sb.store(storage)  # seq 1 on all 4 copies
        # Simulate a torn update: only copy 0 written with seq 2.
        sb2 = dataclasses.replace(sb, commit_min=99)
        sb2.sequence = 2
        storage.write("superblock", 0, sb2.pack_copy())
        loaded = SuperBlock.load(storage)
        assert loaded.sequence == 1  # quorum (2 copies) not reached for seq 2
        # Two copies of seq 2 -> quorum.
        storage.write("superblock", 4096, sb2.pack_copy())
        loaded = SuperBlock.load(storage)
        assert loaded.sequence == 2 and loaded.commit_min == 99


class TestSnapshot:
    def test_roundtrip(self):
        sm = StateMachine()
        sm.create_accounts([Account(id=i, ledger=1, code=1) for i in (1, 2)],
                           1000)
        sm.create_transfers(
            [Transfer(id=9, debit_account_id=1, credit_account_id=2,
                      amount=50, ledger=1, code=1)], 2000)
        raw = snapshot_codec.encode(sm.state)
        back = snapshot_codec.decode(raw)
        assert snapshot_codec.encode(back) == raw
        assert back.accounts == sm.state.accounts
        assert back.transfers == sm.state.transfers
        assert back.account_events == sm.state.account_events


def _create_accounts_body(ids, ledger=1):
    payload = b"".join(Account(id=i, ledger=ledger, code=1).pack() for i in ids)
    return multi_batch.encode([payload], 128)


def _create_transfers_body(specs):
    payload = b"".join(
        Transfer(id=i, debit_account_id=dr, credit_account_id=cr,
                 amount=amt, ledger=1, code=1).pack()
        for (i, dr, cr, amt) in specs)
    return multi_batch.encode([payload], 128)


def _drive(cluster, client, requests):
    """Send requests sequentially; returns replies."""
    replies = []
    for op, body in requests:
        client.request(op, body)
        ok = cluster.run(3000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        replies.append(client.replies[-1])
    return replies


class TestCluster:
    def test_normal_path(self):
        cluster = Cluster(seed=1, replica_count=3)
        client = cluster.client(101)
        _drive(cluster, client, [
            (Operation.create_accounts, _create_accounts_body([1, 2, 3])),
            (Operation.create_transfers, _create_transfers_body(
                [(10, 1, 2, 100), (11, 2, 3, 50)])),
        ])
        cluster.settle()
        for r in cluster.replicas:
            a2 = r.state_machine.state.accounts[2]
            assert a2.debits_posted == 50 and a2.credits_posted == 100

    def test_view_change_on_primary_crash(self):
        cluster = Cluster(seed=2, replica_count=3)
        client = cluster.client(5)
        _drive(cluster, client, [
            (Operation.create_accounts, _create_accounts_body([1, 2])),
        ])
        primary = cluster.replicas[0].primary_index()
        cluster.crash(primary)
        client.request(Operation.create_transfers,
                       _create_transfers_body([(10, 1, 2, 7)]))
        ok = cluster.run(5000, until=lambda: client.idle)
        assert ok, cluster.debug_status()
        live = [r for i, r in enumerate(cluster.replicas)
                if i not in cluster.crashed]
        assert all(r.view > 0 for r in live)
        cluster.settle()

    def test_crash_restart_recovers_state(self):
        cluster = Cluster(seed=3, replica_count=3)
        client = cluster.client(9)
        _drive(cluster, client, [
            (Operation.create_accounts, _create_accounts_body([1, 2])),
            (Operation.create_transfers, _create_transfers_body(
                [(100 + k, 1, 2, k + 1) for k in range(20)])),
        ])
        cluster.settle()
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        _drive(cluster, client, [
            (Operation.create_transfers, _create_transfers_body(
                [(200, 1, 2, 5)])),
        ])
        cluster.restart(victim)
        cluster.settle()
        a1 = cluster.replicas[victim].state_machine.state.accounts[1]
        assert a1.debits_posted == sum(range(1, 21)) + 5

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_packet_chaos(self, seed):
        cluster = Cluster(
            seed=seed, replica_count=3,
            network=NetworkOptions(loss_probability=0.05,
                                   duplicate_probability=0.05,
                                   delay_min_ns=1 * MS,
                                   delay_max_ns=40 * MS))
        client = cluster.client(77)
        _drive(cluster, client, [
            (Operation.create_accounts, _create_accounts_body([1, 2])),
        ] + [
            (Operation.create_transfers,
             _create_transfers_body([(1000 + k, 1, 2, 1)]))
            for k in range(10)
        ])
        cluster.settle()
        a1 = cluster.replicas[0].state_machine.state.accounts[1]
        assert a1.debits_posted == 10
