"""Superbatch (commit-window) kernel: bit-exact vs sequential dispatch.

K prepares stacked into one create_transfers_super_jit dispatch must
produce exactly the statuses, timestamps, and final device state of K
sequential create_transfers_fast_jit dispatches (the semantics the
replica relies on when aggregating a committed window). Reference
analog: the 8-deep prepare pipeline, src/config.zig:155 — batching is a
scheduling choice and must never be observable in results.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.ops.batch import transfers_to_arrays
from tigerbeetle_tpu.ops.fast_kernels import (
    create_transfers_fast_jit,
    create_transfers_super_jit,
)
from tigerbeetle_tpu.ops.ledger import (
    DeviceLedger,
    pad_transfer_events,
    stack_superbatch,
)
from tigerbeetle_tpu.types import Account, Transfer, TransferFlags as TF

TS = 10_000_000_000_000
PAD = 256


def _fresh_state(n_accounts=8):
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, n_accounts + 1)],
        timestamp=TS,
    )
    assert led.fallbacks == 0
    return led.state


def _copy(state):
    return jax.tree.map(jnp.copy, state)


def _run_sequential(state, batches, tss):
    outs = []
    for tr, ts in zip(batches, tss):
        ev = {k: jax.device_put(v) for k, v in pad_transfer_events(
            transfers_to_arrays(tr), PAD).items()}
        state, out = create_transfers_fast_jit(
            state, ev, np.uint64(ts), np.int32(len(tr)))
        assert not bool(out["fallback"]), "sequential arm fell back"
        outs.append(out)
    return state, outs


def _run_super(state, batches, tss):
    ev_s, seg = stack_superbatch(
        [transfers_to_arrays(tr) for tr in batches], tss, PAD)
    ev_s = {k: jax.device_put(v) for k, v in ev_s.items()}
    seg = {k: jax.device_put(v) for k, v in seg.items()}
    return create_transfers_super_jit(state, ev_s, seg)


def _ht_content(table):
    """Logical content of a hash table: sorted (key_hi, key_lo, val)
    triples. Slot LAYOUT legitimately differs between sequential and
    superbatch arms (two-choice placement reads bucket occupancy at
    plan time, and the superbatch plans the whole window against the
    pre-window table) — but the mapping, hence every lookup and every
    derived result, must be identical."""
    from tigerbeetle_tpu.ops.hash_table import SLOTS

    p = np.asarray(table["packed"])[:-1]
    kh = p[:, :SLOTS].reshape(-1)
    kl = p[:, SLOTS:2 * SLOTS].reshape(-1)
    v = p[:, 2 * SLOTS:].reshape(-1)
    live = (kh != 0) | (kl != 0)
    trips = sorted(zip(kh[live].tolist(), kl[live].tolist(),
                       v[live].tolist()))
    return trips


def _assert_equal(seq_state, seq_outs, sup_state, sup_out, k):
    assert not bool(sup_out["fallback"]), "superbatch fell back"
    st = np.asarray(sup_out["r_status"]).reshape(k, PAD)
    ts = np.asarray(sup_out["r_ts"]).reshape(k, PAD)
    for b, out in enumerate(seq_outs):
        np.testing.assert_array_equal(st[b], np.asarray(out["r_status"]))
        np.testing.assert_array_equal(ts[b], np.asarray(out["r_ts"]))
    for key in seq_state:
        if key.endswith("_ht"):
            assert _ht_content(seq_state[key]) == _ht_content(
                sup_state[key]), key
            continue
        flat_seq = jax.tree.leaves(seq_state[key])
        flat_sup = jax.tree.leaves(sup_state[key])
        for a, b in zip(flat_seq, flat_sup):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=key)


def _diff_case(batches, tss):
    state = _fresh_state()
    seq_state, seq_outs = _run_sequential(_copy(state), batches, tss)
    sup_state, sup_out = _run_super(_copy(state), batches, tss)
    _assert_equal(seq_state, seq_outs, sup_state, sup_out, len(batches))


def test_regular_window():
    rng = np.random.default_rng(11)
    batches = []
    next_id = 1000
    for _ in range(3):
        trs = []
        for _ in range(40):
            dr = int(rng.integers(1, 9))
            cr = dr % 8 + 1
            trs.append(Transfer(id=next_id, debit_account_id=dr,
                                credit_account_id=cr, ledger=1, code=1,
                                amount=int(rng.integers(1, 100))))
            next_id += 1
        batches.append(trs)
    tss = [TS + 1000 + b * (PAD + 10) for b in range(3)]
    _diff_case(batches, tss)


def test_mixed_statuses_and_pendings():
    """Pendings with timeouts (pulse evolution spans the window), failures
    (not-found accounts), and posts of pendings committed BEFORE the
    window."""
    state = _fresh_state()
    # Commit a pending first (separate prepare, before the window).
    pend = [Transfer(id=500, debit_account_id=1, credit_account_id=2,
                     ledger=1, code=1, amount=50, timeout=3600,
                     flags=TF.pending)]
    ts0 = TS + 500
    ev = {k: jax.device_put(v) for k, v in pad_transfer_events(
        transfers_to_arrays(pend), PAD).items()}
    state, out = create_transfers_fast_jit(
        state, ev, np.uint64(ts0), np.int32(1))
    assert not bool(out["fallback"])

    batches = [
        # window batch 1: regular + a failing transfer + a new pending
        [Transfer(id=600, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=10),
         Transfer(id=601, debit_account_id=99, credit_account_id=2,
                  ledger=1, code=1, amount=10),
         Transfer(id=602, debit_account_id=3, credit_account_id=4,
                  ledger=1, code=1, amount=7, timeout=60,
                  flags=TF.pending)],
        # window batch 2: post the pre-window pending (full amount)
        [Transfer(id=700, pending_id=500, ledger=0, code=0,
                  amount=(1 << 128) - 1,
                  flags=TF.post_pending_transfer)],
    ]
    tss = [ts0 + 1000, ts0 + 2000]
    seq_state, seq_outs = _run_sequential(_copy(state), batches, tss)
    sup_state, sup_out = _run_super(_copy(state), batches, tss)
    _assert_equal(seq_state, seq_outs, sup_state, sup_out, 2)


def test_chain_at_boundary_does_not_merge():
    """A linked chain open at a sub-batch's end errors with
    linked_event_chain_open and must NOT absorb the next sub-batch's
    head (chains never span prepares)."""
    batches = [
        # ends with an OPEN chain: last event has linked set
        [Transfer(id=800, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=1),
         Transfer(id=801, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=1, flags=TF.linked)],
        # next sub-batch starts with a clean chain pair
        [Transfer(id=810, debit_account_id=3, credit_account_id=4,
                  ledger=1, code=1, amount=1, flags=TF.linked),
         Transfer(id=811, debit_account_id=3, credit_account_id=4,
                  ledger=1, code=1, amount=1)],
    ]
    tss = [TS + 1000, TS + 2000]
    _diff_case(batches, tss)
    # And the failing-chain case: poison inside a chain in batch 2.
    batches2 = [
        [Transfer(id=820, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=1)],
        [Transfer(id=830, debit_account_id=3, credit_account_id=4,
                  ledger=1, code=1, amount=1, flags=TF.linked),
         Transfer(id=831, debit_account_id=77, credit_account_id=4,
                  ledger=1, code=1, amount=1)],
    ]
    _diff_case(batches2, [TS + 3000, TS + 4000])


def test_cross_batch_duplicate_falls_back():
    """A duplicate id across the window's sub-batches is a cross-prepare
    dependency: the superbatch must fall back (the caller then executes
    the window sequentially), never silently diverge."""
    state = _fresh_state()
    batches = [
        [Transfer(id=900, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=1)],
        [Transfer(id=900, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=1)],
    ]
    tss = [TS + 1000, TS + 2000]
    _, sup_out = _run_super(_copy(state), batches, tss)
    assert bool(sup_out["fallback"])


def test_state_machine_commit_window_parity():
    """StateMachine.commit_window replies byte-identically to per-body
    commit, including multi-inner-batch bodies and served lookups
    afterward."""
    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.state_machine import (
        OPERATION_SPECS,
        StateMachine,
    )
    from tigerbeetle_tpu.types import Operation

    def fresh():
        sm = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 12)
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in range(1, 9)], TS)
        return sm

    spec = OPERATION_SPECS[Operation.create_transfers]

    def payload(ids):
        return b"".join(
            Transfer(id=i, debit_account_id=(i % 8) + 1,
                     credit_account_id=(i % 8) % 8 + 2
                     if (i % 8) + 1 != (i % 8) % 8 + 2 else 1,
                     ledger=1, code=1, amount=1 + i % 97).pack()
            for i in ids)

    bodies = [
        multi_batch.encode([payload(range(1000, 1020))], spec.event_size),
        # two inner batches in one prepare
        multi_batch.encode([payload(range(2000, 2010)),
                            payload(range(2100, 2130))], spec.event_size),
        multi_batch.encode([payload(range(3000, 3040))], spec.event_size),
        multi_batch.encode([payload(range(4000, 4004))], spec.event_size),
    ]
    tss = [TS + 10_000 + i * 1000 for i in range(4)]

    sm_a = fresh()
    seq = [sm_a.commit(Operation.create_transfers, b, ts)
           for b, ts in zip(bodies, tss)]
    sm_b = fresh()
    win = sm_b.commit_window(Operation.create_transfers, bodies, tss)
    assert seq == win
    assert sm_b.led.window_fallbacks == 0
    # Served state agrees.
    a = sm_a.lookup_accounts(list(range(1, 9)))
    b = sm_b.lookup_accounts(list(range(1, 9)))
    assert [(x.id, x.debits_posted, x.credits_posted) for x in a] == \
           [(x.id, x.debits_posted, x.credits_posted) for x in b]


def test_commit_window_cross_prepare_dup_seq_fallback():
    """A window with a duplicate id across prepares produces the same
    replies as sequential commits. Since the chain route became the
    default dispatch mode (round 7) this resolves NATIVELY: prepare 2
    executes against the state prepare 1 evolved inside the one scan
    dispatch, so the duplicate reads 'exists' with ZERO fallbacks —
    the flat superbatch used to throw the whole window away (E2)."""
    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.state_machine import (
        OPERATION_SPECS,
        StateMachine,
    )
    from tigerbeetle_tpu.types import Operation

    def fresh():
        sm = StateMachine(engine="device", a_cap=1 << 10, t_cap=1 << 12)
        sm.create_accounts(
            [Account(id=i, ledger=1, code=1) for i in range(1, 9)], TS)
        return sm

    spec = OPERATION_SPECS[Operation.create_transfers]
    tr = Transfer(id=5000, debit_account_id=1, credit_account_id=2,
                  ledger=1, code=1, amount=9).pack()
    bodies = [multi_batch.encode([tr], spec.event_size),
              multi_batch.encode([tr], spec.event_size)]
    tss = [TS + 50_000, TS + 51_000]
    sm_a = fresh()
    seq = [sm_a.commit(Operation.create_transfers, b, ts)
           for b, ts in zip(bodies, tss)]
    sm_b = fresh()
    win = sm_b.commit_window(Operation.create_transfers, bodies, tss)
    assert seq == win
    assert sm_b.led.window_fallbacks == 0
    assert sm_b.led.fallback_stats()["routes"]["windows"] == {"chain": 1}


def test_replica_catchup_windows_preserve_determinism():
    """A lagging device-engine replica catches up through WINDOWED
    commits (commit_journal forms windows over the replayed suffix)
    while its peers committed the same ops one at a time — physical
    checkpoints must still be byte-identical across replicas (the
    storage checker is the arbiter; per-op flush cadence with exact
    chunk attribution is what makes this hold)."""
    from tigerbeetle_tpu import multi_batch
    from tigerbeetle_tpu.state_machine import StateMachine
    from tigerbeetle_tpu.testing.cluster import Cluster
    from tigerbeetle_tpu.types import Operation

    cluster = Cluster(
        seed=31, replica_count=3,
        state_machine_factory=lambda: StateMachine(
            engine="device", a_cap=1 << 10, t_cap=1 << 12))
    client = cluster.client(77)

    def drive(op, body, ticks=4000):
        client.request(op, body)
        ok = cluster.run(ticks, until=lambda: client.idle)
        assert ok, cluster.debug_status()

    drive(Operation.create_accounts, multi_batch.encode(
        [b"".join(Account(id=i, ledger=1, code=1).pack()
                  for i in (1, 2, 3))], 128))
    victim = (cluster.replicas[0].primary_index() + 1) % 3
    cluster.crash(victim)
    # Lag by a multi-op suffix SMALL enough to stay below the state-sync
    # threshold (WAL replay, where windows form), then cross the
    # checkpoint boundary after the restart so every replica checkpoints
    # the same op for the byte-identity check.
    interval = cluster.replicas[0].options.checkpoint_interval
    lagged = max(4, interval - 8)
    k = 0
    for _ in range(lagged):
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=5000 + k, debit_account_id=1,
                      credit_account_id=2, amount=1 + (k % 7),
                      ledger=1, code=1).pack()], 128))
        k += 1
    cluster.restart(victim)
    cluster.settle()
    r = cluster.replicas[victim]
    assert getattr(r, "_windows_committed", 0) >= 1, \
        "catch-up replay never formed a commit window"
    for _ in range(12):
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=5000 + k, debit_account_id=1,
                      credit_account_id=2, amount=1 + (k % 7),
                      ledger=1, code=1).pack()], 128))
        k += 1
    cluster.settle()
    assert all(rep.superblock.op_checkpoint > 0
               for rep in cluster.replicas)
    total = sum(1 + (j % 7) for j in range(k))
    assert r.state_machine.state.accounts[2].credits_posted == total
    cluster.check_convergence()
    cluster.check_storage()


def test_varying_batch_sizes():
    rng = np.random.default_rng(13)
    batches = []
    next_id = 2000
    for n in (1, 37, 200):
        trs = []
        for _ in range(n):
            dr = int(rng.integers(1, 9))
            cr = dr % 8 + 1
            trs.append(Transfer(id=next_id, debit_account_id=dr,
                                credit_account_id=cr, ledger=1, code=1,
                                amount=int(rng.integers(1, 100))))
            next_id += 1
        batches.append(trs)
    tss = [TS + 1000 + b * (PAD + 10) for b in range(3)]
    _diff_case(batches, tss)


def test_balancing_window():
    """Balancing clamps whose cascades span prepare boundaries run
    natively on the balancing super tier, bit-exact vs sequential
    dispatches of the per-batch balancing kernel (amounts re-derived
    from exact prefix balances across the WHOLE window)."""
    from tigerbeetle_tpu.ops.fast_kernels import (
        create_transfers_balancing_jit,
        create_transfers_super_balancing_jit,
    )

    AMOUNT_MAX = (1 << 128) - 1
    BAL_DR = int(TF.balancing_debit)
    BAL_CR = int(TF.balancing_credit)
    PEND = int(TF.pending)

    state = _fresh_state()
    # Fund: account 1 gets 300 credits, account 3 gets 120 debits.
    fund = [Transfer(id=900, debit_account_id=2, credit_account_id=1,
                     amount=300, ledger=1, code=1),
            Transfer(id=901, debit_account_id=3, credit_account_id=4,
                     amount=120, ledger=1, code=1)]
    ev = {k: jax.device_put(v) for k, v in pad_transfer_events(
        transfers_to_arrays(fund), PAD).items()}
    state, out = create_transfers_fast_jit(
        state, ev, np.uint64(TS + 500), np.int32(2))
    assert not bool(out["fallback"])

    batches = [
        # prepare 1: sweep most of account 1's headroom, hold some.
        [Transfer(id=1000, debit_account_id=1, credit_account_id=5,
                  amount=200, ledger=1, code=1, flags=BAL_DR),
         Transfer(id=1001, debit_account_id=1, credit_account_id=5,
                  amount=AMOUNT_MAX, ledger=1, code=1,
                  flags=BAL_DR | PEND, timeout=3600)],
        # prepare 2: the clamp here must see prepare 1's effects: zero
        # headroom left on 1; balancing_credit into 3 clamps at 120.
        [Transfer(id=1010, debit_account_id=1, credit_account_id=5,
                  amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
         Transfer(id=1011, debit_account_id=6, credit_account_id=3,
                  amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_CR)],
        # prepare 3: both flags; headroom restored by new funding.
        [Transfer(id=1020, debit_account_id=5, credit_account_id=1,
                  amount=50, ledger=1, code=1),
         Transfer(id=1021, debit_account_id=1, credit_account_id=3,
                  amount=AMOUNT_MAX, ledger=1, code=1,
                  flags=BAL_DR | BAL_CR)],
    ]
    tss = [TS + 1000 + b * (PAD + 10) for b in range(3)]

    # Sequential arm on the per-batch balancing tier.
    seq_state = _copy(state)
    seq_outs = []
    for tr, ts in zip(batches, tss):
        evb = {k: jax.device_put(v) for k, v in pad_transfer_events(
            transfers_to_arrays(tr), PAD).items()}
        seq_state, o = create_transfers_balancing_jit(
            seq_state, evb, np.uint64(ts), np.int32(len(tr)))
        assert not bool(o["fallback"]), "sequential balancing arm fell back"
        seq_outs.append(o)

    ev_s, seg = stack_superbatch(
        [transfers_to_arrays(tr) for tr in batches], tss, PAD)
    ev_s = {k: jax.device_put(v) for k, v in ev_s.items()}
    seg = {k: jax.device_put(v) for k, v in seg.items()}
    sup_state, sup_out = create_transfers_super_balancing_jit(
        _copy(state), ev_s, seg)
    _assert_equal(seq_state, seq_outs, sup_state, sup_out, len(batches))


def test_balancing_window_through_ledger_vs_oracle():
    """create_transfers_window with balancing prepares: native (no
    window fallback), results and balances identical to the oracle fed
    the same prepares sequentially."""
    from tigerbeetle_tpu.oracle import StateMachineOracle

    AMOUNT_MAX = (1 << 128) - 1
    BAL_DR = int(TF.balancing_debit)

    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
    sm = StateMachineOracle()
    accts = [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
    for eng in (led, sm):
        r = eng.create_accounts(accts, TS)
        assert all(x.status.name == "created" for x in r)
    fund = [Transfer(id=900, debit_account_id=2, credit_account_id=1,
                     amount=100, ledger=1, code=1)]
    got = led.create_transfers(fund, TS + 500)
    want = sm.create_transfers(fund, TS + 500)
    assert [(r.timestamp, r.status) for r in got] == \
           [(r.timestamp, r.status) for r in want]

    batches = [
        [Transfer(id=1000, debit_account_id=1, credit_account_id=5,
                  amount=60, ledger=1, code=1, flags=BAL_DR)],
        [Transfer(id=1010, debit_account_id=1, credit_account_id=5,
                  amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR),
         Transfer(id=1011, debit_account_id=1, credit_account_id=5,
                  amount=AMOUNT_MAX, ledger=1, code=1, flags=BAL_DR)],
    ]
    tss = [TS + 1000, TS + 1000 + PAD + 10]
    evs = [transfers_to_arrays(tr) for tr in batches]
    res = led.create_transfers_window(evs, tss)
    assert res is not None and led.window_fallbacks == 0
    flat = []
    for (st, ts_arr), tr in zip(res, batches):
        flat += [(int(t), int(s)) for s, t in zip(st, ts_arr)]
    want = []
    for tr, ts in zip(batches, tss):
        want += [(r.timestamp, int(r.status))
                 for r in sm.create_transfers(tr, ts)]
    assert flat == want
    # Clamp cascade across the window: 60, then 40, then 0.
    assert [t.amount for t in led.lookup_transfers([1000, 1010, 1011])] \
        == [60, 40, 0]
    a_led = {a.id: a for a in led.lookup_accounts([1, 5])}
    a_sm = {a.id: a for a in sm.lookup_accounts([1, 5])}
    assert a_led == a_sm
