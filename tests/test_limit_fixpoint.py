"""Order-dependent balance limits on device: the K-round status fixpoint.

reference: the exceeds_credits/exceeds_debits checks
(src/tigerbeetle.zig:34-42, src/state_machine.zig:3903-3904) whose
sequential semantics (event i sees every successful earlier event's
balances) previously forced a host fallback whenever the worst-case
headroom proof failed.
"""

import numpy as np

from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    Transfer,
    TransferFlags,
)

DR_LIMIT = int(AccountFlags.debits_must_not_exceed_credits)
CR_LIMIT = int(AccountFlags.credits_must_not_exceed_debits)
LINKED = int(TransferFlags.linked)
PENDING = int(TransferFlags.pending)
VOID = int(TransferFlags.void_pending_transfer)


def _pair():
    led = DeviceLedger(a_cap=1 << 12, t_cap=1 << 14)
    sm = StateMachineOracle()
    return led, sm


def _both(led, sm, events, ts):
    got = led.create_transfers(events, ts)
    want = sm.create_transfers(events, ts)
    assert ([(r.timestamp, r.status) for r in got]
            == [(r.timestamp, r.status) for r in want]), (
        [r.status.name for r in got], [r.status.name for r in want])
    return [r.status.name for r in got]


def _setup(led, sm, accounts, fund=()):
    for eng in (led, sm):
        res = eng.create_accounts(accounts, 100)
        assert all(r.status.name == "created" for r in res)
    ts = 10**12
    for i, (dr, cr, amt) in enumerate(fund):
        _both(led, sm, [Transfer(id=900 + i, debit_account_id=dr,
                                 credit_account_id=cr, amount=amt,
                                 ledger=1, code=1)], ts)
        ts += 10
    return ts


class TestLimitFixpoint:
    def test_simple_breach_resolved_on_device(self):
        """Two debits whose sum breaches the headroom: the first passes,
        the second fails exceeds_credits — on device (no host fallback)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1, flags=DR_LIMIT),
                     Account(id=2, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=1, debit_account_id=1, credit_account_id=2,
                     amount=60, ledger=1, code=1),
            Transfer(id=2, debit_account_id=1, credit_account_id=2,
                     amount=60, ledger=1, code=1)], ts)
        assert st == ["created", "exceeds_credits"]
        assert led.fallbacks == 0 and led.fixpoint_batches == 1

    def test_mid_batch_void_relief_honored(self):
        """A void earlier in the batch releases pending debits; the later
        debit passes exactly as the sequential semantics dictate (the
        worst-case proof ignores relief and must NOT decide this)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1, flags=DR_LIMIT),
                     Account(id=2, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=10, debit_account_id=1, credit_account_id=2,
                     amount=50, ledger=1, code=1, flags=PENDING)], ts)
        assert st == ["created"]
        ts += 10
        st = _both(led, sm, [
            Transfer(id=11, pending_id=10, flags=VOID),
            Transfer(id=12, debit_account_id=1, credit_account_id=2,
                     amount=90, ledger=1, code=1)], ts)
        assert st == ["created", "created"]
        assert led.fixpoint_batches >= 1 and led.fallbacks == 0

    def test_cascade_failure_frees_room_for_later_event(self):
        """[80, 80, 15] against headroom 100: the middle failure releases
        its load, so the third passes — a two-wave cascade the fixpoint
        resolves (round 1 fails both; round 2 re-admits the third)."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1, flags=DR_LIMIT),
                     Account(id=2, ledger=1, code=1)],
                    fund=[(2, 1, 100)])
        st = _both(led, sm, [
            Transfer(id=20, debit_account_id=1, credit_account_id=2,
                     amount=80, ledger=1, code=1),
            Transfer(id=21, debit_account_id=1, credit_account_id=2,
                     amount=80, ledger=1, code=1),
            Transfer(id=22, debit_account_id=1, credit_account_id=2,
                     amount=15, ledger=1, code=1)], ts)
        assert st == ["created", "exceeds_credits", "created"]
        assert led.fixpoint_batches == 1 and led.fallbacks == 0

    def test_chain_rollback_interacts_with_limits(self):
        """A limit failure breaks its chain; the rolled-back member's load
        disappears, which re-admits a later event on the OTHER account."""
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1, flags=DR_LIMIT),
                     Account(id=3, ledger=1, code=1, flags=DR_LIMIT),
                     Account(id=2, ledger=1, code=1)],
                    fund=[(2, 1, 100), (2, 3, 100)])
        st = _both(led, sm, [
            # Chain: the breach on account 1 rolls back the account-3 leg.
            Transfer(id=30, debit_account_id=1, credit_account_id=2,
                     amount=150, ledger=1, code=1, flags=LINKED),
            Transfer(id=31, debit_account_id=3, credit_account_id=2,
                     amount=70, ledger=1, code=1),
            # Passes only because id=31 was rolled back (70+70 > 100).
            Transfer(id=32, debit_account_id=3, credit_account_id=2,
                     amount=70, ledger=1, code=1)], ts)
        assert st == ["exceeds_credits", "linked_event_failed", "created"]
        assert led.fixpoint_batches == 1 and led.fallbacks == 0

    def test_credit_side_limit(self):
        led, sm = _pair()
        ts = _setup(led, sm,
                    [Account(id=1, ledger=1, code=1),
                     Account(id=2, ledger=1, code=1, flags=CR_LIMIT)],
                    fund=[(2, 1, 40)])
        st = _both(led, sm, [
            Transfer(id=40, debit_account_id=1, credit_account_id=2,
                     amount=30, ledger=1, code=1),
            Transfer(id=41, debit_account_id=1, credit_account_id=2,
                     amount=30, ledger=1, code=1)], ts)
        assert st == ["created", "exceeds_debits"]
        assert led.fixpoint_batches == 1 and led.fallbacks == 0

    def test_randomized_limit_heavy_parity(self):
        """Randomized limit-heavy workload: device (fast + fixpoint) stays
        bit-exact vs the oracle, and the final states match."""
        rng = np.random.default_rng(17)
        led, sm = _pair()
        accounts = [Account(id=i, ledger=1, code=1,
                            flags=DR_LIMIT if i % 3 == 0 else
                            (CR_LIMIT if i % 3 == 1 else 0))
                    for i in range(1, 17)]
        ts = _setup(led, sm, accounts,
                    fund=[(2, i, 200) for i in range(3, 16, 3)])
        next_id = 1000
        for _ in range(6):
            events = []
            for _ in range(64):
                dr = int(rng.integers(1, 17))
                cr = int(rng.integers(1, 17))
                if dr == cr:
                    cr = dr % 16 + 1
                events.append(Transfer(
                    id=next_id, debit_account_id=dr, credit_account_id=cr,
                    amount=int(rng.integers(1, 120)), ledger=1, code=1,
                    flags=LINKED if rng.random() < 0.1 else 0))
                next_id += 1
            if events[-1].flags & LINKED:
                events[-1] = Transfer(
                    id=events[-1].id,
                    debit_account_id=events[-1].debit_account_id,
                    credit_account_id=events[-1].credit_account_id,
                    amount=events[-1].amount, ledger=1, code=1)
            ts += 100
            _both(led, sm, events, ts)
        host = led.to_host()
        assert host.accounts == sm.accounts
        assert host.transfers == sm.transfers
        assert led.fixpoint_batches >= 1, "workload must hit the fixpoint"
