"""Release tooling: version bump, commit classification, changelog
assembly, preflight (reference analog: src/scripts/release.zig +
changelog.zig)."""

import importlib.util
import os

import pytest

spec = importlib.util.spec_from_file_location(
    "release", os.path.join(os.path.dirname(__file__), "..", "scripts",
                            "release.py"))
release = importlib.util.module_from_spec(spec)
spec.loader.exec_module(release)


def test_bump_levels():
    assert release.bump("1.2.3", "patch") == "1.2.4"
    assert release.bump("1.2.3", "minor") == "1.3.0"
    assert release.bump("1.2.3", "major") == "2.0.0"
    with pytest.raises(AssertionError):
        release.bump("1.2.3", "nightly")


def test_classify_routes_by_first_matching_prefix():
    assert release.classify(
        ["tigerbeetle_tpu/ops/ledger.py"]) == "Kernel & device engine"
    assert release.classify(
        ["tigerbeetle_tpu/vsr/replica.py"]) == "Consensus & durability"
    assert release.classify(["native/tb_client.cpp"]) == "Native runtime"
    assert release.classify(["clients/go/types.go"]) == "Clients"
    assert release.classify(["README.md"]) == "Other"
    # package fallback comes after the specific subtrees
    assert release.classify(
        ["tigerbeetle_tpu/state_machine.py"]) == "State machine & framework"


def test_changelog_section_grouping_and_order():
    commits = [
        {"sha": "aaa", "subject": "Fix replica repair",
         "files": ["tigerbeetle_tpu/vsr/replica.py"]},
        {"sha": "bbb", "subject": "Faster kernel",
         "files": ["tigerbeetle_tpu/ops/fast_kernels.py"]},
        {"sha": "ccc", "subject": "Go client fix",
         "files": ["clients/go/types.go"]},
    ]
    sec = release.changelog_section("1.0.0", commits, date="2026-08-01")
    assert sec.startswith("## 1.0.0 — 2026-08-01")
    k = sec.index("Kernel & device engine")
    v = sec.index("Consensus & durability")
    c = sec.index("Clients")
    assert k < v < c  # canonical area order
    assert "- Faster kernel (`bbb`)" in sec


def test_current_version_and_preflight():
    v = release.current_version()
    assert len(v.split(".")) == 3
    # Not asserting cleanliness (the working tree varies in dev); the
    # version-shape check must pass on the real repo.
    problems = release.preflight(require_clean=False)
    assert all("semver" not in p for p in problems)
