"""Unified grid-block header (reference: src/lsm/schema.zig:624): every
grid block self-describes; misdirected or misclassified reads fail
loudly instead of misparsing."""

import pytest

from tigerbeetle_tpu.lsm.forest import Forest
from tigerbeetle_tpu.lsm.grid import Grid, MemoryDevice
from tigerbeetle_tpu.lsm.schema import (
    BLOCK_HEADER_SIZE,
    BlockKind,
    classify,
    unwrap,
    wrap,
)


def _forest():
    grid = Grid(MemoryDevice(8192 * 512), block_size=8192, block_count=512)
    return Forest(grid, {"a": (8, 16), "b": (8, 16)}), grid


def test_wrap_unwrap_roundtrip_and_kind_check():
    payload = b"\x07" * 100
    raw = wrap(BlockKind.value, payload, tree_id=5)
    assert len(raw) == BLOCK_HEADER_SIZE + 100
    assert unwrap(raw, BlockKind.value) == payload
    assert classify(raw) == (BlockKind.value, 5, 100)
    with pytest.raises(ValueError, match="kind"):
        unwrap(raw, BlockKind.index)
    with pytest.raises(ValueError, match="magic"):
        unwrap(b"\x00" * 64, BlockKind.value)


def test_every_grid_block_is_classifiable():
    """After real tree activity + a checkpoint, every allocated block
    carries a valid header with the right kind and tree id."""
    forest, grid = _forest()
    tree_a = forest.trees["a"]
    for i in range(3000):
        tree_a.put(i.to_bytes(8, "big"), bytes(16))
    for op in range(1, 97):
        forest.compact_beat(op)
    forest.checkpoint()
    kinds = set()
    seen_tree_ids = set()
    for index, free in enumerate(grid.free):
        if free:
            continue
        raw = grid.device.read(index * grid.block_size, grid.block_size)
        got = classify(raw)
        assert got is not None, f"block {index} carries no valid header"
        kind, tree_id, _ = got
        kinds.add(kind)
        seen_tree_ids.add(tree_id)
    assert BlockKind.value in kinds and BlockKind.index in kinds
    assert BlockKind.manifest in kinds
    assert 1 in seen_tree_ids  # tree "a" (sorted-name id 1)


def test_misdirected_block_read_fails_loudly():
    """A valid VALUE block served where an INDEX block is expected (the
    misdirected-write shape) must raise, not misparse."""
    from tigerbeetle_tpu.lsm.table import Table, TableInfo, write_value_block

    forest, grid = _forest()
    addr, size, _first = write_value_block(
        grid, [(b"k" * 8, b"v" * 16)], tree_id=1)
    info = TableInfo(index_address=addr, index_size=size,
                     key_min=b"k" * 8, key_max=b"k" * 8, entry_count=1)
    with pytest.raises(ValueError, match="kind"):
        Table(grid, info, 8, 16)
