"""Manifest level structure: snapshot ranges, point-in-time reads, pruning.

reference: src/lsm/manifest_level.zig (per-level (key x snapshot) index),
manifest.zig TableInfo snapshot_min/snapshot_max lifecycle.
"""

import dataclasses

from tigerbeetle_tpu.lsm.manifest_level import (
    SNAPSHOT_LATEST,
    ManifestLevel,
)
from tigerbeetle_tpu.lsm.grid import Grid, MemoryDevice
from tigerbeetle_tpu.lsm.tree import BAR_LENGTH, Tree


@dataclasses.dataclass
class _Info:
    key_min: bytes
    key_max: bytes


class _FakeTable:
    def __init__(self, key_min: bytes, key_max: bytes):
        self.info = _Info(key_min, key_max)


def k(i: int) -> bytes:
    return i.to_bytes(4, "big")


class TestManifestLevel:
    def test_insert_keeps_key_order(self):
        lvl = ManifestLevel(keep_sorted=True)
        for lo in (30, 10, 20):
            lvl.insert(_FakeTable(k(lo), k(lo + 5)), snapshot=1)
        assert [t.info.key_min for t in lvl] == [k(10), k(20), k(30)]

    def test_lookup_latest_binary_search(self):
        lvl = ManifestLevel(keep_sorted=True)
        t1 = _FakeTable(k(10), k(19))
        t2 = _FakeTable(k(20), k(29))
        lvl.insert(t1, 1)
        lvl.insert(t2, 1)
        assert lvl.lookup(k(15)) == [t1]
        assert lvl.lookup(k(20)) == [t2]
        assert lvl.lookup(k(35)) == []
        assert lvl.lookup(k(5)) == []

    def test_removed_entry_visible_to_older_snapshots(self):
        lvl = ManifestLevel(keep_sorted=True)
        old = _FakeTable(k(10), k(29))
        lvl.insert(old, snapshot=5)
        lvl.remove(old, snapshot=40)
        new = _FakeTable(k(10), k(29))
        lvl.insert(new, snapshot=40)
        # Latest sees only the replacement; snapshot 39 sees the original.
        assert lvl.lookup(k(15)) == [new]
        assert lvl.lookup(k(15), snapshot=39) == [old]
        assert lvl.lookup(k(15), snapshot=40) == [new]
        # Visibility bounds: not visible before its snapshot_min.
        assert lvl.lookup(k(15), snapshot=4) == []

    def test_level0_recency_order(self):
        lvl = ManifestLevel(keep_sorted=False)
        a = _FakeTable(k(0), k(99))
        b = _FakeTable(k(0), k(99))
        lvl.insert(a, 10)
        lvl.insert(b, 20)
        # lookup returns newest-first for overlapping L0 tables.
        assert lvl.lookup(k(5), snapshot=25) == [b, a]
        assert lvl.lookup(k(5), snapshot=15) == [a]

    def test_prune_returns_only_stale_history(self):
        lvl = ManifestLevel(keep_sorted=True)
        t1 = _FakeTable(k(0), k(9))
        t2 = _FakeTable(k(10), k(19))
        lvl.insert(t1, 1)
        lvl.insert(t2, 1)
        lvl.remove(t1, snapshot=32)
        lvl.remove(t2, snapshot=64)
        assert lvl.prune(snapshot_oldest=32) == [t1]
        assert [e.table for e in lvl.history] == [t2]
        assert lvl.prune(snapshot_oldest=32) == []
        assert lvl.prune(snapshot_oldest=64) == [t2]

    def test_query_range_at_snapshot(self):
        lvl = ManifestLevel(keep_sorted=True)
        t1 = _FakeTable(k(0), k(9))
        t2 = _FakeTable(k(10), k(19))
        t3 = _FakeTable(k(20), k(29))
        for t in (t1, t2, t3):
            lvl.insert(t, 1)
        lvl.remove(t2, snapshot=10)
        assert lvl.query(k(5), k(25)) == [t1, t3]
        assert lvl.query(k(5), k(25), snapshot=9) == [t1, t2, t3]
        assert lvl.query(k(12), k(15), snapshot=9) == [t2]
        assert lvl.query(k(12), k(15)) == []


def _tree(value_size=16, blocks=4096, block_size=512):
    grid = Grid(MemoryDevice(blocks * block_size), block_size=block_size,
                block_count=blocks)
    return Tree(grid, key_size=8, value_size=value_size, name="t"), grid


def _put(tree, i: int, tag: bytes):
    tree.put(i.to_bytes(8, "big"), tag.ljust(16, b"\0"))


class TestTreeSnapshots:
    def test_point_in_time_read_survives_compaction(self):
        """A value overwritten and compacted away stays readable at the
        snapshot where it was live (within the retention bar)."""
        tree, _ = _tree()
        op = 0

        def advance_bar():
            nonlocal op
            for _ in range(BAR_LENGTH):
                op += 1
                tree.compact_beat(op)

        _put(tree, 1, b"v1")
        advance_bar()  # flush: v1 lands in L0 at snapshot s1
        s1 = op
        _put(tree, 1, b"v2")
        advance_bar()  # flush v2; compaction may rewrite tables
        assert tree.get((1).to_bytes(8, "big")) == b"v2".ljust(16, b"\0")
        assert tree.get((1).to_bytes(8, "big"),
                        snapshot=s1) == b"v1".ljust(16, b"\0")
        # Scans honor the snapshot too.
        rows = tree.scan((0).to_bytes(8, "big"), (9).to_bytes(8, "big"),
                         snapshot=s1)
        assert rows == [((1).to_bytes(8, "big"), b"v1".ljust(16, b"\0"))]

    def test_prune_frees_blocks_deterministically(self):
        """Two replicas running the same op sequence release identical
        block sets; removed tables' blocks stay allocated for at least one
        bar (the snapshot retention window)."""
        def run():
            tree, grid = _tree()
            op = 0
            for bar in range(6):
                for i in range(40):
                    _put(tree, bar * 100 + i, b"x%d" % bar)
                for _ in range(BAR_LENGTH):
                    op += 1
                    tree.compact_beat(op)
            return tree, grid

        t1, g1 = run()
        t2, g2 = run()
        assert g1.checkpoint_free_set() == g2.checkpoint_free_set()
        # History exists at some point during the run; by the final bar
        # boundary, entries older than one bar are pruned.
        oldest = t1.beat - BAR_LENGTH
        for lvl in t1.levels:
            for e in lvl.history:
                assert e.snapshot_max > oldest

    def test_manifest_roundtrip_preserves_history(self):
        tree, grid = _tree()
        op = 0
        for bar in range(4):
            for i in range(60):
                _put(tree, i, b"b%d" % bar)
            for _ in range(BAR_LENGTH):
                op += 1
                tree.compact_beat(op)
        blob = tree.manifest_pack()
        tree2 = Tree(grid, key_size=8, value_size=16, name="t")
        tree2.manifest_restore(blob)
        for a, b in zip(tree.levels, tree2.levels):
            assert ([(e.snapshot_min, e.snapshot_max, e.key_min)
                     for e in a.live]
                    == [(e.snapshot_min, e.snapshot_max, e.key_min)
                        for e in b.live])
            assert ([(e.snapshot_min, e.snapshot_max, e.key_min)
                     for e in a.history]
                    == [(e.snapshot_min, e.snapshot_max, e.key_min)
                        for e in b.history])
        assert (tree2.manifest_pack() == tree.manifest_pack())


class TestRestoreRecency:
    def test_post_restore_flush_wins_over_restored_tables(self):
        """Regression (cfo seeds 41760302, 819016629): a manifest restore
        must preserve the op clock and insertion sequence — a flush right
        after restore previously stamped snapshot 0, inverting level-0
        recency so restored tables shadowed newer overwrites."""
        from tigerbeetle_tpu.lsm.forest import Forest

        grid = Grid(MemoryDevice(4096 * 512), block_size=512,
                    block_count=4096)
        forest = Forest(grid, {"t": (8, 16)})
        tree = forest.trees["t"]
        key = (7).to_bytes(8, "big")
        tree.put(key, b"old".ljust(16, b"\0"))
        tree.compact_beat(32)  # flush at a bar boundary
        root = forest.checkpoint()
        fresh = Forest(grid, {"t": (8, 16)})
        fresh.open(root)
        tree2 = fresh.trees["t"]
        assert tree2.beat == 32, "restore must keep the op clock"
        tree2.put(key, b"new".ljust(16, b"\0"))
        # Checkpoint-time flush (no intervening compact_beat): the new
        # table must still rank newer than the restored one.
        fresh.checkpoint()
        assert tree2.get(key) == b"new".ljust(16, b"\0")
        assert dict(tree2.scan(b"\0" * 8, b"\xff" * 8)) == {
            key: b"new".ljust(16, b"\0")}

    def test_seq_determinism_across_restore(self):
        """A restored replica's manifest must stay byte-identical to a
        never-restarted one for the same op sequence — including the
        insertion-sequence counters (re-deriving next_seq from surviving
        entries diverges once the max-seq entry is pruned)."""
        from tigerbeetle_tpu.lsm.forest import Forest

        def run(restart):
            grid = Grid(MemoryDevice(8192 * 512), block_size=512,
                        block_count=8192)
            forest = Forest(grid, {"t": (8, 16)})
            tree = forest.trees["t"]
            op = 0
            for bar in range(8):
                for i in range(30):
                    _put(tree, (bar * 7 + i) % 50, b"b%d" % bar)
                for _ in range(BAR_LENGTH):
                    op += 1
                    tree.compact_beat(op)
                if bar == 4:
                    # BOTH runs checkpoint here (checkpoints apply grid
                    # frees, so the schedule must match); only one
                    # restarts from it.
                    root = forest.checkpoint()
                    if restart:
                        forest = Forest(grid, {"t": (8, 16)})
                        forest.open(root)
                        tree = forest.trees["t"]
            return tree.manifest_pack()

        assert run(restart=False) == run(restart=True)
