"""Differential tests: JAX kernel vs oracle, bit-identical results and state.

This is the TPU analog of the reference's state-machine oracle tests
(src/state_machine_tests.zig) plus a state_machine_fuzz-style randomized
generator with bit-edge-biased integers (src/state_machine_fuzz.zig:17-35).
"""

import random

import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.constants import NS_PER_S, U128_MAX
from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops import run_create_accounts, run_create_transfers
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags,
    Transfer,
    TransferFlags as TF,
)

TS_BASE = 10_000_000_000_000


def assert_state_equal(oracle: StateMachineOracle, kstate: StateMachineOracle):
    assert oracle.accounts == kstate.accounts
    assert oracle.transfers == kstate.transfers
    assert oracle.orphaned == kstate.orphaned
    assert oracle.pending_status == kstate.pending_status
    assert oracle.expiry == kstate.expiry
    assert oracle.accounts_key_max == kstate.accounts_key_max
    assert oracle.transfers_key_max == kstate.transfers_key_max
    assert oracle.pulse_next_timestamp == kstate.pulse_next_timestamp
    assert oracle.account_by_timestamp == kstate.account_by_timestamp
    assert oracle.transfer_by_timestamp == kstate.transfer_by_timestamp
    assert oracle.account_events == kstate.account_events


class Differ:
    """Drives the same operations through the oracle and the kernel path."""

    def __init__(self):
        self.oracle = StateMachineOracle()
        self.kstate = StateMachineOracle()  # plain state store for the kernel

    def create_accounts(self, events, timestamp):
        expect = self.oracle.create_accounts(events, timestamp)
        got = run_create_accounts(self.kstate, events, timestamp)
        self._compare(expect, got, events)
        return expect

    def create_transfers(self, events, timestamp):
        expect = self.oracle.create_transfers(events, timestamp)
        got = run_create_transfers(self.kstate, events, timestamp)
        self._compare(expect, got, events)
        return expect

    def _compare(self, expect, got, events):
        for i, (e, g) in enumerate(zip(expect, got)):
            assert (e.timestamp, e.status) == (g.timestamp, g.status), (
                f"event {i}: oracle ({e.timestamp}, {e.status!r}) != "
                f"kernel ({g.timestamp}, {g.status!r})\n  event: {events[i]}"
            )
        assert_state_equal(self.oracle, self.kstate)


def two_accounts(d: Differ, **kwargs):
    d.create_accounts(
        [Account(id=1, ledger=1, code=1, **kwargs), Account(id=2, ledger=1, code=1, **kwargs)],
        TS_BASE,
    )


class TestKernelScenarios:
    def test_simple_and_errors(self):
        d = Differ()
        two_accounts(d)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=0, ledger=1, code=1),
                Transfer(id=0),
                Transfer(id=U128_MAX),
                Transfer(id=3, debit_account_id=1, credit_account_id=1, ledger=1, code=1),
                Transfer(id=3, debit_account_id=1, credit_account_id=9, ledger=1, code=1),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, ledger=1, code=1),
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1),
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=99, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )

    def test_account_scenarios(self):
        d = Differ()
        d.create_accounts(
            [
                Account(id=1, ledger=1, code=1),
                Account(id=1, ledger=1, code=1),  # exists
                Account(id=1, ledger=2, code=1),  # exists_with_different_ledger
                Account(id=0, ledger=1, code=1),
                Account(id=2, ledger=1, code=1, reserved=9),
                Account(id=3, ledger=1, code=1, debits_posted=5),
                Account(id=4, ledger=0, code=1),
                Account(id=5, ledger=1, code=1, flags=int(AccountFlags.history)),
            ],
            TS_BASE,
        )

    def test_account_chains(self):
        d = Differ()
        linked = int(AccountFlags.linked)
        d.create_accounts(
            [
                Account(id=1, ledger=1, code=1, flags=linked),
                Account(id=2, ledger=0, code=1),  # break -> rollback
                Account(id=3, ledger=1, code=1, flags=linked),
                Account(id=4, ledger=1, code=1),  # chain ok
                Account(id=1, ledger=1, code=1),  # created (first was rolled back)
                Account(id=5, ledger=1, code=1, flags=linked),  # chain open at end
            ],
            TS_BASE,
        )

    def test_transfer_chains_with_rollback(self):
        d = Differ()
        two_accounts(d)
        linked = int(TF.linked)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10, ledger=1, code=1, flags=linked),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=10, ledger=0, code=1),
                Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=7, ledger=1, code=1),
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=3, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )

    def test_two_phase(self):
        d = Differ()
        two_accounts(d)
        d.create_transfers(
            [Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100,
                      ledger=1, code=1, flags=int(TF.pending))],
            TS_BASE + 100,
        )
        d.create_transfers(
            [
                Transfer(id=2, pending_id=1, amount=40, flags=int(TF.post_pending_transfer)),
                Transfer(id=3, pending_id=1, amount=U128_MAX, flags=int(TF.post_pending_transfer)),
                Transfer(id=4, pending_id=99, flags=int(TF.void_pending_transfer)),
            ],
            TS_BASE + 200,
        )

    def test_two_phase_same_batch(self):
        """Pending created and posted within one batch (batch-store p lookup)."""
        d = Differ()
        two_accounts(d)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100,
                         ledger=1, code=1, flags=int(TF.pending), timeout=60),
                Transfer(id=2, pending_id=1, amount=U128_MAX, flags=int(TF.post_pending_transfer)),
                Transfer(id=3, pending_id=1, flags=int(TF.void_pending_transfer)),
            ],
            TS_BASE + 100,
        )

    def test_void_and_closing(self):
        d = Differ()
        two_accounts(d)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=0,
                         ledger=1, code=1, flags=int(TF.pending | TF.closing_debit)),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1),
                Transfer(id=3, pending_id=1, flags=int(TF.void_pending_transfer)),
                Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )

    def test_balancing(self):
        d = Differ()
        d.create_accounts(
            [
                Account(id=1, ledger=1, code=1, flags=int(AccountFlags.debits_must_not_exceed_credits)),
                Account(id=2, ledger=1, code=1, flags=int(AccountFlags.credits_must_not_exceed_debits)),
                Account(id=3, ledger=1, code=1),
            ],
            TS_BASE,
        )
        d.create_transfers(
            [Transfer(id=1, debit_account_id=3, credit_account_id=1, amount=70, ledger=1, code=1)],
            TS_BASE + 100,
        )
        d.create_transfers(
            [
                Transfer(id=2, debit_account_id=1, credit_account_id=3, amount=100,
                         ledger=1, code=1, flags=int(TF.balancing_debit)),
                Transfer(id=2, debit_account_id=1, credit_account_id=3, amount=100,
                         ledger=1, code=1, flags=int(TF.balancing_debit)),  # exists
                Transfer(id=2, debit_account_id=1, credit_account_id=3, amount=69,
                         ledger=1, code=1, flags=int(TF.balancing_debit)),  # different_amount
            ],
            TS_BASE + 200,
        )

    def test_balance_limits(self):
        d = Differ()
        d.create_accounts(
            [
                Account(id=1, ledger=1, code=1, flags=int(AccountFlags.debits_must_not_exceed_credits)),
                Account(id=2, ledger=1, code=1),
            ],
            TS_BASE,
        )
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=2, credit_account_id=1, amount=100, ledger=1, code=1),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=101, ledger=1, code=1),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=101, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )

    def test_overflows(self):
        d = Differ()
        two_accounts(d)
        big = U128_MAX - 10
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=big, ledger=1, code=1),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=11, ledger=1, code=1),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=5,
                         ledger=1, code=1, flags=int(TF.pending)),
            ],
            TS_BASE + 100,
        )

    def test_expiry_pulse_scheduling(self):
        d = Differ()
        two_accounts(d)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, timeout=60, flags=int(TF.pending)),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, timeout=30, flags=int(TF.pending)),
            ],
            TS_BASE + 100,
        )
        d.create_transfers(
            [Transfer(id=3, pending_id=2, amount=U128_MAX, flags=int(TF.post_pending_transfer))],
            TS_BASE + 200,
        )
        # Posting after expiry fails identically.
        d.create_transfers(
            [Transfer(id=4, pending_id=1, amount=U128_MAX, flags=int(TF.post_pending_transfer))],
            TS_BASE + 200 + 61 * NS_PER_S,
        )

    def test_imported(self):
        d = Differ()
        imported_a = int(AccountFlags.imported)
        d.create_accounts(
            [
                Account(id=1, ledger=1, code=1, flags=imported_a, timestamp=100),
                Account(id=2, ledger=1, code=1, flags=imported_a, timestamp=200),
                Account(id=3, ledger=1, code=1, flags=imported_a, timestamp=150),  # regress
                Account(id=4, ledger=1, code=1),  # expected
            ],
            TS_BASE,
        )
        imported_t = int(TF.imported)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=150),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=250),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=240),  # regress
                Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=10,
                         ledger=1, code=1, flags=imported_t, timestamp=200),  # acct collision
            ],
            TS_BASE + 100,
        )

    def test_transient_poisoning_in_batch(self):
        d = Differ()
        two_accounts(d)
        d.create_transfers(
            [
                Transfer(id=7, debit_account_id=1, credit_account_id=99, amount=1, ledger=1, code=1),
                Transfer(id=7, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1),
            ],
            TS_BASE + 100,
        )
        d.create_transfers(
            [Transfer(id=7, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 200,
        )


# ------------------------------------------------------------------- fuzzing

EDGE_AMOUNTS = [0, 1, 2, 99, 100, (1 << 64) - 1, 1 << 64, (1 << 127), U128_MAX - 1, U128_MAX]


def random_transfer(rng: random.Random, id_space: int, acct_space: int) -> Transfer:
    flags = 0
    r = rng.random()
    if r < 0.15:
        flags |= int(TF.pending)
    elif r < 0.25:
        flags |= int(TF.post_pending_transfer)
    elif r < 0.35:
        flags |= int(TF.void_pending_transfer)
    if rng.random() < 0.15:
        flags |= int(TF.linked)
    if rng.random() < 0.08:
        flags |= int(TF.balancing_debit)
    if rng.random() < 0.08:
        flags |= int(TF.balancing_credit)
    if rng.random() < 0.05:
        flags |= int(TF.closing_debit)
    if rng.random() < 0.05:
        flags |= int(TF.closing_credit)
    if rng.random() < 0.02:
        flags |= 1 << rng.randrange(9, 16)  # reserved padding bits
    return Transfer(
        id=rng.randrange(0, id_space) if rng.random() < 0.9 else rng.choice([0, U128_MAX]),
        debit_account_id=rng.randrange(0, acct_space),
        credit_account_id=rng.randrange(0, acct_space),
        amount=rng.choice(EDGE_AMOUNTS) if rng.random() < 0.5 else rng.randrange(0, 1000),
        pending_id=rng.randrange(0, id_space) if rng.random() < 0.5 else 0,
        user_data_128=rng.choice([0, 1, U128_MAX]),
        user_data_64=rng.choice([0, 7]),
        user_data_32=rng.choice([0, 3]),
        timeout=rng.choice([0, 0, 0, 1, 60, 0xFFFFFFFF]),
        ledger=rng.choice([0, 1, 1, 1, 2]),
        code=rng.choice([0, 1, 1, 1, 9]),
        flags=flags,
        timestamp=rng.choice([0, 0, 0, 5]),
    )


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_fuzz_transfers(seed):
    rng = random.Random(seed)
    d = Differ()
    accounts = []
    for aid in range(1, 8):
        aflags = 0
        if rng.random() < 0.3:
            aflags |= int(AccountFlags.debits_must_not_exceed_credits)
        elif rng.random() < 0.3:
            aflags |= int(AccountFlags.credits_must_not_exceed_debits)
        if rng.random() < 0.2:
            aflags |= int(AccountFlags.history)
        accounts.append(Account(id=aid, ledger=1, code=1, flags=aflags))
    d.create_accounts(accounts, TS_BASE)

    ts = TS_BASE + 1000
    for batch_idx in range(6):
        batch = [random_transfer(rng, id_space=30, acct_space=10) for _ in range(rng.randrange(1, 40))]
        # Never leave a chain open by accident unless the rng wants it.
        d.create_transfers(batch, ts)
        ts += 10_000_000_000


@pytest.mark.parametrize("seed", [11, 12])
def test_fuzz_accounts(seed):
    rng = random.Random(seed)
    d = Differ()
    ts = TS_BASE
    for _ in range(4):
        batch = []
        for _ in range(rng.randrange(1, 25)):
            flags = 0
            if rng.random() < 0.2:
                flags |= int(AccountFlags.linked)
            if rng.random() < 0.15:
                flags |= int(AccountFlags.debits_must_not_exceed_credits)
            if rng.random() < 0.15:
                flags |= int(AccountFlags.credits_must_not_exceed_debits)
            if rng.random() < 0.03:
                flags |= 1 << rng.randrange(6, 16)
            batch.append(
                Account(
                    id=rng.randrange(0, 15) if rng.random() < 0.9 else rng.choice([0, U128_MAX]),
                    debits_pending=rng.choice([0, 0, 0, 1]),
                    user_data_64=rng.choice([0, 7]),
                    ledger=rng.choice([0, 1, 1, 2]),
                    code=rng.choice([0, 1, 1]),
                    flags=flags,
                    timestamp=rng.choice([0, 0, 0, 5]),
                )
            )
        d.create_accounts(batch, ts)
        ts += 10_000_000_000


class TestRollbackOrdering:
    def test_close_then_void_in_rolled_back_chain(self):
        """LIFO rollback: chain [close, void-reopen, fail] must restore the
        pre-chain closed bit (absolute-snapshot restores unwind newest-first)."""
        d = Differ()
        two_accounts(d)
        linked = int(TF.linked)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=5,
                         ledger=1, code=1, flags=linked | int(TF.pending | TF.closing_debit)),
                Transfer(id=2, pending_id=1, flags=linked | int(TF.void_pending_transfer)),
                Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=1, ledger=0, code=1),
            ],
            TS_BASE + 100,
        )
        # Account 1 must be open again in BOTH paths.
        d.create_transfers(
            [Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1)],
            TS_BASE + 200,
        )

    def test_pulse_not_restored_on_rollback(self):
        """A rolled-back pending+timeout still lowers pulse_next_timestamp
        (state-machine state is not groove state; reference keeps it)."""
        d = Differ()
        two_accounts(d)
        # Settle pulse_next to timestamp_max first.
        d.oracle.expire_pending_transfers(TS_BASE + 10)
        d.kstate.expire_pending_transfers(TS_BASE + 10)
        linked = int(TF.linked)
        d.create_transfers(
            [
                Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=5,
                         ledger=1, code=1, timeout=60, flags=linked | int(TF.pending)),
                Transfer(id=2, debit_account_id=1, credit_account_id=2, amount=1, ledger=0, code=1),
            ],
            TS_BASE + 100,
        )
        assert d.oracle.pulse_next_timestamp == TS_BASE + 99 + 60 * NS_PER_S
