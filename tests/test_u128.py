"""u128 limb arithmetic vs Python bignum ground truth (vectorized)."""

import random

import numpy as np

from tigerbeetle_tpu.ops import u128

EDGES = [0, 1, 2, (1 << 64) - 1, 1 << 64, (1 << 64) + 1, (1 << 127),
         (1 << 128) - 2, (1 << 128) - 1]
M = 1 << 128


def _pairs(rng, k=4000):
    vals = list(EDGES)
    for _ in range(200):
        vals.append(rng.getrandbits(rng.randrange(0, 129)))
    a = [rng.choice(vals) for _ in range(k)]
    b = [rng.choice(vals) for _ in range(k)]
    return a, b


def test_add_sub_cmp():
    rng = random.Random(42)
    a, b = _pairs(rng)
    ah, al = u128.from_ints(a)
    bh, bl = u128.from_ints(b)

    h, l, ovf = u128.add(ah, al, bh, bl)
    h, l, ovf = np.asarray(h), np.asarray(l), np.asarray(ovf)
    sh, sl = u128.sub(ah, al, bh, bl)
    sh, sl = np.asarray(sh), np.asarray(sl)
    lt = np.asarray(u128.lt(ah, al, bh, bl))
    le = np.asarray(u128.le(ah, al, bh, bl))
    eq = np.asarray(u128.eq(ah, al, bh, bl))
    mh, ml = u128.min_(ah, al, bh, bl)
    mh, ml = np.asarray(mh), np.asarray(ml)
    th, tl = u128.sat_sub(ah, al, bh, bl)
    th, tl = np.asarray(th), np.asarray(tl)

    for i, (x, y) in enumerate(zip(a, b)):
        assert u128.to_int(h[i], l[i]) == (x + y) % M
        assert bool(ovf[i]) == (x + y >= M)
        assert u128.to_int(sh[i], sl[i]) == (x - y) % M
        assert bool(lt[i]) == (x < y)
        assert bool(le[i]) == (x <= y)
        assert bool(eq[i]) == (x == y)
        assert u128.to_int(mh[i], ml[i]) == min(x, y)
        assert u128.to_int(th[i], tl[i]) == max(0, x - y)


def test_add3_overflow():
    rng = random.Random(7)
    a, b = _pairs(rng)
    c, _ = _pairs(rng)
    ah, al = u128.from_ints(a)
    bh, bl = u128.from_ints(b)
    ch, cl = u128.from_ints(c)
    h, l, ovf = u128.add3(ah, al, bh, bl, ch, cl)
    h, l, ovf = np.asarray(h), np.asarray(l), np.asarray(ovf)
    for i, (x, y, z) in enumerate(zip(a, b, c)):
        assert u128.to_int(h[i], l[i]) == (x + y + z) % M
        assert bool(ovf[i]) == (x + y + z >= M)


def test_zero_max_select():
    vals = [0, 1, (1 << 128) - 1, 1 << 64]
    hi, lo = u128.from_ints(vals)
    assert list(np.asarray(u128.is_zero(hi, lo))) == [True, False, False, False]
    assert list(np.asarray(u128.is_max(hi, lo))) == [False, False, True, False]
    cond = np.array([True, False, True, False])
    sh, sl = u128.select(cond, hi, lo, lo, hi)
    assert u128.to_int(np.asarray(sh)[0], np.asarray(sl)[0]) == 0
