"""Whole-program window chain: kernel differentials + the ROUTE tests.

Part 1 (slow tier): the chain kernel executes W commit windows inside
ONE compiled program (scan or unrolled form, ops/fast_kernels.py
_create_transfers_chain*); its statuses, timestamps, created counts,
and final ledger state must equal W sequential superbatch dispatches,
and a mid-chain fallback must poison every later window on device
(state untouched) exactly like the host pipeline's chained
force_fallback.

Part 2 (quick tier): the chain as the DEFAULT serving dispatch route —
submit_window/resolve_windows and the sync window path route eligible
windows through one chain dispatch; composition with per-prepare
(ineligible-window) fallback, pipelined force_fallback poisoning, and
chaos (bit-flip mid-window -> bounded replay from the last verified
epoch) — all bit-exact vs sequential dispatch / the oracle.
"""

import numpy as np
import pytest

import jax

from tigerbeetle_tpu.benchmark import _soa
from tigerbeetle_tpu.ops import fast_kernels as fk
from tigerbeetle_tpu.ops.ledger import DeviceLedger, stack_superbatch
from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

N = 256
STACK = 2
W = 3

# The raw-kernel differentials are jit-heavy (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
# The route tests further down are quick-tier.
slow = pytest.mark.slow


def _mk_windows(seed=5, poison_window=None):
    rng = np.random.default_rng(seed)
    nid = 10 ** 6
    ts = 10 ** 12
    windows = []
    for w in range(W):
        evs, tss = [], []
        for _ in range(STACK):
            dr = rng.integers(1, 33, N, dtype=np.uint64)
            cr = rng.integers(1, 33, N, dtype=np.uint64)
            clash = dr == cr
            cr[clash] = dr[clash] % 32 + 1
            flags = np.zeros(N, dtype=np.uint32)
            if poison_window == w:
                # balancing_credit (1<<5) is a hard E1 fallback.
                flags[3] = np.uint32(int(TransferFlags.balancing_credit))
            ev = _soa(np.arange(nid, nid + N), dr, cr,
                      rng.integers(1, 1000, N), flags=flags)
            nid += N
            evs.append(ev)
            tss.append(ts)
            ts += N + 10
        ev_s, seg = stack_superbatch(evs, tss)
        windows.append((ev_s, seg))
    return windows


def _fresh_state():
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 33)], 1000)
    return led.state


def _stack_windows(windows):
    ev_stack = {k: np.stack([np.asarray(w[0][k]) for w in windows])
                for k in windows[0][0]}
    seg_stack = {k: np.stack([np.asarray(w[1][k]) for w in windows])
                 for k in windows[0][1]}
    return ev_stack, seg_stack


def _sequential(windows):
    state = _fresh_state()
    poisoned = None
    outs = []
    for ev_s, seg in windows:
        state, out = fk.create_transfers_super_jit(
            state, {k: jax.device_put(v) for k, v in ev_s.items()},
            {k: jax.device_put(v) for k, v in seg.items()}, poisoned)
        poisoned = out["fallback"]
        outs.append({k: np.asarray(out[k]) for k in
                     ("r_status", "r_ts", "fallback", "created_count")})
    return state, outs


@slow
@pytest.mark.parametrize("form", ["scan", "unrolled"])
@pytest.mark.parametrize("poison_window", [None, 1])
def test_chain_matches_sequential(form, poison_window):
    windows = _mk_windows(poison_window=poison_window)
    want_state, want = _sequential(windows)

    ev_stack, seg_stack = _stack_windows(windows)
    chain = (fk.create_transfers_chain_jit if form == "scan"
             else fk.create_transfers_chain_unrolled_jit)
    got_state, outs = chain(_fresh_state(), ev_stack, seg_stack)

    for w in range(W):
        for key in ("r_status", "r_ts", "created_count", "fallback"):
            np.testing.assert_array_equal(
                np.asarray(outs[key])[w], want[w][key],
                err_msg=f"window {w} {key} ({form})")
    if poison_window is not None:
        fbs = np.asarray(outs["fallback"])
        assert not fbs[0] and fbs[1] and fbs[2]  # suffix poisoned
    # Final ledger state identical (the poisoned windows left it alone).
    for table in ("transfers", "accounts"):
        for mat in ("u64",):
            np.testing.assert_array_equal(
                np.asarray(got_state[table][mat]),
                np.asarray(want_state[table][mat]),
                err_msg=f"{table}.{mat} diverged ({form})")
    np.testing.assert_array_equal(
        np.asarray(got_state["transfers"]["count"]),
        np.asarray(want_state["transfers"]["count"]))


# ===================================================== route tests (quick)
# The chain as the DEFAULT dispatch route. Small shapes (k=3 prepares of
# 48-64 events, 1024-row pad bucket) keep these inside the quick tier.

U128MAX = (1 << 128) - 1
PEND = int(TransferFlags.pending)
POST = int(TransferFlags.post_pending_transfer)


def _mk_serving(recycle=True):
    from tigerbeetle_tpu.oracle import StateMachineOracle

    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13,
                       write_through=StateMachineOracle())
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    led.recycle_events = recycle
    led.retain_flush_columns = recycle
    return led


def _route_windows(rng, n_windows, k=3, n=48, base=10 ** 6,
                   poison=None):
    out, nid, ts = [], base, 10 ** 12
    for w in range(n_windows):
        evs, tss = [], []
        for b in range(k):
            batch = []
            for _ in range(n):
                dr = int(rng.integers(1, 65))
                batch.append(Transfer(
                    id=nid, debit_account_id=dr,
                    credit_account_id=dr % 64 + 1,
                    amount=int(rng.integers(1, 100)), ledger=1, code=1))
                nid += 1
            if poison is not None and (w, b) == poison:
                # duplicate id within ONE prepare: hard E2 — the chain
                # route must isolate it to this prepare.
                batch[-1] = Transfer(
                    id=batch[0].id, debit_account_id=1,
                    credit_account_id=2, amount=1, ledger=1, code=1)
            ts += n + 10
            evs.append(batch)
            tss.append(ts)
        out.append((evs, tss))
    return out


def _drive_pipelined(led, windows):
    """Depth-2 pipelined submit/resolve; returns per-window result
    lists in order (the serving driver's shape)."""
    from tigerbeetle_tpu.ops.batch import transfers_to_arrays

    pending, results = [], []
    for evs, tss in windows:
        arrays = [transfers_to_arrays(b) for b in evs]
        tk = led.submit_window(arrays, tss)
        if tk is None:
            led.resolve_windows()
            while pending:
                results.append(pending.pop(0).results[1])
            results.append(led.create_transfers_window(arrays, tss))
            continue
        pending.append(tk)
        if len(pending) > 1:
            led.resolve_windows(count=1)
            while pending and pending[0].results is not None:
                results.append(pending.pop(0).results[1])
    led.resolve_windows()
    for tk in pending:
        results.append(tk.results[1])
    return results


def _drive_sync(led, windows):
    from tigerbeetle_tpu.ops.batch import transfers_to_arrays

    return [led.create_transfers_window(
        [transfers_to_arrays(b) for b in evs], tss)
        for evs, tss in windows]


def _assert_results_equal(res_a, res_b):
    assert len(res_a) == len(res_b)
    for wa, wb in zip(res_a, res_b):
        assert len(wa) == len(wb)
        for (st_a, ts_a), (st_b, ts_b) in zip(wa, wb):
            np.testing.assert_array_equal(np.asarray(st_a),
                                          np.asarray(st_b))
            np.testing.assert_array_equal(np.asarray(ts_a),
                                          np.asarray(ts_b))


def _oracle_with_accounts():
    from tigerbeetle_tpu.oracle import StateMachineOracle

    orc = StateMachineOracle()
    orc.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    return orc


def test_chain_route_default_bit_exact():
    """Eligible windows take the chain route BY DEFAULT — pipelined and
    sync — with zero host fallbacks, bit-exact vs the oracle."""
    rng = np.random.default_rng(11)
    windows = _route_windows(rng, 3)
    led_p, led_s = _mk_serving(), _mk_serving()
    orc = _oracle_with_accounts()

    res_p = _drive_pipelined(led_p, windows)
    res_s = _drive_sync(led_s, windows)
    for evs, tss in windows:
        for b, tb in zip(evs, tss):
            orc.create_transfers(b, tb)
    _assert_results_equal(res_p, res_s)
    for led in (led_p, led_s):
        stats = led.fallback_stats()
        assert stats["routes"]["windows"] == {"chain": 3}, stats
        assert stats["host_fallbacks"] == 0, stats
        assert stats["window_fallbacks"] == 0, stats
        host = led.to_host()
        assert host.accounts == orc.accounts
        assert host.transfers == orc.transfers
        assert host.pending_status == orc.pending_status
    # Write-through capture parity on the clean run: the serving-mode
    # flush columns of both drivers agree chunk for chunk (per-prepare
    # watermarks survive the chain route).
    led_p.drain_mirror()
    led_s.drain_mirror()
    cols_p = led_p.take_flush_columns()
    cols_s = led_s.take_flush_columns()
    assert [c[3] for c in cols_p] == [c[3] for c in cols_s]
    for cp, cs in zip(cols_p, cols_s):
        if cp[3]:
            for key in ("id_hi", "id_lo", "ts", "flags"):
                np.testing.assert_array_equal(
                    np.asarray(cp[0][key]), np.asarray(cs[0][key]))


def test_chain_route_cross_prepare_pend_refs_go_deep():
    """A window with cross-prepare pending references pre-routes to the
    deep superbatch tier (the chain's plain body cannot resolve
    in-window defs) — still zero host fallbacks, oracle-exact."""
    rng = np.random.default_rng(13)
    nid, ts = 5 * 10 ** 6, 10 ** 12
    pends = [Transfer(id=nid + i, debit_account_id=1 + i % 64,
                      credit_account_id=(1 + i) % 64 + 1, amount=10,
                      ledger=1, code=1, flags=PEND, timeout=1000)
             for i in range(48)]
    posts = [Transfer(id=nid + 100 + i, pending_id=nid + i,
                      amount=U128MAX, flags=POST)
             for i in range(48)]
    windows = [([pends, posts], [ts + 58, ts + 116])]
    led = _mk_serving()
    orc = _oracle_with_accounts()
    res = _drive_pipelined(led, windows)
    want = [[(r.timestamp, int(r.status))
             for r in orc.create_transfers(b, tb)]
            for b, tb in zip(*windows[0])]
    got = [[(int(t), int(s)) for s, t in zip(st.tolist(), tl.tolist())]
           for st, tl in res[0]]
    assert got == want
    stats = led.fallback_stats()
    assert stats["routes"]["windows"] == {"super_deep": 1}, stats
    assert stats["host_fallbacks"] == 0, stats


def test_chain_route_per_batch_fallback_and_poisoning():
    """Chain x pipelined force_fallback poisoning: an ineligible prepare
    mid-window falls back PER PREPARE (clean prefix committed), the
    poisoned suffix and the next in-flight window replay — results,
    mirror state, and flush columns bit-exact vs the sync path and the
    oracle."""
    rng = np.random.default_rng(17)
    windows = _route_windows(rng, 4, base=2 * 10 ** 6, poison=(1, 1))
    led_p, led_s = _mk_serving(), _mk_serving()
    orc = _oracle_with_accounts()

    res_p = _drive_pipelined(led_p, windows)
    res_s = _drive_sync(led_s, windows)
    for evs, tss in windows:
        for b, tb in zip(evs, tss):
            orc.create_transfers(b, tb)
    _assert_results_equal(res_p, res_s)
    for led in (led_p, led_s):
        stats = led.fallback_stats()
        assert stats["routes"]["chain_batch_fallbacks"].get(
            "e2_collision", 0) >= 1, stats
        host = led.to_host()
        assert host.accounts == orc.accounts
        assert host.transfers == orc.transfers
        assert set(host.orphaned) == set(orc.orphaned)
    # (Flush-column chunk parity is asserted on the CLEAN run above:
    # after a host fallback the mirror-regime hysteresis may probe the
    # fast path one batch apart between the two drivers — both exact,
    # but chunk boundaries legitimately differ.)


def test_chain_route_chaos_bitflip_bounded_replay():
    """Chain x chaos: a bit flipped in device HBM mid-run is caught by
    the next epoch's state digest; the supervisor replays AT MOST the
    windows since the last verified epoch and resumes — with the chain
    route serving the windows before and after recovery."""
    import jax.numpy as jnp

    from tigerbeetle_tpu.serving import ServingSupervisor
    from tigerbeetle_tpu.trace import Event, Tracer

    tracer = Tracer(pid=0)
    sup = ServingSupervisor(a_cap=1 << 10, t_cap=1 << 13,
                            epoch_interval=2, seed=7, tracer=tracer)
    sup.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 65)], 120)
    rng = np.random.default_rng(23)
    windows = _route_windows(rng, 5, base=3 * 10 ** 6)
    for w, (evs, tss) in enumerate(windows):
        if w == 2:
            # Flip one bit in a live account balance limb on device
            # (HBM corruption model): the epoch check after window 3
            # must catch it via the state digest.
            bal = np.asarray(sup.led.state["accounts"]["bal"]).copy()
            bal[1, 4] ^= np.uint64(1 << 17)
            sup.led.state["accounts"]["bal"] = jnp.asarray(bal)
        sup.create_transfers_window(evs, tss)
    sup.verify_epoch()
    assert sup.counters["recoveries"].get("state_digest", 0) >= 1, \
        sup.counters
    # Bounded replay: never more windows than one epoch interval.
    assert sup.counters["replayed_windows"] <= 2 * sup.epoch_interval
    # The route was the chain before and after recovery (the rebuilt
    # ledger serves through the same default), and the supervisor
    # tagged it into the trace catalog.
    assert sup.led.fallback_stats()["routes"]["windows"].get(
        "chain", 0) >= 1
    assert Event.dispatch_route.name in tracer.emitted
    # Post-recovery ground truth: the full history equals a pure oracle
    # replay of every submitted window.
    orc = _oracle_with_accounts()
    want = []
    for evs, tss in windows:
        want.append([[(r.timestamp, int(r.status))
                      for r in orc.create_transfers(b, tb)]
                     for b, tb in zip(evs, tss)])
    assert sup.history[1:] == want


def test_chain_route_counters_reach_bench_record():
    """The route record rides fallback_stats() -> bench diagnostics."""
    rng = np.random.default_rng(31)
    led = _mk_serving()
    _drive_sync(led, _route_windows(rng, 2, base=4 * 10 ** 6))
    stats = led.fallback_stats()
    assert stats["routes"]["windows"] == {"chain": 2}
    assert stats["routes"]["chain_batch_fallbacks"] == {}
