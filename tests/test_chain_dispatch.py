"""Whole-program window chain vs sequential dispatches — bit-exact.

The chain executes W commit windows inside ONE compiled program (scan
or unrolled form, ops/fast_kernels.py _create_transfers_chain*); its
statuses, timestamps, created counts, and final ledger state must equal
W sequential superbatch dispatches, and a mid-chain fallback must
poison every later window on device (state untouched) exactly like the
host pipeline's chained force_fallback.
"""

import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

import jax

from tigerbeetle_tpu.benchmark import _soa
from tigerbeetle_tpu.ops import fast_kernels as fk
from tigerbeetle_tpu.ops.ledger import DeviceLedger, stack_superbatch
from tigerbeetle_tpu.types import Account, TransferFlags

N = 256
STACK = 2
W = 3


def _mk_windows(seed=5, poison_window=None):
    rng = np.random.default_rng(seed)
    nid = 10 ** 6
    ts = 10 ** 12
    windows = []
    for w in range(W):
        evs, tss = [], []
        for _ in range(STACK):
            dr = rng.integers(1, 33, N, dtype=np.uint64)
            cr = rng.integers(1, 33, N, dtype=np.uint64)
            clash = dr == cr
            cr[clash] = dr[clash] % 32 + 1
            flags = np.zeros(N, dtype=np.uint32)
            if poison_window == w:
                # balancing_credit (1<<5) is a hard E1 fallback.
                flags[3] = np.uint32(int(TransferFlags.balancing_credit))
            ev = _soa(np.arange(nid, nid + N), dr, cr,
                      rng.integers(1, 1000, N), flags=flags)
            nid += N
            evs.append(ev)
            tss.append(ts)
            ts += N + 10
        ev_s, seg = stack_superbatch(evs, tss)
        windows.append((ev_s, seg))
    return windows


def _fresh_state():
    led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 13)
    led.create_accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 33)], 1000)
    return led.state


def _stack_windows(windows):
    ev_stack = {k: np.stack([np.asarray(w[0][k]) for w in windows])
                for k in windows[0][0]}
    seg_stack = {k: np.stack([np.asarray(w[1][k]) for w in windows])
                 for k in windows[0][1]}
    return ev_stack, seg_stack


def _sequential(windows):
    state = _fresh_state()
    poisoned = None
    outs = []
    for ev_s, seg in windows:
        state, out = fk.create_transfers_super_jit(
            state, {k: jax.device_put(v) for k, v in ev_s.items()},
            {k: jax.device_put(v) for k, v in seg.items()}, poisoned)
        poisoned = out["fallback"]
        outs.append({k: np.asarray(out[k]) for k in
                     ("r_status", "r_ts", "fallback", "created_count")})
    return state, outs


@pytest.mark.parametrize("form", ["scan", "unrolled"])
@pytest.mark.parametrize("poison_window", [None, 1])
def test_chain_matches_sequential(form, poison_window):
    windows = _mk_windows(poison_window=poison_window)
    want_state, want = _sequential(windows)

    ev_stack, seg_stack = _stack_windows(windows)
    chain = (fk.create_transfers_chain_jit if form == "scan"
             else fk.create_transfers_chain_unrolled_jit)
    got_state, outs = chain(_fresh_state(), ev_stack, seg_stack)

    for w in range(W):
        for key in ("r_status", "r_ts", "created_count", "fallback"):
            np.testing.assert_array_equal(
                np.asarray(outs[key])[w], want[w][key],
                err_msg=f"window {w} {key} ({form})")
    if poison_window is not None:
        fbs = np.asarray(outs["fallback"])
        assert not fbs[0] and fbs[1] and fbs[2]  # suffix poisoned
    # Final ledger state identical (the poisoned windows left it alone).
    for table in ("transfers", "accounts"):
        for mat in ("u64",):
            np.testing.assert_array_equal(
                np.asarray(got_state[table][mat]),
                np.asarray(want_state[table][mat]),
                err_msg=f"{table}.{mat} diverged ({form})")
    np.testing.assert_array_equal(
        np.asarray(got_state["transfers"]["count"]),
        np.asarray(want_state["transfers"]["count"]))
