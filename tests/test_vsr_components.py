"""VSR auxiliary components: durable client sessions/replies, fault
detector, repair budget, grid scrubber.

reference analogs: src/vsr/client_sessions.zig + client_replies.zig,
src/vsr/fault_detector.zig, src/vsr/repair_budget.zig,
src/vsr/grid_scrubber.zig.
"""

from tigerbeetle_tpu import multi_batch
from tigerbeetle_tpu.lsm.forest import Forest
from tigerbeetle_tpu.lsm.grid import Grid, MemoryDevice
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Account, Operation, Transfer
from tigerbeetle_tpu.vsr.client_sessions import ClientSessions
from tigerbeetle_tpu.vsr.fault_detector import FaultDetector
from tigerbeetle_tpu.vsr.grid_scrubber import GridScrubber
from tigerbeetle_tpu.vsr.header import Command, Header, Message
from tigerbeetle_tpu.vsr.repair_budget import RepairBudget
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT, MemoryStorage

MS = 1_000_000


def _reply(client: int, request: int, body: bytes = b"x" * 16) -> Message:
    h = Header(command=Command.reply, cluster=1, client=client,
               request=request)
    return Message(h.finalize(body), body=body)


class TestClientSessions:
    def test_put_get_roundtrip_and_zone_persistence(self):
        storage = MemoryStorage(TEST_LAYOUT)
        sessions = ClientSessions(storage)
        for c in range(1, 4):
            assert sessions.put_reply(c, 1, _reply(c, 1)) is None
        blob = sessions.pack()

        restored = ClientSessions(storage)
        restored.restore(blob)
        for c in range(1, 4):
            e = restored.get(c)
            assert e["request"] == 1
            assert e["reply"].body == b"x" * 16
            assert e["reply"].valid()

    def test_eviction_oldest_request_first(self):
        storage = MemoryStorage(TEST_LAYOUT)
        sessions = ClientSessions(storage)
        cap = storage.layout.clients_max
        for c in range(1, cap + 1):
            assert sessions.put_reply(c, c, _reply(c, c)) is None
        # Table full: the session with the lowest request number goes.
        evicted = sessions.put_reply(999, 100, _reply(999, 100))
        assert evicted == 1
        assert sessions.get(1) is None
        assert sessions.get(999)["request"] == 100

    def test_corrupt_reply_slot_detected(self):
        storage = MemoryStorage(TEST_LAYOUT)
        sessions = ClientSessions(storage)
        sessions.put_reply(5, 7, _reply(5, 7))
        blob = sessions.pack()
        slot = sessions.get(5)["slot"]
        storage.write("client_replies",
                      slot * storage.layout.message_size_max + 100, b"\xff")
        restored = ClientSessions(storage)
        restored.restore(blob)
        e = restored.get(5)
        assert e["request"] == 7 and e["reply"] is None  # fault, not garbage


class TestSessionsSurviveRestart:
    def test_duplicate_request_after_restart_answered_from_disk(self):
        cluster = Cluster(seed=77, replica_count=3)
        client = cluster.client(11)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        for k in range(20):  # run past a checkpoint (interval 16)
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=100 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128))
        cluster.settle()
        last_reply = client.replies[-1].body

        victim = cluster.replicas[0].primary_index()
        cluster.crash(victim)
        cluster.restart(victim)
        cluster.settle()
        e = cluster.replicas[victim].sessions.get(11)
        assert e is not None
        assert e["request"] == client.request_number
        assert e["reply"].body == last_reply


class TestStandbys:
    def test_standby_follows_without_voting(self):
        """3 active + 1 standby: the standby converges byte-identically but
        never acks or votes (reference: standbys,
        docs/ARCHITECTURE.md — warm spares outside the quorums)."""
        cluster = Cluster(seed=61, replica_count=3, standby_count=1)
        client = cluster.client(4)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        for k in range(20):
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=100 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128))
        cluster.settle()
        standby = cluster.replicas[3]
        assert standby.is_standby
        assert standby.commit_min == cluster.replicas[0].commit_min
        a1 = standby.state_machine.state.accounts[1]
        assert a1.debits_posted == 20
        # It holds checkpoints too (usable as a state-sync source).
        assert standby.superblock.op_checkpoint > 0

    def test_quorum_survives_active_crash_with_standby_up(self):
        """Losing one ACTIVE replica of 3 still commits (quorum 2); the
        standby's presence neither helps nor hurts the quorum math."""
        cluster = Cluster(seed=62, replica_count=3, standby_count=1)
        client = cluster.client(5)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(6000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=200, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1).pack()], 128))
        cluster.restart(victim)
        cluster.settle()
        # The standby never voted: no prepare_ok from id 3 possible (it
        # would have tripped the quorum assert if counted).
        standby = cluster.replicas[3]
        assert standby.is_standby and not standby.is_primary
        assert standby.state_machine.state.accounts[1].debits_posted == 5


class TestStateSync:
    def test_lagging_replica_jumps_to_peer_checkpoint(self):
        """Crash a replica, drive the cluster past the WAL wrap
        (slot_count=32 in TEST_LAYOUT), restart it: normal repair cannot
        bridge the gap, so it must state-sync to a peer's checkpoint
        (reference: docs/internals/sync.md:49-79)."""
        cluster = Cluster(seed=55, replica_count=3)
        client = cluster.client(3)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        cluster.crash(victim)
        for k in range(40):  # > slot_count: the WAL wraps past the victim
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=100 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128))
        cluster.restart(victim)
        cluster.settle(ticks=6000)
        r = cluster.replicas[victim]
        # It cannot have replayed the whole log — it jumped via sync.
        assert r.superblock.op_checkpoint >= 32
        a1 = r.state_machine.state.accounts[1]
        assert a1.debits_posted == 40
        e = r.sessions.get(3)
        assert e is not None and e["request"] == client.request_number


class TestScrubRepairEndToEnd:
    def test_corrupt_block_repaired_from_peer(self):
        """Corrupt one replica's grid block; the scrubber finds it and the
        repair path installs a validated copy from a peer (grids are
        byte-identical, reference: docs/ARCHITECTURE.md:281-307)."""
        cluster = Cluster(seed=91, replica_count=3)
        client = cluster.client(2)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        for k in range(18):  # past a checkpoint: tables exist on the grid
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=100 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128))
        cluster.settle()

        r0 = cluster.replicas[0]
        tables = [t for tree in r0.durable.forest.trees.values()
                  for level in tree.levels for t in level]
        assert tables, "expected flushed tables after a checkpoint"
        victim = tables[0].info.index_address
        zones = cluster.layout.zone_offsets
        off = zones["grid"] + victim.index * cluster.layout.grid_block_size + 8
        cluster.storages[0].data[off] ^= 0xFF

        # Let the scrubber tour (every 64 ticks) and the repair path run:
        # wait for two FULL tours after the corruption (the first detects,
        # a later one confirms the repaired block scans clean).
        r0.scrubber.cycle_ticks = 4
        cycles0 = r0.scrubber.cycles
        cluster.run(20000, until=lambda: (
            r0.scrubber.cycles >= cycles0 + 2
            and victim.index not in r0.block_repair
            and victim.index not in r0.scrubber.faults))
        raw = cluster.storages[0].read(
            "grid", victim.index * cluster.layout.grid_block_size,
            tables[0].info.index_size)
        r0.durable.grid.read_block(victim, tables[0].info.index_size)
        assert raw is not None  # read_block above validated the checksum

    def test_fully_corrupt_grid_repaired_from_peers(self):
        """The reference's hardest scrub case (replica_test.zig:1561
        "background scrubber, fully corrupt grid"): EVERY grid block of
        one replica is corrupted; the scrubber tour + peer repair must
        restore the whole referenced set while the cluster keeps
        serving, ending with a clean tour and identical storage."""
        cluster = Cluster(seed=93, replica_count=3)
        client = cluster.client(4)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        for k in range(18):  # past a checkpoint: tables exist on the grid
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=300 + k, debit_account_id=1,
                          credit_account_id=2, amount=2, ledger=1,
                          code=1).pack()], 128))
        cluster.settle()

        r0 = cluster.replicas[0]
        # Fully corrupt the replica's REACHABLE grid: every block the
        # current checkpoint root references (the sim's storage checker
        # byte-compares the reachable set, so unreferenced scratch blocks
        # stay out of scope — the reference's checker scopes the same
        # way).
        from tigerbeetle_tpu.vsr.durable import allocated_blocks

        sb = r0.superblock
        root = cluster.storages[0].read(
            "snapshot",
            sb.snapshot_slot * cluster.layout.snapshot_size_max,
            sb.snapshot_size)
        from tigerbeetle_tpu.vsr.replica import _split_root

        forest_root, _ = _split_root(root)
        reachable = allocated_blocks(forest_root)
        assert len(reachable) > 3, "expected a populated grid"
        zones = cluster.layout.zone_offsets
        bs = cluster.layout.grid_block_size
        for i in reachable:
            cluster.storages[0].data[zones["grid"] + i * bs + 8] ^= 0xFF

        r0.scrubber.cycle_ticks = 4
        cycles0 = r0.scrubber.cycles
        ok = cluster.run(40000, until=lambda: (
            r0.scrubber.cycles >= cycles0 + 2
            and not r0.block_repair and not r0.scrubber.faults))
        assert ok, (len(r0.block_repair), len(r0.scrubber.faults),
                    cluster.debug_status())
        # And the repaired replica keeps serving: one more commit lands.
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=400, debit_account_id=1, credit_account_id=2,
                      amount=3, ledger=1, code=1).pack()], 128))
        cluster.settle()
        # Every referenced block reads back checksum-valid.
        tables = [t for tree in r0.durable.forest.trees.values()
                  for level in tree.levels for t in level]
        assert tables
        for t in tables[:8]:
            r0.durable.grid.read_block(t.info.index_address,
                                       t.info.index_size)
        cluster.check_convergence()

    def test_missing_reply_repaired_from_peer(self):
        """Blow away a replica's reply slot + restart: the periodic reply
        repair refills it from peers (reference: client_replies repair)."""
        cluster = Cluster(seed=92, replica_count=3)
        client = cluster.client(6)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(4000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        for k in range(17):  # past a checkpoint so sessions are durable
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=200 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128))
        cluster.settle()
        victim = 2 if cluster.replicas[0].primary_index() != 2 else 1
        r = cluster.replicas[victim]
        entry = r.sessions.get(6)
        assert entry is not None and entry["reply"] is not None
        # Corrupt the reply slot on disk, then restart the replica.
        zones = cluster.layout.zone_offsets
        off = (zones["client_replies"]
               + entry["slot"] * cluster.layout.message_size_max + 300)
        cluster.storages[victim].data[off] ^= 0xFF
        cluster.crash(victim)
        cluster.restart(victim)
        r = cluster.replicas[victim]
        cluster.run(500, until=lambda: r.status == "normal")
        # If the WAL replay rebuilt the reply it's already fine; otherwise
        # the repair path must refill it from a peer.
        cluster.run(4000, until=lambda: not r.sessions.missing_replies())
        assert not r.sessions.missing_replies()
        e = r.sessions.get(6)
        assert e["reply"] is not None and e["reply"].valid()


class TestFaultDetector:
    def test_adapts_to_observed_rate(self):
        fd = FaultDetector(suspect_multiplier=4.0)
        t = 0
        for _ in range(100):
            t += 100 * MS
            fd.observe_progress(t)
        # EWMA converged to ~100ms; deadline ~400ms.
        assert 350 * MS < fd.deadline_ns() < 450 * MS
        assert not fd.suspect(t + 300 * MS)
        assert fd.suspect(t + 500 * MS)

    def test_reset_restores_generous_deadline(self):
        fd = FaultDetector(suspect_multiplier=4.0)
        t = 0
        for _ in range(50):
            t += 60 * MS
            fd.observe_progress(t)
        fd.reset(t)
        assert fd.deadline_ns() == 4 * fd.ceil_ns
        assert not fd.suspect(t + 1000 * MS)

    def test_ewma_clamps_at_floor(self):
        # A burst of sub-floor intervals must not drive the expectation
        # below floor_ns (a hyperactive primary would otherwise set an
        # unmeetably tight deadline for its successor intervals).
        fd = FaultDetector(floor_ns=50 * MS, suspect_multiplier=4.0)
        t = 0
        for _ in range(200):
            t += 1 * MS  # far below the floor
            fd.observe_progress(t)
        assert fd.ewma_ns == float(fd.floor_ns)
        assert fd.deadline_ns() == int(4.0 * fd.floor_ns)

    def test_ewma_clamps_at_ceil(self):
        # Huge gaps (e.g. across a partition heal) must not inflate the
        # expectation past ceil_ns — the detector has to stay able to
        # suspect a primary within a bounded horizon.
        fd = FaultDetector(ceil_ns=1000 * MS, suspect_multiplier=4.0)
        t = 0
        for _ in range(10):
            t += 60_000 * MS
            fd.observe_progress(t)
        assert fd.ewma_ns == float(fd.ceil_ns)
        assert fd.suspect(t + 4001 * MS)

    def test_reset_after_view_change_starts_fresh(self):
        # A view change installs a new primary: the OLD primary's
        # observed rate must not carry over — the new one gets the full
        # ceiling-based grace period, and the first post-reset interval
        # re-seeds the estimate from scratch.
        fd = FaultDetector(suspect_multiplier=4.0)
        t = 0
        for _ in range(100):
            t += 10 * MS  # old primary was fast
            fd.observe_progress(t)
        tight = fd.deadline_ns()
        fd.reset(t)
        assert fd.ewma_ns == float(fd.ceil_ns)
        assert fd.last_progress_ns == t
        assert fd.deadline_ns() > tight
        # The new primary progressing slowly is NOT suspect inside the
        # restored generous deadline.
        assert not fd.suspect(t + 3000 * MS)

    def test_no_suspicion_before_first_progress(self):
        # Before ANY observed progress there is no baseline to be late
        # against (startup: the replica must not instantly escalate to
        # a view change on a cold clock).
        fd = FaultDetector()
        assert not fd.suspect(10 ** 18)
        fd.observe_progress(10 ** 18)
        assert not fd.suspect(10 ** 18 + 1)


class TestRepairBudget:
    def test_spend_and_refill(self):
        rb = RepairBudget(capacity=4, refill_interval_ns=50 * MS)
        t = 10**9
        for _ in range(4):
            assert rb.spend(t)
        assert not rb.spend(t)
        assert rb.spend(t + 50 * MS)  # one token earned
        assert not rb.spend(t + 50 * MS)
        t2 = t + 50 * MS + 4 * 50 * MS
        rb.refill(t2)
        assert rb.tokens == 4  # capped at capacity

    def test_first_refill_only_anchors_the_clock(self):
        # The first refill observation sets last_refill_ns without
        # granting tokens for the (undefined) interval before it.
        rb = RepairBudget(capacity=2, refill_interval_ns=50 * MS)
        for _ in range(2):
            assert rb.spend(10 ** 15)  # spends anchor the clock too
        assert not rb.spend(10 ** 15)
        # Elapsed time counts from the ANCHOR, not from zero.
        assert not rb.spend(10 ** 15 + 49 * MS)
        assert rb.spend(10 ** 15 + 50 * MS)

    def test_multi_token_spend_is_all_or_nothing(self):
        rb = RepairBudget(capacity=4, refill_interval_ns=50 * MS)
        t = 10 ** 9
        assert rb.spend(t, amount=3)
        # One token left: a 2-token request must not partially deduct.
        assert not rb.spend(t, amount=2)
        assert rb.tokens == 1
        assert rb.spend(t, amount=1)

    def test_partial_interval_earns_nothing(self):
        rb = RepairBudget(capacity=1, refill_interval_ns=50 * MS)
        t = 10 ** 9
        assert rb.spend(t)
        assert not rb.spend(t + 49 * MS)
        # last_refill_ns advances by WHOLE intervals only, so fractional
        # progress accumulates instead of being lost.
        assert rb.spend(t + 50 * MS)
        assert not rb.spend(t + 99 * MS)
        assert rb.spend(t + 100 * MS)


class TestGridScrubber:
    def _forest(self):
        grid = Grid(MemoryDevice(8192 * 256), block_size=8192,
                    block_count=256)
        forest = Forest(grid, {"t": (8, 8)})
        tree = forest.trees["t"]
        for i in range(100):
            tree.put(i.to_bytes(8, "big"), i.to_bytes(8, "little"))
        tree.flush_memtable()
        return grid, forest

    def test_clean_tour_finds_nothing(self):
        _, forest = self._forest()
        scrubber = GridScrubber(forest, cycle_ticks=16)
        while scrubber.cycles == 0:
            assert scrubber.tick() == []
        assert scrubber.checked > 0 and not scrubber.faults

    def test_corrupt_block_surfaced(self):
        grid, forest = self._forest()
        table = forest.trees["t"].levels[0][0]
        victim = table.block_addresses[0]
        grid.device.data[victim.index * grid.block_size + 4] ^= 0xFF
        scrubber = GridScrubber(forest, cycle_ticks=16)
        found = []
        while scrubber.cycles == 0:
            found += scrubber.tick()
        assert any(addr == victim for _, addr, _ in found)
        assert victim.index in scrubber.faults

    def test_full_tour_on_schedule_covers_every_block(self):
        """Cycle pacing: one full tour completes within cycle_ticks ticks
        and validates every reachable block exactly once (reference:
        grid_scrubber.zig tour accounting :135-138)."""
        _, forest = self._forest()
        scrubber = GridScrubber(forest, cycle_ticks=10)
        ticks = 0
        while scrubber.cycles == 0:
            scrubber.tick()
            ticks += 1
            assert ticks <= 10 + 1, "tour overran its cycle budget"
        assert scrubber.tour_blocks_scrubbed == scrubber.tour_size
        assert scrubber.checked == scrubber.tour_size

    def test_pacing_spreads_reads_across_cycle(self):
        """With cycle_ticks >= tour_size the budget is ~1 block/tick —
        the scrubber must not burst the whole grid in one tick."""
        _, forest = self._forest()
        scrubber = GridScrubber(forest, cycle_ticks=10_000)
        scrubber.tick()
        assert 0 < scrubber.tour_blocks_scrubbed <= 2

    def test_origin_rotation_decorrelates_replicas(self):
        """Different origin seeds tour the same block set in different
        rotations (grid_scrubber.zig:170-182: per-replica origins so the
        same latent fault is scrubbed at different times)."""
        _, forest = self._forest()
        s0 = GridScrubber(forest, origin_seed=0)
        s1 = GridScrubber(forest, origin_seed=7 * 2654435761)
        t0 = list(s0._tour())
        t1 = list(s1._tour())
        assert sorted(a.index for _, a, _ in t0) == \
            sorted(a.index for _, a, _ in t1)
        assert [a.index for _, a, _ in t0] != \
            [a.index for _, a, _ in t1]


class TestPrimaryRestartAfterViewChange:
    def test_restarted_primary_recommits_and_cluster_progresses(self):
        """A mundane primary crash+restart after a view change must not
        wedge the cluster: the completed-view primary replays its own
        journal (provably canonical up to its persisted commit point),
        re-installs canonical headers on backups, and new ops commit."""
        cluster = Cluster(seed=88, replica_count=3)
        client = cluster.client(2)

        def drive(op, body):
            client.request(op, body)
            ok = cluster.run(6000, until=lambda: client.idle)
            assert ok, cluster.debug_status()

        drive(Operation.create_accounts, multi_batch.encode(
            [b"".join(Account(id=i, ledger=1, code=1).pack()
                      for i in (1, 2))], 128))
        # Force a view change by crashing the view-0 primary.
        old_primary = cluster.replicas[0].primary_index()
        cluster.crash(old_primary)
        for k in range(4):
            drive(Operation.create_transfers, multi_batch.encode(
                [Transfer(id=100 + k, debit_account_id=1,
                          credit_account_id=2, amount=1, ledger=1,
                          code=1).pack()], 128))
        cluster.restart(old_primary)
        cluster.settle()
        new_primary = cluster.replicas[0].primary_index()
        assert new_primary != old_primary or cluster.replicas[0].view > 0
        # Crash + restart the CURRENT (post-view-change) primary: it
        # re-broadcasts start_view + re-replicates its suffix; commits
        # regain quorum within a few ticks.
        cluster.crash(new_primary)
        cluster.restart(new_primary)
        r = cluster.replicas[new_primary]
        cluster.run(4000, until=lambda: r.commit_min >= 5)
        assert r.commit_min >= 5, \
            f"restarted primary must re-commit its log: {cluster.debug_status()}"
        # The cluster must still commit new ops.
        drive(Operation.create_transfers, multi_batch.encode(
            [Transfer(id=200, debit_account_id=1, credit_account_id=2,
                      amount=5, ledger=1, code=1).pack()], 128))
        cluster.settle()
        assert cluster.replicas[0].state_machine.state.accounts[1] \
            .debits_posted == 9
