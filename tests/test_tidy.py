"""Repo hygiene as a test (reference: src/tidy.zig runs lint as a unit
test): banned patterns, parseability, reference-citation presence."""

import ast
import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = REPO / "tigerbeetle_tpu"

BANNED = [
    # Wall-clock and randomness inside the deterministic core: the simulator
    # and replicas must get time via injected providers only.
    (re.compile(r"\btime\.time\(\)"), "use the injected time provider",
     ("vsr", "testing")),
    (re.compile(r"random\.random\(\)\s*$"), "seeded PRNGs only",
     ("vsr",)),
    (re.compile(r"\bprint\("), "no prints in library code (trace/log instead)",
     ("vsr", "ops", "lsm", "oracle")),
]


def _python_files():
    return sorted(p for p in PACKAGE.rglob("*.py"))


def test_all_files_parse_and_have_docstrings():
    for path in _python_files():
        tree = ast.parse(path.read_text(), filename=str(path))
        if path.name != "__main__.py":
            assert ast.get_docstring(tree), f"{path} missing module docstring"


def test_banned_patterns():
    for path in _python_files():
        rel = path.relative_to(PACKAGE)
        text = path.read_text()
        for pattern, why, scopes in BANNED:
            if rel.parts and rel.parts[0] in scopes:
                for i, line in enumerate(text.splitlines(), 1):
                    if pattern.search(line) and "# tidy:allow" not in line:
                        raise AssertionError(f"{rel}:{i}: {why}: {line.strip()}")


def test_reference_citations_present():
    """Core modules must cite reference file:line so parity is checkable."""
    required = [
        "types.py", "state_machine.py", "multi_batch.py",
        "ops/create_kernels.py", "ops/fast_kernels.py", "ops/ledger.py",
        "vsr/replica.py", "vsr/journal.py", "vsr/superblock.py",
        "lsm/tree.py", "lsm/grid.py", "testing/cluster.py",
    ]
    for rel in required:
        text = (PACKAGE / rel).read_text()
        assert re.search(r"src/[\w/]+\.zig", text), f"{rel} lacks citations"


TRACE_CALL = re.compile(
    r"\btracer\.(?:span|count|gauge|begin|end)\(\s*(['\"]?)(Event\.(\w+))?")


def test_tracer_call_sites_use_catalog_members():
    """ISSUE 5 satellite: every tracer.span/count/gauge/begin/end call
    site references a typed catalog member (trace/event.py), never a
    string literal — the recording tracer would reject a free-form name
    at runtime, but the lint catches it before anything runs."""
    from tigerbeetle_tpu.trace import Event

    for path in _python_files():
        rel = path.relative_to(PACKAGE)
        if rel.parts and rel.parts[0] == "trace":
            continue  # the tracer's own internals
        for i, line in enumerate(path.read_text().splitlines(), 1):
            m = TRACE_CALL.search(line)
            if m is None or "# tidy:allow" in line:
                continue
            assert not m.group(1), \
                f"{rel}:{i}: tracer call with a string literal — use " \
                f"trace.Event members: {line.strip()}"
            if m.group(3):
                assert hasattr(Event, m.group(3)), \
                    f"{rel}:{i}: Event.{m.group(3)} is not in the catalog"


def test_monitoring_doc_lists_every_catalog_event():
    """docs/operating/monitoring.md is the operator rendering of the
    catalog: a new event without a documented meaning cannot ship."""
    from tigerbeetle_tpu.trace import Event

    doc = (REPO / "docs" / "operating" / "monitoring.md").read_text()
    missing = [e.name for e in Event if f"`{e.name}`" not in doc]
    assert not missing, \
        f"monitoring.md lacks catalog events: {missing}"


def test_jaxhound_pragmas_name_real_rules():
    """ISSUE 14 satellite: every `# jaxhound: allow(<rule>)` pragma in
    the tree names a rule hostdet actually enforces — a typo'd pragma
    suppresses nothing and would silently rot."""
    from tigerbeetle_tpu.jaxhound import hostdet

    for path in _python_files():
        rel = path.relative_to(PACKAGE)
        for i, line in enumerate(path.read_text().splitlines(), 1):
            m = hostdet._PRAGMA_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",")
                     if r.strip()}
            unknown = rules - set(hostdet.RULES)
            assert not unknown, \
                f"{rel}:{i}: pragma names unknown jaxhound rule(s) " \
                f"{sorted(unknown)} (valid: {hostdet.RULES})"


def test_no_reference_code_imports():
    """Nothing may read from /root/reference at runtime."""
    for path in _python_files():
        assert "/root/reference" not in path.read_text(), path
