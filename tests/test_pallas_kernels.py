"""Pallas prototype kernels: interpreter-mode differentials against the
XLA implementations (semantics pinned before the first on-chip window
profiles them — see PERF.md and ops/pallas_kernels.py's adoption gate).
"""

import numpy as np

import jax.numpy as jnp

from tigerbeetle_tpu.ops import hash_table as HT
from tigerbeetle_tpu.ops.pallas_kernels import (
    ht_lookup_fused,
    probe_fusable,
)


def _filled_table(cap=1 << 12, n_keys=1500, seed=3):
    rng = np.random.default_rng(seed)
    table = HT.ht_init(cap)
    k_hi = rng.integers(0, 1 << 63, n_keys, dtype=np.uint64)
    k_lo = rng.integers(1, 1 << 63, n_keys, dtype=np.uint64)
    # Unique keys (ht contract).
    seen = set()
    for i in range(n_keys):
        while (int(k_hi[i]), int(k_lo[i])) in seen:
            k_lo[i] += 1
        seen.add((int(k_hi[i]), int(k_lo[i])))
    vals = np.arange(n_keys, dtype=np.int32)
    table, ok = HT.ht_insert(table, jnp.asarray(k_hi), jnp.asarray(k_lo),
                             jnp.asarray(vals),
                             jnp.ones(n_keys, dtype=bool))
    assert bool(ok)
    return table, k_hi, k_lo, vals


def test_fused_probe_matches_xla_lookup():
    table, k_hi, k_lo, vals = _filled_table()
    rng = np.random.default_rng(7)
    # Query mix: present keys, absent keys, and zero sentinels.
    q_hi = np.concatenate([k_hi[:800],
                           rng.integers(0, 1 << 63, 300, dtype=np.uint64),
                           np.zeros(20, dtype=np.uint64)])
    q_lo = np.concatenate([k_lo[:800],
                           rng.integers(0, 1 << 63, 300, dtype=np.uint64),
                           np.zeros(20, dtype=np.uint64)])
    want_f, want_v = HT.ht_lookup(table, jnp.asarray(q_hi),
                                  jnp.asarray(q_lo))
    got_f, got_v = ht_lookup_fused(table, jnp.asarray(q_hi),
                                   jnp.asarray(q_lo), interpret=True)
    assert (np.asarray(got_f) == np.asarray(want_f)).all()
    assert (np.asarray(got_v) == np.asarray(want_v)).all()
    # Found keys resolve to their inserted values.
    assert (np.asarray(got_v)[:800] == vals[:800]).all()


def test_vmem_gate():
    small = HT.ht_init(1 << 12)
    assert probe_fusable(small)
    huge = HT.ht_init(1 << 21)  # (2^18+1, 24) u64 ≈ 50 MB
    assert not probe_fusable(huge)
