"""Filter/query table scenarios across all three engines (reference:
src/state_machine_tests.zig's get_account_transfers /
get_account_balances / query_* tables). Every case runs on the host
kernel engine, the sequential oracle, AND the device engine — the
serving path must agree with the spec tables wherever they disagree is
a served-result bug, not a kernel bug."""

import pytest

from tigerbeetle_tpu.state_machine import StateMachine
from tigerbeetle_tpu.types import (
    Account,
    AccountFilter,
    AccountFilterFlags as AFF,
    AccountFlags as AF,
    QueryFilter,
    QueryFilterFlags as QFF,
    Transfer,
    TransferFlags as TF,
)

TS = 10**13
ENGINES = ["kernel", "oracle", "device"]


def _setup(engine):
    kw = {"a_cap": 1 << 10, "t_cap": 1 << 12} if engine == "device" else {}
    sm = StateMachine(engine=engine, **kw)
    res = sm.create_accounts([
        Account(id=1, ledger=1, code=10, user_data_64=7,
                user_data_32=3),
        Account(id=2, ledger=1, code=10, flags=int(AF.history)),
        Account(id=3, ledger=1, code=20, user_data_64=7),
        Account(id=4, ledger=2, code=10, user_data_128=5),
    ], TS)
    assert all(r.status.name == "created" for r in res)
    res = sm.create_transfers([
        Transfer(id=101, debit_account_id=1, credit_account_id=2,
                 amount=10, ledger=1, code=5, user_data_64=77),
        Transfer(id=102, debit_account_id=2, credit_account_id=3,
                 amount=20, ledger=1, code=5),
        Transfer(id=103, debit_account_id=3, credit_account_id=1,
                 amount=30, ledger=1, code=6, user_data_64=77),
        Transfer(id=104, debit_account_id=1, credit_account_id=2,
                 amount=40, ledger=1, code=6, flags=int(TF.pending)),
        Transfer(id=105, debit_account_id=4, credit_account_id=1,
                 amount=50, ledger=0, code=5),  # cross-ledger: rejected
    ], TS + 100)
    assert [r.status.name for r in res] == [
        "created", "created", "created", "created",
        "ledger_must_not_be_zero"]
    return sm


@pytest.mark.parametrize("engine", ENGINES)
def test_account_filter_direction_flags(engine):
    sm = _setup(engine)
    # debits only
    f = AccountFilter(account_id=1, limit=100, flags=int(AFF.debits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 104]
    # credits only
    f = AccountFilter(account_id=1, limit=100, flags=int(AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [103]
    # neither direction flag: nothing matches (reference: the filter
    # must request at least one side)
    f = AccountFilter(account_id=1, limit=100, flags=0)
    assert sm.get_account_transfers(f) == []


@pytest.mark.parametrize("engine", ENGINES)
def test_account_filter_reversed_and_limit(engine):
    sm = _setup(engine)
    f = AccountFilter(account_id=1, limit=100,
                      flags=int(AFF.debits | AFF.credits | AFF.reversed))
    assert [t.id for t in sm.get_account_transfers(f)] == [104, 103, 101]
    f = AccountFilter(account_id=1, limit=2,
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 103]
    f = AccountFilter(account_id=1, limit=2,
                      flags=int(AFF.debits | AFF.credits | AFF.reversed))
    assert [t.id for t in sm.get_account_transfers(f)] == [104, 103]


@pytest.mark.parametrize("engine", ENGINES)
def test_account_filter_timestamp_window(engine):
    sm = _setup(engine)
    all_f = AccountFilter(account_id=1, limit=100,
                          flags=int(AFF.debits | AFF.credits))
    ts_by_id = {t.id: t.timestamp for t in sm.get_account_transfers(all_f)}
    f = AccountFilter(account_id=1, limit=100,
                      timestamp_min=ts_by_id[103],
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [103, 104]
    f = AccountFilter(account_id=1, limit=100,
                      timestamp_max=ts_by_id[103],
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 103]
    # Empty window (min > max) matches nothing.
    f = AccountFilter(account_id=1, limit=100,
                      timestamp_min=ts_by_id[104],
                      timestamp_max=ts_by_id[101],
                      flags=int(AFF.debits | AFF.credits))
    assert sm.get_account_transfers(f) == []


@pytest.mark.parametrize("engine", ENGINES)
def test_account_filter_secondary_fields(engine):
    sm = _setup(engine)
    f = AccountFilter(account_id=1, user_data_64=77, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [101, 103]
    f = AccountFilter(account_id=1, code=6, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    assert [t.id for t in sm.get_account_transfers(f)] == [103, 104]
    # Unknown account: empty, not an error.
    f = AccountFilter(account_id=99, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    assert sm.get_account_transfers(f) == []


@pytest.mark.parametrize("engine", ENGINES)
def test_query_accounts_tables(engine):
    sm = _setup(engine)
    q = QueryFilter(user_data_64=7, limit=100)
    assert [a.id for a in sm.query_accounts(q)] == [1, 3]
    q = QueryFilter(user_data_64=7, code=20, limit=100)
    assert [a.id for a in sm.query_accounts(q)] == [3]
    q = QueryFilter(ledger=2, limit=100)
    assert [a.id for a in sm.query_accounts(q)] == [4]
    q = QueryFilter(user_data_64=7, limit=100, flags=int(QFF.reversed))
    assert [a.id for a in sm.query_accounts(q)] == [3, 1]
    q = QueryFilter(user_data_64=7, ledger=2, limit=100)
    assert sm.query_accounts(q) == []


@pytest.mark.parametrize("engine", ENGINES)
def test_query_transfers_tables(engine):
    sm = _setup(engine)
    q = QueryFilter(code=5, limit=100)
    assert [t.id for t in sm.query_transfers(q)] == [101, 102]
    q = QueryFilter(user_data_64=77, limit=1)
    assert [t.id for t in sm.query_transfers(q)] == [101]
    q = QueryFilter(user_data_64=77, limit=100, flags=int(QFF.reversed))
    assert [t.id for t in sm.query_transfers(q)] == [103, 101]


@pytest.mark.parametrize("engine", ENGINES)
def test_balances_require_history_flag(engine):
    sm = _setup(engine)
    # Account 2 has history: one balance row per touching transfer.
    f = AccountFilter(account_id=2, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    balances = sm.get_account_balances(f)
    assert len(balances) == 3  # transfers 101, 102, 104
    assert balances[0].credits_posted == 10
    assert balances[1].debits_posted == 20
    assert balances[2].credits_pending == 40
    # Account 1 has no history flag: empty.
    f = AccountFilter(account_id=1, limit=100,
                      flags=int(AFF.debits | AFF.credits))
    assert sm.get_account_balances(f) == []


@pytest.mark.parametrize("engine", ENGINES)
def test_engines_agree_pairwise(engine):
    """Belt and braces: the parametrized cases above assert absolute
    expectations; this one diffs the engine against the oracle on a
    broader filter sweep so NEW filter features can't diverge
    silently."""
    if engine == "oracle":
        pytest.skip("oracle is the baseline")
    sm = _setup(engine)
    base = _setup("oracle")
    sweeps = [
        AccountFilter(account_id=a, limit=lim, code=code,
                      user_data_64=u64,
                      flags=int(AFF.debits | AFF.credits) | extra)
        for a in (1, 2, 3)
        for lim in (1, 3, 100)
        for code in (0, 5)
        for u64 in (0, 77)
        for extra in (0, int(AFF.reversed))
    ]
    for f in sweeps:
        got = [(t.id, t.timestamp) for t in sm.get_account_transfers(f)]
        want = [(t.id, t.timestamp) for t in base.get_account_transfers(f)]
        assert got == want, f
