"""Admission plane (ISSUE 18): session credits, the global queue
bound, the SLO-driven shed line's rise/fall hysteresis, the deadline
sweep, conservation (zero silent drops), and admitted-history
bit-exactness vs the admitted-only oracle replay.

Everything runs on a VirtualClock over a real (small) ServingSupervisor
so queue waits, deadline sweeps, and burn windows are exactly
reproducible.

Quick tier: the pure-host plane logic (fast rejects, deadline sweep,
shed-line rise/cool/pin, conservation accounting) — tests that never
dispatch a window, so the 1-core tier-1 budget pays no jit. Slow tier:
everything that pumps real windows through the supervisor (admit paths,
oracle parity, stage-ahead consumption)."""

import pytest

from tigerbeetle_tpu.admission import (
    SHED_REASONS,
    AdmissionClass,
    AdmissionPlane,
    ShedResult,
    VirtualClock,
)
from tigerbeetle_tpu.serving import ServingSupervisor
from tigerbeetle_tpu.types import Account, Transfer

CLASSES = (
    AdmissionClass("critical", 0, slo_ms=50.0, deadline_ms=200.0),
    AdmissionClass("standard", 1, slo_ms=100.0, deadline_ms=400.0),
    AdmissionClass("batch", 2, slo_ms=150.0, deadline_ms=600.0),
)


def _mk_plane(**kw):
    clock = VirtualClock()
    sup = ServingSupervisor(a_cap=1 << 8, t_cap=1 << 11,
                            epoch_interval=4, sleep=lambda s: None,
                            seed=3)
    args = dict(classes=CLASSES, prepare_max=16, window_prepares=2,
                session_credits=2, max_queue=32, burn_window_ticks=4,
                burn_budget=0.25, cool_ticks=2, clock=clock, seed=3)
    args.update(kw)
    plane = AdmissionPlane(sup, **args)
    plane.open_accounts([Account(id=i, ledger=1, code=1)
                         for i in (1, 2)], 1_000)
    return plane, sup, clock


def _evs(n, start):
    return [Transfer(id=start + i, debit_account_id=1,
                     credit_account_id=2, amount=1, ledger=1, code=1)
            for i in range(n)]


class TestBackpressure:
    @pytest.mark.slow
    def test_no_credit_fast_reject_and_credit_return(self):
        plane, _, _ = _mk_plane(session_credits=2)
        r1 = plane.submit(7, _evs(2, 100))
        r2 = plane.submit(7, _evs(2, 200))
        r3 = plane.submit(7, _evs(2, 300))
        assert r1.state == "queued" and r2.state == "queued"
        assert r3.state == "shed"
        assert isinstance(r3.shed, ShedResult)
        assert r3.shed.reason == "no_credit"
        assert r3.shed.session_id == 7 and r3.shed.cls == "standard"
        # Credits return on admit: after a pump the session queues again.
        plane.pump()
        assert r1.state == "admitted" and r2.state == "admitted"
        assert plane.submit(7, _evs(2, 400)).state == "queued"
        plane.drain()
        assert plane.conservation()["ok"]

    def test_queue_full_fast_reject(self):
        plane, _, _ = _mk_plane(max_queue=3, session_credits=100)
        rs = [plane.submit(i + 1, _evs(1, 100 + i * 10))
              for i in range(5)]
        assert [r.state for r in rs[:3]] == ["queued"] * 3
        assert {r.shed.reason for r in rs[3:]} == {"queue_full"}
        cons = plane.conservation()
        assert cons["ok"] and cons["queued"] == 3 and cons["shed"] == 2

    def test_shed_is_returned_never_raised(self):
        plane, _, _ = _mk_plane(session_credits=0)
        r = plane.submit(1, _evs(1, 100))
        assert r.state == "shed" and r.shed.reason == "no_credit"
        assert r.shed.reason in SHED_REASONS
        assert r.shed.retry_after_ms == pytest.approx(100.0)


class TestDeadlineSweep:
    def test_expired_queued_requests_shed_not_admitted_late(self):
        plane, _, clock = _mk_plane(stage_ahead=False,
                                    max_windows_per_pump=0)
        r = plane.submit(1, _evs(1, 100), cls="critical")
        clock.advance(0.5)  # past critical's 200ms hard deadline
        plane.pump()
        assert r.state == "shed" and r.shed.reason == "deadline"
        # The swept request released its session credit.
        assert plane.submit(1, _evs(1, 200)).state == "queued"
        assert plane.conservation()["ok"]

    @pytest.mark.slow
    def test_admitted_wait_bounded_by_deadline(self):
        # Starved pump (1 event of service per tick) under steady load:
        # whatever IS admitted was admitted within its class deadline.
        plane, _, clock = _mk_plane(
            stage_ahead=False, prepare_max=1, window_prepares=1,
            session_credits=100, max_queue=100)
        # Pin the shed line open: this test isolates the deadline sweep
        # (the burn controller would otherwise gate the class first).
        plane.force_shed_level(0)
        nid = 10 ** 4
        for t in range(20):
            for sid in (1, 2, 3):
                plane.submit(sid, _evs(1, nid), cls="batch")
                nid += 1
            plane.pump()
            clock.advance(0.1)
        plane.drain()
        assert plane.conservation()["ok"]
        st = plane.stats()["classes"]["batch"]
        assert st["shed"].get("deadline", 0) > 0
        mx = st["admit_wait_ms"]["max"]
        assert mx is not None and mx <= CLASSES[2].deadline_ms + 1e-6


class TestShedLine:
    def test_rises_one_class_per_tick_top_class_never(self):
        plane, _, clock = _mk_plane(stage_ahead=False,
                                    max_windows_per_pump=0,
                                    session_credits=100)
        nid = 10 ** 4
        for i in range(4):
            plane.submit(1, _evs(1, nid), cls="batch")
            plane.submit(2, _evs(1, nid + 1), cls="standard")
            nid += 2
        # Ages (200ms) breach batch's 150ms and standard's 100ms SLOs
        # but stay inside both hard deadlines.
        clock.advance(0.2)
        plane.pump()  # tick 1: burn windows fill, level still 0
        assert plane.shed_level == 0
        plane.pump()  # tick 2: burn > budget -> gate batch
        assert plane.shed_level == 1
        batch_rs = [r for r in plane.shed_results if r.cls == "batch"]
        assert batch_rs and all(r.reason == "shed_line"
                                for r in batch_rs)
        assert plane.submit(3, _evs(1, nid), cls="batch").shed.reason \
            == "shed_line"
        plane.pump()  # tick 3: still burning -> gate standard too
        assert plane.shed_level == 2
        assert plane.submit(3, _evs(1, nid + 1),
                            cls="standard").shed.reason == "shed_line"
        # The top class is NEVER gated, at any level.
        assert plane.submit(3, _evs(1, nid + 2),
                            cls="critical").state == "queued"
        assert plane.conservation()["ok"]

    def test_cools_down_after_clean_ticks(self):
        plane, _, clock = _mk_plane(stage_ahead=False,
                                    max_windows_per_pump=0,
                                    session_credits=100,
                                    burn_window_ticks=4, cool_ticks=2)
        for i in range(3):
            plane.submit(1, _evs(1, 10 ** 4 + i), cls="batch")
        clock.advance(0.2)
        plane.pump()
        plane.pump()
        assert plane.shed_level >= 1
        # Queues are now empty (flushed); the burn window decays to
        # zero and after cool_ticks clean ticks per step the line walks
        # back down to 0 — hysteresis, not flapping.
        levels = []
        for _ in range(16):
            plane.pump()
            levels.append(plane.shed_level)
        assert plane.shed_level == 0
        assert sorted(levels, reverse=True) == levels  # monotonic down

    def test_force_shed_level_pins_and_releases(self):
        # max_windows_per_pump=0: no window ever dispatches, so this
        # stays a pure-host (quick-tier) test of the pin semantics.
        plane, _, _ = _mk_plane(stage_ahead=False,
                                max_windows_per_pump=0)
        plane.force_shed_level(2)
        assert plane.submit(1, _evs(1, 100),
                            cls="batch").shed.reason == "shed_line"
        assert plane.submit(1, _evs(1, 200),
                            cls="standard").shed.reason == "shed_line"
        assert plane.submit(1, _evs(1, 300),
                            cls="critical").state == "queued"
        plane.force_shed_level(None)
        for _ in range(16):
            plane.pump()
        assert plane.shed_level == 0
        # The critical request is still queued (nothing dispatches at
        # zero windows per pump) and conservation counts it as such.
        assert plane.conservation()["ok"]
        assert plane.conservation()["queued"] == 1

    def test_depth_signal_raises_line_without_burn(self):
        plane, _, _ = _mk_plane(stage_ahead=False,
                                max_windows_per_pump=0,
                                session_credits=100, max_queue=8,
                                depth_shed_fraction=0.5)
        for i in range(4):  # depth 4 >= 0.5 * 8
            plane.submit(i + 1, _evs(1, 100 + i), cls="batch")
        plane.pump()
        assert plane.shed_level == 1


class TestConservationAndOracle:
    @pytest.mark.slow
    def test_conservation_and_history_bit_exact_with_sheds(self):
        plane, sup, clock = _mk_plane(session_credits=1, max_queue=8)
        nid = 10 ** 5
        for t in range(6):
            for sid in range(1, 7):
                cls = ("critical" if sid == 1
                       else "standard" if sid < 4 else "batch")
                # Second submit in the same tick: the session's single
                # credit is taken -> typed no_credit fast-reject.
                plane.submit(sid, _evs(2, nid), cls=cls)
                plane.submit(sid, _evs(2, nid + 2), cls=cls)
                nid += 4
            plane.pump()
            clock.advance(0.05)
        plane.drain()
        cons = plane.conservation()
        assert cons["ok"] and cons["queued"] == 0 and cons["staged"] == 0
        assert cons["shed"] > 0
        for r in plane.shed_results:
            assert isinstance(r, ShedResult)
            assert r.reason in SHED_REASONS
        # Bit-exactness under shedding: the supervisor's history equals
        # an oracle replay of exactly the admitted requests.
        hist, _oracle = plane.oracle_history()
        assert hist == sup.history
        assert sup.verify_epoch()
        sup.led.shutdown_staging()

    @pytest.mark.slow
    def test_stats_record_shape(self):
        plane, _, _ = _mk_plane()
        plane.submit(1, _evs(2, 100), cls="critical")
        plane.pump()
        plane.drain()
        st = plane.stats()
        assert set(st["classes"]) == {c.name for c in CLASSES}
        cs = st["classes"]["critical"]
        assert cs["submitted"] == 1 and cs["admitted"] == 1
        assert cs["admit_wait_ms"]["count"] == 1
        assert st["conservation"]["ok"]
        assert 0.0 <= st["queue"]["occupancy"] <= 1.0


class TestStageAhead:
    @pytest.mark.slow
    def test_prestaged_window_is_consumed_not_restaged(self):
        # prepare_max=4 with 8 offered events/tick -> every window is 2
        # prepares, the pipelined route's staging-eligibility floor
        # (DeviceLedger._window_plan requires len(evs) > 1).
        plane, sup, clock = _mk_plane(stage_ahead=True, prepare_max=4,
                                      session_credits=100)
        nid = 10 ** 5
        for t in range(5):
            for sid in (1, 2, 3, 4):
                plane.submit(sid, _evs(2, nid))
                nid += 2
            plane.pump()
            clock.advance(0.02)
        plane.drain()
        stats = sup.led.staging_stats
        assert stats["staged"] > 0, stats
        assert plane.conservation()["ok"]
        hist, _ = plane.oracle_history()
        assert hist == sup.history
        sup.led.shutdown_staging()
