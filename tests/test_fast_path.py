"""Differential tests: DeviceLedger (vectorized fast path) vs oracle.

Mirrors the reference's state-machine oracle + fuzz strategy
(src/state_machine_tests.zig, src/state_machine_fuzz.zig): every batch runs
through both engines; results must match (timestamp, status) exactly and the
reconstructed host state must equal the oracle state. Hard batches exercise
the fallback path; eligible batches exercise the vectorized kernel.
"""

import random

import numpy as np
import pytest

# Tier: jit-heavy parity/differential suite (see pytest.ini) —
# excluded from the quick gate; run via scripts/gate.py --tier slow.
pytestmark = pytest.mark.slow

from tigerbeetle_tpu.constants import U128_MAX
from tigerbeetle_tpu.oracle import StateMachineOracle
from tigerbeetle_tpu.ops.ledger import DeviceLedger
from tigerbeetle_tpu.types import (
    Account,
    AccountFlags as AF,
    Transfer,
    TransferFlags as TF,
)

TS = 10_000_000_000_000


class Differ:
    def __init__(self, a_cap=1 << 12, t_cap=1 << 14):
        self.led = DeviceLedger(a_cap=a_cap, t_cap=t_cap)
        self.sm = StateMachineOracle()
        self.ts = TS

    def _step(self, fn, events):
        self.ts += len(events) + 7
        got = getattr(self.led, fn)(events, self.ts)
        want = getattr(self.sm, fn)(events, self.ts)
        assert [(r.timestamp, r.status.name) for r in got] == [
            (r.timestamp, r.status.name) for r in want
        ], fn
        return want

    def accounts(self, events):
        return self._step("create_accounts", events)

    def transfers(self, events):
        return self._step("create_transfers", events)

    def check_state(self):
        host = self.led.to_host()
        for f in ("accounts", "transfers", "pending_status", "orphaned",
                  "expiry", "pulse_next_timestamp", "commit_timestamp",
                  "accounts_key_max", "transfers_key_max",
                  "account_events"):
            assert getattr(host, f) == getattr(self.sm, f), f


def test_accounts_scenarios():
    d = Differ()
    d.accounts([
        Account(id=1, ledger=1, code=1),
        Account(id=2, ledger=1, code=1, flags=int(AF.history)),
        Account(id=0, ledger=1, code=1),
        Account(id=U128_MAX, ledger=1, code=1),
        Account(id=3, ledger=0, code=1),
        Account(id=4, ledger=1, code=0),
        Account(id=5, ledger=1, code=1, debits_posted=5),
        Account(id=6, ledger=1, code=1,
                flags=int(AF.debits_must_not_exceed_credits
                          | AF.credits_must_not_exceed_debits)),
        Account(id=7, ledger=1, code=1, timestamp=55),
    ])
    # exists comparisons
    d.accounts([
        Account(id=1, ledger=1, code=1),
        Account(id=1, ledger=2, code=1),
        Account(id=1, ledger=1, code=9),
        Account(id=2, ledger=1, code=1),
    ])
    # linked chains (ok / broken / open)
    d.accounts([
        Account(id=10, ledger=1, code=1, flags=int(AF.linked)),
        Account(id=11, ledger=1, code=1),
        Account(id=12, ledger=1, code=1, flags=int(AF.linked)),
        Account(id=0, ledger=1, code=1),
        Account(id=13, ledger=1, code=1, flags=int(AF.linked)),
    ])
    d.check_state()


def test_transfer_scenarios():
    d = Differ()
    d.accounts(
        [Account(id=i, ledger=1, code=1) for i in range(1, 9)]
        + [Account(id=9, ledger=2, code=1),
           Account(id=10, ledger=1, code=1, flags=int(AF.closed))]
    )
    d.transfers([
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1),
        Transfer(id=2, debit_account_id=1, credit_account_id=1, amount=1, ledger=1, code=1),
        Transfer(id=3, debit_account_id=1, credit_account_id=99, amount=1, ledger=1, code=1),
        Transfer(id=4, debit_account_id=1, credit_account_id=9, amount=1, ledger=1, code=1),
        Transfer(id=5, debit_account_id=1, credit_account_id=2, amount=1, ledger=2, code=1),
        Transfer(id=6, debit_account_id=1, credit_account_id=10, amount=1, ledger=1, code=1),
        Transfer(id=7, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=0),
        Transfer(id=8, debit_account_id=1, credit_account_id=2, amount=1, ledger=0, code=1),
        Transfer(id=9, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1,
                 timeout=5),
        Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1,
                 pending_id=77),
    ])
    # retry orphaned id (id=3 failed with credit_account_not_found: transient)
    d.transfers([
        Transfer(id=3, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1),
    ])
    # exists / exists_with_different_*
    d.transfers([
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=1),
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=7, ledger=1, code=1),
        Transfer(id=1, debit_account_id=3, credit_account_id=2, amount=100, ledger=1, code=1),
        Transfer(id=1, debit_account_id=1, credit_account_id=2, amount=100, ledger=1, code=2),
    ])
    d.check_state()


def test_two_phase_and_chains():
    d = Differ()
    d.accounts([Account(id=i, ledger=1, code=1) for i in range(1, 7)])
    d.transfers([
        Transfer(id=100, debit_account_id=1, credit_account_id=2, amount=50, ledger=1, code=1,
                 flags=int(TF.pending)),
        Transfer(id=101, debit_account_id=3, credit_account_id=4, amount=60, ledger=1, code=1,
                 flags=int(TF.pending), timeout=100),
        Transfer(id=102, debit_account_id=5, credit_account_id=6, amount=70, ledger=1, code=1,
                 flags=int(TF.pending)),
    ])
    # post (partial), void, post-after-expiry-window still valid, errors
    d.transfers([
        Transfer(id=110, pending_id=100, amount=20, flags=int(TF.post_pending_transfer)),
        Transfer(id=111, pending_id=102, flags=int(TF.void_pending_transfer)),
        Transfer(id=112, pending_id=999, amount=U128_MAX, flags=int(TF.post_pending_transfer)),
        Transfer(id=113, pending_id=113, flags=int(TF.void_pending_transfer)),
    ])
    # already posted / voided
    d.transfers([
        Transfer(id=120, pending_id=100, amount=U128_MAX, flags=int(TF.post_pending_transfer)),
        Transfer(id=121, pending_id=102, flags=int(TF.void_pending_transfer)),
    ])
    # chains over two-phase
    d.transfers([
        Transfer(id=130, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1,
                 flags=int(TF.linked)),
        Transfer(id=131, pending_id=101, flags=int(TF.void_pending_transfer)),
    ])
    d.check_state()


def test_hard_batches_fall_back():
    d = Differ()
    d.accounts([
        Account(id=1, ledger=1, code=1),
        Account(id=2, ledger=1, code=1),
        Account(id=3, ledger=1, code=1, flags=int(AF.debits_must_not_exceed_credits)),
    ])
    # balance limits breached -> resolved on device by the limit
    # fixpoint (no host fallback), still exact
    d.transfers([
        Transfer(id=1, debit_account_id=1, credit_account_id=3, amount=10, ledger=1, code=1),
        Transfer(id=2, debit_account_id=3, credit_account_id=2, amount=5, ledger=1, code=1),
        Transfer(id=3, debit_account_id=3, credit_account_id=2, amount=6, ledger=1, code=1),
    ])
    assert d.led.fallbacks == 0 and d.led.fixpoint_batches == 1
    # balancing flag -> native (the balancing fixpoint tier), no
    # host fallback
    d.transfers([
        Transfer(id=4, debit_account_id=1, credit_account_id=2, amount=U128_MAX, ledger=1, code=1,
                 flags=int(TF.balancing_debit)),
    ])
    assert d.led.fallbacks == 0
    # in-batch pending+post -> native (the in-window pending join on
    # the fixpoint tier)
    d.transfers([
        Transfer(id=5, debit_account_id=1, credit_account_id=2, amount=5, ledger=1, code=1,
                 flags=int(TF.pending)),
        Transfer(id=6, pending_id=5, amount=U128_MAX, flags=int(TF.post_pending_transfer)),
    ])
    assert d.led.fallbacks == 0
    # closing transfer -> native (escalates to the closing-native
    # fixpoint tier; no host fallback)
    d.transfers([
        Transfer(id=7, debit_account_id=1, credit_account_id=2, amount=1, ledger=1, code=1,
                 flags=int(TF.pending | TF.closing_debit)),
    ])
    # void of closing pending (reopen) -> native too
    d.transfers([
        Transfer(id=8, pending_id=7, flags=int(TF.void_pending_transfer)),
    ])
    assert d.led.fallbacks == 0
    d.check_state()


def test_overflow_pair_sum_falls_back():
    """overflows_debits sums dp+dpos+amount; the eligibility bound must use
    the pair sums (regression: single-field max admitted a diverging batch)."""
    d = Differ()
    d.accounts([Account(id=1, ledger=1, code=1), Account(id=2, ledger=1, code=1),
                Account(id=3, ledger=1, code=1)])
    big = 1 << 127
    d.transfers([Transfer(id=1, debit_account_id=1, credit_account_id=2,
                          amount=big, ledger=1, code=1,
                          flags=int(TF.pending))])
    d.transfers([Transfer(id=2, pending_id=1, amount=(1 << 128) - 1,
                          flags=int(TF.post_pending_transfer))])
    d.transfers([Transfer(id=3, debit_account_id=1, credit_account_id=2,
                          amount=big - 10, ledger=1, code=1,
                          flags=int(TF.pending))])
    # dp + dpos + 100 overflows u128: must report overflows_debits exactly.
    res = d.transfers([Transfer(id=4, debit_account_id=1, credit_account_id=3,
                                amount=100, ledger=1, code=1)])
    assert res[0].status.name == "overflows_debits"
    d.check_state()


def test_chain_open_after_earlier_failure():
    """The open-chain terminator keeps linked_event_chain_open even when an
    earlier chain member failed (regression: broadcast rewrote it)."""
    d = Differ()
    d.accounts([Account(id=1, ledger=1, code=1), Account(id=2, ledger=1, code=1)])
    res = d.transfers([
        Transfer(id=10, debit_account_id=1, credit_account_id=2, amount=1,
                 ledger=0, code=1, flags=int(TF.linked)),
        Transfer(id=11, debit_account_id=1, credit_account_id=2, amount=1,
                 ledger=1, code=1, flags=int(TF.linked)),
    ])
    assert [r.status.name for r in res] == [
        "ledger_must_not_be_zero", "linked_event_chain_open"]
    # Same shape for accounts.
    res = d.accounts([
        Account(id=20, ledger=0, code=1, flags=int(AF.linked)),
        Account(id=21, ledger=1, code=1, flags=int(AF.linked)),
    ])
    assert [r.status.name for r in res] == [
        "ledger_must_not_be_zero", "linked_event_chain_open"]
    d.check_state()


def test_pulse_next_survives_chain_rollback():
    """pulse_next updates from a pending that was applied then rolled back by
    a chain break are kept (reference scope semantics)."""
    d = Differ()
    d.accounts([Account(id=1, ledger=1, code=1), Account(id=2, ledger=1, code=1)])
    res = d.transfers([
        Transfer(id=30, debit_account_id=1, credit_account_id=2, amount=1,
                 ledger=1, code=1, flags=int(TF.linked | TF.pending), timeout=5),
        Transfer(id=31, debit_account_id=1, credit_account_id=2, amount=1,
                 ledger=0, code=1),
    ])
    assert [r.status.name for r in res] == [
        "linked_event_failed", "ledger_must_not_be_zero"]
    assert d.led.fallbacks == 0  # must be exact on the fast path
    d.check_state()


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_fuzz_differential(seed):
    """Random workload biased to eligible batches with occasional hard ones."""
    rng = random.Random(seed)
    d = Differ()
    account_ids = list(range(1, 40))
    d.accounts([Account(id=i, ledger=1 + (i % 2), code=1,
                        flags=int(AF.history) if i % 7 == 0 else 0)
                for i in account_ids])
    next_id = 1000
    pending_ids = []
    for _ in range(12):
        batch = []
        n = rng.randrange(1, 40)
        for _ in range(n):
            roll = rng.random()
            tid = next_id
            next_id += 1
            if roll < 0.60:
                batch.append(Transfer(
                    id=tid,
                    debit_account_id=rng.choice(account_ids + [0, 99]),
                    credit_account_id=rng.choice(account_ids + [99]),
                    amount=rng.choice([0, 1, rng.randrange(1, 10**6)]),
                    ledger=rng.choice([1, 2]),
                    code=rng.choice([0, 1]),
                ))
            elif roll < 0.75:
                t = Transfer(
                    id=tid,
                    debit_account_id=rng.choice(account_ids),
                    credit_account_id=rng.choice(account_ids),
                    amount=rng.randrange(1, 100),
                    ledger=rng.choice([1, 2]), code=1,
                    flags=int(TF.pending),
                    timeout=rng.choice([0, 0, 5]),
                )
                pending_ids.append(tid)
                batch.append(t)
            elif roll < 0.88 and pending_ids:
                pid = rng.choice(pending_ids)
                post = rng.random() < 0.5
                batch.append(Transfer(
                    id=tid, pending_id=pid,
                    amount=U128_MAX if post else 0,
                    flags=int(TF.post_pending_transfer if post
                              else TF.void_pending_transfer),
                ))
            elif roll < 0.94:
                # duplicate of an existing id (exists path)
                batch.append(Transfer(
                    id=rng.randrange(1000, max(1001, next_id)),
                    debit_account_id=rng.choice(account_ids),
                    credit_account_id=rng.choice(account_ids),
                    amount=rng.randrange(0, 100),
                    ledger=1, code=1,
                ))
            else:
                # chain head
                batch.append(Transfer(
                    id=tid,
                    debit_account_id=rng.choice(account_ids),
                    credit_account_id=rng.choice(account_ids),
                    amount=rng.randrange(1, 100),
                    ledger=1, code=1,
                    flags=int(TF.linked),
                ))
        d.transfers(batch)
    d.check_state()


class TestDeviceHistoryRing:
    def test_snapshots_exact_on_hot_accounts(self):
        """Per-event balance snapshots are prefix sums: a hot account
        touched by many events in one batch (both as debit and credit)
        must match the oracle record-for-record (reference: account_event
        snapshots, src/state_machine.zig:4384-4470)."""
        from tigerbeetle_tpu.ops.ledger import DeviceLedger
        from tigerbeetle_tpu.oracle.state_machine import StateMachineOracle
        from tigerbeetle_tpu.types import Account, Transfer, TransferFlags

        led = DeviceLedger(a_cap=1 << 8, t_cap=1 << 10)
        sm = StateMachineOracle()
        accounts = [Account(id=i, ledger=1, code=1) for i in (1, 2, 3)]
        for engine in (led, sm):
            engine.create_accounts(accounts, 100)

        ts = 10_000
        batch1 = [
            Transfer(id=10, debit_account_id=1, credit_account_id=2,
                     amount=5, ledger=1, code=1),
            Transfer(id=11, debit_account_id=2, credit_account_id=1,
                     amount=3, ledger=1, code=1),
            Transfer(id=12, debit_account_id=1, credit_account_id=3,
                     amount=7, ledger=1, code=1,
                     flags=int(TransferFlags.pending)),
            Transfer(id=13, debit_account_id=3, credit_account_id=1,
                     amount=2, ledger=1, code=1),
            Transfer(id=14, debit_account_id=1, credit_account_id=2,
                     amount=11, ledger=1, code=1),
        ]
        for engine in (led, sm):
            engine.create_transfers(batch1, ts)
        ts += 1000
        batch2 = [  # resolve the pending + more traffic on account 1
            Transfer(id=20, pending_id=12, amount=7, ledger=1, code=1,
                     flags=int(TransferFlags.post_pending_transfer)),
            Transfer(id=21, debit_account_id=2, credit_account_id=1,
                     amount=1, ledger=1, code=1),
        ]
        for engine in (led, sm):
            engine.create_transfers(batch2, ts)

        assert led.fallbacks == 0, "must exercise the DEVICE history path"
        host = led.to_host()
        assert host.account_events == sm.account_events


class TestLimitHeadroomEligibility:
    """E3 relaxed: limit-flagged accounts ride the fast path when the
    batch provably fits their headroom; a potential breach falls back to
    the exact path (bit-exact either way)."""

    def _pair(self, funded):
        from tigerbeetle_tpu.oracle import StateMachineOracle
        from tigerbeetle_tpu.ops.ledger import DeviceLedger
        from tigerbeetle_tpu.types import Account, AccountFlags, Transfer

        limit = int(AccountFlags.debits_must_not_exceed_credits)
        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
        sm = StateMachineOracle()
        accts = [Account(id=i, ledger=1, code=1,
                         flags=limit if i % 2 == 0 else 0)
                 for i in range(1, 21)]
        for eng in (led, sm):
            eng.create_accounts(accts, 30)
        if funded:
            fund = [Transfer(id=100 + i, debit_account_id=1 + (i % 9) * 2,
                             credit_account_id=2 + (i % 10) * 2,
                             amount=10**6, ledger=1, code=1)
                    for i in range(10)]
            for eng in (led, sm):
                eng.create_transfers(fund, 10**6)
        return led, sm

    def test_healthy_limits_stay_fast(self):
        import numpy as np
        from tigerbeetle_tpu.types import Transfer

        led, sm = self._pair(funded=True)
        rng = np.random.default_rng(8)
        ts, nid = 10**9, 10**6
        for b in range(3):
            evs = [Transfer(id=nid + i,
                            debit_account_id=2 + int(rng.integers(0, 10)) * 2,
                            credit_account_id=1 + int(rng.integers(0, 10)) * 2,
                            amount=int(rng.integers(1, 50)), ledger=1, code=1)
                   for i in range(200)]
            nid += 200
            ts += 300
            got = led.create_transfers(evs, ts)
            want = sm.create_transfers(evs, ts)
            assert [(r.timestamp, r.status) for r in got] == \
                   [(r.timestamp, r.status) for r in want], b
        assert led.fallbacks == 0, "funded limits must stay on device"
        host = led.to_host()
        assert host.accounts == sm.accounts

    def test_breachable_limits_fall_back_exactly(self):
        import numpy as np
        from tigerbeetle_tpu.types import Transfer

        led, sm = self._pair(funded=False)  # zero balances: breaches real
        rng = np.random.default_rng(9)
        ts, nid = 10**9, 10**6
        evs = [Transfer(id=nid + i,
                        debit_account_id=2 + int(rng.integers(0, 10)) * 2,
                        credit_account_id=1 + int(rng.integers(0, 10)) * 2,
                        amount=int(rng.integers(1, 50)), ledger=1, code=1)
               for i in range(100)]
        ts += 150
        got = led.create_transfers(evs, ts)
        want = sm.create_transfers(evs, ts)
        assert [(r.timestamp, r.status) for r in got] == \
               [(r.timestamp, r.status) for r in want]
        assert led.fallbacks == 0, "breaches resolve on device now"
        assert led.fixpoint_batches == 1
        assert any(r.status.name == "exceeds_credits" for r in want)


class TestExactPulseScheduling:
    """E6 retired: mixed pending-with-timeout + post/void batches run on
    the fast path with the EXACT sequential pulse evolution computed in
    closed form (prefix-min + reset detection)."""

    def _pair(self):
        from tigerbeetle_tpu.oracle import StateMachineOracle
        from tigerbeetle_tpu.ops.ledger import DeviceLedger
        from tigerbeetle_tpu.types import Account

        led = DeviceLedger(a_cap=1 << 10, t_cap=1 << 12)
        sm = StateMachineOracle()
        accts = [Account(id=i, ledger=1, code=1) for i in range(1, 21)]
        for eng in (led, sm):
            eng.create_accounts(accts, 30)
        return led, sm

    def test_mixed_timeout_and_resolve_stays_fast(self):
        from tigerbeetle_tpu.types import Transfer, TransferFlags

        pend = int(TransferFlags.pending)
        post = int(TransferFlags.post_pending_transfer)
        void = int(TransferFlags.void_pending_transfer)
        led, sm = self._pair()
        ts = 10**9
        setup = [Transfer(id=100 + i, debit_account_id=1 + i,
                          credit_account_id=2 + i, amount=5, ledger=1,
                          code=1, flags=pend, timeout=100 + i)
                 for i in range(4)]
        ts += 10
        for eng in (led, sm):
            r = eng.create_transfers(setup, ts)
            assert all(x.status.name == "created" for x in r)
        # One batch mixing: a void of the EARLIEST pending (whose expiry
        # is the current pulse_next -> reset fires), new pendings with
        # earlier/later timeouts, and a post — interleaved so the
        # sequential evolution matters.
        mixed = [
            Transfer(id=200, debit_account_id=5, credit_account_id=6,
                     amount=3, ledger=1, code=1, flags=pend, timeout=500),
            Transfer(id=201, pending_id=100, amount=0, flags=void),
            Transfer(id=202, debit_account_id=7, credit_account_id=8,
                     amount=3, ledger=1, code=1, flags=pend, timeout=1),
            Transfer(id=203, pending_id=101, amount=5, flags=post),
        ]
        ts += 10
        got = led.create_transfers(mixed, ts)
        want = sm.create_transfers(mixed, ts)
        assert [(r.timestamp, r.status) for r in got] == \
               [(r.timestamp, r.status) for r in want]
        assert led.fallbacks == 0, "mixed batch must stay on device"
        host = led.to_host()
        assert host.pulse_next_timestamp == sm.pulse_next_timestamp
        assert host.expiry == sm.expiry
        # Expiry pulse after the mix behaves identically.
        later = ts + 10**12
        assert (led.pulse_needed(later), sm.pulse_needed(later)) == \
            (True, True)
        led.expire_pending_transfers(later)
        sm.expire_pending_transfers(later)
        host = led.to_host()
        assert host.pending_status == sm.pending_status
        assert host.pulse_next_timestamp == sm.pulse_next_timestamp

    def test_reset_fires_only_on_exact_running_pulse(self):
        """A void whose pending's expiry is NOT the running pulse must
        not reset it (the closed form's fired-detection edge)."""
        from tigerbeetle_tpu.types import Transfer, TransferFlags

        pend = int(TransferFlags.pending)
        void = int(TransferFlags.void_pending_transfer)
        led, sm = self._pair()
        ts = 10**9
        setup = [
            Transfer(id=100, debit_account_id=1, credit_account_id=2,
                     amount=5, ledger=1, code=1, flags=pend, timeout=50),
            Transfer(id=101, debit_account_id=3, credit_account_id=4,
                     amount=5, ledger=1, code=1, flags=pend, timeout=900),
        ]
        ts += 10
        for eng in (led, sm):
            eng.create_transfers(setup, ts)
        # A pulse scan (nothing due) recomputes pulse_next to the real
        # minimum (it sits at TIMESTAMP_MIN until then).
        led.expire_pending_transfers(ts + 1)
        sm.expire_pending_transfers(ts + 1)
        host0 = led.to_host()
        assert host0.pulse_next_timestamp == sm.pulse_next_timestamp != 1
        # expire() put the standalone ledger into its mirror regime; drop
        # the mirror so the next batch exercises the device kernel.
        led.mirror = None
        led._mirror_batches = 0
        # Void the LATER-expiring pending: pulse_next tracks id=100's
        # earlier expiry, so no reset fires.
        batch = [
            Transfer(id=200, pending_id=101, amount=0, flags=void),
            Transfer(id=201, debit_account_id=5, credit_account_id=6,
                     amount=1, ledger=1, code=1, flags=pend, timeout=2000),
        ]
        ts += 10
        got = led.create_transfers(batch, ts)
        want = sm.create_transfers(batch, ts)
        assert [(r.timestamp, r.status) for r in got] == \
               [(r.timestamp, r.status) for r in want]
        assert led.fallbacks == 0
        host = led.to_host()
        assert host.pulse_next_timestamp == sm.pulse_next_timestamp
        assert host.pulse_next_timestamp != 1  # no spurious reset
