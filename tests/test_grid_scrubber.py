"""GridScrubber repair-request path (ISSUE 4 satellite): a scrub cycle
over a grid with injected bad blocks surfaces every fault, issues peer
repairs WITHIN the repair budget, and converges back to byte-identical
grids. (FaultDetector/RepairBudget already have direct units in
test_vsr_components; this covers the scrub -> block_repair ->
request_blocks -> on_block loop end to end.)
"""

from tests.test_vsr import (
    _create_accounts_body,
    _create_transfers_body,
    _drive,
)
from tigerbeetle_tpu.testing.cluster import Cluster
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.grid_scrubber import GridScrubber
from tigerbeetle_tpu.vsr.header import Command
from tigerbeetle_tpu.vsr.storage import TEST_LAYOUT


def _setup(seed, n_transfers=20):
    """3-replica cluster with enough commits to populate the grid."""
    cluster = Cluster(seed=seed, replica_count=3)
    client = cluster.client(80 + seed)
    _drive(cluster, client, [
        (Operation.create_accounts, _create_accounts_body([1, 2]))])
    for k in range(n_transfers):
        _drive(cluster, client, [
            (Operation.create_transfers,
             _create_transfers_body([(100 + k, 1, 2, 1)]))])
    cluster.settle()
    return cluster, client


def _corrupt_reachable(cluster, victim, prng_like, count):
    """Flip one byte inside `count` reachable blocks' checksummed region;
    returns the corrupted block indices."""
    replica = cluster.replicas[victim]
    storage = cluster.storages[victim]
    zones = TEST_LAYOUT.zone_offsets
    bs = TEST_LAYOUT.grid_block_size
    blocks = sorted({(a.index, size)
                     for _, a, size in replica.scrubber._blocks()})
    victims = blocks[:: max(1, len(blocks) // count)][:count]
    for index, size in victims:
        storage.data[zones["grid"] + index * bs + size // 2] ^= 0xFF
    return [index for index, _ in victims]


class TestScrubRepairPath:
    def test_scrub_cycle_repairs_within_budget_and_converges(self):
        cluster, _client = _setup(41)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        replica = cluster.replicas[victim]
        # Fast tour so the test doesn't wait out the production pacing.
        replica.scrubber = GridScrubber(replica.durable.forest,
                                        cycle_ticks=8, origin_seed=victim)
        corrupted = _corrupt_reachable(cluster, victim, None, 3)
        assert corrupted
        requests = []
        t_start = cluster.time.now
        orig = replica.bus.send_to_replica

        def spy(dst, msg):
            if msg.header.command == Command.request_blocks:
                requests.append(cluster.time.now)
            orig(dst, msg)

        replica.bus.send_to_replica = spy
        ok = cluster.run(6000, until=lambda: (
            replica.scrubber.cycles >= 1
            and not replica.scrubber.faults
            and not replica.block_repair))
        assert ok, (replica.scrubber.faults, replica.block_repair)
        # Faults were surfaced by the scrub (not silently skipped) and
        # repairs were requested...
        assert replica.scrubber.checked > 0
        assert requests, "no repair requests issued for scrubbed faults"
        # ...WITHIN the budget: the token bucket (capacity 8, one token
        # per 50ms) bounds how many request_blocks rounds may have gone
        # out in the elapsed simulated time.
        budget = replica.repair_budget
        elapsed = cluster.time.now - t_start
        allowed = budget.capacity + elapsed // budget.refill_interval_ns
        assert len(requests) <= allowed, (len(requests), allowed)
        # ...and the repaired bytes are bit-identical to a healthy peer.
        donor = next(i for i in range(3) if i != victim)
        bs = TEST_LAYOUT.grid_block_size
        for index in corrupted:
            assert (cluster.storages[victim].read("grid", index * bs, bs)
                    == cluster.storages[donor].read("grid", index * bs, bs))
        cluster.settle()

    def test_certify_surfaces_every_fault_at_once(self):
        """certify() (the post-rebuild pass) is an unpaced full tour: all
        injected faults surface in ONE call, then the ordinary repair
        loop drains them."""
        cluster, _client = _setup(42)
        victim = (cluster.replicas[0].primary_index() + 2) % 3
        replica = cluster.replicas[victim]
        corrupted = set(_corrupt_reachable(cluster, victim, None, 2))
        faults = replica.scrubber.certify()
        assert {a.index for _, a, _ in faults} >= corrupted
        for name, address, size in faults:
            replica.block_repair[address.index] = (name, address, size)
        ok = cluster.run(4000, until=lambda: not replica.block_repair)
        assert ok, replica.block_repair
        # A clean re-certification proves convergence.
        assert replica.scrubber.certify() == []
        cluster.settle()

    def test_scrub_fault_dropped_when_block_freed(self):
        """A queued repair whose table was compacted away resolves itself
        (still_referenced) instead of re-requesting forever."""
        cluster, client = _setup(43, n_transfers=8)
        victim = (cluster.replicas[0].primary_index() + 1) % 3
        replica = cluster.replicas[victim]
        corrupted = _corrupt_reachable(cluster, victim, None, 1)
        faults = replica.scrubber.certify()
        assert faults
        for name, address, size in faults:
            replica.block_repair[address.index] = (name, address, size)
        # Churn the forest so compaction rewrites tables; any entry whose
        # address fell out of the manifests must be dropped, and the
        # repair queue must drain either way (repaired or moot).
        for k in range(24):
            _drive(cluster, client, [
                (Operation.create_transfers,
                 _create_transfers_body([(900 + k, 1, 2, 1)]))])
        ok = cluster.run(4000, until=lambda: not replica.block_repair)
        assert ok, replica.block_repair
        cluster.settle()
