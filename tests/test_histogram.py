"""Histogram correctness: bucket round-trip, lossless merge
associativity, quantile relative-error bound vs exact numpy quantiles,
and the degenerate (empty / one-sample) cases."""

import json

import numpy as np
import pytest

from tigerbeetle_tpu.trace.histogram import (
    Histogram, REL_ERROR, SUB, bucket_index, bucket_mid, bucket_upper)


# ------------------------------------------------------------- buckets

def test_bucket_boundary_round_trip():
    # Every value lands in a bucket whose [lower, upper) straddles it,
    # and the midpoint is within REL_ERROR of any value in the bucket.
    rng = np.random.default_rng(7)
    for v in np.concatenate([
            10.0 ** rng.uniform(-6, 6, size=500),
            [1e-9, 1.0, 2.0, 1000.0, 2.0 ** 20]]):
        i = bucket_index(float(v))
        lower = bucket_upper(i - 1)
        upper = bucket_upper(i)
        assert lower <= v < upper * (1 + 1e-12)
        mid = bucket_mid(i)
        assert abs(mid - v) / v <= REL_ERROR * (1 + 1e-9)


def test_bucket_exact_powers_of_two():
    # 2^k is the inclusive lower edge of its octave's first sub-bucket.
    for k in (-4, 0, 1, 10):
        assert bucket_index(2.0 ** k) == k * SUB


def test_record_round_trip_through_dict():
    h = Histogram()
    h.record_many([0.5, 1.5, 3.0, 900.0, 0.0, -2.0])
    d = json.loads(json.dumps(h.to_dict()))  # survives JSON
    h2 = Histogram.from_dict(d)
    assert h2.count == h.count
    assert h2.zero_count == h.zero_count == 2
    assert h2.buckets == h.buckets
    assert h2.min == h.min == -2.0
    assert h2.max == h.max == 900.0
    assert h2.quantile(0.5) == h.quantile(0.5)


def test_layout_mismatch_rejected():
    with pytest.raises(AssertionError):
        Histogram.from_dict({"sub_bits": 3, "buckets": {}})


# --------------------------------------------------------------- merge

def test_merge_associative_and_lossless():
    # Three "replicas" record disjoint slices of one sample set; any
    # merge order reproduces the histogram of the whole set exactly.
    rng = np.random.default_rng(11)
    vals = rng.lognormal(mean=3.0, sigma=1.5, size=3000)
    whole = Histogram()
    whole.record_many(vals)
    parts = []
    for chunk in np.array_split(vals, 3):
        h = Histogram()
        h.record_many(chunk)
        parts.append(h)
    a = Histogram.merged([parts[0], parts[1], parts[2]])
    b = Histogram.merged([Histogram.merged(parts[2:]), parts[0], parts[1]])
    for m in (a, b):
        assert m.buckets == whole.buckets
        assert m.count == whole.count
        assert m.min == whole.min and m.max == whole.max
        assert m.sum == pytest.approx(whole.sum)
        for q in (0.5, 0.95, 0.99):
            assert m.quantile(q) == whole.quantile(q)


def test_merge_returns_self_for_chaining():
    h = Histogram()
    other = Histogram()
    other.record(5.0)
    assert h.merge(other) is h
    assert h.count == 1


# ------------------------------------------------------------ quantiles

def _rel_err(got, want):
    return abs(got - want) / want


def test_quantile_rel_error_bimodal():
    # Fast-path/slow-path mixture: the shape the serving router
    # produces (chain route vs fallback).
    rng = np.random.default_rng(23)
    vals = np.concatenate([rng.normal(100.0, 5.0, size=9000),
                           rng.normal(5000.0, 200.0, size=1000)])
    vals = np.abs(vals)
    h = Histogram()
    h.record_many(vals)
    # q=0.9 sits exactly on the mode boundary, where numpy interpolates
    # across the gap between modes — any bucketed sketch "disagrees"
    # there by construction, so probe either side of the cliff instead.
    for q in (0.10, 0.50, 0.85, 0.99, 0.999):
        exact = float(np.quantile(vals, q))
        got = h.quantile(q)
        # 2x: REL_ERROR bounds bucket rounding; nearest-rank vs numpy's
        # interpolated quantile adds at most one sample of separation.
        assert _rel_err(got, exact) <= 2 * REL_ERROR + 0.01, (q, got, exact)


def test_quantile_rel_error_heavy_tail():
    rng = np.random.default_rng(31)
    vals = rng.pareto(a=1.5, size=20000) + 1.0
    h = Histogram()
    h.record_many(vals)
    for q in (0.5, 0.9, 0.99):
        exact = float(np.quantile(vals, q))
        assert _rel_err(h.quantile(q), exact) <= 2 * REL_ERROR + 0.01


def test_empty_histogram():
    h = Histogram()
    assert h.count == 0
    assert h.quantile(0.5) is None
    assert h.cumulative() == []
    s = h.summary()
    assert s["count"] == 0 and s["p99"] is None and s["min"] is None


def test_one_sample_exact():
    h = Histogram()
    h.record(42.0)
    # min/max clipping makes every quantile of a singleton exact.
    for q in (0.0, 0.5, 0.99, 1.0):
        assert h.quantile(q) == 42.0
    assert h.summary()["p999"] == 42.0


def test_zero_and_negative_samples():
    h = Histogram()
    h.record_many([0.0, -1.0, 0.0, 10.0])
    assert h.zero_count == 3
    assert h.quantile(0.5) == -1.0  # exact floor for non-positive mass
    assert h.quantile(1.0) == 10.0
    cum = h.cumulative()
    assert cum[0] == (0.0, 3)  # zero bucket first
    assert cum[-1][1] == 4


def test_cumulative_monotone():
    rng = np.random.default_rng(41)
    h = Histogram()
    h.record_many(rng.exponential(50.0, size=500))
    cum = h.cumulative()
    uppers = [u for u, _ in cum]
    counts = [c for _, c in cum]
    assert uppers == sorted(uppers)
    assert counts == sorted(counts)
    assert counts[-1] == h.count
