"""LSM engine tests: tables, tree semantics across compactions, forest
checkpoint/restore, and byte-determinism of the grid."""

import random
import struct

import pytest

from tigerbeetle_tpu.lsm.grid import Grid, MemoryDevice
from tigerbeetle_tpu.lsm.table import Table, release_table, write_table
from tigerbeetle_tpu.lsm.tree import BAR_LENGTH, Tree
from tigerbeetle_tpu.lsm.forest import Forest

KEY = 8
VAL = 16


def _grid(blocks=4096, block_size=4096):
    return Grid(MemoryDevice(blocks * block_size), block_size=block_size,
                block_count=blocks)


def k(i):
    return struct.pack(">Q", i)  # big-endian: numeric order == bytes order


def v(i):
    return struct.pack(">QQ", i, i * 7)


class TestTable:
    def test_write_read_multiblock(self):
        grid = _grid(block_size=4096)
        entries = [(k(i), v(i)) for i in range(2000)]  # ~12 value blocks
        info = write_table(grid, entries, KEY, VAL)
        table = Table(grid, info, KEY, VAL)
        assert len(table.block_addresses) > 1
        assert table.get(k(0)) == v(0)
        assert table.get(k(1999)) == v(1999)
        assert table.get(k(777)) == v(777)
        assert table.get(k(5000)) is None
        assert list(table.iter_entries()) == entries

    def test_corruption_detected(self):
        grid = _grid()
        info = write_table(grid, [(k(1), v(1))], KEY, VAL)
        grid.device.data[info.index_address.index * grid.block_size] ^= 0xFF
        with pytest.raises(IOError):
            Table(grid, info, KEY, VAL)


class TestTree:
    def test_put_get_overwrite_remove_across_flushes(self):
        tree = Tree(_grid(), key_size=KEY, value_size=VAL)
        model = {}
        rng = random.Random(3)
        for i in range(2000):
            key = rng.randrange(300)
            if rng.random() < 0.15:
                tree.remove(k(key))
                model.pop(k(key), None)
            else:
                tree.put(k(key), v(i))
                model[k(key)] = v(i)
            tree.compact_beat()
        for key in range(300):
            assert tree.get(k(key)) == model.get(k(key)), key
        got = tree.scan(k(0), k(299))
        assert got == sorted(model.items())
        # Deep levels actually formed.
        assert sum(len(lv) for lv in tree.levels[1:]) > 0

    def test_scan_range(self):
        tree = Tree(_grid(), key_size=KEY, value_size=VAL)
        for i in range(100):
            tree.put(k(i), v(i))
            tree.compact_beat()
        tree.flush_memtable()
        assert [kk for kk, _ in tree.scan(k(10), k(19))] == [
            k(i) for i in range(10, 20)]


class TestForest:
    SCHEMA = {"accounts": (KEY, VAL), "transfers": (KEY, VAL)}

    def test_checkpoint_reopen(self):
        grid = _grid()
        forest = Forest(grid, self.SCHEMA)
        for i in range(200):
            forest.trees["accounts"].put(k(i), v(i))
            forest.trees["transfers"].put(k(1000 + i), v(i))
            forest.compact_beat()
        root = forest.checkpoint()

        # Re-open over the same device bytes.
        grid2 = Grid(grid.device, block_size=grid.block_size,
                     block_count=grid.block_count)
        forest2 = Forest(grid2, self.SCHEMA)
        forest2.open(root)
        for i in range(200):
            assert forest2.trees["accounts"].get(k(i)) == v(i)
            assert forest2.trees["transfers"].get(k(1000 + i)) == v(i)
        # Free set restored: allocations continue without clobbering data.
        for i in range(200, 260):
            forest2.trees["accounts"].put(k(i), v(i))
            forest2.compact_beat()
        forest2.trees["accounts"].flush_memtable()
        assert forest2.trees["accounts"].get(k(0)) == v(0)
        assert forest2.trees["accounts"].get(k(259)) == v(259)

    def test_checkpoint_discards_pending_frees_until_flip(self):
        grid = _grid(blocks=256)
        forest = Forest(grid, {"t": (KEY, VAL)})
        tree = forest.trees["t"]
        for i in range(600):
            tree.put(k(i % 50), v(i))
            tree.compact_beat()
        free_before = sum(grid.free)
        assert grid.freed_pending  # compactions released blocks
        forest.checkpoint()
        assert not grid.freed_pending
        assert sum(grid.free) >= free_before  # frees landed at the flip


def test_grid_byte_determinism():
    """Same op sequence => byte-identical device contents (the property
    replica repair relies on; reference: docs/ARCHITECTURE.md:281-307)."""

    def run():
        grid = _grid(blocks=512)
        forest = Forest(grid, {"a": (KEY, VAL), "b": (KEY, VAL)})
        rng = random.Random(42)
        for i in range(1500):
            tree = forest.trees["a" if rng.random() < 0.7 else "b"]
            key = rng.randrange(200)
            if rng.random() < 0.1:
                tree.remove(k(key))
            else:
                tree.put(k(key), v(i))
            forest.compact_beat()
        root = forest.checkpoint()
        return bytes(grid.device.data), root

    bytes1, root1 = run()
    bytes2, root2 = run()
    assert root1 == root2
    assert bytes1 == bytes2
