"""LSM engine tests: tables, tree semantics across compactions, forest
checkpoint/restore, and byte-determinism of the grid."""

import random
import struct

import pytest

from tigerbeetle_tpu.lsm.grid import Grid, MemoryDevice
from tigerbeetle_tpu.lsm.table import Table, release_table, write_table
from tigerbeetle_tpu.lsm.tree import BAR_LENGTH, Tree
from tigerbeetle_tpu.lsm.forest import Forest

KEY = 8
VAL = 16


def _grid(blocks=4096, block_size=4096):
    return Grid(MemoryDevice(blocks * block_size), block_size=block_size,
                block_count=blocks)


def k(i):
    return struct.pack(">Q", i)  # big-endian: numeric order == bytes order


def v(i):
    return struct.pack(">QQ", i, i * 7)


class TestTable:
    def test_write_read_multiblock(self):
        grid = _grid(block_size=4096)
        entries = [(k(i), v(i)) for i in range(2000)]  # ~12 value blocks
        info = write_table(grid, entries, KEY, VAL)
        table = Table(grid, info, KEY, VAL)
        assert len(table.block_addresses) > 1
        assert table.get(k(0)) == v(0)
        assert table.get(k(1999)) == v(1999)
        assert table.get(k(777)) == v(777)
        assert table.get(k(5000)) is None
        assert list(table.iter_entries()) == entries

    def test_corruption_detected(self):
        grid = _grid()
        info = write_table(grid, [(k(1), v(1))], KEY, VAL)
        grid.device.data[info.index_address.index * grid.block_size] ^= 0xFF
        grid.cache.clear()  # cold read (a warm cache legitimately serves
        # the immutable copy; detection is the media-read path's job)
        with pytest.raises(IOError):
            Table(grid, info, KEY, VAL)
        # The scrubber's bypass path detects it even through a warm cache.
        info2 = write_table(grid, [(k(2), v(2))], KEY, VAL)
        grid.device.data[info2.index_address.index * grid.block_size] ^= 0xFF
        with pytest.raises(IOError):
            grid.read_block(info2.index_address, info2.index_size,
                            bypass_cache=True)
        # While the serving path still reads the cached immutable copy.
        assert grid.read_block(info2.index_address, info2.index_size)


class TestTree:
    def test_put_get_overwrite_remove_across_flushes(self):
        tree = Tree(_grid(), key_size=KEY, value_size=VAL)
        model = {}
        rng = random.Random(3)
        for i in range(2000):
            key = rng.randrange(300)
            if rng.random() < 0.15:
                tree.remove(k(key))
                model.pop(k(key), None)
            else:
                tree.put(k(key), v(i))
                model[k(key)] = v(i)
            tree.compact_beat()
        for key in range(300):
            assert tree.get(k(key)) == model.get(k(key)), key
        got = tree.scan(k(0), k(299))
        assert got == sorted(model.items())
        # Deep levels actually formed.
        assert sum(len(lv) for lv in tree.levels[1:]) > 0

    def test_scan_range(self):
        tree = Tree(_grid(), key_size=KEY, value_size=VAL)
        for i in range(100):
            tree.put(k(i), v(i))
            tree.compact_beat()
        tree.flush_memtable()
        assert [kk for kk, _ in tree.scan(k(10), k(19))] == [
            k(i) for i in range(10, 20)]


class TestForest:
    SCHEMA = {"accounts": (KEY, VAL), "transfers": (KEY, VAL)}

    def test_checkpoint_reopen(self):
        grid = _grid()
        forest = Forest(grid, self.SCHEMA)
        for i in range(200):
            forest.trees["accounts"].put(k(i), v(i))
            forest.trees["transfers"].put(k(1000 + i), v(i))
            forest.compact_beat()
        root = forest.checkpoint()

        # Re-open over the same device bytes.
        grid2 = Grid(grid.device, block_size=grid.block_size,
                     block_count=grid.block_count)
        forest2 = Forest(grid2, self.SCHEMA)
        forest2.open(root)
        for i in range(200):
            assert forest2.trees["accounts"].get(k(i)) == v(i)
            assert forest2.trees["transfers"].get(k(1000 + i)) == v(i)
        # Free set restored: allocations continue without clobbering data.
        for i in range(200, 260):
            forest2.trees["accounts"].put(k(i), v(i))
            forest2.compact_beat()
        forest2.trees["accounts"].flush_memtable()
        assert forest2.trees["accounts"].get(k(0)) == v(0)
        assert forest2.trees["accounts"].get(k(259)) == v(259)

    def test_checkpoint_discards_pending_frees_until_flip(self):
        grid = _grid(blocks=256)
        forest = Forest(grid, {"t": (KEY, VAL)})
        tree = forest.trees["t"]
        for i in range(600):
            tree.put(k(i % 50), v(i))
            tree.compact_beat()
        free_before = sum(grid.free)
        assert grid.freed_pending  # compactions released blocks
        forest.checkpoint()
        assert not grid.freed_pending
        assert sum(grid.free) >= free_before  # frees landed at the flip


def test_grid_byte_determinism():
    """Same op sequence => byte-identical device contents (the property
    replica repair relies on; reference: docs/ARCHITECTURE.md:281-307)."""

    def run():
        grid = _grid(blocks=512)
        forest = Forest(grid, {"a": (KEY, VAL), "b": (KEY, VAL)})
        rng = random.Random(42)
        for i in range(1500):
            tree = forest.trees["a" if rng.random() < 0.7 else "b"]
            key = rng.randrange(200)
            if rng.random() < 0.1:
                tree.remove(k(key))
            else:
                tree.put(k(key), v(i))
            forest.compact_beat()
        root = forest.checkpoint()
        return bytes(grid.device.data), root

    bytes1, root1 = run()
    bytes2, root2 = run()
    assert root1 == root2
    assert bytes1 == bytes2


class TestIncrementalCompaction:
    """VERDICT r1 #5: compaction work must spread across the bar's beats
    (no stop-the-world at bar boundaries), stay deterministic in the op
    sequence, and never expose partial grid state mid-bar."""

    def _loaded_tree(self, n_bars=8, per_bar=200):
        from tigerbeetle_tpu.lsm.tree import BAR_LENGTH, Tree

        grid = _grid()
        tree = Tree(grid, key_size=8, value_size=16, name="t")
        op = 0
        for bar in range(n_bars):
            for beat in range(BAR_LENGTH):
                op += 1
                k = (bar * BAR_LENGTH + beat) % per_bar
                tree.put(k.to_bytes(8, "big"), op.to_bytes(16, "big"))
                tree.compact_beat(op)
        return tree, op

    def test_work_spreads_across_beats(self):
        from tigerbeetle_tpu.lsm.tree import BAR_LENGTH

        tree, op = self._loaded_tree()
        # Force an over-budget L0 so the next bar schedules a job.
        while not tree._jobs:
            op += 1
            tree.put(b"\xff" * 8, op.to_bytes(16, "big"))
            tree.compact_beat(op)
            if op > 10_000:
                raise AssertionError("no job ever scheduled")
        job = tree._jobs[0]
        budget = tree._per_beat
        assert budget * (BAR_LENGTH - 1) >= job.total
        # Each mid-bar beat merges at most the per-beat budget (+1 slack).
        merged_before = len(job.merged)
        progressed = False
        while tree._jobs and op % BAR_LENGTH != BAR_LENGTH - 1:
            op += 1
            tree.compact_beat(op)
            if tree._jobs:
                now = len(tree._jobs[0].merged)
                assert now - merged_before <= budget + 1
                progressed = progressed or now > merged_before
                merged_before = now
        assert progressed or not tree._jobs
        # By the bar's drain beat every scheduled job has installed (the
        # NEXT bar boundary may legitimately schedule fresh jobs).
        while True:
            op += 1
            tree.compact_beat(op)
            if op % BAR_LENGTH == BAR_LENGTH - 1:
                break
        assert not tree._jobs

    def test_reads_consistent_while_job_in_flight(self):
        tree, op = self._loaded_tree(n_bars=6)
        # Capture ground truth, then advance into a bar with live jobs and
        # verify every key still reads its newest value at every beat.
        want = {k: tree.get(k.to_bytes(8, "big")) for k in range(200)}
        from tigerbeetle_tpu.lsm.tree import BAR_LENGTH

        for _ in range(2 * BAR_LENGTH):
            op += 1
            tree.compact_beat(op)
            for k in (0, 57, 130, 199):
                assert tree.get(k.to_bytes(8, "big")) == \
                    want[k], (k, op)

    def test_deterministic_vs_oneshot_replay(self):
        """Two trees fed the identical op sequence (one with a mid-run
        manifest pack/restore, i.e. a checkpoint+restart) end with the
        identical manifest — physical determinism survives the
        incremental pacing."""
        from tigerbeetle_tpu.lsm.tree import BAR_LENGTH

        def run(checkpoint_at, restart):
            from tigerbeetle_tpu.lsm.tree import Tree

            tree = Tree(_grid(), key_size=8, value_size=16, name="t")
            for op in range(1, 6 * BAR_LENGTH + 1):
                k = op % 100
                tree.put(k.to_bytes(8, "big"), op.to_bytes(16, "big"))
                tree.compact_beat(op)
                if op == checkpoint_at:
                    # Every replica checkpoints at the same op (the
                    # manifest pack flushes the memtable mid-bar on all
                    # of them identically).
                    raw = tree.manifest_pack()
                    if restart:
                        tree.manifest_restore(raw)
            return tree.manifest_pack()

        # Checkpoint-and-continue vs checkpoint-crash-restart-replay must
        # converge to the identical manifest — at a bar boundary AND
        # mid-bar while compaction jobs are in flight (the manifest
        # persists the job plans, so the restored tree resumes the same
        # merges and installs them at the same beat).
        for ckpt in (4 * BAR_LENGTH, 4 * BAR_LENGTH + 3,
                     4 * BAR_LENGTH + 17, 4 * BAR_LENGTH + 30):
            cont = run(ckpt, restart=False)
            rest = run(ckpt, restart=True)
            assert cont == rest, ckpt


class TestMemtableSplit:
    """Mutable/immutable memtable pair (reference: tree.zig:543 swap +
    table_memory.zig): the frozen memtable stays readable while its flush
    job streams it into level-0 tables across the bar's beats."""

    def test_frozen_rows_readable_while_flush_in_flight(self):
        grid = _grid()
        tree = Tree(grid, key_size=8, value_size=16, name="t")
        op = 0
        for i in range(300):
            tree.put(k(i), v(i))
        op += 32
        tree.compact_beat(op)  # bar boundary: freeze, do NOT drain yet
        # Mid-freeze: rows must come from the immutable map (L0 not yet
        # fully installed) and reads must be exact on every beat.
        saw_pending_flush = tree._flush is not None
        for beat in range(1, 32):
            for i in range(0, 300, 37):
                assert tree.get(k(i)) == v(i), (beat, i)
            assert dict(tree.scan(k(0), k(299)))[k(123)] == v(123)
            op += 1
            tree.compact_beat(op)
        assert saw_pending_flush, "freeze must defer the write-out"
        assert tree._flush is None and not tree.immutable_map
        assert len(tree.levels[0]) >= 1
        # New puts during the flight went to the NEW mutable memtable.
        tree.put(k(1), v(9999))
        assert tree.get(k(1)) == v(9999)

    def test_flush_work_spreads_across_beats(self):
        grid = _grid()
        tree = Tree(grid, key_size=8, value_size=16, name="t")
        op = 0
        for i in range(2000):
            tree.put(k(i), v(i))
        op += 32
        tree.compact_beat(op)
        job = tree._flush
        assert job is not None
        budget = tree._flush_per_beat
        last = job.pos
        while tree._flush is not None and op % 32 != 31:
            op += 1
            tree.compact_beat(op)
            if tree._flush is not None:
                # Whole value blocks: progress per beat bounded by the
                # budget rounded up to the block size.
                per_block = max(1, (grid.block_size - 4) // 24)
                assert tree._flush.pos - last <= budget + per_block
                last = tree._flush.pos
        # Fully installed by the drain beat at the latest.
        while op % 32 != 31:
            op += 1
            tree.compact_beat(op)
        assert tree._flush is None
        for i in range(0, 2000, 97):
            assert tree.get(k(i)) == v(i)

    def test_snapshot_reads_stable_across_flush_install(self):
        """A snapshot taken while the flush is in flight must answer
        identically before and after the tables install (the frozen rows
        are logically table-visible from the freeze op on)."""
        grid = _grid()
        tree = Tree(grid, key_size=8, value_size=16, name="t")
        for i in range(500):
            tree.put(k(i), v(i))
        tree.compact_beat(32)  # freeze; flush streams over the bar
        assert tree._flush is not None
        s = 33
        before = tree.get(k(123), snapshot=s)
        scan_before = dict(tree.scan(k(100), k(130), snapshot=s))
        for op in range(33, 64):
            tree.compact_beat(op)
        assert tree._flush is None  # installed
        assert tree.get(k(123), snapshot=s) == before == v(123)
        assert dict(tree.scan(k(100), k(130), snapshot=s)) == scan_before
        # A snapshot BEFORE the freeze still excludes those rows.
        assert tree.get(k(123), snapshot=31) is None
