"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; shardings are validated on a virtual
CPU mesh (the reference's analogous trick is compile-time-injecting simulated
Storage/MessageBus into real replicas — src/testing/cluster.zig:58).

The environment pins JAX_PLATFORMS=axon (the TPU tunnel), so env vars alone
are not enough: jax.config.update must run before any backend initializes.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _verify_flag_isolated():
    """constants.VERIFY is process-global and the simulator flips it on
    (VOPR doctrine); restore it around every test so a Cluster in one
    test cannot silently enable extra checks (or fire their asserts) in
    unrelated later tests."""
    from tigerbeetle_tpu import constants

    was = constants.VERIFY
    yield
    constants.set_verify(was)


@pytest.fixture(autouse=True, scope="module")
def _bound_live_executables():
    """Full-suite single-process runs accumulate hundreds of compiled
    XLA executables; past a threshold the CPU backend's compiler
    segfaults DETERMINISTICALLY (observed twice at the same test with
    identical stacks — compile of the ring window kernel after ~530
    tests — while the same module passes in isolation). Clearing the
    jit caches at module boundaries bounds live executables; modules
    recompile what they use, trading some wall time for a crash-free
    single-command suite run."""
    yield
    jax.clear_caches()
