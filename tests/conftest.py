"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; shardings are validated on a virtual
CPU mesh (the reference's analogous trick is compile-time-injecting simulated
Storage/MessageBus into real replicas — src/testing/cluster.zig:58).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
