"""Scripted consensus scenarios (reference: src/vsr/replica_test.zig —
exact fault sequences that randomized simulation rarely hits;
docs/internals/vopr.md:44-46). Message-level tests drive a single
sans-io replica; the NACK-specific scenarios live in tests/test_nack.py.
"""

from tests.test_nack import (
    CLUSTER,
    _dvc,
    _mk_replica,
    _prepare_msg,
    _svc,
)
from tigerbeetle_tpu.vsr.header import Command, Header, Message


def _chain(n, start_op=1, parent=0, view=0):
    msgs = []
    for op in range(start_op, start_op + n):
        m = _prepare_msg(op, view=view, parent=parent)
        parent = m.header.checksum
        msgs.append(m)
    return msgs


class TestViewChangeScenarios:
    def test_view_change_with_gap_repairs_before_start(self):
        """The new primary's journal has a hole inside the chosen suffix:
        it must repair the body from a peer BEFORE broadcasting
        start_view (a suffix with holes would strand backups)."""
        r, bus, _ = _mk_replica(2)
        msgs = _chain(5)
        for m in msgs:
            if m.header.op != 3:  # the hole
                r.journal.append(m)
        r.op = 5
        r.commit_min = r.commit_max = 2
        for peer in (3, 4, 5):
            r.on_message(_svc(peer, 2))
        headers = [m.header for m in msgs]
        r.on_message(_dvc(3, 2, 5, 2, 0, headers))
        r.on_message(_dvc(4, 2, 5, 2, 0, headers))
        r.on_message(_dvc(5, 2, 5, 2, 0, headers))
        # Pending: op 3's body is missing; no start_view yet.
        assert r._pending_view == 2
        assert not bus.of(Command.start_view)
        # Requests go out on the repair tick; a peer serves the body.
        # (Small advances: a long gap would escalate the view-change
        # timer to the next view.)
        r.time.advance(60 * 10**6)
        r.tick()
        assert any(m.header.op == 3
                   for _, m in bus.of(Command.request_prepare))
        r.on_message(msgs[2])  # the prepare for op 3 arrives
        r.time.advance(60 * 10**6)
        r.tick()  # repair completion check finalizes the view
        assert r._pending_view is None and r.status == "normal"
        assert bus.of(Command.start_view)
        assert r.journal.read_prepare(3) is not None

    def test_duplicate_and_stale_prepares_are_idempotent(self):
        """Replayed/duplicated prepares must not corrupt the journal or
        double-ack (the bus contract allows duplication)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        r.view = 0
        msgs = _chain(3)
        for m in msgs:
            r.on_message(m)
        assert r.op == 3
        acked_ops = {m.header.op for _, m in bus.of(Command.prepare_ok)}
        assert acked_ops, "backup must ack prepares"
        for m in msgs:  # replay all (the bus may duplicate)
            r.on_message(m)
        assert r.op == 3
        # Re-acks are fine (idempotent at the primary); journal intact.
        for op in (1, 2, 3):
            held = r.journal.read_prepare(op)
            assert held is not None
            assert held.header.checksum == msgs[op - 1].header.checksum

    def test_lower_view_messages_rejected(self):
        """A replica that moved to view 2 ignores view-0 prepares (an
        isolated stale primary cannot fork it)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        msgs = _chain(2)
        for m in msgs:
            r.on_message(m)
        r.view = 2
        r.log_view = 2
        stale = _prepare_msg(3, view=0,
                             parent=msgs[-1].header.checksum)
        r.on_message(stale)
        assert r.journal.read_prepare(3) is None
        assert r.op == 2

    def test_dvc_from_two_elections_highest_log_view_wins(self):
        """Log selection is (log_view, op)-max: a shorter suffix from a
        NEWER log_view beats a longer stale one (VSR's core rule)."""
        r, bus, _ = _mk_replica(2)
        old_chain = _chain(5)
        new_chain = _chain(3, view=1)
        r.op = 0
        for peer in (3, 4, 5):
            r.on_message(_svc(peer, 2))
        # Peer 3: long suffix but log_view 0; peer 4: short, log_view 1.
        r.on_message(_dvc(3, 2, 5, 0, 0, [m.header for m in old_chain]))
        r.on_message(_dvc(4, 2, 3, 0, 1, [m.header for m in new_chain]))
        r.on_message(_dvc(5, 2, 0, 0, 0, []))
        # The chosen log is peer 4's: ops 1..3 with view-1 checksums.
        assert r.op == 3
        for op in (1, 2, 3):
            assert r.canonical[op].checksum == \
                new_chain[op - 1].header.checksum
        assert 4 not in r.canonical and 5 not in r.canonical

    def test_backup_truncates_on_start_view(self):
        """A backup holding uncommitted ops beyond the new canonical log
        truncates them when the start_view arrives."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        msgs = _chain(5)
        for m in msgs:
            r.on_message(m)
        assert r.op == 5
        # New view's canonical log ends at op 3.
        body = b"".join(m.header.pack() for m in msgs[:3])
        sv = Header(command=Command.start_view, cluster=CLUSTER,
                    replica=2, view=2, op=3, commit=3)
        r.on_message(Message(sv.finalize(body), body=body))
        assert r.view == 2 and r.op == 3

    def test_request_start_view_answered_by_primary(self):
        """A lagging replica probing with request_start_view gets the
        current view's start_view back (standby/rejoin catch-up path)."""
        r, bus, _ = _mk_replica(2)
        for m in _chain(2, view=2):
            r.journal.append(m)
        r.op = 2
        r.commit_min = r.commit_max = 2
        r.status = "normal"
        r.view = 2
        r.log_view = 2
        assert r.is_primary
        probe = Header(command=Command.request_start_view, cluster=CLUSTER,
                       replica=5, view=2)
        r.on_message(Message(probe.finalize()))
        svs = bus.of(Command.start_view)
        assert svs and svs[-1][0] == 5
        assert svs[-1][1].header.op == 2
