"""Scripted consensus scenarios (reference: src/vsr/replica_test.zig —
exact fault sequences that randomized simulation rarely hits;
docs/internals/vopr.md:44-46). Message-level tests drive a single
sans-io replica; the NACK-specific scenarios live in tests/test_nack.py.
"""

from tests.test_nack import (
    CLUSTER,
    _dvc,
    _mk_replica,
    _prepare_msg,
    _svc,
)
from tigerbeetle_tpu.types import Operation
from tigerbeetle_tpu.vsr.header import Command, Header, Message


def _genesis() -> int:
    from tigerbeetle_tpu.vsr.checksum import checksum

    return checksum(CLUSTER.to_bytes(16, "little"), domain=b"genesis")


def _pulse_msg(op: int, *, view: int = 0, parent: int = 0,
               commit: int = 0) -> Message:
    """A committable prepare (pulse, empty body — scripted scenarios that
    advance commit_min execute the real state machine)."""
    header = Header(command=Command.prepare, cluster=CLUSTER, view=view,
                    op=op, operation=int(Operation.pulse), parent=parent,
                    commit=commit, timestamp=op * 10**9)
    return Message(header.finalize())


def _pulse_chain(n, start_op=1, parent=None, view=0, commit=0):
    """A hash chain of committable prepares; op 1 chains from the genesis
    checksum (the cluster's op-0 parent)."""
    if parent is None:
        parent = _genesis() if start_op == 1 else 0
    msgs = []
    for op in range(start_op, start_op + n):
        m = _pulse_msg(op, view=view, parent=parent, commit=commit)
        parent = m.header.checksum
        msgs.append(m)
    return msgs


def _ok(replica: int, view: int, prepare: Message) -> Message:
    h = Header(command=Command.prepare_ok, cluster=CLUSTER, replica=replica,
               view=view, op=prepare.header.op,
               context=prepare.header.checksum)
    return Message(h.finalize())


def _chain(n, start_op=1, parent=0, view=0):
    msgs = []
    for op in range(start_op, start_op + n):
        m = _prepare_msg(op, view=view, parent=parent)
        parent = m.header.checksum
        msgs.append(m)
    return msgs


class TestViewChangeScenarios:
    def test_view_change_with_gap_repairs_before_start(self):
        """The new primary's journal has a hole inside the chosen suffix:
        it must repair the body from a peer BEFORE broadcasting
        start_view (a suffix with holes would strand backups)."""
        r, bus, _ = _mk_replica(2)
        msgs = _chain(5)
        for m in msgs:
            if m.header.op != 3:  # the hole
                r.journal.append(m)
        r.op = 5
        r.commit_min = r.commit_max = 2
        for peer in (3, 4, 5):
            r.on_message(_svc(peer, 2))
        headers = [m.header for m in msgs]
        r.on_message(_dvc(3, 2, 5, 2, 0, headers))
        r.on_message(_dvc(4, 2, 5, 2, 0, headers))
        r.on_message(_dvc(5, 2, 5, 2, 0, headers))
        # Pending: op 3's body is missing; no start_view yet.
        assert r._pending_view == 2
        assert not bus.of(Command.start_view)
        # Requests go out on the repair tick; a peer serves the body.
        # (Small advances: a long gap would escalate the view-change
        # timer to the next view.)
        r.time.advance(60 * 10**6)
        r.tick()
        assert any(m.header.op == 3
                   for _, m in bus.of(Command.request_prepare))
        r.on_message(msgs[2])  # the prepare for op 3 arrives
        r.time.advance(60 * 10**6)
        r.tick()  # repair completion check finalizes the view
        assert r._pending_view is None and r.status == "normal"
        assert bus.of(Command.start_view)
        assert r.journal.read_prepare(3) is not None

    def test_duplicate_and_stale_prepares_are_idempotent(self):
        """Replayed/duplicated prepares must not corrupt the journal or
        double-ack (the bus contract allows duplication)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        r.view = 0
        msgs = _chain(3)
        for m in msgs:
            r.on_message(m)
        assert r.op == 3
        acked_ops = {m.header.op for _, m in bus.of(Command.prepare_ok)}
        assert acked_ops, "backup must ack prepares"
        for m in msgs:  # replay all (the bus may duplicate)
            r.on_message(m)
        assert r.op == 3
        # Re-acks are fine (idempotent at the primary); journal intact.
        for op in (1, 2, 3):
            held = r.journal.read_prepare(op)
            assert held is not None
            assert held.header.checksum == msgs[op - 1].header.checksum

    def test_lower_view_messages_rejected(self):
        """A replica that moved to view 2 ignores view-0 prepares (an
        isolated stale primary cannot fork it)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        msgs = _chain(2)
        for m in msgs:
            r.on_message(m)
        r.view = 2
        r.log_view = 2
        stale = _prepare_msg(3, view=0,
                             parent=msgs[-1].header.checksum)
        r.on_message(stale)
        assert r.journal.read_prepare(3) is None
        assert r.op == 2

    def test_dvc_from_two_elections_highest_log_view_wins(self):
        """Log selection is (log_view, op)-max: a shorter suffix from a
        NEWER log_view beats a longer stale one (VSR's core rule)."""
        r, bus, _ = _mk_replica(2)
        old_chain = _chain(5)
        new_chain = _chain(3, view=1)
        r.op = 0
        for peer in (3, 4, 5):
            r.on_message(_svc(peer, 2))
        # Peer 3: long suffix but log_view 0; peer 4: short, log_view 1.
        r.on_message(_dvc(3, 2, 5, 0, 0, [m.header for m in old_chain]))
        r.on_message(_dvc(4, 2, 3, 0, 1, [m.header for m in new_chain]))
        r.on_message(_dvc(5, 2, 0, 0, 0, []))
        # The chosen log is peer 4's: ops 1..3 with view-1 checksums.
        assert r.op == 3
        for op in (1, 2, 3):
            assert r.canonical[op].checksum == \
                new_chain[op - 1].header.checksum
        assert 4 not in r.canonical and 5 not in r.canonical

    def test_backup_truncates_on_start_view(self):
        """A backup holding uncommitted ops beyond the new canonical log
        truncates them when the start_view arrives."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        msgs = _chain(5)
        for m in msgs:
            r.on_message(m)
        assert r.op == 5
        # New view's canonical log ends at op 3.
        body = b"".join(m.header.pack() for m in msgs[:3])
        sv = Header(command=Command.start_view, cluster=CLUSTER,
                    replica=2, view=2, op=3, commit=3)
        r.on_message(Message(sv.finalize(body), body=body))
        assert r.view == 2 and r.op == 3

    def test_uncommitted_suffix_recommitted_in_new_view(self):
        """Possibly-committed ops survive a view change: the new primary
        re-replicates the canonical uncommitted suffix and commits it once
        the new view's quorum acks (VSR safety — the view-change quorum
        intersects every replication quorum; reference: replica.zig
        primary repair + re-replication after start_view)."""
        r, bus, _ = _mk_replica(2)
        msgs = _pulse_chain(3)
        for m in msgs:
            r.journal.append(m)
        r.op = 3
        for peer in (3, 4, 5):
            r.on_message(_svc(peer, 2))
        headers = [m.header for m in msgs]
        for peer in (3, 4, 5):
            r.on_message(_dvc(peer, 2, 3, 0, 0, headers))
        # Log complete -> the view finalized and the suffix was
        # re-replicated (fresh quorum gathering).
        assert r.status == "normal" and r._pending_view is None
        assert set(r.pipeline) == {1, 2, 3}
        resent = {m.header.op for _, m in bus.of(Command.prepare)}
        assert resent == {1, 2, 3}
        # Two peer acks (+ self) = replication quorum of 3: all commit.
        for m in msgs:
            r.on_message(_ok(3, 2, m))
        assert r.commit_min == 0, "one ack + self is below quorum"
        for m in msgs:
            r.on_message(_ok(4, 2, m))
        assert r.commit_min == 3
        assert not r.pipeline

    def test_request_start_view_answered_by_primary(self):
        """A lagging replica probing with request_start_view gets the
        current view's start_view back (standby/rejoin catch-up path)."""
        r, bus, _ = _mk_replica(2)
        for m in _chain(2, view=2):
            r.journal.append(m)
        r.op = 2
        r.commit_min = r.commit_max = 2
        r.status = "normal"
        r.view = 2
        r.log_view = 2
        assert r.is_primary
        probe = Header(command=Command.request_start_view, cluster=CLUSTER,
                       replica=5, view=2)
        r.on_message(Message(probe.finalize()))
        svs = bus.of(Command.start_view)
        assert svs and svs[-1][0] == 5
        assert svs[-1][1].header.op == 2


class TestCommitPipeline:
    def test_quorum_commits_in_pipeline_order(self):
        """Out-of-order quorum completion must not commit out of order:
        op 2's quorum completing before op 1's commits nothing until op 1
        completes (reference: commit_dispatch executes strictly in op
        order, replica.zig:4374)."""
        r, bus, _ = _mk_replica(0)
        r.status = "normal"
        assert r.is_primary
        msgs = _pulse_chain(2)
        for m in msgs:
            r.journal.append(m)
            r.pipeline[m.header.op] = {
                "message": m, "oks": {r.replica_id}}
        r.op = 2
        # Quorum for op 2 first: nothing commits (op 1 incomplete).
        r.on_message(_ok(1, 0, msgs[1]))
        r.on_message(_ok(2, 0, msgs[1]))
        assert r.commit_min == 0 and 2 in r.pipeline
        # Op 1 completes: both commit, in order.
        r.on_message(_ok(1, 0, msgs[0]))
        r.on_message(_ok(2, 0, msgs[0]))
        assert r.commit_min == 2
        assert not r.pipeline

    def test_mismatched_ok_checksum_does_not_count(self):
        """A prepare_ok for a different prepare under the same op number
        (stale view) must not count toward the quorum."""
        r, bus, _ = _mk_replica(0)
        r.status = "normal"
        m = _pulse_chain(1)[0]
        r.journal.append(m)
        r.pipeline[1] = {"message": m, "oks": {r.replica_id}}
        r.op = 1
        impostor = _prepare_msg(1)  # different body -> different checksum
        r.on_message(_ok(1, 0, impostor))
        r.on_message(_ok(2, 0, impostor))
        assert r.commit_min == 0
        r.on_message(_ok(1, 0, m))
        r.on_message(_ok(2, 0, m))
        assert r.commit_min == 1

    def test_backup_executes_via_heartbeat_commit(self):
        """Backups learn commits from the primary's commit heartbeat and
        execute from their journal (reference: commit heartbeats,
        docs/internals/vsr.md:79-81)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        for m in _pulse_chain(3):
            r.on_message(m)
        assert r.op == 3 and r.commit_min == 0
        hb = Header(command=Command.commit, cluster=CLUSTER, replica=0,
                    view=0, commit=3)
        r.on_message(Message(hb.finalize()))
        assert r.commit_min == 3

    def test_faulty_slot_blocks_then_repairs_then_commits(self):
        """A backup with a corrupt WAL slot inside the committed prefix
        requests the prepare, re-journals the served body, and resumes
        execution (reference: journal repair, docs/internals/vsr.md:
        188-257)."""
        r, bus, time = _mk_replica(1)
        r.status = "normal"
        msgs = _pulse_chain(3)
        for m in msgs:
            r.on_message(m)
        # Corrupt op 2: header ring forgets it, slot marked faulty.
        slot = r.journal.slot_for_op(2)
        r.journal.headers[slot] = None
        r.journal.faulty.add(slot)
        hb = Header(command=Command.commit, cluster=CLUSTER, replica=0,
                    view=0, commit=3)
        r.on_message(Message(hb.finalize()))
        assert r.commit_min == 1, "execution must stop at the hole"
        assert 2 in r.repair_requested
        time.advance(60 * 10**6)
        r.tick()
        assert any(m.header.op == 2
                   for _, m in bus.of(Command.request_prepare))
        r.on_message(msgs[1])  # a peer serves the prepare
        assert r.journal.read_prepare(2) is not None
        assert r.commit_min == 3


class TestStaleLeftovers:
    def test_chain_tripwire_quarantines_stale_same_op_prepare(self):
        """A deposed primary's prepare under a reused op number chains
        from nothing we executed: the backward-chain tripwire must
        quarantine it (chain_suspect) and repair, never execute it
        (reference: the reuse-op hazard behind protocol-aware recovery,
        docs/ARCHITECTURE.md:540-563)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        good = _pulse_chain(2)
        for m in good:
            r.on_message(m)
        # Stale op 3 from a deposed primary: parent checksum garbage.
        stale = _pulse_msg(3, parent=0xDEAD)
        r.journal.append(stale)
        r.op = 3
        hb = Header(command=Command.commit, cluster=CLUSTER, replica=0,
                    view=0, commit=3)
        r.on_message(Message(hb.finalize()))
        assert r.commit_min == 2, "stale prepare must not execute"
        assert 3 in r.chain_suspect and 3 in r.repair_requested
        # The true op 3 (chains from op 2) arrives: replaces and executes.
        true3 = _pulse_msg(3, parent=good[-1].header.checksum)
        r.on_message(true3)
        assert r.commit_min == 3
        held = r.journal.read_prepare(3)
        assert held.header.checksum == true3.header.checksum
        assert 3 not in r.chain_suspect

    def test_sync_floor_blocks_unverifiable_prefix(self):
        """A start_view whose suffix begins beyond our position proves the
        electorate checkpointed past us: our journaled leftovers below the
        suffix base are unverifiable and must never execute — repair leads
        to state sync instead (reference: sync.md's checkpoint-jump
        trigger)."""
        r, bus, _ = _mk_replica(1)
        r.status = "normal"
        for m in _pulse_chain(3):
            r.on_message(m)
        assert r.commit_min == 0
        # New primary's start_view: suffix covers only ops 50..52.
        far = _pulse_chain(3, start_op=50)
        body = b"".join(m.header.pack() for m in far)
        sv = Header(command=Command.start_view, cluster=CLUSTER, replica=2,
                    view=2, op=52, commit=52)
        r.on_message(Message(sv.finalize(body), body=body))
        assert r.sync_floor >= 50
        assert r.commit_min == 0, "unverifiable ops 1..3 must not execute"


class TestCheckpointRollback:
    def _commit_through(self, r, msgs, commit):
        for m in msgs:
            r.on_message(m)
        hb = Header(command=Command.commit, cluster=CLUSTER, replica=0,
                    view=r.view, commit=commit)
        r.on_message(Message(hb.finalize()))

    def test_divergence_rolls_back_and_reexecutes(self):
        """A replica that executed a deposed primary's prepares under
        reused op numbers rolls back to its last checkpoint and re-executes
        the canonical history zipped down from the view-change suffix —
        instead of stalling until a peer checkpoint covers it (reference:
        the protocol-aware recovery goal, docs/ARCHITECTURE.md:540-563)."""
        r, bus, time = _mk_replica(1)
        r.status = "normal"
        # Ops 1..16 commit; checkpoint_interval=16 -> checkpoint at 16.
        good = _pulse_chain(16)
        self._commit_through(r, good, 16)
        assert r.commit_min == 16
        assert r.superblock.op_checkpoint == 16
        c16 = good[-1].header.checksum
        # A deposed primary's divergent suffix: B17, B18 (view 0) commit
        # locally on false evidence.
        b_chain = _pulse_chain(2, start_op=17, parent=c16)
        self._commit_through(r, b_chain, 18)
        assert r.commit_min == 18
        # The cluster actually committed A17..A20 (view 2): start_view.
        a_chain = _pulse_chain(4, start_op=17, parent=c16, view=2)
        body = b"".join(m.header.pack() for m in a_chain)
        sv = Header(command=Command.start_view, cluster=CLUSTER, replica=2,
                    view=2, op=20, commit=20)
        r.on_message(Message(sv.finalize(body), body=body))
        # Feed A19: executing it exposes the divergence (its parent is
        # A18, not our executed B18) -> rollback to checkpoint 16.
        r.on_message(a_chain[2])
        assert r._rollback_checkpoint == (16, 2)
        assert r.commit_min == 16, "state must rewind to the checkpoint"
        assert {17, 18} <= r.chain_suspect
        # The canonical prepares zip in; everything re-executes.
        for m in a_chain:
            r.on_message(m)
        assert r.commit_min == 20
        for op, m in zip(range(17, 21), a_chain):
            held = r.journal.read_prepare(op)
            assert held.header.checksum == m.header.checksum
        assert not r.chain_suspect
        assert r.sync_floor == 0, "recovered without state sync"

    def test_second_divergence_at_same_checkpoint_escalates_to_sync(self):
        """If the checkpoint itself is off the canonical history, the
        re-executed chain trips again — the second detection at the same
        checkpoint must NOT loop on rollback but fall to the sync floor."""
        r, bus, time = _mk_replica(1)
        r.status = "normal"
        good = _pulse_chain(16)
        self._commit_through(r, good, 16)
        assert r.superblock.op_checkpoint == 16
        c16 = good[-1].header.checksum
        b_chain = _pulse_chain(2, start_op=17, parent=c16)
        self._commit_through(r, b_chain, 18)
        # Canonical suffix chains from a DIFFERENT op-16 history: parent
        # unknown to us (our whole prefix diverged before the checkpoint).
        a_chain = _pulse_chain(4, start_op=17, parent=0xBEEF, view=2)
        body = b"".join(m.header.pack() for m in a_chain)
        sv = Header(command=Command.start_view, cluster=CLUSTER, replica=2,
                    view=2, op=20, commit=20)
        r.on_message(Message(sv.finalize(body), body=body))
        r.on_message(a_chain[2])  # A19 exposes divergence -> rollback
        assert r._rollback_checkpoint == (16, 2) and r.commit_min == 16
        # A17 arrives; it does NOT chain from our op 16 -> second
        # divergence at the same checkpoint -> sync floor, no loop.
        for m in a_chain:
            r.on_message(m)
        assert r.commit_min == 16, "divergent checkpoint must not execute"
        assert r.sync_floor > 16
