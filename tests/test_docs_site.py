"""Docs site generator: markdown rendering, link rewriting/checking,
full-tree build (reference analog: src/docs_website/)."""

import importlib.util
import os

import pytest

spec = importlib.util.spec_from_file_location(
    "docs_build", os.path.join(os.path.dirname(__file__), "..",
                               "scripts", "docs_build.py"))
docs_build = importlib.util.module_from_spec(spec)
spec.loader.exec_module(docs_build)


def test_render_subset():
    title, body = docs_build.render(
        "# Title\n\nPara with `code` and **bold** and "
        "[a link](other.md).\n\n"
        "```\nraw <code>\n```\n\n"
        "- item one\n- item two\n\n"
        "| a | b |\n|---|---|\n| 1 | 2 |\n")
    assert title == "Title"
    assert "<h1>Title</h1>" in body
    assert "<code>code</code>" in body and "<b>bold</b>" in body
    assert '<a href="other.html">a link</a>' in body
    assert "raw &lt;code&gt;" in body
    assert body.count("<li>") == 2
    assert "<th>a</th>" in body and "<td>1</td>" in body


def test_full_build_and_links(tmp_path):
    pages = docs_build.build(str(tmp_path))
    assert "start.md" in pages
    assert (tmp_path / "index.html").exists()  # README.md -> index
    assert (tmp_path / "internals" / "serving-kernel.html").exists()
    html = (tmp_path / "start.html").read_text()
    assert 'href="concepts/debit-credit.html"' in html


def test_broken_link_fails(tmp_path, monkeypatch):
    d = tmp_path / "docs"
    d.mkdir()
    (d / "a.md").write_text("# A\n\n[missing](nope.md)\n")
    monkeypatch.setattr(docs_build, "DOCS", str(d))
    with pytest.raises(SystemExit, match="broken internal links"):
        docs_build.build(str(tmp_path / "out"))
