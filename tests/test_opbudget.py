"""Op-budget ledger + jaxhound static-lint unit tests (quick tier).

The budgets themselves are enforced by scripts/gate.py running
`perf/opbudget.py --check --lint` (a full-tier census); these tests pin
the MACHINERY — census classification, packed-layout round-trips, the
donation/while/closure detectors — and the committed budget file's
shape, so a regression in the measuring stick is caught by the cheap
tier before the gate trusts it.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tigerbeetle_tpu import jaxhound

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# r07: historical pin for the round-7 reduction-campaign assertions.
# r09: the LIVE budget file perf/opbudget.py --check enforces (r08's
# tiers carried forward + the fused partitioned_chain tiers).
BUDGET_PATH = os.path.join(REPO, "perf", "opbudget_r07.json")
BUDGET_PATH_LIVE = os.path.join(REPO, "perf", "opbudget_r09.json")


# ------------------------------------------------------------- census

def test_heavy_census_classifies_primitives():
    def f(x, idx, seg):
        g = x[idx]                                   # gather
        s = jnp.sort(x)                              # sort
        ss = jax.ops.segment_sum(x, seg, num_segments=4)  # scatter-add
        sc = jnp.zeros_like(x).at[idx].set(x)        # scatter
        return g.sum() + s.sum() + ss.sum() + sc.sum()

    x = jnp.arange(8, dtype=jnp.float32)
    idx = jnp.zeros(8, dtype=jnp.int32)
    cj = jax.make_jaxpr(f)(x, idx, idx)
    c = jaxhound.heavy_census(cj)
    assert c["heavy"]["gather"] >= 1
    assert c["heavy"]["sort"] == 1
    assert c["heavy"]["segment_sum"] == 1
    assert c["heavy"]["scatter"] == 1
    assert c["heavy_total"] == sum(c["heavy"].values())
    assert c["heavy_operand_bytes"] > 0


def test_heavy_census_recurses_into_scan():
    def f(x):
        idx = jnp.zeros(2, dtype=jnp.int32)

        def body(c, xi):
            return c + x[idx].sum(), xi  # gather inside the body
        c, _ = jax.lax.scan(body, jnp.float32(0), x)
        return c

    cj = jax.make_jaxpr(f)(jnp.arange(4, dtype=jnp.float32))
    c = jaxhound.heavy_census(cj)
    assert c["heavy"]["scan"] == 1
    assert c["heavy"]["gather"] >= 1


def test_scan_body_census_counts_body_once():
    """The chain route's gate number: the scan BODY census is the
    per-iteration op mass — body ops x 1 in the program regardless of
    the scan length (the whole-window dispatch's point)."""
    def mk(w):
        def f(x, idx):
            def body(c, xi):
                g = c[idx]                       # 1 gather / iteration
                s = jnp.sort(c)                  # 1 sort / iteration
                return c + g.sum() + s.sum() + xi.sum(), ()
            c, _ = jax.lax.scan(
                body, x, jnp.zeros((w, 4), jnp.float32))
            return c
        return jax.make_jaxpr(f)(jnp.arange(8, dtype=jnp.float32),
                                 jnp.zeros(8, jnp.int32))

    bodies = [jaxhound.scan_body_census(mk(w)) for w in (2, 8, 32)]
    assert bodies[0]["heavy_total"] == bodies[1]["heavy_total"] \
        == bodies[2]["heavy_total"]
    assert bodies[0]["heavy"]["gather"] >= 1
    assert bodies[0]["heavy"]["sort"] == 1
    # Whole-program census = body (once) + the outer scan op.
    whole = jaxhound.heavy_census(mk(32))
    assert whole["heavy_total"] == bodies[0]["heavy_total"] + 1
    # No scan -> zero census, not an error.
    empty = jaxhound.scan_body_census(
        jax.make_jaxpr(lambda x: x + 1)(jnp.zeros(4)))
    assert empty["heavy_total"] == 0


def test_chain_body_census_within_plain_budget():
    """Acceptance pin: the committed chain BODY budget stays at or
    under the per-batch plain tier's, and the whole-program chain
    census is depth-independent (body + 1 scan at every committed
    depth)."""
    with open(BUDGET_PATH) as f:
        d = json.load(f)
    b = d["budget"]
    assert (b["chain_body_w8"]["heavy_total"]
            <= b["plain"]["heavy_total"])
    for w in (2, 8, 32):
        assert (b[f"chain_w{w}"]["heavy_total"]
                == b["chain_body_w8"]["heavy_total"] + 1), w


def test_heavy_census_counts_collectives_inside_shard_map():
    """The partitioned tiers' gate number: the census must descend into
    a shard_map body (raw Jaxpr param, not ClosedJaxpr) and classify
    the exchange collectives, and state_gathers must flag any
    collective whose operand exceeds the whole-state threshold."""
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from tigerbeetle_tpu.parallel.shard_utils import get_shard_map

    shard_map = get_shard_map()
    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))

    def body(a):
        return jax.lax.psum(a, "x")

    try:
        f = shard_map(body, mesh=mesh, in_specs=(P("x"),),
                      out_specs=P(), check_vma=False)
    except TypeError:
        f = shard_map(body, mesh=mesh, in_specs=(P("x"),),
                      out_specs=P(), check_rep=False)
    cj = jax.make_jaxpr(f)(jnp.zeros((8, 8), jnp.float32))
    c = jaxhound.heavy_census(cj)
    assert c["heavy"]["collective"] >= 1
    hits = jaxhound.state_gathers(cj, limit=8)
    assert hits and any("psum" in name for name, _ in hits)
    assert jaxhound.state_gathers(cj, limit=1 << 20) == []


def test_scan_body_census_counts_collectives_and_bytes():
    """The fused partitioned-chain route runs its psum exchange INSIDE
    the scan body: the body census must count the collective class and
    carry its operand-byte mass (collective_operand_bytes), and
    state_gathers must still flag an oversized collective through the
    scan — collectives in scan bodies must not escape either check."""
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from tigerbeetle_tpu.parallel.shard_utils import get_shard_map

    shard_map = get_shard_map()
    mesh = Mesh(np.array(jax.devices()[:1]), ("x",))

    def body(a, xs):
        def step(c, x):
            return c + jax.lax.psum(x, "x"), ()
        c, _ = jax.lax.scan(step, a, xs)
        return c

    try:
        f = shard_map(body, mesh=mesh, in_specs=(P("x"), P()),
                      out_specs=P("x"), check_vma=False)
    except TypeError:
        f = shard_map(body, mesh=mesh, in_specs=(P("x"), P()),
                      out_specs=P("x"), check_rep=False)
    cj = jax.make_jaxpr(f)(jnp.zeros((8,), jnp.float32),
                           jnp.zeros((4, 8), jnp.float32))
    whole = jaxhound.heavy_census(cj)
    assert whole["heavy"]["collective"] >= 1
    assert whole["collective_operand_bytes"] > 0
    bodyc = jaxhound.scan_body_census(cj)
    assert bodyc["heavy"]["collective"] >= 1
    assert bodyc["collective_operand_bytes"] > 0
    # The collective's bytes are a subset of the body's heavy bytes.
    assert (bodyc["collective_operand_bytes"]
            <= bodyc["heavy_operand_bytes"])
    hits = jaxhound.state_gathers(cj, limit=8)
    assert hits and any("psum" in name for name, _ in hits)
    # A collective-free scan censuses zero collective bytes.
    def plain(x):
        def step(c, xi):
            return c + jnp.sort(xi), ()
        c, _ = jax.lax.scan(step, x, jnp.zeros((4, 8), jnp.float32))
        return c

    clean = jaxhound.scan_body_census(
        jax.make_jaxpr(plain)(jnp.zeros((8,), jnp.float32)))
    assert clean["heavy"]["collective"] == 0
    assert clean["collective_operand_bytes"] == 0
    assert clean["heavy"]["sort"] == 1


# ----------------------------------------------------------- lints

def test_while_detector_sees_searchsorted_scan_method():
    def f(a, q):
        return jnp.searchsorted(a, q)  # default method lowers to while

    a = jnp.arange(64, dtype=jnp.uint64)
    low = jax.jit(f).lower(a, a[:4])
    assert low.as_text().count("stablehlo.while") >= 1

    def g(a, q):
        return jnp.searchsorted(a, q, method="sort")

    low2 = jax.jit(g).lower(a, a[:4])
    assert low2.as_text().count("stablehlo.while") == 0


def test_donated_inputs_counts_aliased_params():
    def f(state, y):
        return {k: v + y for k, v in state.items()}

    state = {"a": jnp.zeros(4), "b": jnp.zeros(4)}
    donated = jaxhound.donated_inputs(
        jax.jit(f, donate_argnums=0).lower(state, jnp.float32(1)))
    assert donated == 2
    undonated = jaxhound.donated_inputs(
        jax.jit(f).lower(state, jnp.float32(1)))
    assert undonated == 0


def test_closure_constant_detector():
    big = jnp.arange(4096, dtype=jnp.uint64)  # 32 KiB baked constant

    def f(x):
        return big[x]

    consts = jaxhound.closure_constants(
        jax.make_jaxpr(f)(jnp.zeros(4, jnp.int32)))
    assert consts and consts[0][1] == 4096 * 8

    def g(x):
        return x + 1  # no large consts

    assert jaxhound.closure_constants(
        jax.make_jaxpr(g)(jnp.zeros(4, jnp.int32))) == []


# ----------------------------------------------- packed store layouts

def test_packed_layout_roundtrip_transfers():
    from tigerbeetle_tpu.ops.ev_layout import (
        XF_NCOLS, XF_P32_POS, XF_U64_IDX, pack32, xf_col, xf_named)

    m = np.zeros((3, XF_NCOLS), dtype=np.uint64)
    m[:, XF_U64_IDX["ts"]] = [7, 8, 9]
    # ud32 above 2^31 (sign-sensitive), pstat/dr_row as i32 views.
    col, half = XF_P32_POS["ud32"]
    m[:, col] |= np.uint64(0xDEADBEEF) << np.uint64(32 * half)
    col, half = XF_P32_POS["timeout"]
    m[:, col] |= np.uint64(17) << np.uint64(32 * half)
    col, half = XF_P32_POS["pstat"]
    m[:, col] |= np.uint64(2) << np.uint64(32 * half)
    xfr = {"u64": m}
    assert list(xf_col(xfr, "ud32")) == [0xDEADBEEF] * 3
    assert xf_col(xfr, "ud32").dtype == np.uint32
    assert list(xf_col(xfr, "timeout")) == [17] * 3
    named = xf_named(xfr)
    assert named["pstat"].dtype == np.int32
    assert list(named["pstat"]) == [2, 2, 2]
    assert list(named["ts"]) == [7, 8, 9]
    # pack32 zero-extends signed inputs (no sign smear into the partner).
    w = pack32(np.array([-1], dtype=np.int32),
               np.array([5], dtype=np.int32))
    assert int(w[0]) == (5 << 32) | 0xFFFFFFFF


def test_packed_layout_roundtrip_events_negative_p_row():
    from tigerbeetle_tpu.ops.ledger import init_state
    from tigerbeetle_tpu.ops.ev_layout import ev_col, ev_named

    evr = init_state(1 << 6, 1 << 6)["events"]
    p_row = np.asarray(ev_col(evr, "p_row"))
    assert p_row.dtype == np.int32
    assert (p_row == -1).all()  # the init sentinel survives packing
    tflags = np.asarray(ev_col(evr, "tflags"))
    assert (tflags == np.uint32(0xFFFFFFFF)).all()
    named = ev_named(evr)
    assert named["dr_row"].dtype == np.int32


def test_packed_layout_accounts_flags_isolated_from_code():
    from tigerbeetle_tpu.ops.ev_layout import (
        AC_NCOLS, AC_P32_POS, ac_named, pack32)

    m = np.zeros((2, AC_NCOLS), dtype=np.uint64)
    col, _ = AC_P32_POS["code"]
    assert AC_P32_POS["flags"][0] == col, \
        "flags must share its packed column with code only (the " \
        "closing-native RMW write-back preserves exactly that half)"
    m[:, col] = pack32(np.array([77, 78], dtype=np.uint32),
                       np.array([0x10, 0x20], dtype=np.uint32))
    named = ac_named({"u64": m})
    assert list(named["code"]) == [77, 78]
    assert list(named["flags"]) == [0x10, 0x20]


# ------------------------------------------------- committed budgets

def test_budget_file_covers_core_tiers():
    with open(BUDGET_PATH_LIVE) as f:
        d = json.load(f)
    for tier in ("per_event_plain", "plain", "fixpoint_8",
                 "balancing_8", "imported", "super_plain_s4",
                 "super_deep24_s4", "sharded_plain", "sharded_fixpoint",
                 "chain_w2", "chain_w8", "chain_w32", "chain_body_w8",
                 "partitioned_plain", "partitioned_fixpoint",
                 "partitioned_chain_w2", "partitioned_chain_w8",
                 "partitioned_chain_w32", "partitioned_chain_body"):
        assert tier in d["budget"], tier
        b = d["budget"][tier]
        assert b["heavy_total"] == sum(b["heavy"].values())
        assert b["heavy_operand_bytes"] > 0
    # post must not exceed budget (the gate's invariant, pinned here
    # against hand-edits that would silently loosen it backwards).
    for tier, b in d["budget"].items():
        post = d["post"][tier]
        assert post["heavy_total"] <= b["heavy_total"], tier
    # The partitioned tiers' exchange is budget-pinned: a bounded,
    # NONZERO collective count (two psum exchange rounds + the merged
    # bad-flag reduction), never a whole-state gather (run_lints).
    for tier in ("partitioned_plain", "partitioned_fixpoint",
                 "partitioned_chain_body"):
        assert 0 < d["budget"][tier]["heavy"]["collective"] <= 8, tier


def test_partitioned_chain_budget_is_amortized_x1():
    """Acceptance pin for the fused route: the scan-BODY op count
    equals the per-batch partitioned tier (the window amortizes
    dispatch, it adds no per-prepare op mass), the whole-program census
    is flat in W (body + the one outer scan op at every committed
    depth), and the exchange's ICI byte mass is pinned nonzero inside
    the scan body (collective_operand_bytes in the post census)."""
    with open(BUDGET_PATH_LIVE) as f:
        d = json.load(f)
    b = d["budget"]
    body = b["partitioned_chain_body"]["heavy_total"]
    assert body == b["partitioned_plain"]["heavy_total"]
    for w in (2, 8, 32):
        assert b[f"partitioned_chain_w{w}"]["heavy_total"] == body + 1, w
    post = d["post"]["partitioned_chain_body"]
    assert post["heavy"]["collective"] >= 1
    assert post["collective_operand_bytes"] > 0


def test_campaign_hit_the_15pct_reduction():
    with open(BUDGET_PATH) as f:
        d = json.load(f)
    pre = d["pre"]["per_event_plain"]["heavy_total"]
    post = d["post"]["per_event_plain"]["heavy_total"]
    assert post <= 0.85 * pre, (pre, post)
    # The full plain tier rode along.
    assert (d["post"]["plain"]["heavy_total"]
            <= 0.85 * d["pre"]["plain"]["heavy_total"])


def test_check_budgets_flags_excess(monkeypatch):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tb_opbudget_test", os.path.join(REPO, "perf", "opbudget.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with open(mod.BUDGET_PATH) as f:
        budgets = json.load(f)["budget"]
    ok = {t: {"heavy_total": b["heavy_total"],
              "heavy": dict(b["heavy"]),
              "heavy_operand_bytes": b["heavy_operand_bytes"]}
          for t, b in budgets.items()}
    assert mod.check_budgets(current=ok) == []
    bad = {t: dict(c, heavy=dict(c["heavy"])) for t, c in ok.items()}
    tier = "plain"
    bad[tier]["heavy_total"] += 1
    bad[tier]["heavy"]["gather"] += 1
    fails = mod.check_budgets(current=bad)
    assert any(tier in f and "heavy_total" in f for f in fails)
    assert any(tier in f and "gather" in f for f in fails)
